"""Pass 9 — inter-procedural lock-order analysis (deadlocks + blocking).

Python has no ``go test -race``; this pass is the static half of the
substitute.  It reuses trace_safety's cross-module call-closure machinery
to build the lock-ACQUISITION graph over every ``with <lock>:`` region in
the tree:

  * lock-order         — a cycle in the acquired-while-held graph: thread
                         1 takes A then B, thread 2 takes B then A, and
                         the serve plane wedges.  Re-acquiring the SAME
                         non-reentrant ``threading.Lock`` (directly or
                         through a called function) is the length-1 cycle
                         and reported the same way; RLocks are exempt.
  * lock-blocking-call — a blocking operation (``time.sleep``, socket
                         accept/connect/recv, ``thread.join()``,
                         ``.block_until_ready()``, the estimator RPC)
                         executed while a lock is held, directly or
                         transitively through the call closure: every
                         other thread needing that lock stalls for the
                         full wait.

Locks are identified by their CREATION site — ``threading.Lock()`` /
``RLock()`` / ``Condition(...)`` (any module alias), plus the runtime
detector's ``VetLock(...)`` / ``make_lock(...)`` / ``make_rlock(...)``
wrappers — as ``self.<attr>`` instance state or a module-global name.  A
``Condition(self._lock)`` shares its wrapped lock's identity (acquiring
the condition IS acquiring the lock).  ``with`` targets that do not
resolve to a known creation site (parameters, computed locks) are skipped
— the analysis is compositional, RacerD-style, no whole-program aliasing.

Call closure: bare-name calls resolve to module-level defs and
``from ... import`` names (via trace_safety._resolve_module);
``self.m()`` resolves to methods of the same class.  Nested ``def`` /
``lambda`` bodies are deferred work — a ``with`` around a ``def`` does
not guard (or order) the eventual call, so they are analyzed as if the
surrounding stack were empty and their acquires are NOT charged to the
enclosing function.

Findings anchor at the acquiring/blocking line (direct) or the call site
that reaches it (transitive), so the standing `# vet: ignore[rule] why`
waiver grammar applies per-edge.  ``Condition.wait`` is deliberately NOT
a blocking call: it releases the lock while waiting — that is the one
correct way to block under a lock.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from karmada_tpu.analysis.core import Finding, SourceFile, dotted
from karmada_tpu.analysis.trace_safety import _resolve_module

#: constructor name (last dotted component) -> lock kind
_LOCK_CTORS = {
    "Lock": "lock",
    "RLock": "rlock",
    "Condition": "condition",
    "VetLock": "lock",       # utils/locks runtime detector proxy
    "make_lock": "lock",
    "make_rlock": "rlock",
}

#: attribute names that block regardless of receiver (``x.sleep(...)``).
#: `wait` is NOT here: Condition.wait releases the lock while waiting.
_BLOCKING_ATTRS = frozenset({
    "sleep", "_sleep",            # time.sleep + injectable clock sleeps
    "block_until_ready",          # device sync
    "accept", "connect", "recv", "recv_into", "sendall", "makefile",
    "getresponse", "communicate",  # socket / HTTP / subprocess waits
    "urlopen",                    # urllib.request.urlopen
    "assign_replicas",            # the estimator RPC (facade/estimator)
})

#: fully-dotted callables that block (bare-name or module-attr form)
_BLOCKING_DOTTED = frozenset({
    "time.sleep", "select.select", "socket.create_connection",
    "urllib.request.urlopen", "sleep",
})


@dataclass
class _LockDef:
    """One lock creation site.  `lock_id` is the graph node identity."""

    lock_id: str       # "<path>::<Class.attr|NAME>" after alias resolution
    kind: str          # "lock" | "rlock" | "condition"
    file: str
    line: int
    display: str       # short human name for messages


@dataclass
class _FnInfo:
    """Per-function facts harvested in one lexical walk."""

    # lock ids acquired anywhere in the body (direct `with` regions)
    acquires: Set[str] = field(default_factory=set)
    # (held_id, acquired_id, line): direct nesting observed lexically
    held_edges: List[Tuple[str, str, int]] = field(default_factory=list)
    # (line, description): blocking ops regardless of held state
    blocking: List[Tuple[int, str]] = field(default_factory=list)
    # (line, description, held ids): blocking ops under a held lock
    held_blocking: List[Tuple[int, str, Tuple[str, ...]]] = \
        field(default_factory=list)
    # (callee key, line, held ids) for every resolved call
    calls: List[Tuple[Tuple[str, str], int, Tuple[str, ...]]] = \
        field(default_factory=list)
    # nested def/closure bodies: analyzed as separate functions (their
    # acquires are NOT charged to the enclosing function — deferred work)
    nested: List[Tuple[str, "_FnInfo"]] = field(default_factory=list)


def _short(path: str) -> str:
    parts = path.split(os.sep)
    return os.sep.join(parts[-2:]) if len(parts) > 1 else path


def _ctor_kind(call: ast.AST) -> Optional[str]:
    """Lock kind when `call` is a recognized lock-constructor Call."""
    if not isinstance(call, ast.Call):
        return None
    d = dotted(call.func)
    if d is None:
        return None
    return _LOCK_CTORS.get(d.rsplit(".", 1)[-1])


class _Mod:
    """One module's lock-definition table + call-resolution context."""

    def __init__(self, sf: SourceFile) -> None:
        self.sf = sf
        # qualname ("f" or "Class.m") -> FunctionDef, and owning class
        self.funcs: Dict[str, ast.FunctionDef] = {}
        self.func_class: Dict[str, Optional[str]] = {}
        for node in sf.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.funcs[node.name] = node
                self.func_class[node.name] = None
            elif isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        q = f"{node.name}.{item.name}"
                        self.funcs[q] = item
                        self.func_class[q] = node.name
        # local name -> (source module, original name, relative level)
        self.imports: Dict[str, Tuple[Optional[str], str, int]] = {}
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ImportFrom):
                for a in node.names:
                    self.imports[a.asname or a.name] = (
                        node.module, a.name, node.level or 0)
        # lock tables: module globals and per-class instance attrs.
        # raw entries may alias (Condition(self._lock)); resolved after.
        self._raw_mod: Dict[str, Tuple[str, int, Optional[str]]] = {}
        self._raw_cls: Dict[str, Dict[str, Tuple[str, int,
                                                 Optional[str]]]] = {}
        self._harvest_locks()
        self.module_locks: Dict[str, _LockDef] = {}
        self.class_locks: Dict[str, Dict[str, _LockDef]] = {}
        self._resolve_lock_defs()

    def _harvest_locks(self) -> None:
        for node in self.sf.tree.body:
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                self._harvest_assign(node, self._raw_mod, module=True)
            elif isinstance(node, ast.ClassDef):
                table = self._raw_cls.setdefault(node.name, {})
                for sub in ast.walk(node):
                    if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                        self._harvest_assign(sub, table, module=False)

    def _harvest_assign(self, node, table, module: bool) -> None:
        value = node.value
        kind = _ctor_kind(value)
        if kind is None:
            return
        # Condition(self._lock) / Condition(_LOCK) aliases the wrapped
        # lock; Condition() owns a private lock of its own
        alias: Optional[str] = None
        if kind == "condition" and value.args:
            d = dotted(value.args[0])
            if d is not None:
                alias = d[5:] if d.startswith("self.") else d
                if "." in alias:
                    alias = None
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for t in targets:
            if module and isinstance(t, ast.Name):
                table[t.id] = (kind, node.lineno, alias)
            elif not module and isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name) and t.value.id == "self":
                table[t.attr] = (kind, node.lineno, alias)

    def _resolve_lock_defs(self) -> None:
        path = self.sf.path

        def build(table, scope: Optional[str]):
            out: Dict[str, _LockDef] = {}
            for attr, (kind, line, alias) in table.items():
                # follow the Condition alias chain within the same scope
                root, root_kind = attr, kind
                seen = {attr}
                while True:
                    entry = table.get(root)
                    nxt = entry[2] if entry else None
                    if nxt is None or nxt not in table or nxt in seen:
                        break
                    seen.add(nxt)
                    root = nxt
                    root_kind = table[root][0]
                label = f"{scope}.{root}" if scope else root
                out[attr] = _LockDef(
                    lock_id=f"{path}::{label}", kind=root_kind,
                    file=path, line=line,
                    display=f"{_short(path)}:{label}")
            return out

        self.module_locks = build(self._raw_mod, None)
        for cls, table in self._raw_cls.items():
            self.class_locks[cls] = build(table, cls)

    def lock_for(self, expr: ast.AST,
                 cls: Optional[str]) -> Optional[_LockDef]:
        """The _LockDef a `with` target resolves to, or None (unknown
        receivers — parameters, computed locks — are skipped)."""
        d = dotted(expr)
        if d is None:
            return None
        if d.startswith("self."):
            attr = d[5:]
            if "." in attr or cls is None:
                return None
            return self.class_locks.get(cls, {}).get(attr)
        if "." in d:
            return None
        return self.module_locks.get(d)


def _blocking_desc(node: ast.Call) -> Optional[str]:
    """A short description when `node` is a recognized blocking call."""
    d = dotted(node.func)
    if d is not None and (d in _BLOCKING_DOTTED
                          or d.rsplit(".", 1)[-1] in ("block_until_ready",)):
        return f"`{d}()`"
    if isinstance(node.func, ast.Attribute):
        attr = node.func.attr
        if attr in _BLOCKING_ATTRS:
            return f"`.{attr}()`"
        # thread.join() / thread.join(timeout=...) — zero POSITIONAL
        # args distinguishes it from str.join(iterable)
        if attr == "join" and not node.args:
            return "`.join()`"
    elif isinstance(node.func, ast.Name) and node.func.id in ("sleep",):
        return f"`{node.func.id}()`"
    return None


class _Walker:
    """Lexical walk of one function body carrying the held-lock stack."""

    def __init__(self, mod: _Mod, cls: Optional[str], info: _FnInfo) -> None:
        self.mod = mod
        self.cls = cls
        self.info = info

    def walk(self, fn: ast.FunctionDef) -> None:
        for stmt in fn.body:
            self._stmt(stmt, [])

    def _held(self, stack: List[List[_LockDef]]) -> Tuple[str, ...]:
        out: List[str] = []
        for frame in stack:
            for ld in frame:
                if ld.lock_id not in out:
                    out.append(ld.lock_id)
        return tuple(out)

    def _stmt(self, node: ast.stmt, stack: List[List[_LockDef]]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # deferred body: the surrounding with neither guards nor
            # orders the eventual call, and the closure's own acquires
            # belong to the eventual caller's context, not this one —
            # analyze it as a separate (synthetic) function
            sub = _FnInfo()
            inner = _Walker(self.mod, self.cls, sub)
            for stmt in node.body:
                inner._stmt(stmt, [])
            self.info.nested.append((node.name, sub))
            return
        if isinstance(node, ast.With):
            frame: List[_LockDef] = []
            held_before = self._held(stack)
            for item in node.items:
                self._expr(item.context_expr, stack)
                ld = self.mod.lock_for(item.context_expr, self.cls)
                if ld is None:
                    continue
                self.info.acquires.add(ld.lock_id)
                for h in held_before + self._held([frame]):
                    self.info.held_edges.append(
                        (h, ld.lock_id, node.lineno))
                frame.append(ld)
            stack.append(frame)
            for stmt in node.body:
                self._stmt(stmt, stack)
            stack.pop()
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt):
                self._stmt(child, stack)
            elif isinstance(child, ast.excepthandler):
                for stmt in child.body:
                    self._stmt(stmt, stack)
            elif isinstance(child, ast.expr):
                self._expr(child, stack)

    def _expr(self, e: ast.AST, stack: List[List[_LockDef]]) -> None:
        if isinstance(e, ast.Lambda):
            return  # deferred body
        if isinstance(e, ast.Call):
            held = self._held(stack)
            desc = _blocking_desc(e)
            if desc is not None:
                self.info.blocking.append((e.lineno, desc))
                if held:
                    self.info.held_blocking.append((e.lineno, desc, held))
            callee = self._resolve_call(e)
            if callee is not None:
                self.info.calls.append((callee, e.lineno, held))
        for child in ast.iter_child_nodes(e):
            if isinstance(child, ast.expr):
                self._expr(child, stack)
            else:
                for sub in ast.iter_child_nodes(child):
                    if isinstance(sub, ast.expr):
                        self._expr(sub, stack)

    def _resolve_call(self, e: ast.Call) -> Optional[Tuple[str, str]]:
        mod = self.mod
        f = e.func
        if isinstance(f, ast.Name):
            name = f.id
            if name in mod.funcs and mod.func_class[name] is None:
                return (mod.sf.path, name)
            if name in mod.imports:
                src_module, orig, level = mod.imports[name]
                src_path = _resolve_module(
                    mod.sf.path, src_module, level, _PATHS.get())
                if src_path is not None:
                    return (src_path, orig)
            return None
        if isinstance(f, ast.Attribute) and \
                isinstance(f.value, ast.Name) and f.value.id == "self" \
                and self.cls is not None:
            q = f"{self.cls}.{f.attr}"
            if q in mod.funcs:
                return (mod.sf.path, q)
        return None


class _Paths:
    """The scanned-path set, visible to call resolution without threading
    it through every walker (one pass run at a time)."""

    def __init__(self) -> None:
        self._paths: Dict[str, bool] = {}

    def set(self, paths: Sequence[str]) -> None:
        self._paths = {p: True for p in paths}

    def get(self) -> Dict[str, bool]:
        return self._paths


_PATHS = _Paths()


def _closure(infos: Dict[Tuple[str, str], _FnInfo]) -> Tuple[
        Dict[Tuple[str, str], Set[str]],
        Dict[Tuple[str, str], Set[Tuple[int, str, str]]]]:
    """Fixpoint: transitive acquires and transitive blocking ops per
    function.  Blocking entries carry their ORIGIN (file, line, desc) so
    transitive findings can say where the wait actually happens."""
    acq: Dict[Tuple[str, str], Set[str]] = {
        k: set(v.acquires) for k, v in infos.items()}
    blk: Dict[Tuple[str, str], Set[Tuple[int, str, str]]] = {
        k: {(line, desc, k[0]) for line, desc in v.blocking}
        for k, v in infos.items()}
    changed = True
    while changed:
        changed = False
        for key, info in infos.items():
            for callee, _line, _held in info.calls:
                if callee not in infos:
                    continue
                if not acq[callee] <= acq[key]:
                    acq[key] |= acq[callee]
                    changed = True
                if not blk[callee] <= blk[key]:
                    blk[key] |= blk[callee]
                    changed = True
    return acq, blk


def _sccs(nodes: Sequence[str],
          succ: Dict[str, Set[str]]) -> List[List[str]]:
    """Tarjan SCCs, iterative (analysis code must not recurse on user
    graph depth)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    out: List[List[str]] = []
    counter = [0]
    for root in nodes:
        if root in index:
            continue
        work: List[Tuple[str, int]] = [(root, 0)]
        while work:
            v, pi = work[-1]
            if pi == 0:
                index[v] = low[v] = counter[0]
                counter[0] += 1
                stack.append(v)
                on_stack.add(v)
            advanced = False
            children = sorted(succ.get(v, ()))
            while pi < len(children):
                w = children[pi]
                pi += 1
                work[-1] = (v, pi)
                if w not in index:
                    work.append((w, 0))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            if pi >= len(children):
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[v])
                if low[v] == index[v]:
                    comp: List[str] = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        comp.append(w)
                        if w == v:
                            break
                    out.append(comp)
    return out


def run(files: Sequence[SourceFile]) -> List[Finding]:
    mods = {sf.path: _Mod(sf) for sf in files}
    _PATHS.set(list(mods))
    # union of every module's lock tables, keyed by lock_id
    lock_defs: Dict[str, _LockDef] = {}
    for mod in mods.values():
        for table in ([mod.module_locks] + list(mod.class_locks.values())):
            for ld in table.values():
                lock_defs.setdefault(ld.lock_id, ld)

    infos: Dict[Tuple[str, str], _FnInfo] = {}

    def register(path: str, qual: str, info: _FnInfo) -> None:
        infos[(path, qual)] = info
        for name, sub in info.nested:
            register(path, f"{qual}.<locals>.{name}", sub)

    for path, mod in mods.items():
        for qual, fn in mod.funcs.items():
            info = _FnInfo()
            _Walker(mod, mod.func_class[qual], info).walk(fn)
            register(path, qual, info)
    acq, blk = _closure(infos)

    findings: List[Finding] = []
    # edge -> first (file, line, note); deterministic smallest anchor
    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}

    def add_edge(a: str, b: str, file: str, line: int, note: str) -> None:
        cur = edges.get((a, b))
        if cur is None or (file, line) < (cur[0], cur[1]):
            edges[(a, b)] = (file, line, note)

    for (path, qual), info in infos.items():
        for a, b, line in info.held_edges:
            add_edge(a, b, path, line, f"in `{qual}`")
        for callee, line, held in info.calls:
            if not held or callee not in infos:
                continue
            for b in sorted(acq[callee]):
                for a in held:
                    add_edge(a, b, path, line,
                             f"in `{qual}` via `{callee[1]}()`")
            for bline, desc, bfile in sorted(blk[callee]):
                held_names = ", ".join(
                    lock_defs[h].display for h in held if h in lock_defs)
                findings.append(Finding(
                    rule="lock-blocking-call", file=path, line=line,
                    message=f"`{qual}` calls `{callee[1]}()` which "
                            f"performs {desc} ({_short(bfile)}:{bline}) "
                            f"while holding {held_names} — every thread "
                            "needing the lock stalls for the wait",
                ))
        for line, desc, held in info.held_blocking:
            held_names = ", ".join(
                lock_defs[h].display for h in held if h in lock_defs)
            findings.append(Finding(
                rule="lock-blocking-call", file=path, line=line,
                message=f"{desc} inside `with` holding {held_names} "
                        f"(in `{qual}`) — every thread needing the lock "
                        "stalls for the wait",
            ))

    # self-edges: re-acquiring a held non-reentrant lock IS the deadlock
    succ: Dict[str, Set[str]] = {}
    for (a, b), (file, line, note) in sorted(edges.items()):
        if a == b:
            ld = lock_defs.get(a)
            if ld is not None and ld.kind == "rlock":
                continue
            findings.append(Finding(
                rule="lock-order", file=file, line=line,
                message=f"`{ld.display if ld else a}` re-acquired while "
                        f"already held ({note}) — non-reentrant "
                        "threading.Lock self-deadlocks",
            ))
            continue
        succ.setdefault(a, set()).add(b)

    for comp in _sccs(sorted(lock_defs), succ):
        if len(comp) < 2:
            continue
        comp_set = set(comp)
        cyc_edges = sorted(
            (edges[(a, b)][0], edges[(a, b)][1], a, b)
            for (a, b) in edges
            if a in comp_set and b in comp_set and a != b)
        file, line = cyc_edges[0][0], cyc_edges[0][1]
        path_desc = "; ".join(
            f"{lock_defs[a].display} -> {lock_defs[b].display} "
            f"({_short(f)}:{ln}, {edges[(a, b)][2]})"
            for f, ln, a, b in cyc_edges)
        findings.append(Finding(
            rule="lock-order", file=file, line=line,
            message=f"lock-order cycle across {len(comp)} locks — "
                    f"opposite acquisition orders can deadlock: "
                    f"{path_desc}",
        ))
    return findings
