"""Static-analysis subsystem behind `karmadactl vet` (+ armed runtime guards).

Nine AST-level pass families over the package, each targeting a defect
class that unit tests on one CPU device cannot see but real multichip
topologies and threaded serve processes can (the PR-3 s64/s32 wave-scan
bug is the type specimen):

  * trace-safety       — Python control flow on traced values, host syncs,
                         and dtype-defaulted constructors inside
                         jit-compiled code (trace_safety.py)
  * dtype-contract     — SolverBatch/carry/native-ABI construction sites
                         checked against the canonical per-field dtype
                         tables (ops/tensors; dtype_contract.py)
  * spec-coverage      — every SolverBatch/ResidentPlane tensor field has
                         a PartitionSpec entry in ops/meshing.shard_specs
                         or is declared host-only (spec_coverage.py)
  * guarded-by         — `# guarded-by: <lock>` annotated attributes are
                         only mutated inside the matching `with <lock>:`
                         block (lock_discipline.py)
  * metric-naming      — registered metrics are karmada_-prefixed
                         snake_case with help text (metric_naming.py)
  * metric-docs        — every registered metric is catalogued in
                         OBSERVABILITY.md, and vice versa (metric_docs.py)
  * event-reasons      — lifecycle-ledger emissions pass declared REASON_*
                         constants, catalogued in the doc (event_reasons.py)
  * exception-hygiene  — blanket handlers re-raise, record a metric, or
                         carry a justified waiver (exception_hygiene.py)
  * lock-order         — inter-procedural lock-acquisition graph: cycles
                         (`lock-order`) and blocking calls under a held
                         lock (`lock-blocking-call`) (lock_order.py)

`vet.run_vet` orchestrates the passes; `guards` is the armed RUNTIME mode
(`serve --check-invariants` / KARMADA_CHECK_INVARIANTS=1): shape/dtype/NaN
invariant checks at solver entry and d2h boundaries, plus the
`utils/locks.VetLock` race detector (ownership, order inversions, hold
times, deadlock watchdog) sharing the same arming flag.  All passes are
pure AST work — no jax import, safe in any environment.
"""

from karmada_tpu.analysis.core import Finding, Waiver  # noqa: F401
from karmada_tpu.analysis.vet import run_vet  # noqa: F401
