"""Static-analysis subsystem behind `karmadactl vet` (+ armed runtime guards).

Four AST-level passes over the package, each targeting a defect class that
unit tests on one CPU device cannot see but real multichip topologies and
threaded serve processes can (the PR-3 s64/s32 wave-scan bug is the type
specimen):

  * trace-safety    — Python control flow on traced values, host syncs, and
                      dtype-defaulted constructors inside jit-compiled code
                      (karmada_tpu/analysis/trace_safety.py)
  * dtype-contract  — SolverBatch/carry construction sites checked against
                      the canonical per-field dtype table
                      (ops/tensors.FIELD_DTYPES; dtype_contract.py)
  * spec-coverage   — every SolverBatch tensor field has a PartitionSpec
                      entry in ops/meshing.shard_specs (spec_coverage.py)
  * guarded-by      — `# guarded-by: <lock>` annotated attributes are only
                      mutated inside the matching `with <lock>:` block
                      (lock_discipline.py)

`vet.run_vet` orchestrates the passes; `guards` is the armed RUNTIME mode
(`serve --check-invariants` / KARMADA_CHECK_INVARIANTS=1): shape/dtype/NaN
invariant checks at solver entry and d2h boundaries.  All passes are pure
AST work — no jax import, safe in any environment.
"""

from karmada_tpu.analysis.core import Finding, Waiver  # noqa: F401
from karmada_tpu.analysis.vet import run_vet  # noqa: F401
