"""Pass 2 — SolverBatch/carry dtype contract at construction sites.

The canonical table lives WITH the data it describes
(ops/tensors.FIELD_DTYPES / CARRY_DTYPES); this pass reads it out of the
scanned tree's AST (no import — fixtures bring their own table) and then
checks every ``np.zeros/ones/full/empty/asarray/array`` and ``.astype``
construction site whose assignment target is a declared field name:

    name_rank = np.zeros(C, np.int32)     # finding: table says int64

That is exactly the PR-3 bug class made vet-time: an s32 array where the
kernel contract says s64 (or vice versa) is invisible on one device and a
mixed-dtype HLO verifier failure once the SPMD partitioner is involved.
Constructors with *no* dtype at a declared field site are also findings
(``np.zeros`` defaults to f64).  Dtype expressions the AST cannot resolve
(e.g. ``other.dtype`` pass-throughs, ``zeros_like``) are left alone.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence

from karmada_tpu.analysis.core import Finding, SourceFile, dotted

_CTOR_DTYPE_POS = {"zeros": 1, "ones": 1, "empty": 1, "full": 2,
                   "asarray": 1, "array": 1, "ascontiguousarray": 1}

#: table variable names the pass harvests from scanned files.
#: NATIVE_ABI_DTYPES (ops/tensors.py) covers the native decode boundary —
#: the int32 COO / verdict planes handed to native/decode_fast.c, whose C
#: loop reads raw buffers and would decode garbage (not crash) on a
#: drifted dtype, the same class of bug as NativeSnapshot.name_rank.
TABLE_NAMES = ("FIELD_DTYPES", "CARRY_DTYPES", "NATIVE_ABI_DTYPES")

_DTYPE_NORMALIZE = {
    "bool": "bool", "bool_": "bool",
    "int32": "int32", "int64": "int64",
    "int16": "int16", "int8": "int8",
    "float32": "float32", "float64": "float64",
    "int": "int64", "float": "float64",  # builtins on 64-bit linux
}


def resolve_dtype(node: Optional[ast.AST]) -> Optional[str]:
    """'int64' for np.int64 / jnp.int64 / "int64" / bool / int; None when
    the expression is dynamic (e.g. ``arr.dtype``)."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return _DTYPE_NORMALIZE.get(node.value)
    d = dotted(node)
    if d is None:
        return None
    return _DTYPE_NORMALIZE.get(d.rsplit(".", 1)[-1])


def harvest_tables(files: Sequence[SourceFile]) -> Dict[str, str]:
    """field -> dtype string, merged from every scanned FIELD_DTYPES /
    CARRY_DTYPES dict literal."""
    table: Dict[str, str] = {}
    for sf in files:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Assign):
                continue
            names = [t.id for t in node.targets if isinstance(t, ast.Name)]
            if not any(n in TABLE_NAMES for n in names):
                continue
            if isinstance(node.value, ast.Dict):
                for k, v in zip(node.value.keys, node.value.values):
                    if isinstance(k, ast.Constant) and \
                            isinstance(v, ast.Constant) and \
                            isinstance(k.value, str):
                        table[k.value] = str(v.value)
    return table


def _dtype_arg(call: ast.Call, attr: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == "dtype":
            return kw.value
    pos = _CTOR_DTYPE_POS[attr]
    if len(call.args) > pos:
        return call.args[pos]
    return None


def _target_field(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def run(files: Sequence[SourceFile]) -> List[Finding]:
    table = harvest_tables(files)
    if not table:
        return []
    findings: List[Finding] = []
    for sf in files:
        for node in ast.walk(sf.tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            fields = [f for f in (_target_field(t) for t in targets)
                      if f in table]
            if not fields or node.value is None:
                continue
            call = node.value
            if not isinstance(call, ast.Call):
                continue
            d = dotted(call.func)
            attr = d.rsplit(".", 1)[-1] if d else None
            if isinstance(call.func, ast.Attribute) and \
                    call.func.attr == "astype":
                got = resolve_dtype(call.args[0] if call.args else None)
            elif attr in _CTOR_DTYPE_POS and d is not None and "." in d:
                got = resolve_dtype(_dtype_arg(call, attr))
                if got is None and _dtype_arg(call, attr) is None and \
                        attr in ("zeros", "ones", "empty", "full"):
                    for f in fields:
                        findings.append(Finding(
                            rule="dtype-contract", file=sf.path,
                            line=node.lineno,
                            message=f"`{f}` built by np.{attr} with no "
                                    f"dtype (defaults to float64); the "
                                    f"contract says {table[f]}",
                        ))
                    continue
            else:
                continue
            if got is None:
                continue  # dynamic dtype expression: not statically checkable
            for f in fields:
                want = table[f]
                if got != want:
                    findings.append(Finding(
                        rule="dtype-contract", file=sf.path,
                        line=node.lineno,
                        message=f"`{f}` constructed as {got} but the "
                                f"canonical table (FIELD_DTYPES) says "
                                f"{want} — the s64/s32 drift class",
                    ))
    return findings
