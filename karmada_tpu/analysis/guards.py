"""Armed runtime invariants (`serve --check-invariants` /
KARMADA_CHECK_INVARIANTS=1) — the dynamic half of the vet subsystem.

Functionalized runtime checking in the jax.checkify spirit, applied at
the two places the static passes cannot see across: the host->device
boundary (solver entry: every SolverBatch field checked against the
canonical dtype/axis tables in ops/tensors.py) and the device->host
boundary (compact d2h: index bounds, value sanity, status codes, NaN).

Disarmed cost is one list read per dispatch (``armed()``), so the hooks
live directly on the production paths (ops/solver.solve /
dispatch_compact / finalize_compact, ops/spread.solve_spread).  A
violation raises InvariantViolation — loud and early, instead of an XLA
verifier error three layers later or silent s64/s32 drift.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import numpy as np


class InvariantViolation(AssertionError):
    """An armed shape/dtype/value invariant failed at a checked boundary.

    Construction fires the incident plane's invariant-violation trigger
    (obs/incidents): one hook covers every raise site — check_batch /
    check_used / check_d2h, VetLock.require_held, OwnerThread.check.
    The trigger is reentrancy-latched and never raises, so building the
    exception stays safe even mid-capture."""

    def __init__(self, *args) -> None:
        super().__init__(*args)
        from karmada_tpu.obs import incidents as obs_incidents

        obs_incidents.trigger(
            obs_incidents.TRIGGER_INVARIANT_VIOLATION,
            str(args[0]) if args else "invariant violation",
            detail={"message": str(args[0]) if args else ""})


_ARMED = [os.environ.get("KARMADA_CHECK_INVARIANTS", "") not in ("", "0")]


def arm(on: bool = True) -> None:
    """Arm/disarm the runtime checks process-wide (serve --check-invariants
    calls this before any controller thread runs)."""
    _ARMED[0] = bool(on)


def armed() -> bool:
    return _ARMED[0]


def _dims_of(batch) -> dict:
    return {"B": batch.B, "C": batch.C}


#: FIELD_DTYPES entries that may legitimately be absent/None on a batch:
#: the shortlist kernel's OUTPUT planes (ops/shortlist — typed in the
#: table for the dtype-contract pass, never SolverBatch attributes) and
#: the sub-vocabulary lane map (dense batches carry none; when present
#: on a shortlisted sub-batch it is checked like any other field)
_OPTIONAL_FIELDS = frozenset(
    {"shortlist_idx", "shortlist_fcount", "sub_lanes"})


def check_batch(batch, where: str = "solver-entry") -> None:
    """Validate a SolverBatch against the canonical per-field dtype table
    (tensors.FIELD_DTYPES) and axis table (tensors.FIELD_AXES): dtype
    match, dimensionality, and B/C axis extents.  Raises
    InvariantViolation on the first mismatch."""
    from karmada_tpu.ops.tensors import FIELD_AXES, FIELD_DTYPES

    dims = _dims_of(batch)
    for field_name, want in FIELD_DTYPES.items():
        arr = getattr(batch, field_name, None)
        if arr is None:
            if field_name in _OPTIONAL_FIELDS:
                continue
            raise InvariantViolation(
                f"[{where}] SolverBatch.{field_name} is None")
        arr = np.asarray(arr)
        got = "bool" if arr.dtype == np.bool_ else str(arr.dtype)
        if got != want:
            raise InvariantViolation(
                f"[{where}] SolverBatch.{field_name} dtype {got} != "
                f"canonical {want} (FIELD_DTYPES) — s64/s32 drift")
        axes = FIELD_AXES.get(field_name)
        if axes is None:
            continue
        if arr.ndim != len(axes):
            raise InvariantViolation(
                f"[{where}] SolverBatch.{field_name} has {arr.ndim} dims, "
                f"expected {len(axes)} {axes}")
        for i, ax in enumerate(axes):
            if ax in dims and arr.shape[i] != dims[ax]:
                raise InvariantViolation(
                    f"[{where}] SolverBatch.{field_name} axis {i} ({ax}) "
                    f"is {arr.shape[i]}, batch says {dims[ax]}")
        if np.issubdtype(arr.dtype, np.floating) and \
                not np.isfinite(arr).all():
            raise InvariantViolation(
                f"[{where}] SolverBatch.{field_name} contains "
                "NaN/Inf values")


def check_used(used: Optional[Sequence], where: str = "carry") -> None:
    """Validate a (used_milli, used_pods, used_sets) carry triple's dtypes
    against tensors.CARRY_DTYPES (device arrays are inspected by dtype
    attribute only — no host sync)."""
    if used is None:
        return
    from karmada_tpu.ops.tensors import CARRY_DTYPES
    names = tuple(CARRY_DTYPES)
    if len(used) != len(names):
        raise InvariantViolation(
            f"[{where}] carry triple has {len(used)} members, "
            f"expected {len(names)} {names}")
    for name, arr in zip(names, used):
        dt = getattr(arr, "dtype", None)
        if dt is None:
            continue
        got = "bool" if dt == np.bool_ else str(dt)
        if got != CARRY_DTYPES[name]:
            raise InvariantViolation(
                f"[{where}] carry {name} dtype {got} != canonical "
                f"{CARRY_DTYPES[name]} (CARRY_DTYPES)")


def check_d2h(idx: np.ndarray, val: np.ndarray, status: np.ndarray,
              dense_nnz: int, where: str = "d2h") -> None:
    """Validate a compact COO result at the device->host boundary: int32
    planes, indices within [-1, dense_nnz), non-negative replica values,
    known status codes, and finiteness (NaN guard on any float input)."""
    from karmada_tpu.ops.tensors import (
        STATUS_FIT_ERROR,
        STATUS_NO_CLUSTER,
        STATUS_OK,
        STATUS_UNSCHEDULABLE,
    )

    idx = np.asarray(idx)
    val = np.asarray(val)
    status = np.asarray(status)
    for name, arr in (("idx", idx), ("val", val), ("status", status)):
        if np.issubdtype(arr.dtype, np.floating):
            if not np.isfinite(arr).all():
                raise InvariantViolation(
                    f"[{where}] compact {name} contains NaN/Inf")
            raise InvariantViolation(
                f"[{where}] compact {name} is float ({arr.dtype}); the "
                "COO planes are int32 by contract")
        if arr.dtype != np.int32:
            raise InvariantViolation(
                f"[{where}] compact {name} dtype {arr.dtype} != int32")
    if idx.size and (int(idx.min()) < -1 or int(idx.max()) >= dense_nnz):
        raise InvariantViolation(
            f"[{where}] compact idx out of range [-1, {dense_nnz}): "
            f"min={int(idx.min())}, max={int(idx.max())}")
    if val.size and int(val[idx >= 0].min(initial=0)) < 0:
        raise InvariantViolation(
            f"[{where}] compact val has negative replica counts")
    known = {STATUS_OK, STATUS_FIT_ERROR, STATUS_UNSCHEDULABLE,
             STATUS_NO_CLUSTER}
    bad = set(np.unique(status).tolist()) - known
    if bad:
        raise InvariantViolation(
            f"[{where}] unknown solver status code(s) {sorted(bad)}")
