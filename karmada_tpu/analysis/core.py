"""Shared plumbing for the vet passes: findings, waivers, source loading.

Waiver convention (docs/STATIC_ANALYSIS.md): a finding is suppressed by

    <offending statement>  # vet: ignore[<rule>] <justification>

on the statement's FIRST line, or by the same comment alone on the line
directly above it.  The justification is mandatory — a bare ignore is
itself reported (rule "waiver-syntax") and suppresses nothing, so every
waiver in the tree documents why the rule does not apply.  Waivers are
never silent: run_vet counts and enumerates them in its JSON output.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

#: every rule a pass can emit (CLI --rules validates against this)
RULES = (
    "trace-branch",      # Python if/while on a traced (jnp/lax) value
    "trace-host-sync",   # .item()/float()/int()/np.asarray inside jit code
    "trace-weak-int",    # dtype-defaulted jnp constructor inside jit code
    "dtype-contract",    # construction site disagrees with FIELD_DTYPES
    "spec-coverage",     # SolverBatch field missing from shard_specs
    "guarded-by",        # annotated state mutated outside its lock
    "metric-naming",     # registry metric not karmada_-prefixed snake_case
                         # with help text
    "metric-docs",       # registered metric missing from
                         # docs/OBSERVABILITY.md (or a doc row gone stale)
    "event-reasons",     # ledger emission without a declared REASON_*
                         # constant, or a reason missing from the
                         # docs/OBSERVABILITY.md catalog
    "exception-hygiene",  # blanket except that neither re-raises nor
                          # records a metric (nor carries a waiver)
    "lock-order",        # cycle (or non-reentrant re-acquire) in the
                         # inter-procedural lock-acquisition graph
    "lock-blocking-call",  # sleep/socket/join/device-sync/estimator RPC
                           # executed while a lock is held
    "waiver-syntax",     # vet: ignore[...] without a justification
)

_WAIVER_RE = re.compile(
    r"#\s*vet:\s*ignore\[([A-Za-z0-9_,\- ]+)\]\s*(.*?)\s*$")


@dataclass
class Finding:
    rule: str
    file: str
    line: int
    message: str

    def to_dict(self) -> dict:
        return {"rule": self.rule, "file": self.file, "line": self.line,
                "message": self.message}


@dataclass
class Waiver:
    rule: str
    file: str
    line: int  # line of the waived FINDING (not of the comment)
    justification: str

    def to_dict(self) -> dict:
        return {"rule": self.rule, "file": self.file, "line": self.line,
                "justification": self.justification}


@dataclass
class SourceFile:
    """One parsed python file, shared by every pass."""

    path: str
    text: str
    tree: ast.Module
    lines: List[str] = field(default_factory=list)
    # comment line -> [(rule, justification)]; a waiver on line L covers
    # findings anchored at L (trailing comment) and L+1 (comment above)
    waivers: Dict[int, List[Tuple[str, str]]] = field(default_factory=dict)

    def waiver_for(self, rule: str, line: int) -> Optional[Tuple[int, str]]:
        """(comment_line, justification) covering (rule, line), or None."""
        for cline in (line, line - 1):
            for wrule, just in self.waivers.get(cline, ()):
                if wrule == rule and just:
                    return cline, just
        return None


def _collect_waivers(lines: Sequence[str]) -> Dict[int, List[Tuple[str, str]]]:
    out: Dict[int, List[Tuple[str, str]]] = {}
    for i, line in enumerate(lines, start=1):
        m = _WAIVER_RE.search(line)
        if m is None:
            continue
        rules = [r.strip() for r in m.group(1).split(",") if r.strip()]
        just = m.group(2).strip()
        out[i] = [(r, just) for r in rules]
    return out


def load_file(path: str) -> Optional[SourceFile]:
    """Parse one file; None when it is not parseable python (vet reports
    syntax errors through the caller, never crashes on them)."""
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
        tree = ast.parse(text, filename=path)
    except (OSError, SyntaxError, ValueError):
        return None
    lines = text.splitlines()
    return SourceFile(path=path, text=text, tree=tree, lines=lines,
                      waivers=_collect_waivers(lines))


def collect_files(paths: Sequence[str]) -> List[SourceFile]:
    """Every .py file under the given files/directories, parsed once.
    __pycache__ and hidden directories are skipped."""
    seen: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d != "__pycache__" and not d.startswith("."))
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        seen.append(os.path.join(root, fn))
        elif p.endswith(".py"):
            seen.append(p)
    out: List[SourceFile] = []
    for path in seen:
        sf = load_file(path)
        if sf is not None:
            out.append(sf)
    return out


def apply_waivers(
    findings: Sequence[Finding], files: Sequence[SourceFile]
) -> Tuple[List[Finding], List[Waiver]]:
    """Split raw findings into (kept, waived); also surfaces bare ignores
    (no justification) as waiver-syntax findings — an undocumented waiver
    is a finding, not a suppression."""
    by_path = {sf.path: sf for sf in files}
    kept: List[Finding] = []
    waived: List[Waiver] = []
    for f in findings:
        sf = by_path.get(f.file)
        hit = sf.waiver_for(f.rule, f.line) if sf is not None else None
        if hit is not None:
            waived.append(Waiver(rule=f.rule, file=f.file, line=f.line,
                                 justification=hit[1]))
        else:
            kept.append(f)
    for sf in files:
        for cline, entries in sf.waivers.items():
            for rule, just in entries:
                if not just:
                    kept.append(Finding(
                        rule="waiver-syntax", file=sf.path, line=cline,
                        message=f"vet: ignore[{rule}] without a "
                                "justification — waivers must say why",
                    ))
    return kept, waived


def dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None
