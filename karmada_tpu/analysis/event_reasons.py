"""Pass 8 — event-reason taxonomy (docs/OBSERVABILITY.md catalog).

Two legs, mirroring the metric-docs pass:

  * **call sites** — every lifecycle-ledger emission
    (``recorder.event(obj, type_, REASON, msg)``, ``emit(ref, type_,
    REASON, msg)``, ``emit_key(key, type_, REASON, msg)``) must pass a
    declared ``REASON_*`` constant, never a string literal or computed
    value: the reason vocabulary is the timeline's query key (the
    auditor's terminal-state walk, the per-reason metrics, the doc
    catalog), and an ad-hoc string silently forks it;
  * **catalog** — every ``REASON_* = "..."`` constant declared in the
    taxonomy home (``obs/events.py``) must appear in the
    docs/OBSERVABILITY.md reason catalog, so an operator reading a
    timeline can look up what each reason means.  Only runs on
    whole-package scans (the scanned set must include ``obs/events.py``)
    — vetting one file must not report the rest of the tree's doc.

Waivers: ``# vet: ignore[event-reasons] <why>`` on the call site, and
the doc side needs no waiver channel (declare the constant where the
pass harvests or don't declare it at all).
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional, Sequence, Tuple

from karmada_tpu.analysis.core import Finding, SourceFile, dotted
from karmada_tpu.analysis.metric_docs import DOC_RELPATH, _find_doc

#: module-level emitter names (obs/events): calls to these are ledger
#: emissions wherever they appear (bare or attribute-qualified)
EMIT_FUNCS = ("emit", "emit_key")

#: the taxonomy home — REASON_* assignments are harvested only here
TAXONOMY_SUFFIX = os.path.join("obs", "events.py")


def _reason_arg(node: ast.Call) -> Optional[ast.AST]:
    """The reason argument of an emission call: positional index 2
    (after obj/ref and type_) or the ``reason=`` keyword."""
    for kw in node.keywords:
        if kw.arg == "reason":
            return kw.value
    if len(node.args) > 2:
        return node.args[2]
    return None


def _is_emission(node: ast.Call) -> Optional[str]:
    """\"recorder.event\" / \"emit\" / \"emit_key\" when the call is a
    ledger emission, else None."""
    f = node.func
    if isinstance(f, ast.Attribute):
        if f.attr == "event":
            chain = dotted(f.value) or ""
            if chain == "recorder" or chain.endswith(".recorder"):
                return "recorder.event"
            return None
        if f.attr in EMIT_FUNCS:
            return f.attr
        return None
    if isinstance(f, ast.Name) and f.id in EMIT_FUNCS:
        return f.id
    return None


def _reason_const_name(node: ast.AST) -> Optional[str]:
    """The terminal identifier of a Name/Attribute reason argument."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def declared_reasons(
        files: Sequence[SourceFile]) -> List[Tuple[str, str, SourceFile, int]]:
    """(constant name, reason value, file, line) for every module-level
    ``REASON_* = "literal"`` assignment in the taxonomy home."""
    out: List[Tuple[str, str, SourceFile, int]] = []
    for sf in files:
        if not sf.path.endswith(TAXONOMY_SUFFIX):
            continue
        for node in sf.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            for target in node.targets:
                if (isinstance(target, ast.Name)
                        and target.id.startswith("REASON_")
                        and isinstance(node.value, ast.Constant)
                        and isinstance(node.value.value, str)):
                    out.append((target.id, node.value.value, sf, node.lineno))
    return out


def run(files: Sequence[SourceFile]) -> List[Finding]:
    findings: List[Finding] = []
    # -- leg 1: every emission call site names a REASON_* constant ----------
    for sf in files:
        if sf.path.endswith(TAXONOMY_SUFFIX):
            continue  # the ledger's own internals forward parameters
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            shape = _is_emission(node)
            if shape is None:
                continue
            arg = _reason_arg(node)
            if arg is None:
                continue  # too few args: not the emission signature
            name = _reason_const_name(arg)
            if name is None or not name.startswith("REASON_"):
                what = ("string literal"
                        if isinstance(arg, ast.Constant) else "expression")
                findings.append(Finding(
                    rule="event-reasons", file=sf.path, line=node.lineno,
                    message=f"{shape}(...) passes a {what} as the event "
                            "reason — every emission must name a declared "
                            "REASON_* constant (obs/events.py taxonomy; "
                            "ad-hoc reasons fork the timeline vocabulary)",
                ))
    # -- leg 2: every declared reason is catalogued in the doc --------------
    declared = declared_reasons(files)
    if not declared:
        return findings  # partial scan: the taxonomy home is not in view
    doc_path = _find_doc(files)
    if doc_path is None:
        _, _, sf, line = declared[0]
        findings.append(Finding(
            rule="event-reasons", file=sf.path, line=line,
            message=f"{DOC_RELPATH} not found above the scanned tree — "
                    "the event-reason catalog gate cannot run",
        ))
        return findings
    try:
        with open(doc_path, encoding="utf-8") as f:
            doc_text = f.read()
    except OSError as e:
        _, _, sf, line = declared[0]
        findings.append(Finding(
            rule="event-reasons", file=sf.path, line=line,
            message=f"cannot read {doc_path}: {e}"))
        return findings
    for cname, value, sf, line in declared:
        if value not in doc_text:
            findings.append(Finding(
                rule="event-reasons", file=sf.path, line=line,
                message=f"event reason `{value}` ({cname}) is not "
                        f"catalogued in {DOC_RELPATH} — every reason an "
                        "operator can meet on a timeline needs its row",
            ))
    return findings
