"""Pass 3 — PartitionSpec coverage for every solver-plane tensor field.

Drift detector for the mesh-sharded solve path: a field added to
``SolverBatch`` (ops/tensors.py) — or to the resident-state plane's
``ResidentPlane`` (resident/state.py), whose per-cycle gathered copies
ship into the very same dispatch — without a PartitionSpec entry in
``shard_specs`` (ops/meshing.py) would silently dispatch with whatever
default placement jax picks — correct on one device, an implicit
all-replicate (or a crash) on a mesh.  The pass AST-extracts:

  * the ndarray-annotated fields of the ``class SolverBatch`` and
    ``class ResidentPlane`` dataclasses,
  * the string keys of the dict literal inside ``def shard_specs``,
  * ``HOST_ONLY_FIELDS`` (fields that by design never cross the host ->
    device boundary, e.g. ``route``) and ``RESIDENT_HOST_ONLY`` (the
    resident plane's own exemptions),

and reports both directions of drift: fields missing a spec entry, and
spec entries naming no field (stale keys).  This is the same gate that
caught SolverBatch drift on day one, now covering the resident plane.

The fused gather path (ops/resident_gather + the resident device slot
store) adds a third drift class this pass closes:

  * every binding-row SLOT-STORE field (``BINDING_SLOT_FIELDS`` /
    ``DEVICE_SLOT_FIELDS`` in resident/state.py) must appear in
    ``shard_specs`` or a declared host-only set — its device mirror is
    gathered straight into the dispatch, so an uncovered field would be
    mesh-placed by accident exactly like an uncovered batch field;
  * the gather kernel's field set (``GATHER_FIELDS`` in
    ops/resident_gather.py) must equal the slot store's — a field added
    to one tuple but not the other would silently ship stale/garbage
    rows;
  * every gather OUTPUT (``OUT_FIELDS``) must have a ``shard_specs``
    entry: the kernel pins its out-shardings FROM that table, which is
    also the solver's in-sharding table — one table, so the fused
    chain's in/out shardings cannot drift apart; this check makes the
    table-totality explicit.

The shortlist plane (ops/shortlist) adds a fourth drift class, the same
shape as the gather's: every tier-1 kernel output
(``SHORTLIST_OUT_FIELDS``) must have BOTH a ``shard_specs`` entry (the
kernel pins its out-shardings from the table the tier-2 dispatch places
its in-shardings with) and an ``ops/tensors.FIELD_DTYPES`` entry (the
armed runtime guards and the dtype-contract pass read the same table) —
a field added to the kernel without either would be placed or typed by
accident.
"""

from __future__ import annotations

import ast
from typing import List, Sequence, Set, Tuple

from karmada_tpu.analysis.core import Finding, SourceFile, dotted

#: (dataclass, host-only exemption set) pairs covered by the pass; the
#: exemption constant is looked up in the SAME file as its class
COVERED_CLASSES = (
    ("SolverBatch", "HOST_ONLY_FIELDS"),
    ("ResidentPlane", "RESIDENT_HOST_ONLY"),
)


def _ndarray_fields(tree: ast.Module, cls: str) -> Tuple[int, Set[str]]:
    """(class lineno, ndarray-annotated field names) of dataclass `cls`."""
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == cls:
            fields: Set[str] = set()
            for stmt in node.body:
                if not isinstance(stmt, ast.AnnAssign):
                    continue
                ann = dotted(stmt.annotation)
                if ann is not None and ann.rsplit(".", 1)[-1] == "ndarray" \
                        and isinstance(stmt.target, ast.Name):
                    fields.add(stmt.target.id)
            return node.lineno, fields
    return 0, set()


def _const_strings(tree: ast.Module, name: str) -> Set[str]:
    """Every string literal inside the module-level `name = ...`."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            names = [t.id for t in node.targets if isinstance(t, ast.Name)]
            if name in names:
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Constant) and \
                            isinstance(sub.value, str):
                        out.add(sub.value)
    return out


def _spec_table(tree: ast.Module) -> Tuple[int, Set[str]]:
    """(shard_specs lineno, spec keys)."""
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == "shard_specs":
            best: Set[str] = set()
            for sub in ast.walk(node):
                if isinstance(sub, ast.Dict):
                    ks = {k.value for k in sub.keys
                          if isinstance(k, ast.Constant)
                          and isinstance(k.value, str)}
                    if len(ks) > len(best):
                        best = ks
            return node.lineno, best
    return 0, set()


def run(files: Sequence[SourceFile]) -> List[Finding]:
    specs_file = None
    keys: Set[str] = set()
    host_only: Set[str] = set()
    specs_line = 0
    # cls -> (file, line, fields, extra host-only set)
    classes: dict = {}
    for sf in files:
        line, k = _spec_table(sf.tree)
        if k and specs_file is None:
            specs_file, keys, specs_line = sf, k, line
            host_only = _const_strings(sf.tree, "HOST_ONLY_FIELDS")
        for cls, exempt_name in COVERED_CLASSES:
            line, f = _ndarray_fields(sf.tree, cls)
            if f and cls not in classes:
                classes[cls] = (sf, line, f,
                                _const_strings(sf.tree, exempt_name))
    if specs_file is None:
        return []  # scanned subtree lacks the spec table: nothing to compare
    findings: List[Finding] = []
    for cls, _exempt in COVERED_CLASSES:
        if cls not in classes:
            continue
        _sf, _line, fields, extra = classes[cls]
        for f in sorted(fields - keys - host_only - extra):
            findings.append(Finding(
                rule="spec-coverage", file=specs_file.path, line=specs_line,
                message=f"{cls} field `{f}` has no PartitionSpec entry "
                        "in shard_specs (and is not in HOST_ONLY_FIELDS / "
                        "RESIDENT_HOST_ONLY) — a mesh dispatch would "
                        "place it by accident",
            ))
    # shortlist kernel outputs (ops/shortlist.SHORTLIST_OUT_FIELDS):
    # legitimate spec keys that are not SolverBatch fields — collected
    # before the stale-key sweep so they are exempt from it, then
    # checked for their own two-table coverage below
    shortlist_fields: Set[str] = set()
    shortlist_file = None
    field_dtypes: Set[str] = set()
    for sf in files:
        s = _const_strings(sf.tree, "SHORTLIST_OUT_FIELDS")
        if s and shortlist_file is None:
            shortlist_fields, shortlist_file = s, sf
        d = _const_strings(sf.tree, "FIELD_DTYPES")
        if d and not field_dtypes:
            field_dtypes = d
    if "SolverBatch" in classes:
        # stale-key drift is judged against SolverBatch only: the resident
        # plane's fields are a subset of the batch vocabulary by design
        for k in sorted(keys - classes["SolverBatch"][2] - shortlist_fields):
            findings.append(Finding(
                rule="spec-coverage", file=specs_file.path, line=specs_line,
                message=f"shard_specs entry `{k}` names no SolverBatch "
                        "field — stale key",
            ))
    if shortlist_file is not None:
        for f in sorted(shortlist_fields - keys):
            findings.append(Finding(
                rule="spec-coverage", file=shortlist_file.path, line=1,
                message=f"shortlist kernel output `{f}` has no "
                        "shard_specs entry — its out-sharding cannot "
                        "chain into the tier-2 solver's in-sharding",
            ))
        if field_dtypes:
            for f in sorted(shortlist_fields - field_dtypes):
                findings.append(Finding(
                    rule="spec-coverage", file=shortlist_file.path, line=1,
                    message=f"shortlist kernel output `{f}` has no "
                            "ops/tensors.FIELD_DTYPES entry — the dtype "
                            "contract would not cover it",
                ))
    # -- fused gather path: slot store x gather kernel x spec table ----------
    slot_fields: Set[str] = set()
    slot_file = None
    gather_fields: Set[str] = set()
    out_fields: Set[str] = set()
    gather_file = None
    for sf in files:
        s = _const_strings(sf.tree, "BINDING_SLOT_FIELDS") | \
            _const_strings(sf.tree, "DEVICE_SLOT_FIELDS")
        if s and slot_file is None:
            slot_fields, slot_file = s, sf
        g = _const_strings(sf.tree, "GATHER_FIELDS")
        if g and gather_file is None:
            gather_fields, gather_file = g, sf
            out_fields = _const_strings(sf.tree, "OUT_FIELDS")
    if slot_file is not None:
        resident_exempt = (classes.get("ResidentPlane") or
                           (None, 0, set(), set()))[3]
        for f in sorted(slot_fields - keys - host_only - resident_exempt):
            findings.append(Finding(
                rule="spec-coverage", file=slot_file.path, line=1,
                message=f"resident slot-store field `{f}` has no "
                        "PartitionSpec entry in shard_specs (and is not "
                        "host-only) — its device mirror feeds the fused "
                        "gather and would be mesh-placed by accident",
            ))
    if slot_file is not None and gather_file is not None:
        for f in sorted(slot_fields ^ gather_fields):
            where = ("slot store but not the gather kernel"
                     if f in slot_fields
                     else "gather kernel but not the slot store")
            findings.append(Finding(
                rule="spec-coverage", file=gather_file.path, line=1,
                message=f"fused-gather field `{f}` is in the {where} "
                        "(DEVICE_SLOT_FIELDS vs GATHER_FIELDS drift)",
            ))
    if gather_file is not None and keys:
        for f in sorted(out_fields - keys):
            findings.append(Finding(
                rule="spec-coverage", file=gather_file.path, line=1,
                message=f"fused-gather output `{f}` has no shard_specs "
                        "entry — its out-sharding cannot chain into the "
                        "solver's in-sharding",
            ))
    return findings
