"""Pass 3 — PartitionSpec coverage for every SolverBatch tensor field.

Drift detector for the mesh-sharded solve path: a field added to
``SolverBatch`` (ops/tensors.py) without a PartitionSpec entry in
``shard_specs`` (ops/meshing.py) would silently dispatch with whatever
default placement jax picks — correct on one device, an implicit
all-replicate (or a crash) on a mesh.  The pass AST-extracts:

  * the ndarray-annotated fields of the ``class SolverBatch`` dataclass,
  * the string keys of the dict literal inside ``def shard_specs``,
  * ``HOST_ONLY_FIELDS`` (fields that by design never cross the host ->
    device boundary, e.g. ``route``),

and reports both directions of drift: fields missing a spec entry, and
spec entries naming no field (stale keys).
"""

from __future__ import annotations

import ast
from typing import List, Sequence, Set, Tuple

from karmada_tpu.analysis.core import Finding, SourceFile, dotted


def _ndarray_fields(tree: ast.Module) -> Tuple[int, Set[str]]:
    """(class lineno, ndarray-annotated field names) of SolverBatch."""
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "SolverBatch":
            fields: Set[str] = set()
            for stmt in node.body:
                if not isinstance(stmt, ast.AnnAssign):
                    continue
                ann = dotted(stmt.annotation)
                if ann is not None and ann.rsplit(".", 1)[-1] == "ndarray" \
                        and isinstance(stmt.target, ast.Name):
                    fields.add(stmt.target.id)
            return node.lineno, fields
    return 0, set()


def _spec_table(tree: ast.Module) -> Tuple[int, Set[str], Set[str]]:
    """(shard_specs lineno, spec keys, HOST_ONLY_FIELDS entries)."""
    line, keys = 0, set()
    host_only: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == "shard_specs":
            line = node.lineno
            best: Set[str] = set()
            for sub in ast.walk(node):
                if isinstance(sub, ast.Dict):
                    ks = {k.value for k in sub.keys
                          if isinstance(k, ast.Constant)
                          and isinstance(k.value, str)}
                    if len(ks) > len(best):
                        best = ks
            keys = best
        elif isinstance(node, ast.Assign):
            names = [t.id for t in node.targets if isinstance(t, ast.Name)]
            if "HOST_ONLY_FIELDS" in names:
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Constant) and \
                            isinstance(sub.value, str):
                        host_only.add(sub.value)
    return line, keys, host_only


def run(files: Sequence[SourceFile]) -> List[Finding]:
    fields_file = specs_file = None
    fields: Set[str] = set()
    fields_line = 0
    keys: Set[str] = set()
    host_only: Set[str] = set()
    specs_line = 0
    for sf in files:
        line, f = _ndarray_fields(sf.tree)
        if f and fields_file is None:
            fields_file, fields, fields_line = sf, f, line
        line, k, h = _spec_table(sf.tree)
        if k and specs_file is None:
            specs_file, keys, specs_line = sf, k, line
            host_only = h
    if fields_file is None or specs_file is None:
        return []  # scanned subtree lacks one side: nothing to compare
    findings: List[Finding] = []
    for f in sorted(fields - keys - host_only):
        findings.append(Finding(
            rule="spec-coverage", file=specs_file.path, line=specs_line,
            message=f"SolverBatch field `{f}` has no PartitionSpec entry "
                    "in shard_specs (and is not in HOST_ONLY_FIELDS) — a "
                    "mesh dispatch would place it by accident",
        ))
    for k in sorted(keys - fields):
        findings.append(Finding(
            rule="spec-coverage", file=specs_file.path, line=specs_line,
            message=f"shard_specs entry `{k}` names no SolverBatch field "
                    "— stale key",
        ))
    return findings
