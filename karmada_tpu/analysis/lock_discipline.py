"""Pass 4 — `# guarded-by:` lock-discipline checking.

Convention (docs/STATIC_ANALYSIS.md): annotate the statement that creates
a lock-protected attribute with the lock that guards it —

    self._ring = collections.deque(maxlen=cap)  # guarded-by: _lock
    _LAST: dict = {...}                         # guarded-by: _LAST_LOCK

The checker then verifies every MUTATION of the annotated attribute in
that class (or module, for module-level state) happens lexically inside a
``with <lock>:`` block — ``self.<lock>`` for instance locks, the bare
name for module locks.  Mutations are: assignment / augmented assignment
to the attribute, subscript assignment or deletion through it, and calls
of known mutating methods on it (append, add, pop, update, ...).  Domain
mutators beyond the builtin set are declared in the annotation:

    self.queue = SchedulingQueue()  # guarded-by: _queue_lock; mutators: push,pop_ready

Reads are not checked (the recorder intentionally allows brief lock-free
reads); the analysis is compositional, RacerD-style: each attribute is
judged against its own declared lock, with no whole-program alias
analysis.  A nested ``def``/``lambda`` body resets the lock context — a
``with`` around a ``def`` does not guard the deferred call.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from karmada_tpu.analysis.core import Finding, SourceFile, dotted

_ANNOT_RE = re.compile(
    r"#\s*guarded-by:\s*([A-Za-z_]\w*)"
    r"(?:\s*;\s*mutators:\s*([A-Za-z_][\w,\s]*))?")

#: builtin container mutators (dict/list/set/deque/OrderedDict)
MUTATORS = frozenset({
    "append", "appendleft", "add", "pop", "popitem", "update", "clear",
    "discard", "remove", "sort", "insert", "extend", "setdefault",
})


class _Guarded:
    def __init__(self, attr: str, lock: str, mutators: Set[str],
                 line: int) -> None:
        self.attr = attr
        self.lock = lock
        self.mutators = MUTATORS | mutators
        self.line = line  # the annotated (defining) statement's line


def _annotations(sf: SourceFile) -> Dict[Optional[str], Dict[str, _Guarded]]:
    """scope -> {attr: _Guarded}; scope is the class name or None for
    module level.  The annotation attaches to the assignment starting on
    the comment's line (trailing) or the next line (comment above)."""
    annots: Dict[int, Tuple[str, Set[str]]] = {}
    for i, line in enumerate(sf.lines, start=1):
        m = _ANNOT_RE.search(line)
        if m:
            extra = {s.strip() for s in (m.group(2) or "").split(",")
                     if s.strip()}
            annots[i] = (m.group(1), extra)
    if not annots:
        return {}
    classes: List[Tuple[str, int, int]] = []
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.ClassDef):
            classes.append((node.name, node.lineno,
                            node.end_lineno or node.lineno))

    def scope_of(line: int) -> Optional[str]:
        best = None
        for name, lo, hi in classes:
            if lo <= line <= hi:
                best = name  # innermost wins (walk order is outer-first)
        return best

    out: Dict[Optional[str], Dict[str, _Guarded]] = {}
    for node in ast.walk(sf.tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        entry = annots.get(node.lineno) or annots.get(node.lineno - 1)
        if entry is None:
            continue
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for t in targets:
            attr = None
            if isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name) and t.value.id == "self":
                attr = t.attr
            elif isinstance(t, ast.Name):
                attr = t.id
            if attr is None:
                continue
            lock, extra = entry
            out.setdefault(scope_of(node.lineno), {})[attr] = _Guarded(
                attr, lock, extra, node.lineno)
    return out


def _is_attr_ref(node: ast.AST, attr: str, module_scope: bool) -> bool:
    if isinstance(node, ast.Attribute):
        return (node.attr == attr and isinstance(node.value, ast.Name)
                and node.value.id == "self")
    if module_scope and isinstance(node, ast.Name):
        return node.id == attr
    return False


def _with_locks(node: ast.With) -> Set[str]:
    locks: Set[str] = set()
    for item in node.items:
        d = dotted(item.context_expr)
        if d is None:
            continue
        locks.add(d.rsplit(".", 1)[-1] if d.startswith("self.") else d)
    return locks


class _Checker:
    """Lexical walk of one function: statements carry the with-lock stack;
    expression subtrees are scanned for mutator calls with the stack in
    effect at their statement — never across a nested def boundary."""

    def __init__(self, sf: SourceFile, guarded: Dict[str, _Guarded],
                 module_scope: bool, findings: List[Finding]) -> None:
        self.sf = sf
        self.guarded = guarded
        self.module_scope = module_scope
        self.findings = findings

    def _flag(self, g: _Guarded, node: ast.AST, how: str) -> None:
        prefix = "" if self.module_scope else "self."
        self.findings.append(Finding(
            rule="guarded-by", file=self.sf.path, line=node.lineno,
            message=f"`{g.attr}` {how} outside `with {prefix}{g.lock}:` "
                    f"— annotated guarded-by {g.lock}",
        ))

    def check_fn(self, fn: ast.FunctionDef) -> None:
        for stmt in fn.body:
            self._stmt(stmt, [], fn.name == "__init__")

    def _held(self, stack: Sequence[Set[str]], lock: str) -> bool:
        return any(lock in frame for frame in stack)

    def _stmt(self, node: ast.stmt, stack: List[Set[str]],
              init: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # deferred body: the surrounding with does NOT guard it
            for stmt in node.body:
                self._stmt(stmt, [], node.name == "__init__")
            return
        if isinstance(node, ast.With):
            for item in node.items:
                self._expr(item.context_expr, stack)
            stack.append(_with_locks(node))
            for stmt in node.body:
                self._stmt(stmt, stack, init)
            stack.pop()
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                self._target(t, node, stack, init)
            if node.value is not None:
                self._expr(node.value, stack)
            return
        if isinstance(node, ast.Delete):
            for t in node.targets:
                self._target(t, node, stack, init)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt):
                self._stmt(child, stack, init)
            elif isinstance(child, ast.excepthandler):
                for stmt in child.body:
                    self._stmt(stmt, stack, init)
            elif isinstance(child, ast.expr):
                self._expr(child, stack)

    def _target(self, t: ast.AST, node: ast.stmt, stack, init: bool) -> None:
        for attr, g in self.guarded.items():
            if _is_attr_ref(t, attr, self.module_scope):
                if init and isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue  # initialization in __init__
                if node.lineno == g.line:
                    continue  # the annotated defining statement itself
                if not self._held(stack, g.lock):
                    self._flag(g, node, "rebound")
            elif isinstance(t, ast.Subscript) and \
                    _is_attr_ref(t.value, attr, self.module_scope):
                if not self._held(stack, g.lock):
                    how = ("item deleted" if isinstance(node, ast.Delete)
                           else "item assigned")
                    self._flag(g, node, how)
        if isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                self._target(el, node, stack, init)
        if isinstance(t, ast.Subscript):
            self._expr(t.slice, stack)

    def _expr(self, e: ast.AST, stack) -> None:
        if isinstance(e, ast.Lambda):
            return  # deferred body
        if isinstance(e, ast.Call) and isinstance(e.func, ast.Attribute):
            for attr, g in self.guarded.items():
                if e.func.attr in g.mutators and \
                        _is_attr_ref(e.func.value, attr, self.module_scope) \
                        and not self._held(stack, g.lock):
                    self._flag(g, e, f".{e.func.attr}() call")
        for child in ast.iter_child_nodes(e):
            if isinstance(child, ast.expr):
                self._expr(child, stack)
            else:
                for sub in ast.iter_child_nodes(child):
                    if isinstance(sub, ast.expr):
                        self._expr(sub, stack)


def run(files: Sequence[SourceFile]) -> List[Finding]:
    findings: List[Finding] = []
    for sf in files:
        scoped = _annotations(sf)
        if not scoped:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef) and node.name in scoped:
                checker = _Checker(sf, scoped[node.name], False, findings)
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        checker.check_fn(item)
        if None in scoped:
            checker = _Checker(sf, scoped[None], True, findings)
            for item in sf.tree.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    checker.check_fn(item)
    return findings
