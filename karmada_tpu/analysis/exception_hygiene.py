"""Pass 6 — exception hygiene: no silently-swallowed blanket handlers.

The chaos plane's first soak proved the failure mode this pass exists
for: the estimator client's blanket ``except Exception`` arms flattened
a dead estimator, a timeout, and a garbage reply into one silent
sentinel — indistinguishable from a full cluster, invisible to every
dashboard.  The rule: an ``except Exception`` (or bare ``except:`` /
``except BaseException``) handler must do at least one of

  * re-raise (any ``raise`` statement in the handler body — bare
    re-raise, a wrapped exception, or a deferred ``box['err']`` pattern
    still counts when a literal raise is present);
  * record a metric (a ``.inc(...)`` / ``.observe(...)`` / ``.set(...)``
    call anywhere in the handler body — the failure reaches /metrics);
  * carry a ``# vet: ignore[exception-hygiene] <why>`` waiver whose
    justification explains why swallowing is the correct handling
    (e.g. "serialized back to the peer", "per-binding failure object").

Anything else is a finding: the handler observes a failure the rest of
the system can never see.
"""

from __future__ import annotations

import ast
from typing import List, Sequence

from karmada_tpu.analysis.core import Finding, SourceFile, dotted

#: handler types the rule covers (narrow handlers are presumed typed
#: and intentional; the blanket forms are where failures vanish)
_BLANKET = ("Exception", "BaseException")

#: attribute calls that count as "records a metric"
_METRIC_METHODS = ("inc", "observe", "set")


def _is_blanket(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True  # bare except:
    name = dotted(handler.type)
    if name is not None and name.rsplit(".", 1)[-1] in _BLANKET:
        return True
    # except (A, Exception): — the tuple form is blanket if any member is
    if isinstance(handler.type, ast.Tuple):
        for elt in handler.type.elts:
            n = dotted(elt)
            if n is not None and n.rsplit(".", 1)[-1] in _BLANKET:
                return True
    return False


def _handled(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _METRIC_METHODS):
            return True
    return False


def run(files: Sequence[SourceFile]) -> List[Finding]:
    findings: List[Finding] = []
    for sf in files:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Try):
                continue
            for handler in node.handlers:
                if not _is_blanket(handler) or _handled(handler):
                    continue
                findings.append(Finding(
                    rule="exception-hygiene", file=sf.path,
                    line=handler.lineno,
                    message="blanket `except Exception` neither "
                            "re-raises nor records a metric — the "
                            "failure is invisible to every dashboard; "
                            "fix it or waive with a justification",
                ))
    return findings
