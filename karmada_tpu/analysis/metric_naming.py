"""Pass 5 — metric naming conventions for every registry registration.

Every metric the package registers (``REGISTRY.counter/gauge/histogram``
— any ``*REGISTRY``-named receiver, covering the ``_REGISTRY`` aliases)
must be:

  * ``karmada_``-prefixed — the scrape surface is shared with upstream
    dashboards, and an unprefixed series is unfindable next to the
    reference's metrics;
  * snake_case (``karmada_[a-z0-9]+(_[a-z0-9]+)*``) — the Prometheus
    naming convention, and what every existing alert template assumes;
  * carrying non-empty help text — ``# HELP`` is the only in-band
    documentation a scrape consumer ever sees.

The metric NAME must also be a string literal: a computed name cannot be
vetted and would silently bypass this pass (and the registry-collision
test), so it is itself a finding.  Help text given as a non-literal
expression is accepted (f-strings assembling static fragments) — only a
missing or literally-empty help fails.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Sequence

from karmada_tpu.analysis.core import Finding, SourceFile, dotted

_METRIC_METHODS = ("counter", "gauge", "histogram")
_NAME_RE = re.compile(r"^karmada_[a-z0-9]+(_[a-z0-9]+)*$")


def _registration(node: ast.Call) -> Optional[str]:
    """The registry method name when `node` is a metric registration
    (<...>REGISTRY.counter/gauge/histogram(...)), else None."""
    fn = node.func
    if not isinstance(fn, ast.Attribute) or fn.attr not in _METRIC_METHODS:
        return None
    base = dotted(fn.value)
    if base is None or not base.rsplit(".", 1)[-1].upper().endswith("REGISTRY"):
        return None
    return fn.attr


def _arg(node: ast.Call, pos: int, *kw_names: str) -> Optional[ast.expr]:
    if len(node.args) > pos:
        return node.args[pos]
    for k in node.keywords:
        if k.arg in kw_names:
            return k.value
    return None


def run(files: Sequence[SourceFile]) -> List[Finding]:
    findings: List[Finding] = []
    for sf in files:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            method = _registration(node)
            if method is None:
                continue
            name_node = _arg(node, 0, "name")
            if not (isinstance(name_node, ast.Constant)
                    and isinstance(name_node.value, str)):
                findings.append(Finding(
                    rule="metric-naming", file=sf.path, line=node.lineno,
                    message=f"REGISTRY.{method}() metric name must be a "
                            "string literal — a computed name cannot be "
                            "vetted for the karmada_ naming contract",
                ))
                continue
            name = name_node.value
            if not _NAME_RE.match(name):
                findings.append(Finding(
                    rule="metric-naming", file=sf.path, line=node.lineno,
                    message=f"metric `{name}` violates the naming contract: "
                            "must be karmada_-prefixed snake_case "
                            "(karmada_[a-z0-9]+(_[a-z0-9]+)*)",
                ))
            help_node = _arg(node, 1, "help_", "help")
            if help_node is None or (
                isinstance(help_node, ast.Constant)
                and (not isinstance(help_node.value, str)
                     or not help_node.value.strip())
            ):
                findings.append(Finding(
                    rule="metric-naming", file=sf.path, line=node.lineno,
                    message=f"metric `{name}` has no help text — # HELP is "
                            "the only in-band documentation a scrape "
                            "consumer sees",
                ))
    return findings
