"""Store persistence: snapshot + write-ahead log, restart via resync.

The reference keeps all control-plane state in etcd behind the
karmada-apiserver; components are stateless and resume via informer resync
+ leader election (SURVEY §5 checkpoint/resume).  Here the ObjectStore is
the apiserver-equivalent, so durability lives at the same layer:

  * every committed write (the exact deep-copied object the watch bus
    delivers) appends to a length-prefixed WAL;
  * `snapshot()` writes the full object set and truncates the WAL;
  * `load()` rebuilds a store from snapshot + WAL replay, then rotates
    (fresh snapshot, empty WAL) so logs never grow unbounded across
    restarts.

Controllers resync the same way the reference's informers do: the restored
ControlPlane re-publishes one synthetic ADDED event per object
(ControlPlane.resync), and every reconcile is idempotent by design.
"""

from __future__ import annotations

import os
import pickle
import struct
import threading
from typing import Optional

from karmada_tpu.store.store import ADDED, DELETED, Event, ObjectStore

_LEN = struct.Struct("<I")

SNAPSHOT_FILE = "store.snapshot"
WAL_FILE = "store.wal"


class FilePersistence:
    """Attach to an ObjectStore; every bus event lands in the WAL."""

    def __init__(self, directory: str) -> None:
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        self._wal = open(os.path.join(directory, WAL_FILE), "ab")
        self._store: Optional[ObjectStore] = None
        self._paused = False

    # -- wiring -------------------------------------------------------------
    def attach(self, store: ObjectStore) -> None:
        self._store = store
        store.bus.subscribe(self._on_event)

    def pause(self) -> None:
        """Skip WAL appends (resync republication of already-durable state;
        must only bracket single-threaded startup, or real writes drop)."""
        self._paused = True

    def resume(self) -> None:
        self._paused = False

    def _on_event(self, event: Event) -> None:
        if self._paused:
            return
        record = (event.type, pickle.dumps(event.obj, pickle.HIGHEST_PROTOCOL))
        payload = pickle.dumps(record, pickle.HIGHEST_PROTOCOL)
        with self._lock:
            self._wal.write(_LEN.pack(len(payload)))
            self._wal.write(payload)
            self._wal.flush()
            os.fsync(self._wal.fileno())

    # -- snapshot / rotate ---------------------------------------------------
    def snapshot(self) -> None:
        """Write the full object set and truncate the WAL (atomic rename).

        self._lock is held across the store cut AND the rotation: a write
        committed after the cut must land in the NEW wal, never be
        truncated out of the old one (it would survive in neither file).
        Lock order is always persistence._lock -> store._lock; appenders
        take persistence._lock alone, store writers never hold store._lock
        while appending (events publish after the write lock is released).
        """
        assert self._store is not None
        with self._lock:
            with self._store._lock:  # noqa: SLF001 — consistent cut
                objects = list(self._store._objects.values())  # noqa: SLF001
                rv = self._store._rv  # noqa: SLF001
            tmp = os.path.join(self.directory, SNAPSHOT_FILE + ".tmp")
            with open(tmp, "wb") as f:
                pickle.dump({"rv": rv, "objects": objects}, f,
                            pickle.HIGHEST_PROTOCOL)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(self.directory, SNAPSHOT_FILE))
            self._wal.close()
            self._wal = open(os.path.join(self.directory, WAL_FILE), "wb")

    def close(self) -> None:
        with self._lock:
            self._wal.close()


def load_store(directory: str, admission=None) -> ObjectStore:
    """Rebuild an ObjectStore from snapshot + WAL, attach fresh persistence
    (rotating the log), and return it.  Missing files -> empty store."""
    store = ObjectStore(admission=admission)
    snap_path = os.path.join(directory, SNAPSHOT_FILE)
    rv = 0
    if os.path.exists(snap_path):
        with open(snap_path, "rb") as f:
            snap = pickle.load(f)
        rv = snap["rv"]
        for obj in snap["objects"]:
            store._objects[store._key(obj)] = obj  # noqa: SLF001 — rebuild, no events
    wal_path = os.path.join(directory, WAL_FILE)
    if os.path.exists(wal_path):
        with open(wal_path, "rb") as f:
            data = f.read()
        off = 0
        while off + _LEN.size <= len(data):
            (n,) = _LEN.unpack_from(data, off)
            off += _LEN.size
            if off + n > len(data):
                break  # torn tail write: discard (standard WAL recovery)
            etype, blob = pickle.loads(data[off : off + n])
            off += n
            obj = pickle.loads(blob)
            key = store._key(obj)  # noqa: SLF001
            if etype == DELETED:
                store._objects.pop(key, None)  # noqa: SLF001
            else:
                store._objects[key] = obj  # noqa: SLF001
            rv = max(rv, obj.metadata.resource_version or 0)
    store._rv = rv  # noqa: SLF001
    persistence = FilePersistence(directory)
    persistence.attach(store)
    persistence.snapshot()
    store.persistence = persistence
    return store


def new_persistent_store(directory: str, admission=None) -> ObjectStore:
    """Create-or-load, for callers that don't care which happened."""
    return load_store(directory, admission=admission)


def resync(store: ObjectStore) -> None:
    """Informer-style resync: re-publish every object as a synthetic ADDED
    event so freshly wired controllers reconcile the restored state.

    Runs during single-threaded startup; persistence appends pause for the
    duration (the republished objects are already durable — re-logging
    them would refill the WAL that load_store just compacted)."""
    persistence = getattr(store, "persistence", None)
    if persistence is not None:
        persistence.pause()
    try:
        for obj in store.items():
            store.bus.publish(Event(ADDED, obj))
    finally:
        if persistence is not None:
            persistence.resume()
