"""Reconcile worker queues and the controller runtime.

The reference drives every controller with rate-limited workqueues
(util.AsyncWorker, controller-runtime). This module provides the same
contract with two execution modes:

  * pump mode  — deterministic: `Runtime.pump()` drains every queue to
    quiescence on the calling thread (the test/E2E harness; also how the
    end-to-end slice runs a "tick").
  * serve mode — threaded: one worker thread per AsyncWorker with
    exponential backoff on failures (the long-running service).
"""

from __future__ import annotations

import contextlib
import random
import threading
import time
import traceback
import zlib
from collections import OrderedDict
from typing import Callable, Dict, Hashable, List, Optional

from karmada_tpu import chaos, obs
from karmada_tpu.utils.metrics import REGISTRY, exponential_buckets

RECONCILE_ERRORS = REGISTRY.counter(
    "karmada_worker_reconcile_errors_total",
    "Reconcile (or periodic-hook) invocations that raised, by worker — "
    "the retry/backoff machinery's input signal",
    ("worker",),
)

WORKER_BACKOFF = REGISTRY.histogram(
    "karmada_worker_backoff_seconds",
    "Idle-poll backoff sleeps taken by serve-mode worker threads "
    "(full-jitter exponential; soaks read this as retry pressure)",
    ("worker",),
    buckets=exponential_buckets(0.001, 2, 12),
)


class AsyncWorker:
    """Dedup-ing work queue: enqueueing an in-queue key is a no-op; a key
    re-enqueued while being processed is processed again afterwards."""

    def __init__(self, name: str, reconcile: Callable[[Hashable], Optional[bool]],
                 max_retries: int = 10) -> None:
        self.name = name
        self.reconcile = reconcile
        self.max_retries = max_retries
        self._queue: "OrderedDict[Hashable, None]" = OrderedDict()  # guarded-by: _cv
        self._retries: Dict[Hashable, int] = {}  # guarded-by: _cv
        self._processing: set = set()  # guarded-by: _cv
        self._dirty: set = set()  # guarded-by: _cv
        # first-enqueue timestamps for the flight recorder's queue-dwell
        # attribute; only populated while tracing is enabled
        self._enqueued_at: Dict[Hashable, float] = {}  # guarded-by: _cv
        self._cv = threading.Condition()
        self._stopped = False

    def enqueue(self, key: Hashable) -> None:
        with self._cv:
            if key in self._processing:
                self._dirty.add(key)
                return
            if obs.TRACER.enabled and key not in self._queue:
                self._enqueued_at[key] = time.perf_counter()
            self._queue[key] = None
            self._cv.notify()

    def _pop(self, block: bool):
        """Returns (key, first_enqueue_ts) — ts is None when tracing was
        off at enqueue time (or the key was requeued internally)."""
        with self._cv:
            while not self._queue:
                if not block or self._stopped:
                    return None, None
                self._cv.wait(timeout=0.2)
            key, _ = self._queue.popitem(last=False)
            self._processing.add(key)
            return key, self._enqueued_at.pop(key, None)

    def _done(self, key: Hashable, requeue: bool) -> None:
        with self._cv:
            self._processing.discard(key)
            redo = key in self._dirty
            self._dirty.discard(key)
            if requeue:
                retries = self._retries.get(key, 0) + 1
                if retries <= self.max_retries:
                    self._retries[key] = retries
                    self._queue[key] = None
                else:
                    # dropped at max retries: forget the budget (workqueue
                    # Forget semantics) and honor any concurrent enqueue
                    self._retries.pop(key, None)
                    if redo:
                        self._queue[key] = None
            else:
                self._retries.pop(key, None)
                if redo:
                    self._queue[key] = None

    def process_one(self, block: bool = False) -> bool:
        """Run one reconcile; returns False when the queue was empty.

        A reconcile that raises (or returns False) is requeued with a retry
        budget — mirroring workqueue rate-limited requeue.

        With the flight recorder armed, every reconcile runs inside a
        "reconcile.<worker>" span carrying the key and its queue dwell
        time — the root every controller's nested spans parent into.
        """
        key, enq_t = self._pop(block)
        if key is None:
            return False
        requeue = False
        tracer = obs.TRACER
        try:
            if chaos.armed():
                # chaos seam: an injected reconcile fault takes the SAME
                # requeue/backoff path a real controller raise would
                chaos.raise_if(chaos.SITE_WORKER_RECONCILE,
                               worker=self.name, key=key)
            if tracer.enabled:
                span = tracer.start_span(
                    obs.SPAN_RECONCILE_PREFIX + self.name,
                    key=repr(key)[:120])
                if enq_t is not None:
                    span.set_attr(queue_dwell_s=round(
                        time.perf_counter() - enq_t, 6))
                with span:
                    result = self.reconcile(key)
            else:
                result = self.reconcile(key)
            requeue = result is False
        except Exception:  # noqa: BLE001 — controller loops never die
            RECONCILE_ERRORS.inc(worker=self.name)
            traceback.print_exc()
            requeue = True
        self._done(key, requeue)
        return True

    def pending(self) -> int:
        with self._cv:
            return len(self._queue) + len(self._processing)

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._cv.notify_all()


# the names --controllers= governs: the controller-manager's controller
# set.  Workers OUTSIDE this set (the scheduler, the operator, the search
# cache, agent CSR approval) are separate binaries in the reference and
# are never subject to the flag.
GOVERNED_CONTROLLERS = frozenset({
    "detector", "deps-distributor", "binding", "execution", "work-status",
    "binding-status", "cluster-status", "cluster-lifecycle", "cluster-lease",
    "taint-manager", "cluster-taint", "taint-policy", "graceful-eviction",
    "application-failover", "remedy", "namespace-sync", "unified-auth",
    "frq", "federatedhpa", "cronfederatedhpa", "hpa-marker",
    "replicas-syncer", "mcs", "mci", "endpointslice-collect",
    "endpointslice-dispatch", "rebalancer", "cert-rotation", "descheduler",
})

# internal worker names that ride a governed controller's switch
_CONTROLLER_ALIAS = {"detector-policy": "detector"}


def parse_controllers(spec: str) -> tuple:
    """`--controllers=` list semantics (controllermanager.go enablement
    filtering): "*" enables everything not explicitly disabled; "-name"
    disables; without "*", only listed names run.  Unknown names are
    rejected up front (the reference controller-manager refuses to start
    on a typoed controller name)."""
    names = [s.strip() for s in (spec or "*").split(",") if s.strip()]
    star = "*" in names
    disabled = {n[1:] for n in names if n.startswith("-")}
    enabled = {n for n in names if n != "*" and not n.startswith("-")}
    unknown = (disabled | enabled) - GOVERNED_CONTROLLERS
    if unknown:
        raise ValueError(
            f"unknown controller name(s) {sorted(unknown)}; "
            f"valid names: {sorted(GOVERNED_CONTROLLERS)}"
        )
    return star, enabled, disabled


class Runtime:
    """Holds every controller's worker; runs them deterministically (pump)
    or in background threads (serve).

    `controllers` filters which reconcile workers and periodic hooks run,
    by name — the reference's `--controllers=` enable/disable list.  A
    disabled controller still constructs (its worker registers but never
    pumps; its periodic hooks are dropped), matching "registered but not
    started"."""

    def __init__(self, periodic_interval_s: float = 0.5,
                 controllers: str = "*") -> None:
        self.workers: List[AsyncWorker] = []
        self._threads: List[threading.Thread] = []
        self._periodic: List[Callable[[], None]] = []
        self._periodic_interval_s = periodic_interval_s
        self._stop_event = threading.Event()
        self._ctrl_star, self._ctrl_on, self._ctrl_off = parse_controllers(
            controllers)
        self._disabled_workers: set = set()
        self._ungoverned_depth = 0

    def controller_enabled(self, name: Optional[str]) -> bool:
        if self._ungoverned_depth > 0:
            return True  # inside an ungoverned() block (agent machinery)
        name = _CONTROLLER_ALIAS.get(name, name)
        if name is None or name not in GOVERNED_CONTROLLERS:
            return True  # infrastructure (scheduler/operator/search/...)
        if name in self._ctrl_off:
            return False
        return self._ctrl_star or name in self._ctrl_on

    @contextlib.contextmanager
    def ungoverned(self):
        """Context manager: registrations inside bypass the --controllers
        filter.  Pull-mode agents reuse the controller CLASSES (and thus
        their worker names) but are the reference's separate agent binary
        with its own flag — the control plane's list must not kill them."""
        self._ungoverned_depth += 1
        try:
            yield
        finally:
            self._ungoverned_depth -= 1

    def register(self, worker: AsyncWorker) -> AsyncWorker:
        self.workers.append(worker)
        if not self.controller_enabled(worker.name):
            self._disabled_workers.add(worker)
        return worker

    def unregister(self, worker: AsyncWorker) -> None:
        """Tear a worker down (e.g. a pull agent leaving): stopped and
        removed so long-lived planes don't accumulate dead queues."""
        worker.stop()
        try:
            self.workers.remove(worker)
        except ValueError:
            pass
        self._disabled_workers.discard(worker)

    def register_periodic(self, fn: Callable[[], None],
                          name: Optional[str] = None) -> None:
        """A resync-style hook invoked once per pump round (or per serve
        tick); `name` subjects it to the `controllers` enablement filter."""
        if not self.controller_enabled(name):
            return
        self._periodic.append(fn)

    def unregister_periodic(self, fn: Callable[[], None]) -> None:
        try:
            self._periodic.remove(fn)
        except ValueError:
            pass

    # -- deterministic mode ------------------------------------------------
    def pump(self, max_rounds: int = 200) -> int:
        """Drain all queues until quiescent. Returns reconciles executed."""
        total = 0
        for _ in range(max_rounds):
            progressed = False
            for w in self.workers:
                if w in self._disabled_workers:
                    continue
                while w.process_one(block=False):
                    progressed = True
                    total += 1
            if not progressed:
                return total
        raise RuntimeError("runtime did not quiesce (reconcile livelock?)")

    def tick(self) -> int:
        """One periodic round (status resync etc.) followed by a pump."""
        for fn in self._periodic:
            fn()
        return self.pump()

    # -- threaded mode -----------------------------------------------------
    def serve(self) -> None:
        for w in self.workers:
            if w in self._disabled_workers:
                continue
            t = threading.Thread(target=self._run_worker, args=(w,), daemon=True,
                                 name=f"worker-{w.name}")
            t.start()
            self._threads.append(t)
        if self._periodic:
            # resync/flush hooks tick on a timer in serve mode (the
            # reference's wait.Until goroutines; e.g. scheduling-queue
            # backoff expiry must fire without any triggering event)
            t = threading.Thread(target=self._run_periodic, daemon=True,
                                 name="periodic")
            t.start()
            self._threads.append(t)

    def _run_periodic(self) -> None:
        while not self._stop_event.wait(self._periodic_interval_s):
            for fn in self._periodic:
                try:
                    fn()
                except Exception:  # noqa: BLE001 — periodic hooks never die
                    RECONCILE_ERRORS.inc(worker="periodic")
                    traceback.print_exc()

    def _run_worker(self, w: AsyncWorker) -> None:
        # full-jitter exponential backoff: the old fixed 0.005 -> 0.5s
        # doubling put every idle worker on the SAME sleep schedule, so a
        # shared-dependency blip (store stall, dead estimator) woke the
        # whole fleet simultaneously and the retry storm re-synchronized
        # each round.  Jitter draws uniform over [0, min(cap, base*2^k)];
        # the stream is seeded per worker NAME (stable across runs —
        # builtin hash() is process-randomized) so soaks replay.
        rng = random.Random(zlib.crc32(w.name.encode("utf-8")))
        base, cap = 0.005, 0.5
        attempt = 0
        while not w._stopped:  # noqa: SLF001
            if w.process_one(block=True):
                attempt = 0
            else:
                delay = rng.uniform(0.0, min(cap, base * (2 ** attempt)))
                WORKER_BACKOFF.observe(delay, worker=w.name)
                time.sleep(delay)
                attempt = min(attempt + 1, 10)

    def stop(self) -> None:
        self._stop_event.set()
        for w in self.workers:
            w.stop()
