from karmada_tpu.store.store import Event, ObjectStore, WatchBus  # noqa: F401
from karmada_tpu.store.worker import AsyncWorker, Runtime  # noqa: F401
