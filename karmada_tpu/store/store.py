"""In-process object store with apiserver semantics.

The reference keeps all state in etcd behind a kube-apiserver and every
component is an informer client (SURVEY.md §2.10). This store provides the
same contract without Kubernetes: typed objects keyed by (kind, namespace,
name), monotonically increasing resourceVersion, generation bumps on spec
change, watch subscriptions with ADDED/MODIFIED/DELETED events, and
finalizer-gated deletion.

Thread-safe; watch delivery is synchronous into per-subscriber queues so a
deterministic test pump and a threaded runtime can share the machinery.
"""

from __future__ import annotations

import copy
import threading
from dataclasses import dataclass
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from karmada_tpu.chaos import plane as _chaos
from karmada_tpu.models.meta import TypedObject, new_uid, now

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"


@dataclass
class Event:
    type: str  # ADDED | MODIFIED | DELETED
    obj: TypedObject
    old: Optional[TypedObject] = None

    @property
    def kind(self) -> str:
        return self.obj.KIND


def _spec_view(obj: TypedObject):
    """Generation-relevant content; objects may provide spec_view()."""
    fn = getattr(obj, "spec_view", None)
    if callable(fn):
        return fn()
    return getattr(obj, "spec", None)


class ConflictError(Exception):
    """resourceVersion mismatch on update (optimistic concurrency)."""


class NotFoundError(KeyError):
    pass


class AlreadyExistsError(Exception):
    pass


class WatchBus:
    """Fan-out of store events to subscribers.

    A subscriber is a callable invoked under no lock with each Event; the
    runtime layer wraps these into worker queues.
    """

    def __init__(self) -> None:
        self._subs: List[Tuple[Optional[str], Callable[[Event], None]]] = []
        self._lock = threading.Lock()
        # guarded-by: _lock — chaos-held events ("stall"/"reorder" faults,
        # karmada_tpu/chaos): flushed around the next delivered publish
        self._held: List[Tuple[str, Event]] = []

    def subscribe(self, handler: Callable[[Event], None], kind: Optional[str] = None) -> None:
        with self._lock:
            self._subs.append((kind, handler))

    def unsubscribe(self, handler: Callable[[Event], None]) -> None:
        """Remove every subscription of `handler` (informer teardown)."""
        with self._lock:
            self._subs = [(k, h) for (k, h) in self._subs if h != handler]

    def publish(self, event: Event) -> None:
        """Deliver to every subscriber.  The chaos seam (store.watch)
        sits between the store write and delivery: drop discards the
        event, dup delivers it twice, stall holds it until the next
        publish (delivered BEFORE it — delayed, order kept), reorder
        holds it and delivers it AFTER the next event (order inverted).
        Disarmed cost: one list read plus one empty-list check."""
        events = [event]
        if _chaos.armed():
            f = _chaos.fire(_chaos.SITE_STORE_WATCH, kind=event.kind,
                            type=event.type)
            if f is not None:
                if f.mode == "drop":
                    events = []
                elif f.mode == "dup":
                    events = [event, event]
                elif f.mode in ("stall", "reorder"):
                    with self._lock:
                        self._held.append((f.mode, event))
                    return
        pre: List[Event] = []
        post: List[Event] = []
        if self._held:
            with self._lock:
                held, self._held = self._held, []
            pre = [e for mode, e in held if mode == "stall"]
            post = [e for mode, e in held if mode == "reorder"]
        with self._lock:
            subs = list(self._subs)
        for ev in pre + events + post:
            for kind, handler in subs:
                if kind is None or kind == ev.kind:
                    handler(ev)

    def flush_held(self) -> int:
        """Deliver any chaos-held events now (end-of-soak hygiene: a
        stalled event must never outlive the fault window silently).
        Returns the number delivered."""
        with self._lock:
            held, self._held = self._held, []
            subs = list(self._subs)
        for _mode, ev in held:
            for kind, handler in subs:
                if kind is None or kind == ev.kind:
                    handler(ev)
        return len(held)


class ObjectStore:
    def __init__(self, bus: Optional[WatchBus] = None, admission=None) -> None:
        self._objects: Dict[Tuple[str, str, str], TypedObject] = {}
        self._rv = 0
        self._lock = threading.RLock()
        self.bus = bus or WatchBus()
        # optional webhook.AdmissionRegistry: mutate/validate inside the
        # write path, before persist (reference karmada-webhook semantics)
        self.admission = admission
        # Events are enqueued under self._lock (in resourceVersion order) and
        # drained under _pub_lock, so concurrent writers can never deliver a
        # newer rv to subscribers before an older one.  _drain is re-entrancy
        # safe: a subscriber callback that writes to the store enqueues and
        # returns; the outer drain delivers its event.
        self._pending_events: List[Event] = []
        self._pub_lock = threading.Lock()
        self._draining: Optional[int] = None  # thread id of active drainer
        # nested-write depth per thread: an admission plugin writing to the
        # store runs INSIDE the outer write's lock; its _drain must defer to
        # the outermost write (blocking on _pub_lock there can deadlock
        # against a drainer's subscriber taking _lock)
        self._wd = threading.local()

    def _begin_write(self) -> None:
        self._wd.depth = getattr(self._wd, "depth", 0) + 1

    def _end_write(self) -> None:
        self._wd.depth -= 1

    def _drain(self) -> None:
        if getattr(self._wd, "depth", 0) > 0:
            return  # nested write: the outermost writer drains
        me = threading.get_ident()
        if self._draining == me:
            return  # re-entrant write from a subscriber callback
        with self._pub_lock:
            self._draining = me
            try:
                while True:
                    # pop one at a time: if a subscriber raises, events not
                    # yet popped stay queued for the next writer's drain
                    with self._lock:
                        if not self._pending_events:
                            break
                        ev = self._pending_events.pop(0)
                    self.bus.publish(ev)
            finally:
                self._draining = None

    # -- internal ----------------------------------------------------------
    def _key(self, obj: TypedObject) -> Tuple[str, str, str]:
        return (obj.KIND, obj.metadata.namespace, obj.metadata.name)

    def _next_rv(self) -> int:
        self._rv += 1
        return self._rv

    # -- API ---------------------------------------------------------------
    def create(self, obj: TypedObject) -> TypedObject:
        self._begin_write()
        try:
            with self._lock:
                key = self._key(obj)
                if key in self._objects:
                    raise AlreadyExistsError(f"{key} already exists")
                obj = copy.deepcopy(obj)
                if self.admission is not None:
                    self.admission.admit("CREATE", obj, None)
                if not obj.metadata.uid:
                    obj.metadata.uid = new_uid()
                obj.metadata.creation_timestamp = now()
                obj.metadata.generation = 1
                obj.metadata.resource_version = self._next_rv()
                self._objects[key] = obj
                stored = copy.deepcopy(obj)
                self._pending_events.append(Event(ADDED, stored))
        finally:
            self._end_write()
        self._drain()
        return stored

    def get(self, kind: str, namespace: str, name: str) -> TypedObject:
        with self._lock:
            key = (kind, namespace, name)
            if key not in self._objects:
                raise NotFoundError(f"{key} not found")
            return copy.deepcopy(self._objects[key])

    def try_get(self, kind: str, namespace: str, name: str) -> Optional[TypedObject]:
        try:
            return self.get(kind, namespace, name)
        except NotFoundError:
            return None

    def list(self, kind: str, namespace: Optional[str] = None) -> List[TypedObject]:
        with self._lock:
            out = [
                copy.deepcopy(o)
                for (k, ns, _), o in sorted(self._objects.items())
                if k == kind and (namespace is None or ns == namespace)
            ]
        return out

    def update(self, obj: TypedObject, *, spec_changed: Optional[bool] = None) -> TypedObject:
        """Optimistic-concurrency update. Bumps generation when the spec
        changed (caller may force via spec_changed)."""
        self._begin_write()
        try:
            stored = self._update_inner(obj, spec_changed)
        finally:
            self._end_write()
        self._drain()
        return stored

    def _update_inner(self, obj: TypedObject, spec_changed: Optional[bool]) -> TypedObject:
        with self._lock:
            key = self._key(obj)
            if key not in self._objects:
                raise NotFoundError(f"{key} not found")
            old = self._objects[key]
            if (
                obj.metadata.resource_version
                and obj.metadata.resource_version != old.metadata.resource_version
            ):
                raise ConflictError(
                    f"{key}: rv {obj.metadata.resource_version} != {old.metadata.resource_version}"
                )
            obj = copy.deepcopy(obj)
            if self.admission is not None:
                self.admission.admit("UPDATE", obj, copy.deepcopy(old))
            obj.metadata.uid = old.metadata.uid
            obj.metadata.creation_timestamp = old.metadata.creation_timestamp
            # semantic no-op: identical content gets no new resourceVersion
            # and no event -- the loop-breaker that lets controller chains
            # converge (controllers may mutate unconditionally)
            obj.metadata.resource_version = old.metadata.resource_version
            obj.metadata.generation = old.metadata.generation
            if obj == old:
                return copy.deepcopy(old)
            if spec_changed is None:
                spec_changed = _spec_view(obj) != _spec_view(old)
            obj.metadata.generation = old.metadata.generation + (1 if spec_changed else 0)
            obj.metadata.resource_version = self._next_rv()
            # deletion in progress + finalizers drained -> actually delete
            if obj.metadata.deletion_timestamp is not None and not obj.metadata.finalizers:
                del self._objects[key]
                stored = copy.deepcopy(obj)
                old_copy = copy.deepcopy(old)
                event = Event(DELETED, stored, old_copy)
            else:
                self._objects[key] = obj
                stored = copy.deepcopy(obj)
                old_copy = copy.deepcopy(old)
                event = Event(MODIFIED, stored, old_copy)
            self._pending_events.append(event)
        return stored

    def mutate(self, kind: str, namespace: str, name: str, fn: Callable[[TypedObject], None],
               retries: int = 8) -> TypedObject:
        """Get-mutate-update with conflict retry (controller patch helper)."""
        for _ in range(retries):
            obj = self.get(kind, namespace, name)
            fn(obj)
            try:
                return self.update(obj)
            except ConflictError:
                continue
        raise ConflictError(f"mutate {kind}/{namespace}/{name}: too many conflicts")

    def delete(self, kind: str, namespace: str, name: str) -> None:
        """Finalizer-aware delete: marks deletionTimestamp; removal happens
        once finalizers drain (or immediately when none)."""
        self._begin_write()
        try:
            with self._lock:
                key = (kind, namespace, name)
                if key not in self._objects:
                    raise NotFoundError(f"{key} not found")
                obj = self._objects[key]
                if obj.metadata.finalizers:
                    if obj.metadata.deletion_timestamp is None:
                        obj.metadata.deletion_timestamp = now()
                        obj.metadata.resource_version = self._next_rv()
                        stored = copy.deepcopy(obj)
                        event = Event(MODIFIED, stored)
                    else:
                        return
                else:
                    del self._objects[key]
                    obj.metadata.deletion_timestamp = obj.metadata.deletion_timestamp or now()
                    stored = copy.deepcopy(obj)
                    event = Event(DELETED, stored)
                self._pending_events.append(event)
        finally:
            self._end_write()
        self._drain()

    def items(self) -> Iterator[TypedObject]:
        with self._lock:
            snapshot = [copy.deepcopy(o) for o in self._objects.values()]
        return iter(snapshot)

    def counts_by_kind(self) -> Dict[str, int]:
        """Object tally per kind without copying any values (observability
        endpoints poll this; a deepcopy snapshot would hold the store lock
        proportional to total payload)."""
        with self._lock:
            counts: Dict[str, int] = {}
            for kind, _, _ in self._objects:
                counts[kind] = counts.get(kind, 0) + 1
            return counts

    def __len__(self) -> int:
        with self._lock:
            return len(self._objects)
