"""Fake member clusters: in-process capacity simulators.

The reference's E2E environment spins up kind clusters
(hack/local-up-karmada.sh); unit tests use fake clientsets.  This module is
the framework's member-cluster substitute for the end-to-end slice
(SURVEY.md section 7 step 4): each member owns an ObjectStore of applied
manifests, reports a ResourceSummary/ APIEnablements like the reference's
cluster-status controller collects (cluster_status_controller.go:278-282),
and "runs" workloads by moving their status toward ready on each tick.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from karmada_tpu.models.cluster import APIEnablement, ResourceSummary
from karmada_tpu.models.meta import deep_get
from karmada_tpu.models.unstructured import Unstructured
from karmada_tpu.store.store import AlreadyExistsError, NotFoundError, ObjectStore
from karmada_tpu.utils.quantity import Quantity


@dataclass
class FakeNode:
    """One node's allocatable capacity (estimator-server granularity)."""

    name: str = ""
    cpu_milli: int = 0
    memory_milli: int = 0
    pods: int = 0
    labels: Dict[str, str] = field(default_factory=dict)
    # extended resources (GPUs, ephemeral-storage, ...) in milli units
    extra_milli: Dict[str, int] = field(default_factory=dict)


@dataclass
class FakeMemberCluster:
    name: str
    cpu_allocatable_milli: int = 64_000
    memory_allocatable_gi: int = 256  # GiB (memory quantities are bytes)
    pods_allocatable: int = 110
    nodes: List[FakeNode] = field(default_factory=list)
    api_enablements: List[APIEnablement] = field(default_factory=lambda: [
        APIEnablement("apps/v1", ["Deployment", "StatefulSet", "ReplicaSet"]),
        APIEnablement("batch/v1", ["Job"]),
        APIEnablement("v1", ["Pod", "ConfigMap", "Secret", "Service",
                             "ServiceAccount", "Namespace"]),
    ])
    healthy: bool = True
    # simulated in-cluster DNS plane (CoreDNS analog), probed by
    # members/dns_detector.ServiceNameResolutionDetector
    dns_healthy: bool = True
    store: ObjectStore = field(default_factory=ObjectStore)
    # per-workload live load for the metrics plane: (kind, ns, name) ->
    # per-replica usage in milli-units, e.g. {"cpu": 250, "memory": ...}.
    # Unset workloads idle at 10% of their request (something nonzero for
    # utilization math without claiming precision the simulator lacks).
    load: Dict[tuple, Dict[str, int]] = field(default_factory=dict)
    # custom metric series this member serves (custom.metrics.k8s.io):
    # (kind, namespace, name, metric) -> value — the simulator's stand-in
    # for an in-cluster custom-metrics API (prometheus-adapter etc.)
    custom_metrics: Dict[tuple, float] = field(default_factory=dict)
    # per-workload lifecycle journal: (kind, ns, name) -> lines.  This is
    # what `karmadactl logs/attach` stream through the cluster proxy — the
    # simulator's honest stand-in for container stdout (the reference
    # streams real kubelet logs, pkg/karmadactl/logs).
    journal: Dict[tuple, List[str]] = field(default_factory=dict)
    _JOURNAL_CAP = 200

    def _log(self, kind: str, namespace: str, name: str, line: str) -> None:
        lines = self.journal.setdefault((kind, namespace, name), [])
        lines.append(line)
        del lines[:-self._JOURNAL_CAP]

    def effective_nodes(self) -> List[FakeNode]:
        """Explicit node list, or one synthetic node holding all capacity."""
        if self.nodes:
            return self.nodes
        return [FakeNode(
            name=f"{self.name}-node-0",
            cpu_milli=self.cpu_allocatable_milli,
            memory_milli=Quantity.parse(f"{self.memory_allocatable_gi}Gi").milli,
            pods=self.pods_allocatable,
        )]

    # -- the member "API server" -------------------------------------------
    def apply(self, manifest: Dict[str, Any]) -> Unstructured:
        """Server-side-apply-ish create-or-update keyed by (kind, ns, name)."""
        obj = Unstructured.from_manifest(manifest)
        existing = self.store.try_get(obj.KIND, obj.namespace, obj.name)
        if existing is None:
            self._log(obj.KIND, obj.namespace, obj.name, "created")
            return self.store.create(obj)
        assert isinstance(existing, Unstructured)
        merged = copy.deepcopy(manifest)
        if existing.manifest.get("status") is not None and "status" not in merged:
            merged["status"] = existing.manifest["status"]
        if existing.spec_view() != obj.spec_view():
            self._log(obj.KIND, obj.namespace, obj.name, "spec updated")
        existing.manifest = merged
        existing.metadata.labels = dict(
            deep_get(merged, "metadata.labels", {}) or {})
        existing.metadata.annotations = dict(
            deep_get(merged, "metadata.annotations", {}) or {})
        return self.store.update(existing)

    def get(self, kind: str, namespace: str, name: str) -> Optional[Unstructured]:
        obj = self.store.try_get(kind, namespace, name)
        return obj  # type: ignore[return-value]

    def delete(self, kind: str, namespace: str, name: str) -> None:
        try:
            self.store.delete(kind, namespace, name)
            # drop the journal with the workload: no pod can read it anymore
            # and keys must not accumulate across churn in serve mode
            self.journal.pop((kind, namespace, name), None)
        except NotFoundError:
            pass

    # -- capacity telemetry (what cluster-status collects) ------------------
    def used_milli(self) -> Dict[str, int]:
        cpu = mem = pods = 0
        for obj in self.store.items():
            if not isinstance(obj, Unstructured):
                continue
            kind = obj.KIND
            if kind not in ("Deployment", "StatefulSet", "ReplicaSet", "Job", "Pod"):
                continue
            m = obj.manifest
            replicas = int(deep_get(m, "spec.replicas", 1) or 0)
            if kind == "Job":
                replicas = int(deep_get(m, "spec.parallelism", 1) or 1)
            if kind == "Pod":
                replicas = 1
            pod_spec = deep_get(m, "spec.template.spec", {}) or m.get("spec", {})
            c_cpu = c_mem = 0
            for container in pod_spec.get("containers", []) or []:
                reqs = deep_get(container, "resources.requests", {}) or {}
                c_cpu += Quantity.parse(reqs.get("cpu", 0)).milli
                c_mem += Quantity.parse(reqs.get("memory", 0)).milli
            cpu += replicas * c_cpu
            mem += replicas * c_mem
            pods += replicas
        return {"cpu": cpu, "memory": mem, "pods": pods * 1000}

    def resource_summary(self) -> ResourceSummary:
        used = self.used_milli()
        nodes = self.effective_nodes()
        return ResourceSummary(
            allocatable={
                "cpu": Quantity.from_milli(sum(n.cpu_milli for n in nodes)),
                "memory": Quantity.from_milli(sum(n.memory_milli for n in nodes)),
                "pods": Quantity.from_units(sum(n.pods for n in nodes)),
            },
            allocated={
                "cpu": Quantity.from_milli(used["cpu"]),
                "memory": Quantity.from_milli(used["memory"]),
                "pods": Quantity.from_milli(used["pods"]),
            },
        )

    # -- workload simulation ------------------------------------------------
    def _workload_request(self, m: Dict[str, Any]) -> Dict[str, int]:
        pod_spec = deep_get(m, "spec.template.spec", {}) or m.get("spec", {})
        req: Dict[str, int] = {"cpu": 0, "memory": 0}
        for container in pod_spec.get("containers", []) or []:
            reqs = deep_get(container, "resources.requests", {}) or {}
            for rname, qty in reqs.items():
                req[rname] = req.get(rname, 0) + Quantity.parse(qty).milli
        return req

    def admission_plan(self) -> Dict[tuple, int]:
        """Deterministic capacity admission: workloads in (kind, ns, name)
        order greedily admit replicas until cpu/memory/pods run out.  The
        remainder stays pending -- what the reference's unschedulable-replica
        estimator counts (pkg/estimator/server/replica/replica.go:43)."""
        nodes = self.effective_nodes()
        cpu_left = sum(n.cpu_milli for n in nodes)
        mem_left = sum(n.memory_milli for n in nodes)
        pods_left = sum(n.pods for n in nodes)
        plan: Dict[tuple, int] = {}
        for obj in sorted(self.store.items(), key=lambda o: (o.KIND, o.namespace, o.name)):
            if not isinstance(obj, Unstructured):
                continue
            kind = obj.KIND
            if kind not in ("Deployment", "StatefulSet", "ReplicaSet", "Job", "Pod"):
                continue
            m = obj.manifest
            want = int(deep_get(m, "spec.replicas", 1) or 0)
            if kind == "Job":
                want = int(deep_get(m, "spec.parallelism", 1) or 1)
            if kind == "Pod":
                want = 1
            req = self._workload_request(m)
            admitted = 0
            for _ in range(want):
                if pods_left <= 0:
                    break
                if req["cpu"] > cpu_left or req["memory"] > mem_left:
                    break
                cpu_left -= req["cpu"]
                mem_left -= req["memory"]
                pods_left -= 1
                admitted += 1
            plan[(kind, obj.namespace, obj.name)] = admitted
        return plan

    def unschedulable_replicas(self, kind: str, namespace: str, name: str) -> int:
        """Desired-but-unadmitted replicas for one workload (the estimator's
        GetUnschedulableReplicas answer)."""
        obj = self.get(kind, namespace, name)
        if obj is None:
            return 0
        m = obj.manifest
        want = int(deep_get(m, "spec.replicas", 1) or 0)
        if kind == "Job":
            want = int(deep_get(m, "spec.parallelism", 1) or 1)
        admitted = self.admission_plan().get((kind, namespace, name), 0)
        return max(want - admitted, 0)

    # -- metrics plane (what the metrics adapter scrapes) -------------------
    def set_load(self, kind: str, namespace: str, name: str,
                 per_replica: Dict[str, int]) -> None:
        """Drive per-replica usage (milli-units) for one workload."""
        self.load[(kind, namespace, name)] = dict(per_replica)

    def pod_metrics(self, kind: str, namespace: str, name: str) -> List[Dict[str, Any]]:
        """metrics.k8s.io-style PodMetrics for one workload's READY replicas:
        [{"name": pod, "usage": {"cpu": milli, "memory": milli}}].  Usage is
        the driven load (set_load) or 10% of request when idle."""
        obj = self.get(kind, namespace, name)
        if obj is None or not self.healthy:
            return []
        ready = self.admission_plan().get((kind, namespace, name), 0)
        req = self._workload_request(obj.manifest)
        load = self.load.get((kind, namespace, name))
        if load is None:
            load = {k: v // 10 for k, v in req.items()}
        return [
            {"name": f"{name}-{i}", "usage": dict(load), "request": dict(req)}
            for i in range(ready)
        ]

    def tick(self) -> None:
        """Advance every applied workload's status toward ready, capped by
        the capacity admission plan."""
        if not self.healthy:
            return
        plan = self.admission_plan()
        for obj in list(self.store.items()):
            if not isinstance(obj, Unstructured):
                continue
            m = obj.manifest
            kind = obj.KIND
            if kind in ("Deployment", "StatefulSet", "ReplicaSet"):
                want = int(deep_get(m, "spec.replicas", 1) or 0)
                ready = plan.get((kind, obj.namespace, obj.name), want)
                status = {
                    "observedGeneration": deep_get(m, "metadata.generation",
                                                   obj.metadata.generation),
                    "replicas": want,
                    "readyReplicas": ready,
                    "updatedReplicas": ready,
                    "availableReplicas": ready,
                }
                if m.get("status") != status:
                    prev_ready = deep_get(m, "status.readyReplicas", 0) or 0
                    if prev_ready != ready:
                        self._log(kind, obj.namespace, obj.name,
                                  f"readyReplicas {prev_ready} -> {ready}")

                    def setst(o, status=status):
                        o.manifest["status"] = status
                    self.store.mutate(kind, obj.namespace, obj.name, setst)
            elif kind == "Job":
                par = int(deep_get(m, "spec.parallelism", 1) or 1)
                active = plan.get((kind, obj.namespace, obj.name), par)
                status = {"active": active, "succeeded": 0, "failed": 0}
                if m.get("status") != status:
                    def setst(o, status=status):
                        o.manifest["status"] = status
                    self.store.mutate(kind, obj.namespace, obj.name, setst)

    # -- pod plane (what karmadactl exec/logs/attach reach via the proxy) ---
    _POD_OWNERS = ("Deployment", "StatefulSet", "ReplicaSet", "Job")

    def list_pods(self, namespace: Optional[str] = None) -> List[Dict[str, Any]]:
        """Synthesized pod views: one per admitted replica of every applied
        workload, plus standalone Pod objects.  The reference lists real
        pods through the cluster proxy (pkg/karmadactl/get); the simulator
        derives them from the admission plan."""
        plan = self.admission_plan()
        pods: List[Dict[str, Any]] = []
        for obj in sorted(self.store.items(), key=lambda o: (o.KIND, o.namespace, o.name)):
            if not isinstance(obj, Unstructured):
                continue
            if namespace is not None and obj.namespace != namespace:
                continue
            if obj.KIND == "Pod":
                pods.append({"name": obj.name, "namespace": obj.namespace,
                             "owner": "Pod/" + obj.name, "ready": True})
            elif obj.KIND in self._POD_OWNERS:
                ready = plan.get((obj.KIND, obj.namespace, obj.name), 0)
                for i in range(ready):
                    pods.append({
                        "name": f"{obj.name}-{i}", "namespace": obj.namespace,
                        "owner": f"{obj.KIND}/{obj.name}", "ready": True,
                    })
        return pods

    def _resolve_pod(self, namespace: str, pod: str) -> Optional[tuple]:
        """Pod name -> owning workload key, or None."""
        for p in self.list_pods(namespace):
            if p["name"] == pod:
                kind, name = p["owner"].split("/", 1)
                return (kind, namespace, name)
        return None

    def pod_logs(self, namespace: str, pod: str,
                 tail: Optional[int] = None) -> List[str]:
        """The pod's stream: its workload's lifecycle journal prefixed with
        a startup line (reference: kubelet container logs via proxy,
        pkg/karmadactl/logs)."""
        key = self._resolve_pod(namespace, pod)
        if key is None:
            raise NotFoundError(f"pod {namespace}/{pod} not found in {self.name}")
        lines = [f"{pod} started on {self.name}"]
        lines += self.journal.get(key, [])
        # kubectl --tail semantics: 0 = nothing, negative = everything,
        # more-than-available = everything
        if tail is not None and tail >= 0:
            lines = lines[max(len(lines) - tail, 0):] if tail else []
        return lines

    def pod_exec(self, namespace: str, pod: str,
                 command: List[str]) -> tuple:
        """Simulated in-container command execution -> (exit_code, output).
        A few commands answer from real simulator state; the rest echo a
        simulated marker (the reference streams an SPDY exec session,
        pkg/karmadactl/exec)."""
        key = self._resolve_pod(namespace, pod)
        if key is None:
            raise NotFoundError(f"pod {namespace}/{pod} not found in {self.name}")
        if not command:
            return (1, "no command")
        prog = command[0]
        if prog == "hostname":
            return (0, pod)
        if prog == "env":
            kind, ns, name = key
            load = self.load.get(key, {})
            lines = [f"KARMADA_CLUSTER={self.name}",
                     f"POD_NAMESPACE={ns}",
                     f"WORKLOAD={kind}/{name}"]
            if load:
                lines.append("LOAD=" + ",".join(
                    f"{k}:{v}" for k, v in sorted(load.items())))
            return (0, "\n".join(lines))
        return (0, f"(simulated) {' '.join(command)}")
