from karmada_tpu.members.member import FakeMemberCluster  # noqa: F401
