"""Service-name-resolution detector — the example failure-detector sidecar.

Reference: cmd/service-name-resolution-detector-example +
pkg/servicenameresolutiondetector/coredns/detector.go:92 — a sidecar that
periodically resolves a well-known in-cluster service name and feeds the
result into a Cluster status condition, which ClusterTaintPolicy /
Remedy rules then act on (condition -> taint -> eviction / TrafficControl).

Here the probe targets the member simulator's DNS health flag; the
aggregation mirrors the reference's windowed success/failure vote: the
condition only transitions after `threshold` consecutive observations of
the new state (detector.go's period/successThreshold/failureThreshold),
so a single flaky probe cannot flap the condition.
"""

from __future__ import annotations

from collections import deque
from typing import Deque

from karmada_tpu.models.cluster import Cluster
from karmada_tpu.models.meta import Condition, set_condition

COND_SERVICE_DNS_READY = "ServiceDomainNameResolutionReady"


class ServiceNameResolutionDetector:
    """Per-member sidecar: probe -> windowed vote -> cluster condition."""

    def __init__(self, store, member, runtime, threshold: int = 3) -> None:
        self.store = store
        self.member = member
        self.runtime = runtime
        self.threshold = max(1, threshold)
        self._window: Deque[bool] = deque(maxlen=self.threshold)
        self._reported = None  # nothing reported yet: first vote writes
        runtime.register_periodic(self.probe)
        self.probe()

    def stop(self) -> None:
        """Detach from the runtime (call on member unjoin so long-lived
        planes don't accumulate dead probes)."""
        self.runtime.unregister_periodic(self.probe)

    # -- the probe ----------------------------------------------------------
    def _resolve(self) -> bool:
        """One resolution attempt against the member's DNS plane (the
        simulator's dns_healthy flag; a real deployment would dial CoreDNS
        for a well-known name, detector.go:92)."""
        return bool(getattr(self.member, "dns_healthy", True))

    def probe(self) -> None:
        self._window.append(self._resolve())
        votes = list(self._window)
        if len(votes) < self.threshold:
            # bootstrap: report the very first observation immediately so
            # the condition exists from the sidecar's first cycle
            if self._reported is None:
                self._set_condition(votes[-1])
            return
        if all(votes) and self._reported is not True:
            self._set_condition(True)
        elif not any(votes) and self._reported is not False:
            self._set_condition(False)

    def _set_condition(self, ready: bool) -> None:
        name = self.member.name

        def update(c: Cluster) -> None:
            set_condition(c.status.conditions, Condition(
                type=COND_SERVICE_DNS_READY,
                status="True" if ready else "False",
                reason="ServiceNameResolutionSucceed" if ready
                else "ServiceNameResolutionFailed",
                message="service name resolution is working" if ready
                else "service name resolution keeps failing",
            ))
        try:
            self.store.mutate(Cluster.KIND, "", name, update)
            self._reported = ready
        except KeyError:
            pass  # cluster unjoined mid-probe: nothing to report against
