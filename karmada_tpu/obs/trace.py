"""Span/trace core of the flight recorder.

One Trace is the tree of Spans hanging off a single root span — a
scheduler cycle, a controller reconcile, a bench pipeline run.  Spans
carry monotonic start/end times, free-form attributes, and a parent id;
the tree is finalized and handed to the recorder exactly once, when the
root span ends.  Parentage is propagated through a contextvar so nested
code auto-parents without plumbing span objects through every signature,
and `Tracer.attach` hands a context across an explicit thread boundary
(the scheduler's guarded device-cycle thread, estimator fan-out pools).

Disabled-path contract (the hot loops depend on it): `Tracer.start_span`
returns the ONE process-wide `NOOP_SPAN` instance — no allocation, no
clock read — so call sites may either guard on `tracer.enabled` or just
use the returned span; both are zero-cost when tracing is off.

Degradation-guard interplay: a cycle abandoned mid-pipeline leaves its
stage spans open on the zombie thread.  When the trace root ends (on the
live worker thread), every still-open span is force-closed with
`unfinished=true` and the complete trace — marked `cancelled=true` by
the guard's attribute — is recorded: the evidence the guard used to
discard along with the cycle.  A zombie that unblocks minutes later and
touches its spans again hits a finalized trace and is ignored.
"""

from __future__ import annotations

import contextvars
import itertools
import threading
import time
from typing import Dict, List, Optional

_CURRENT: contextvars.ContextVar = contextvars.ContextVar(
    "karmada_tpu_obs_current_span", default=None)

_next_id = itertools.count(1).__next__  # GIL-atomic


class NoopSpan:
    """The disabled path: one process-wide instance, every operation a
    no-op.  Usable as a context manager and falsy so call sites can write
    `if sp:` around attribute math they'd rather skip entirely."""

    __slots__ = ()
    trace = None

    def set_attr(self, **kw):
        return self

    def end(self, **kw):
        return None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def __bool__(self):
        return False


NOOP_SPAN = NoopSpan()

# sentinel: "parent from the ambient context" (None means "force a root")
FROM_CONTEXT = object()


class Trace:
    """Accumulator for one root span's tree.  Thread-safe: spans may end
    on any thread; finalization (submission to the recorder) happens
    exactly once, under the trace lock, when the root span ends."""

    __slots__ = ("trace_id", "root_name", "start_unix", "_t0", "_recorder",
                 "_records", "_open", "_lock", "_done")

    def __init__(self, trace_id: str, recorder, t0: float,
                 root_name: str) -> None:
        self.trace_id = trace_id
        self.root_name = root_name
        self.start_unix = time.time()
        self._t0 = t0
        self._recorder = recorder
        self._records: List[dict] = []
        self._open: Dict[int, "Span"] = {}
        self._lock = threading.Lock()
        self._done = False

    def _register(self, span: "Span") -> None:
        with self._lock:
            if not self._done:
                self._open[span.span_id] = span

    def _finish(self, span: "Span", t_end: float, attrs: dict) -> None:
        """Close `span` exactly once.  A double end, or an end arriving
        after the trace finalized (abandoned-cycle zombie), is a no-op."""
        with self._lock:
            if self._done or span.span_id not in self._open:
                return
            del self._open[span.span_id]
            if attrs:
                span.attrs.update(attrs)
            span.t1 = t_end
            self._records.append(span._record(self._t0))
            if span.parent_id is None:
                self._finalize_locked(t_end)

    def _finalize_locked(self, t_end: float) -> None:
        # root ended: force-close every still-open span (a cancelled cycle
        # yields a COMPLETE trace — its dangling stages are the evidence)
        for sp in self._open.values():
            sp.t1 = t_end
            sp.attrs.setdefault("unfinished", True)
            self._records.append(sp._record(self._t0))
        self._open.clear()
        self._done = True
        spans = sorted(self._records, key=lambda r: (r["start_s"],
                                                     r["span_id"]))
        self._recorder.record({
            "trace_id": self.trace_id,
            "root": self.root_name,
            "start_unix": round(self.start_unix, 3),
            "duration_s": round(t_end - self._t0, 9),
            "cancelled": any(r["attrs"].get("cancelled") for r in spans),
            "spans": spans,
        })


class Span:
    __slots__ = ("name", "trace", "span_id", "parent_id", "t0", "t1",
                 "attrs", "_token")

    def __init__(self, name: str, trace: Trace, parent_id: Optional[int],
                 attrs: Optional[dict]) -> None:
        self.name = name
        self.trace = trace
        self.span_id = _next_id()
        self.parent_id = parent_id
        self.t0 = time.perf_counter()
        self.t1: Optional[float] = None
        self.attrs = dict(attrs) if attrs else {}
        self._token = None
        trace._register(self)

    def set_attr(self, **kw):
        self.attrs.update(kw)
        return self

    def end(self, **kw) -> None:
        self.trace._finish(self, time.perf_counter(), kw)

    def _record(self, t0: float) -> dict:
        return {"name": self.name, "span_id": self.span_id,
                "parent_id": self.parent_id,
                "start_s": round(self.t0 - t0, 9),
                "end_s": round(self.t1 - t0, 9),
                "attrs": self.attrs}

    # context-manager use: entering makes the span the ambient parent for
    # nested spans on this thread/task; exiting restores and ends it
    def __enter__(self) -> "Span":
        self._token = _CURRENT.set(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        _CURRENT.reset(self._token)
        self._token = None
        if exc is not None:
            self.attrs.setdefault("error", repr(exc))
        self.end()
        return False


class _Attach:
    """Adopt a span from another thread as this thread's ambient parent
    (without ending it on exit) — the thread-handoff helper."""

    __slots__ = ("_span", "_token")

    def __init__(self, span: Span) -> None:
        self._span = span
        self._token = None

    def __enter__(self) -> Span:
        self._token = _CURRENT.set(self._span)
        return self._span

    def __exit__(self, *exc):
        _CURRENT.reset(self._token)
        return False


class Tracer:
    """The process-wide tracing switch + span factory.  Disabled (the
    default) it returns NOOP_SPAN everywhere; `configure()` arms it with
    a bounded TraceRecorder."""

    def __init__(self) -> None:
        self.recorder = None

    @property
    def enabled(self) -> bool:
        return self.recorder is not None

    def configure(self, capacity: int = 256, slow_keep: int = 8,
                  recorder=None):
        from karmada_tpu.obs.recorder import TraceRecorder

        self.recorder = (recorder if recorder is not None
                         else TraceRecorder(capacity=capacity,
                                            slow_keep=slow_keep))
        return self.recorder

    def disable(self) -> None:
        self.recorder = None

    def current(self) -> Optional[Span]:
        sp = _CURRENT.get()
        return sp if isinstance(sp, Span) else None

    def start_span(self, name: str, parent=FROM_CONTEXT, **attrs):
        """A new span: child of `parent` (default: the ambient context
        span), else the root of a fresh trace.  Returns NOOP_SPAN when
        tracing is disabled — zero allocation on the hot path."""
        rec = self.recorder
        if rec is None:
            return NOOP_SPAN
        if parent is FROM_CONTEXT:
            parent = self.current()
        if isinstance(parent, Span):
            if parent.trace._done:
                # the parent's trace already finalized — this caller is a
                # zombie (e.g. an abandoned device cycle unblocking late);
                # it must NOT start polluting the ring with fresh roots
                return NOOP_SPAN
            return Span(name, parent.trace, parent.span_id, attrs)
        trace = Trace(f"t{_next_id():06x}", rec, time.perf_counter(), name)
        return Span(name, trace, None, attrs)

    # alias emphasizing with-statement use: `with tracer.span("x"): ...`
    span = start_span

    def attach(self, parent):
        """Context manager adopting `parent` (captured on another thread
        via `tracer.current()`) as this thread's ambient span."""
        if not isinstance(parent, Span):
            return NOOP_SPAN
        return _Attach(parent)
