"""SLO error budgets: declarative objectives + multi-window burn rates.

The north-star SLO (SNIPPETS.md header) is a sub-second p99 schedule
latency; until now nothing in the process JUDGED it — bench rounds
measured offline, the serve plane only exported raw histograms.  This
module evaluates declarative objectives over the telemetry ring
(obs/timeseries) with the standard SRE multi-window burn-rate method:

  * An ``Objective`` is one of three kinds —
      ``latency``: a histogram family; a good event is an observation at
        or under ``threshold_s`` (judged from windowed bucket deltas, no
        raw samples needed);
      ``ratio``:   good fraction = 1 - bad_counter_delta / total_delta;
      ``zero``:    a counter whose windowed delta must be exactly 0
        (conservation violations).
  * Burn rate = (error fraction in window) / (1 - target): burn 1.0
    spends the budget exactly at the sustainable rate; the evaluator
    computes it over a SHORT window (the freshest ring fraction — fast
    detection) and the LONG window (the whole retained ring — fast
    alerts that also reset fast are ignored).  An objective is unhealthy
    only when BOTH windows burn above 1.0 — the classic multi-window
    rule that suppresses blips without missing sustained burn.
  * The regression watchdog compares live steady-state bindings/s
    (schedule-attempt counter deltas over the long window) against the
    committed baseline envelope (BENCH_r07.json) and TRIPS A GAUGE —
    never a crash, never a log-only whisper.

Exported per evaluation: ``karmada_slo_healthy{slo}``,
``karmada_slo_burn_rate_milli{slo,window}``,
``karmada_slo_budget_remaining_milli{slo}``, and the watchdog's
``karmada_slo_regression_tripped`` / ``karmada_slo_live_bindings_per_s``.
Read back through ``/debug/slo``, the ``karmadactl top`` dashboard, and
the SOAK/CHAOS/REBALANCE bench payloads.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from karmada_tpu.utils.metrics import REGISTRY, quantile_from_buckets

SLO_HEALTHY = REGISTRY.gauge(
    "karmada_slo_healthy",
    "1 while the objective's error budget is not burning in both "
    "windows (multi-window burn rate rule); 0 while it is",
    ("slo",),
)
SLO_BURN_MILLI = REGISTRY.gauge(
    "karmada_slo_burn_rate_milli",
    "Error-budget burn rate x1000 per objective and window (1000 = "
    "spending the budget exactly at the sustainable rate)",
    ("slo", "window"),
)
SLO_BUDGET_MILLI = REGISTRY.gauge(
    "karmada_slo_budget_remaining_milli",
    "Remaining error budget x1000 over the long window (1000 = "
    "untouched, 0 = exhausted)",
    ("slo",),
)
REGRESSION_TRIPPED = REGISTRY.gauge(
    "karmada_slo_regression_tripped",
    "1 while live steady-state bindings/s sits below the committed "
    "baseline envelope floor (the runtime regression watchdog)",
)
LIVE_BPS = REGISTRY.gauge(
    "karmada_slo_live_bindings_per_s",
    "Live scheduled-bindings throughput over the telemetry ring's long "
    "window (the regression watchdog's input)",
)

#: burn rates are capped here before export (a zero-total window with a
#: violation would otherwise be infinite; milli-gauges stay finite)
BURN_CAP = 1000.0


@dataclass(frozen=True)
class Objective:
    """One declarative objective (the SLO grammar — docs/OBSERVABILITY)."""

    name: str
    kind: str                       # latency | ratio | zero
    target: float = 0.99            # good-event fraction the SLO promises
    # latency kind: histogram family + the bound a good observation meets
    metric: str = ""
    threshold_s: float = 1.0
    # ratio/zero kinds: counter families summed across label sets; the
    # optional {label_name: value} filter restricts which sets count
    bad: Tuple[str, Optional[Tuple[Tuple[str, str], ...]]] = ("", None)
    total: Tuple[str, Optional[Tuple[Tuple[str, str], ...]]] = ("", None)

    def budget(self) -> float:
        return max(1.0 - self.target, 1e-9)


def default_objectives(schedule_deadline_s: float = 1.0,
                       dwell_deadline_s: Optional[float] = None,
                       shed_target: float = 0.99,
                       estimator_target: float = 0.99) -> Tuple[Objective, ...]:
    """The stock objective set: the <1s p99 schedule-latency north star,
    queue-dwell p99, the shed ratio, the conservation invariant, and the
    estimator error rate (errors per scheduling attempt).

    dwell_deadline_s defaults to TWICE the schedule bound: under
    deadline-based batch formation entries dwell at the batch deadline
    by design, so a dwell objective at the schedule bound itself would
    page on healthy coalescing.  Thresholds are judged conservatively
    at bucket resolution (the last histogram bound at or under the
    threshold) — an off-bucket threshold rounds the error fraction UP,
    never down."""
    if dwell_deadline_s is None:
        dwell_deadline_s = 2.0 * schedule_deadline_s
    return (
        Objective("schedule_p99", "latency", target=0.99,
                  metric="karmada_scheduler_e2e_scheduling_duration_seconds",
                  threshold_s=schedule_deadline_s),
        Objective("dwell_p99", "latency", target=0.99,
                  metric="karmada_scheduler_queue_dwell_seconds",
                  threshold_s=dwell_deadline_s),
        Objective("shed_ratio", "ratio", target=shed_target,
                  bad=("karmada_scheduler_admission_total",
                       (("decision", "shed"),)),
                  total=("karmada_scheduler_admission_total", None)),
        Objective("conservation", "zero",
                  bad=("karmada_rebalance_conservation_violations_total",
                       None)),
        Objective("estimator_errors", "ratio", target=estimator_target,
                  bad=("karmada_estimator_errors_total", None),
                  total=("karmada_scheduler_schedule_attempts_total", None)),
    )


def _counter_sum(snap: dict, name: str,
                 labels: Optional[Tuple[Tuple[str, str], ...]]) -> float:
    """Sum one counter family's value across its label sets, optionally
    filtered by {label_name: value} pairs."""
    fam = snap.get(name)
    if fam is None:
        return 0.0
    names = fam["labels"]
    want = dict(labels) if labels else {}
    total = 0.0
    for s in fam["samples"]:
        have = dict(zip(names, s["labels"]))
        if all(have.get(k) == v for k, v in want.items()):
            total += s["value"]
    return total


def _hist_fold(snap: dict, name: str) -> Tuple[int, List[int], List[float]]:
    """(total, cumulative bucket counts, bounds) of a histogram family
    summed across label sets."""
    fam = snap.get(name)
    if fam is None:
        return 0, [], []
    bounds = fam.get("bounds") or []
    total, cum = 0, [0] * len(bounds)
    for s in fam["samples"]:
        total += s["count"]
        for i, c in enumerate(s["buckets"]):
            cum[i] += c
    return total, cum, bounds


def _delta(a: float, b: float) -> float:
    """Counter delta between window ends, reset-aware (a restart makes
    the end value all increase)."""
    return b if b < a else b - a


class SloEvaluator:
    """Evaluates objectives over a MetricRing and exports the gauges."""

    def __init__(self, objectives: Optional[Sequence[Objective]] = None,
                 short_frac: float = 0.25,
                 watchdog: Optional["RegressionWatchdog"] = None) -> None:
        self.objectives = tuple(objectives if objectives is not None
                                else default_objectives())
        self.short_frac = min(max(short_frac, 0.01), 1.0)
        self.watchdog = watchdog
        self._lock = threading.Lock()
        self._last: dict = {"enabled": True, "objectives": [],
                            "regression": None}  # guarded-by: _lock; mutators: evaluate
        # incident-trigger edge state (evaluate-thread owned): triggers
        # fire on the healthy->unhealthy / watchdog-trip TRANSITIONS
        # only, never per unhealthy window
        self._prev_healthy: Optional[bool] = None
        self._prev_tripped = False

    # -- window math --------------------------------------------------------
    def _err_frac(self, obj: Objective, first: dict,
                  last: dict) -> Tuple[Optional[float], float]:
        """(error fraction, event total) for one window; fraction None
        when the window saw no qualifying events (no data != healthy)."""
        if obj.kind == "latency":
            t0, c0, bounds = _hist_fold(first, obj.metric)
            t1, c1, _ = _hist_fold(last, obj.metric)
            if t1 < t0:  # restart inside the window
                t0, c0 = 0, [0] * len(bounds)
            d_total = t1 - t0
            if d_total <= 0 or not bounds:
                return None, 0.0
            # good = observations <= the LAST bound at or under the
            # threshold (conservative: observations between that bound
            # and the threshold count as misses — bucket resolution
            # rounds the error fraction UP, never hides a miss)
            idx = None
            for i, b in enumerate(bounds):
                if b <= obj.threshold_s:
                    idx = i
            if idx is None:
                good = 0  # threshold under every bound: nothing provably good
            else:
                good = c1[idx] - (c0[idx] if c0 else 0)
            bad = max(0.0, d_total - good)
            return bad / d_total, float(d_total)
        bad = _delta(_counter_sum(first, *obj.bad),
                     _counter_sum(last, *obj.bad))
        if obj.kind == "zero":
            return (1.0 if bad > 0 else 0.0), bad
        total = _delta(_counter_sum(first, *obj.total),
                       _counter_sum(last, *obj.total))
        if total <= 0:
            return None, 0.0
        return min(bad / total, 1.0), total

    def _judge(self, obj: Objective,
               samples: List[Tuple[float, dict]]) -> dict:
        n = len(samples)
        short_n = max(2, int(round(self.short_frac * n)))
        windows = {"long": samples, "short": samples[-short_n:]}
        burn: Dict[str, Optional[float]] = {}
        frac: Dict[str, Optional[float]] = {}
        events: Dict[str, float] = {}
        for wname, w in windows.items():
            if len(w) < 2:
                burn[wname] = frac[wname] = None
                events[wname] = 0.0
                continue
            f, total = self._err_frac(obj, w[0][1], w[-1][1])
            frac[wname] = f
            events[wname] = total
            burn[wname] = (None if f is None
                           else min(f / obj.budget(), BURN_CAP))
        if obj.kind == "zero":
            healthy = (None if burn["long"] is None
                       else events["long"] == 0.0)
        elif burn["long"] is None and burn["short"] is None:
            healthy = None  # no data: reported, never asserted healthy
        else:
            # multi-window rule: unhealthy only when every window with
            # data burns above 1.0
            with_data = [b for b in (burn["short"], burn["long"])
                         if b is not None]
            healthy = not all(b > 1.0 for b in with_data)
        budget_rem = (None if frac["long"] is None else
                      max(0.0, 1.0 - frac["long"] / obj.budget()))
        rec = {
            "name": obj.name,
            "kind": obj.kind,
            "target": obj.target,
            "healthy": healthy,
            "burn_rate": {k: (None if v is None else round(v, 4))
                          for k, v in burn.items()},
            "error_fraction": {k: (None if v is None else round(v, 6))
                               for k, v in frac.items()},
            "events": {k: round(v, 1) for k, v in events.items()},
            "budget_remaining": (None if budget_rem is None
                                 else round(budget_rem, 4)),
        }
        if obj.kind == "latency":
            rec["threshold_s"] = obj.threshold_s
            # the window's estimated quantile rides along so the verdict
            # is inspectable, not just boolean
            t0, c0, bounds = _hist_fold(samples[0][1], obj.metric)
            t1, c1, _ = _hist_fold(samples[-1][1], obj.metric)
            if t1 < t0:
                t0, c0 = 0, [0] * len(bounds)
            d = [b - a for a, b in zip(c0 or [0] * len(bounds), c1)]
            p99 = quantile_from_buckets(bounds, d, t1 - t0, obj.target)
            rec["estimated_p"] = (None if t1 - t0 <= 0
                                  else round(float(p99), 6))
        # gauges: healthy None (no data) exports 1 — absence of traffic
        # must not page; the payload keeps the tri-state
        SLO_HEALTHY.set(0.0 if healthy is False else 1.0, slo=obj.name)
        for wname in ("short", "long"):
            if burn[wname] is not None:
                SLO_BURN_MILLI.set(round(burn[wname] * 1000.0),
                                   slo=obj.name, window=wname)
        if budget_rem is not None:
            SLO_BUDGET_MILLI.set(round(budget_rem * 1000.0), slo=obj.name)
        return rec

    def evaluate(self, ring) -> dict:
        """Judge every objective over the ring's current window, export
        the gauges, run the watchdog, and cache the payload for
        /debug/slo."""
        samples = ring.samples()
        payload: dict = {
            "enabled": True,
            "window": {"samples": len(samples),
                       "span_s": (round(samples[-1][0] - samples[0][0], 6)
                                  if len(samples) >= 2 else 0.0),
                       "short_frac": self.short_frac},
            "objectives": [self._judge(o, samples) for o in self.objectives],
        }
        payload["healthy"] = all(o["healthy"] is not False
                                 for o in payload["objectives"])
        payload["regression"] = (self.watchdog.check(samples)
                                 if self.watchdog is not None else None)
        with self._lock:
            self._last = payload
        # incident triggers AFTER _lock releases: the bundle capture
        # reads last() and must not nest under the evaluator's lock
        from karmada_tpu.obs import incidents as obs_incidents

        healthy = payload["healthy"]
        if healthy is False and self._prev_healthy is not False:
            obs_incidents.trigger(
                obs_incidents.TRIGGER_SLO_UNHEALTHY,
                "SLO transitioned healthy -> unhealthy",
                detail={"unhealthy": [o["name"] for o in
                                      payload["objectives"]
                                      if o["healthy"] is False]})
        self._prev_healthy = healthy
        tripped = bool(self.watchdog is not None and self.watchdog.tripped)
        if tripped and not self._prev_tripped:
            obs_incidents.trigger(
                obs_incidents.TRIGGER_REGRESSION,
                "regression watchdog tripped: live throughput under the "
                "baseline envelope floor",
                detail=payload["regression"])
        self._prev_tripped = tripped
        return payload

    def last(self) -> dict:
        with self._lock:
            return self._last


class RegressionWatchdog:
    """Trips a gauge when live steady-state throughput falls below the
    committed baseline envelope's floor.  Throughput under LIGHT load
    equals the arrival rate, not capability, so the watchdog judges
    only windows where the plane was actually BUSY — a standing active
    queue in at least ``min_busy_frac`` of the window's samples (the
    queue-depth gauge is in the same ring) — with real traffic
    (``min_window_bindings``).  "When there is standing work, the plane
    must clear it at no less than the envelope floor."  A trip is a
    GAUGE (+ payload detail), never an exception — the SLO plane
    observes regressions, it does not cause outages."""

    def __init__(self, baseline_bps: float, floor_frac: float = 0.02,
                 min_window_bindings: int = 256,
                 min_busy_frac: float = 0.5) -> None:
        self.baseline_bps = float(baseline_bps)
        self.floor_frac = float(floor_frac)
        self.min_window_bindings = int(min_window_bindings)
        self.min_busy_frac = float(min_busy_frac)
        self.tripped = False

    @property
    def floor_bps(self) -> float:
        return self.baseline_bps * self.floor_frac

    def check(self, samples) -> dict:
        rec = {"baseline_bps": round(self.baseline_bps, 1),
               "floor_bps": round(self.floor_bps, 1),
               "floor_frac": self.floor_frac,
               "live_bps": None, "window_bindings": 0.0,
               "busy_frac": None,
               "tripped": self.tripped}
        if len(samples) < 2:
            return rec
        (t0, first), (t1, last) = samples[0], samples[-1]
        span = t1 - t0
        labels = (("result", "scheduled"),)
        scheduled = _delta(
            _counter_sum(first, "karmada_scheduler_schedule_attempts_total",
                         labels),
            _counter_sum(last, "karmada_scheduler_schedule_attempts_total",
                         labels))
        busy = sum(
            1 for _, snap in samples
            if _counter_sum(snap, "karmada_scheduler_queue_depth",
                            (("queue", "active"),)) > 0)
        busy_frac = busy / len(samples)
        rec.update(window_bindings=round(scheduled, 1),
                   busy_frac=round(busy_frac, 3))
        if (span <= 0 or scheduled < self.min_window_bindings
                or busy_frac < self.min_busy_frac):
            return rec  # not a saturated window: keep the last verdict
        live = scheduled / span
        LIVE_BPS.set(round(live, 3))
        self.tripped = live < self.floor_bps
        REGRESSION_TRIPPED.set(1.0 if self.tripped else 0.0)
        rec.update(live_bps=round(live, 1), tripped=self.tripped)
        return rec


def load_baseline_envelope(path: Optional[str] = None) -> Optional[dict]:
    """The committed baseline envelope: BENCH_r07.json's headline
    steady-state bindings/s (repo root; an explicit path overrides).
    None when absent/unreadable — the watchdog then stays disarmed,
    reported as such, never a crash."""
    import json

    if path is None:
        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))),
            "BENCH_r07.json")
    try:
        with open(path) as f:
            rec = json.load(f)
        value = float(rec.get("value") or 0.0)
    except (OSError, ValueError, TypeError):
        return None
    if value <= 0:
        return None
    return {"path": path, "bps": value, "metric": rec.get("metric")}


# -- process-wide evaluator ---------------------------------------------------
_ACTIVE: Optional[SloEvaluator] = None  # guarded-by: _ACTIVE_LOCK
_ACTIVE_LOCK = threading.Lock()


def configure(objectives: Optional[Sequence[Objective]] = None,
              short_frac: float = 0.25,
              watchdog: Optional[RegressionWatchdog] = None,
              baseline_path: Optional[str] = None,
              arm_watchdog: bool = True) -> SloEvaluator:
    """Arm the process-wide SLO evaluator.  With no explicit watchdog, a
    committed baseline envelope (BENCH_r07.json) arms the default one;
    no envelope on disk leaves the watchdog off (reported in the
    payload).  ``arm_watchdog=False`` skips it entirely — compressed
    virtual-time soaks on host backends are not the envelope's regime
    (their bindings/s axis is the ServiceModel, not the hardware)."""
    global _ACTIVE
    if watchdog is None and arm_watchdog:
        env = load_baseline_envelope(baseline_path)
        if env is not None:
            watchdog = RegressionWatchdog(env["bps"])
    ev = SloEvaluator(objectives, short_frac=short_frac, watchdog=watchdog)
    with _ACTIVE_LOCK:
        _ACTIVE = ev
    return ev


def active() -> Optional[SloEvaluator]:
    # lock-free read: the sampler consults this once per armed sample
    return _ACTIVE


def disarm() -> None:
    global _ACTIVE
    with _ACTIVE_LOCK:
        _ACTIVE = None


def state_payload() -> dict:
    """The /debug/slo payload: the most recent evaluation, or the
    disarmed marker so dashboards can poll unconditionally."""
    ev = active()
    if ev is None:
        return {"enabled": False, "objectives": []}
    return ev.last()
