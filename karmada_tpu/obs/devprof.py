"""Device cost/memory attribution + on-demand profiler capture.

The fused resident path's whole point (PR 11) is that the win lives on
the DEVICE and the link — host wall-time barely moves on the CPU
fallback — yet every cost surface so far was host-side.  This module is
the TPU-native answer to the reference's pprof profileflag
(pkg/sharedcli/profileflag, already name-checked in utils/httpserve):

  * **Executable cost ledger** — ``record_cost()`` keeps the
    ``compiled.cost_analysis()`` harvest (flops / bytes accessed) of
    every AOT-warmed executable (ops/aotcache feeds it per
    shape x variant label), so "what does one solver dispatch cost the
    chip" is a table, not a guess.
  * **Memory gauges** — ``refresh_memory_gauges()`` exports per-device
    ``memory_stats()`` (HBM in-use / limit / peak, where the backend
    reports them; XLA:CPU reports none) plus the process RSS fallback so
    the attribution surface is never empty off-hardware.  Refreshed per
    guarded scheduler cycle via the telemetry sampler
    (obs/timeseries.maybe_sample), so the series land in the ring.
  * **Profiler capture** — ``capture_profile(seconds, out_dir)`` wraps
    ``jax.profiler`` start/stop around a bounded window (one capture at
    a time; a marker op guarantees a non-empty artifact on an idle
    plane), writing TensorBoard-loadable artifacts under the serve dir.
    Served as ``/debug/profile?seconds=N`` and ``karmadactl profile``.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Sequence

from karmada_tpu.utils.metrics import REGISTRY

DEVICE_MEMORY = REGISTRY.gauge(
    "karmada_device_memory_bytes",
    "Per-device memory_stats() attribution (bytes), by device and kind "
    "(in_use / peak / limit); absent on backends that report no stats",
    ("device", "kind"),
)
PROCESS_MEMORY = REGISTRY.gauge(
    "karmada_process_memory_bytes",
    "Host process memory (bytes) by kind (rss) — the attribution floor "
    "on backends whose devices report no memory_stats",
    ("kind",),
)
CAPTURES = REGISTRY.counter(
    "karmada_devprof_captures_total",
    "On-demand jax.profiler capture windows completed, by outcome",
    ("outcome",),
)

#: memory_stats keys exported when present -> gauge kind label
_MEM_KEYS = (("bytes_in_use", "in_use"),
             ("peak_bytes_in_use", "peak"),
             ("bytes_limit", "limit"))

#: /debug/profile bound: a capture window is a debugging act, not a
#: background service — long windows belong to offline tooling
MAX_CAPTURE_S = 60.0

_LOCK = threading.Lock()
# guarded-by: _LOCK; mutators: record_cost,_note_capture,reset_for_tests
_STATE: Dict[str, object] = {
    "costs": {},          # label -> {"flops": f, "bytes_accessed": b}
    "last_memory": None,  # last refresh summary
    "last_capture": None, # last capture_profile outcome
}
_CAPTURE_GATE = threading.Lock()  # one profiler window at a time


def harvest_cost(compiled) -> Optional[dict]:
    """flops / bytes-accessed totals from a jax Compiled's
    cost_analysis(), or None when the backend exposes none.  Accepts
    both the list-of-dicts (older jax) and plain-dict shapes."""
    try:
        ca = compiled.cost_analysis()
    # vet: ignore[exception-hygiene] cost analysis is best-effort attribution; absence is a valid outcome
    except Exception:  # noqa: BLE001 — backend exposes no analysis
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    out = {}
    if "flops" in ca:
        out["flops"] = float(ca["flops"])
    if "bytes accessed" in ca:
        out["bytes_accessed"] = float(ca["bytes accessed"])
    return out or None


def record_cost(label: str, cost: Optional[dict]) -> None:
    """File one AOT-warmed executable's cost harvest under its
    shape x variant label (ops/aotcache)."""
    if not cost:
        return
    with _LOCK:
        _STATE["costs"][label] = dict(cost)


def cost_ledger() -> Dict[str, dict]:
    with _LOCK:
        return {k: dict(v) for k, v in _STATE["costs"].items()}


def _rss_bytes() -> Optional[int]:
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        return None


def refresh_memory_gauges(devices: Optional[Sequence] = None) -> int:
    """Refresh the per-device memory gauges (+ process RSS).  Returns
    how many per-device series were updated.  `devices` is injectable
    for tests; None enumerates jax.devices() — only call on paths where
    a backend is already initialised (the telemetry sampler runs inside
    the scheduler's guarded device cycle cadence, after init)."""
    if devices is None:
        try:
            import jax

            devices = jax.devices()
        # vet: ignore[exception-hygiene] no backend / dead tunnel: attribution degrades to RSS only
        except Exception:  # noqa: BLE001 — backend unavailable
            devices = []
    updated = 0
    summary: List[dict] = []
    for d in devices:
        try:
            stats = d.memory_stats()
        # vet: ignore[exception-hygiene] a device without stats is a valid outcome, not a fault
        except Exception:  # noqa: BLE001 — backend exposes no stats
            stats = None
        if not stats:
            continue
        name = f"{getattr(d, 'platform', 'dev')}:{getattr(d, 'id', 0)}"
        rec = {"device": name}
        for key, kind in _MEM_KEYS:
            if key in stats:
                DEVICE_MEMORY.set(float(stats[key]), device=name, kind=kind)
                rec[kind] = int(stats[key])
                updated += 1
        summary.append(rec)
    rss = _rss_bytes()
    if rss is not None:
        PROCESS_MEMORY.set(float(rss), kind="rss")
    with _LOCK:
        _STATE["last_memory"] = {"at_unix": round(time.time(), 3),
                                 "devices": summary,
                                 "rss_bytes": rss}
    return updated


def memory_stats_payload(devices: Optional[Sequence] = None) -> List[dict]:
    """Raw per-device memory_stats() as JSON-able records (the device
    probe's HBM-visibility line in watch_bench rides on the same
    shape)."""
    if devices is None:
        try:
            import jax

            devices = jax.devices()
        # vet: ignore[exception-hygiene] no backend: an empty attribution list is the honest answer
        except Exception:  # noqa: BLE001 — backend unavailable
            devices = []
    out: List[dict] = []
    for d in devices:
        try:
            stats = d.memory_stats()
        # vet: ignore[exception-hygiene] a device without stats is a valid outcome
        except Exception:  # noqa: BLE001 — backend exposes no stats
            stats = None
        out.append({
            "device": f"{getattr(d, 'platform', 'dev')}:{getattr(d, 'id', 0)}",
            "memory_stats": ({k: int(v) for k, v in stats.items()}
                             if stats else None),
        })
    return out


def _artifacts_under(root: str) -> List[dict]:
    files = []
    for r, _dirs, fns in os.walk(root):
        for fn in fns:
            p = os.path.join(r, fn)
            try:
                files.append({"path": os.path.relpath(p, root),
                              "bytes": os.path.getsize(p)})
            except OSError:
                continue
    return sorted(files, key=lambda f: f["path"])


def _note_capture(rec: dict) -> dict:
    with _LOCK:
        _STATE["last_capture"] = rec
    return rec


def capture_profile(seconds: float, out_dir: str) -> dict:
    """One bounded jax.profiler capture window: start the trace, keep
    the window open `seconds` (capped at MAX_CAPTURE_S), run one tiny
    marker op so an idle plane still yields a non-empty artifact, stop,
    and inventory what landed on disk.  One capture at a time — a
    second concurrent request answers busy instead of corrupting the
    first window's artifact."""
    seconds = min(max(float(seconds), 0.0), MAX_CAPTURE_S)
    if not _CAPTURE_GATE.acquire(blocking=False):
        CAPTURES.inc(outcome="busy")
        # `busy` is the structured flag the HTTP layer maps to 409 —
        # never couple on the human-readable message
        return {"ok": False, "busy": True,
                "error": "a profiler capture is already running; one "
                         "window at a time"}
    t0 = time.perf_counter()
    try:
        import jax
        import jax.numpy as jnp

        stamp = time.strftime("%Y%m%d-%H%M%S")
        dest = os.path.join(out_dir, f"profile-{stamp}")
        os.makedirs(dest, exist_ok=True)
        jax.profiler.start_trace(dest)
        try:
            deadline = time.perf_counter() + seconds
            # the marker op: guarantees the capture is never empty and
            # stamps a recognizable kernel into an otherwise idle window
            jax.jit(lambda a: a * 2 + 1)(
                jnp.arange(128)).block_until_ready()
            remaining = deadline - time.perf_counter()
            if remaining > 0:
                time.sleep(remaining)
        finally:
            jax.profiler.stop_trace()
        files = _artifacts_under(dest)
        CAPTURES.inc(outcome="ok")
        return _note_capture({
            "ok": True,
            "dir": dest,
            "seconds": seconds,
            "wall_s": round(time.perf_counter() - t0, 3),
            "files": files,
            "total_bytes": sum(f["bytes"] for f in files),
        })
    # vet: ignore[exception-hygiene] counted + returned as the capture outcome; the debug surface must answer, not raise
    except Exception as e:  # noqa: BLE001 — answered as the JSON outcome
        CAPTURES.inc(outcome="error")
        return _note_capture({"ok": False, "error": repr(e)[:400],
                              "seconds": seconds})
    finally:
        _CAPTURE_GATE.release()


def state_payload() -> dict:
    """The devprof block (inside /debug/slo-adjacent surfaces and
    /debug/state consumers that want attribution): the executable cost
    ledger, the last memory refresh, and the last capture outcome."""
    with _LOCK:
        return {
            "costs": {k: dict(v) for k, v in _STATE["costs"].items()},
            "last_memory": _STATE["last_memory"],
            "last_capture": _STATE["last_capture"],
        }


def reset_for_tests() -> None:
    with _LOCK:
        _STATE["costs"] = {}
        _STATE["last_memory"] = None
        _STATE["last_capture"] = None
