"""Incident plane: flight recorder, trigger bus, and forensic bundles.

Every detection plane this repo has grown — SLO burn verdicts and the
RegressionWatchdog (obs/slo), the incremental dense audit
(scheduler/incremental), the LockWatchdog and order-inversion detector
(utils/locks), chaos SafetyAuditor violations (chaos/audit), backend
degrade and cycle-fault containment (scheduler/service), and the
InvariantViolation guards (analysis/guards) — fires a counter and then
throws away the context it fired in.  This module keeps that context:

* **Flight recorder** — a bounded ring of cheap structured per-cycle
  records (``kind="cycle"`` from the scheduler, ``"incremental"`` from
  the dirty-set plane, ``"facade"`` from coalesced facade dispatches).
  Armed by default like the lifecycle ledger; the disarmed cost of
  ``record()`` is one module-global list read, and the armed cost is a
  dict append under a plain lock — pure host bookkeeping, zero jit
  surface (bench.py ``measure_flight_overhead`` asserts both, the same
  contract as the ledger/telemetry planes).

* **Trigger bus** — ``trigger(kind, ...)`` with one typed constant per
  detector (``TRIGGER_KINDS``).  Disarmed (no ``IncidentStore``
  configured) it is one list read.  Armed, each trigger kind is
  rate-limited by a per-kind cooldown on an injectable clock
  (compressed soaks pass their VirtualClock), so a flapping detector
  produces ONE bundle per cooldown window, not a bundle storm.

* **Incident bundles** — on an admitted trigger the store captures a
  self-contained JSON bundle: the flight ring, the last N MetricRing
  samples plus the SLO verdict, the lifecycle-ledger timelines of the
  implicated bindings, the ``/debug/state`` locks block, the trigger's
  own detail payload (e.g. the incremental audit divergence diff), and
  an optional bounded ``jax.profiler`` capture (obs/devprof).  Bundles
  are written under ``<plane dir>/incidents/<id>.json`` and indexed in
  memory for ``/debug/incidents[/{id}]`` / ``karmadactl incidents``.

Capture is deliberately defensive: every section is independently
guarded, a failing plane records a ``capture_errors`` entry instead of
losing the bundle, and a thread-local reentrancy latch stops a capture
(or an InvariantViolation raised inside one) from re-triggering itself.
The store's bookkeeping uses a plain ``threading.Lock`` on purpose —
triggers fire from inside utils/locks' own instrumentation, where a
VetLock here would self-trace.

Metrics: ``karmada_incidents_total{trigger}``,
``karmada_incidents_suppressed_total{trigger}``,
``karmada_incident_capture_seconds`` (all registered at import; arming
the plane adds observations, never new families).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

from karmada_tpu.utils.metrics import REGISTRY

INCIDENTS = REGISTRY.counter(
    "karmada_incidents_total",
    "incident bundles captured, by trigger kind",
    ("trigger",))
INCIDENTS_SUPPRESSED = REGISTRY.counter(
    "karmada_incidents_suppressed_total",
    "triggers suppressed by the per-kind capture cooldown",
    ("trigger",))
CAPTURE_SECONDS = REGISTRY.histogram(
    "karmada_incident_capture_seconds",
    "wall seconds spent assembling one incident bundle")

# -- typed trigger kinds (the bus vocabulary) --------------------------------

TRIGGER_SLO_UNHEALTHY = "slo-unhealthy"          # obs/slo healthy -> False
TRIGGER_REGRESSION = "regression-watchdog"       # obs/slo RegressionWatchdog
TRIGGER_LOCK_WATCHDOG = "lock-watchdog"          # utils/locks LockWatchdog
TRIGGER_LOCK_INVERSION = "lock-inversion"        # utils/locks order inversion
TRIGGER_AUDIT_DIVERGENCE = "audit-divergence"    # incremental dense audit
TRIGGER_SAFETY_VIOLATION = "safety-violation"    # chaos SafetyAuditor
TRIGGER_BACKEND_DEGRADE = "backend-degrade"      # scheduler degrade path
TRIGGER_CYCLE_FAULT = "cycle-fault"              # contained cycle fault
TRIGGER_INVARIANT_VIOLATION = "invariant-violation"  # analysis/guards

TRIGGER_KINDS = (
    TRIGGER_SLO_UNHEALTHY, TRIGGER_REGRESSION, TRIGGER_LOCK_WATCHDOG,
    TRIGGER_LOCK_INVERSION, TRIGGER_AUDIT_DIVERGENCE,
    TRIGGER_SAFETY_VIOLATION, TRIGGER_BACKEND_DEGRADE, TRIGGER_CYCLE_FAULT,
    TRIGGER_INVARIANT_VIOLATION,
)


# -- flight recorder ---------------------------------------------------------


class FlightRecorder:
    """Bounded ring of per-cycle flight records (plain dicts)."""

    def __init__(self, capacity: int = 512) -> None:
        self.capacity = max(1, int(capacity))
        self._ring: deque = deque(maxlen=self.capacity)  # guarded-by: _lock
        self._lock = threading.Lock()
        self.recorded = 0  # guarded-by: _lock

    def record(self, rec: dict) -> None:
        with self._lock:
            self._ring.append(rec)
            self.recorded += 1

    def snapshot(self, n: Optional[int] = None) -> List[dict]:
        """The most recent n records (all when None), oldest first."""
        with self._lock:
            out = list(self._ring)
        if n is None:
            return out
        n = int(n)
        return out[-n:] if n > 0 else []

    def stats(self) -> dict:
        with self._lock:
            return {"recorded": self.recorded, "retained": len(self._ring),
                    "capacity": self.capacity}


_FLIGHT_ARMED = [True]
_FLIGHT: List[FlightRecorder] = [FlightRecorder()]


def flight() -> FlightRecorder:
    return _FLIGHT[0]


def flight_armed() -> bool:
    return _FLIGHT_ARMED[0]


def arm_flight(on: bool = True) -> None:
    _FLIGHT_ARMED[0] = bool(on)


def configure_flight(capacity: int = 512) -> FlightRecorder:
    """Install a fresh flight ring (tests wanting isolation; serve keeps
    the default).  Re-arms recording."""
    rec = FlightRecorder(capacity=capacity)
    _FLIGHT[0] = rec
    _FLIGHT_ARMED[0] = True
    return rec


def record(kind: str, **fields) -> bool:
    """Append one flight record.  One list read when disarmed; callers
    computing expensive fields should hoist ``flight_armed()`` first
    (the obs_events.armed() pattern)."""
    if not _FLIGHT_ARMED[0]:
        return False
    fields["kind"] = kind
    _FLIGHT[0].record(fields)
    return True


# -- incident store ----------------------------------------------------------


class IncidentStore:
    """Cooldown-gated bundle capture + the bounded in-memory index.

    ``dir=None`` keeps bundles in memory only (tests); serve passes
    ``<plane dir>/incidents``.  The clock is injectable so compressed
    soaks rate-limit on virtual time."""

    def __init__(self, dir: Optional[str] = None, *,  # noqa: A002 — dir
                 # mirrors ObservabilityServer's profile_dir convention
                 cooldown_s: float = 60.0, flight_n: int = 256,
                 ring_n: int = 64, keep: int = 64, profile_s: float = 0.0,
                 clock: Callable[[], float] = time.time) -> None:
        self.dir = dir
        self.cooldown_s = float(cooldown_s)
        self.flight_n = int(flight_n)
        self.ring_n = int(ring_n)
        self.keep = max(1, int(keep))
        self.profile_s = float(profile_s)
        self._clock = clock
        # plain Lock BY DESIGN: triggers fire from inside utils/locks'
        # own bookkeeping — a VetLock here would self-trace
        self._lock = threading.Lock()
        self._seq = 0  # guarded-by: _lock
        self._last_fire: Dict[str, float] = {}  # guarded-by: _lock
        self._suppressed: Dict[str, int] = {}  # guarded-by: _lock
        self._by_trigger: Dict[str, int] = {}  # guarded-by: _lock
        self._index: deque = deque(maxlen=self.keep)  # guarded-by: _lock
        self._bundles: Dict[str, dict] = {}  # guarded-by: _lock

    # -- the bus entry --------------------------------------------------------
    def trigger(self, kind: str, summary: str = "", *,
                refs: Optional[Sequence] = None,
                detail: Optional[dict] = None) -> Optional[str]:
        """Admit-or-suppress one typed trigger; returns the bundle id
        when a capture ran, None when the cooldown suppressed it."""
        assert kind in TRIGGER_KINDS, f"unknown trigger kind {kind!r}"
        now = self._clock()
        with self._lock:
            last = self._last_fire.get(kind)
            if last is not None and now - last < self.cooldown_s:
                self._suppressed[kind] = self._suppressed.get(kind, 0) + 1
                INCIDENTS_SUPPRESSED.inc(trigger=kind)
                return None
            self._last_fire[kind] = now
            self._seq += 1
            iid = f"inc-{self._seq:04d}-{kind}"
        t0 = time.perf_counter()
        bundle = self._capture(iid, kind, summary, list(refs or []),
                               detail, now)
        capture_s = time.perf_counter() - t0
        bundle["capture_s"] = round(capture_s, 6)
        CAPTURE_SECONDS.observe(capture_s)
        INCIDENTS.inc(trigger=kind)
        entry = {"id": iid, "trigger": kind, "summary": summary,
                 "ts": round(now, 6), "capture_s": round(capture_s, 6),
                 "path": bundle.get("path")}
        with self._lock:
            self._by_trigger[kind] = self._by_trigger.get(kind, 0) + 1
            if len(self._index) == self._index.maxlen:
                evicted = self._index[0]
                self._bundles.pop(evicted["id"], None)
            self._index.append(entry)
            self._bundles[iid] = bundle
        return iid

    # -- bundle assembly ------------------------------------------------------
    def _capture(self, iid: str, kind: str, summary: str, refs: list,
                 detail: Optional[dict], now: float) -> dict:
        errors: List[str] = []

        def guard(name: str, fn):
            # forensics must never take down the plane it observes: a
            # broken section records its error and the rest still lands
            try:
                return fn()
            # vet: ignore[exception-hygiene] recorded in capture_errors
            except Exception as e:  # noqa: BLE001 — one bad plane must
                # not lose the whole bundle
                errors.append(f"{name}: {e!r}")
                return None

        bundle: dict = {
            "id": iid, "trigger": kind, "summary": summary,
            "ts": round(now, 6), "wall_unix": round(time.time(), 3),
            "cooldown_s": self.cooldown_s,
            "detail": detail or {},
        }

        def _flight_block():
            rec = flight()
            return {"armed": flight_armed(), **rec.stats(),
                    "records": rec.snapshot(self.flight_n)}

        bundle["flight"] = guard("flight", _flight_block)

        def _telemetry_block():
            from karmada_tpu.obs import timeseries as obs_ts

            ring = obs_ts.active()
            if ring is None:
                return {"enabled": False, "samples": []}
            return {"enabled": True,
                    "samples": [[round(t, 6), snap]
                                for t, snap in ring.samples(self.ring_n)]}

        bundle["telemetry"] = guard("telemetry", _telemetry_block)

        def _slo_block():
            from karmada_tpu.obs import slo as obs_slo

            return obs_slo.state_payload()

        bundle["slo"] = guard("slo", _slo_block)

        def _locks_block():
            from karmada_tpu.utils import locks

            return locks.state_payload()

        bundle["locks"] = guard("locks", _locks_block)

        def _timelines_block():
            from karmada_tpu.obs import events as obs_events

            led = obs_events.ledger()
            timelines: Dict[str, list] = {}
            for r in refs[:16]:
                if isinstance(r, str):
                    ns, _, nm = r.partition("/")
                else:
                    ns, nm = r
                timelines[f"{ns}/{nm}"] = led.timeline(
                    "ResourceBinding", ns, nm)
            return timelines

        bundle["timelines"] = guard("timelines", _timelines_block)

        def _recent_events_block():
            from karmada_tpu.obs import events as obs_events

            return obs_events.ledger().recent(n=32)

        bundle["recent_events"] = guard("recent_events", _recent_events_block)

        if self.profile_s > 0 and self.dir:
            def _profile_block():
                from karmada_tpu.obs import devprof

                return devprof.capture_profile(
                    self.profile_s, os.path.join(self.dir, f"{iid}-profile"))

            bundle["profile"] = guard("profile", _profile_block)

        def _emit_block():
            from karmada_tpu.obs import events as obs_events

            obs_events.emit(
                obs_events.SCHEDULER_REF, obs_events.TYPE_WARNING,
                obs_events.REASON_INCIDENT_CAPTURED,
                f"incident {iid} captured (trigger {kind})"
                + (f": {summary}" if summary else ""),
                origin="incidents")

        guard("ledger_emit", _emit_block)

        if self.dir:
            def _write_block():
                os.makedirs(self.dir, exist_ok=True)
                path = os.path.join(self.dir, f"{iid}.json")
                with open(path, "w") as f:
                    json.dump(bundle, f, indent=2, default=str)
                return path

            bundle["path"] = guard("write", _write_block)
        else:
            bundle["path"] = None
        if errors:
            bundle["capture_errors"] = errors
        return bundle

    # -- read side ------------------------------------------------------------
    def bundle(self, iid: str) -> Optional[dict]:
        """One bundle by id: the in-memory copy, falling back to the
        on-disk artifact for entries the bounded index evicted."""
        with self._lock:
            b = self._bundles.get(iid)
        if b is not None:
            return b
        if self.dir:
            path = os.path.join(self.dir, f"{os.path.basename(iid)}.json")
            if os.path.exists(path):
                with open(path) as f:
                    return json.load(f)
        return None

    def state_payload(self) -> dict:
        """/debug/incidents: the index plus capture/suppression totals
        (bundles themselves are one fetch deeper)."""
        with self._lock:
            index = list(self._index)
            by_trigger = dict(self._by_trigger)
            suppressed = dict(self._suppressed)
        return {
            "enabled": True,
            "dir": self.dir,
            "cooldown_s": self.cooldown_s,
            "captured": sum(by_trigger.values()),
            "by_trigger": by_trigger,
            "suppressed": suppressed,
            "flight": flight().stats(),
            "incidents": index,
        }


# -- module-level plane (the serve/test arming surface) ----------------------

_STORE: List[Optional[IncidentStore]] = [None]
_TLS = threading.local()


def active() -> Optional[IncidentStore]:
    return _STORE[0]


def configure(dir: Optional[str] = None, *,  # noqa: A002 — mirrors
              # IncidentStore's constructor
              cooldown_s: float = 60.0, flight_n: int = 256,
              ring_n: int = 64, keep: int = 64, profile_s: float = 0.0,
              clock: Callable[[], float] = time.time) -> IncidentStore:
    """Arm the incident store (serve startup / soak tests).  The flight
    recorder is independent and armed by default."""
    store = IncidentStore(dir, cooldown_s=cooldown_s, flight_n=flight_n,
                          ring_n=ring_n, keep=keep, profile_s=profile_s,
                          clock=clock)
    _STORE[0] = store
    return store


def disarm() -> None:
    """Detach the store: triggers become one-list-read no-ops again.
    Captured bundle files stay on disk."""
    _STORE[0] = None


def trigger(kind: str, summary: str = "", *,
            refs: Optional[Sequence] = None,
            detail: Optional[dict] = None) -> Optional[str]:
    """The process-wide trigger bus.  One list read when no store is
    armed.  Reentrancy-latched: a capture's own work (or an
    InvariantViolation raised inside one) cannot recurse into another
    capture.  Never raises — forensics must not break the detector that
    fired it."""
    store = _STORE[0]
    if store is None:
        return None
    if getattr(_TLS, "in_trigger", False):
        return None
    _TLS.in_trigger = True
    try:
        return store.trigger(kind, summary, refs=refs, detail=detail)
    # vet: ignore[exception-hygiene] capture faults must never propagate into the detector paths that fired them
    except Exception:  # noqa: BLE001 — swallowed by contract (see above)
        return None
    finally:
        _TLS.in_trigger = False


def state_payload() -> dict:
    """/debug/incidents (module form): {"enabled": False} plus flight
    stats when no store is armed — pollable unconditionally."""
    store = _STORE[0]
    if store is None:
        return {"enabled": False, "flight": flight().stats()}
    return store.state_payload()


def bundle_payload(iid: str) -> Optional[dict]:
    store = _STORE[0]
    if store is None:
        return None
    return store.bundle(iid)
