"""Flight-recorder tracing subsystem.

The reference ships per-component Prometheus metrics (pkg/metrics/) but
no cross-component timeline; a TPU-native control plane that overlaps
host encode with device dispatch needs a per-cycle flight recorder, not
just counters.  This package provides it:

  trace.py     Span / SpanContext (contextvars) / Tracer — the core
  recorder.py  bounded ring of finished traces + slowest-N shelf +
               a drop counter so truncation is never silent
  export.py    JSON dump, text waterfall, per-stage aggregates

Everything instruments against the ONE process-wide `TRACER`, disabled
by default (zero-cost: call sites get the no-op span singleton).  It is
armed by `karmadactl serve --trace-buffer N` (obs.TRACER.configure) and
read back through /debug/traces* (utils/httpserve) and the `karmadactl
trace` CLI.

Span-name vocabulary (SPAN_*): declared here so the registry-collision
test can assert every span/metric name is unique, and so the waterfall /
bench stage timelines key on constants rather than string literals
scattered through the hot path.
"""

from karmada_tpu.obs.trace import (  # noqa: F401 — the public surface
    FROM_CONTEXT,
    NOOP_SPAN,
    NoopSpan,
    Span,
    Trace,
    Tracer,
)

# the process-wide tracer every call site instruments against
TRACER = Tracer()

# -- span-name vocabulary ----------------------------------------------------
# scheduler/service.py
SPAN_CYCLE = "scheduler.cycle"            # one batched scheduling cycle
SPAN_SERIAL = "scheduler.serial"          # host-serial fallback rows
# scheduler/pipeline.py (the pipelined chunk executor)
SPAN_PIPELINE = "pipeline.cycle"          # one run_pipeline call
SPAN_CHUNK = "pipeline.chunk"             # submit-to-result wall span
SPAN_ENCODE = "pipeline.encode"           # host encode of the chunk
SPAN_DISPATCH = "pipeline.dispatch"       # H2D + async device launch
SPAN_SPREAD = "pipeline.spread"           # spread sub-solves (finalize)
SPAN_BIG = "pipeline.big"                 # big-tier sub-solve (finalize)
SPAN_WAIT = "pipeline.solve_wait"         # device execution wait
SPAN_D2H = "pipeline.d2h"                 # sparse result copy (+ escalation)
SPAN_DECODE = "pipeline.decode"           # COO decode to per-binding results
# ops/aotcache.py (AOT executable plane)
SPAN_WARMUP = "solver.warmup"             # AOT pre-compile of warm shapes
# estimator/client.py
SPAN_ESTIMATOR_RPC = "estimator.rpc"      # one per-cluster estimator call
# karmada_tpu/resident (the device-resident state plane)
SPAN_RESIDENT_APPLY = "resident.apply"    # delta apply / structural rebuild
SPAN_RESIDENT_ENCODE = "resident.encode"  # gather + miss-subset re-encode
SPAN_RESIDENT_AUDIT = "resident.audit"    # bit-exact parity audit
# karmada_tpu/rebalance (the drain-and-re-place plane)
SPAN_REBALANCE_CYCLE = "rebalance.cycle"    # one detect->drain->audit pass
SPAN_REBALANCE_DETECT = "rebalance.detect"  # tensor assembly + jit score
SPAN_REBALANCE_DRAIN = "rebalance.drain"    # paced graceful evictions
# karmada_tpu/facade (scheduler-as-a-service)
SPAN_FACADE_CYCLE = "facade.cycle"          # one coalesced facade dispatch
SPAN_FACADE_WHATIF = "facade.whatif"        # one what-if hypothetical solve
# controllers
SPAN_BINDING_RENDER = "binding.ensure_works"
SPAN_DETECTOR_MATCH = "detector.match_policy"
# store/worker.py: every reconcile is spanned "reconcile.<worker name>"
SPAN_RECONCILE_PREFIX = "reconcile."

SPAN_NAMES = (
    SPAN_CYCLE, SPAN_SERIAL, SPAN_PIPELINE, SPAN_CHUNK, SPAN_ENCODE,
    SPAN_DISPATCH, SPAN_SPREAD, SPAN_BIG, SPAN_WAIT, SPAN_D2H, SPAN_DECODE,
    SPAN_ESTIMATOR_RPC, SPAN_RESIDENT_APPLY, SPAN_RESIDENT_ENCODE,
    SPAN_RESIDENT_AUDIT, SPAN_BINDING_RENDER, SPAN_DETECTOR_MATCH,
    SPAN_WARMUP, SPAN_REBALANCE_CYCLE, SPAN_REBALANCE_DETECT,
    SPAN_REBALANCE_DRAIN, SPAN_FACADE_CYCLE, SPAN_FACADE_WHATIF,
)

# every pipeline stage a healthy device chunk must traverse (the tier-1
# serve smoke asserts a trace covers all of them)
PIPELINE_STAGE_SPANS = (
    SPAN_ENCODE, SPAN_DISPATCH, SPAN_WAIT, SPAN_D2H, SPAN_DECODE,
)
