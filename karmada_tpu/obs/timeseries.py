"""Metric time-series: a bounded ring sampler over the metric Registry.

The reference control plane ships instantaneous Prometheus counters
(pkg/scheduler/metrics, pkg/metrics/cluster.go) and leaves retention to
an external scrape stack; this port has no Prometheus server in the
loop, so nothing retained history — a regression between two looks at
/metrics was invisible, and the SLO plane (obs/slo) had nothing to
compute burn rates over.  This module is the in-process retention tier:

  * ``MetricRing`` — a bounded ring of ``(t, Registry.snapshot())``
    samples (structured dicts, no text-format round trip).  ``t`` is
    whatever clock the caller passes: the scheduler samples on its
    CYCLE clock (``SchedulingQueue.now``), which is the loadgen
    VirtualClock in compressed soaks — a 10-minute synthetic soak
    produces a real 10-minute series in milliseconds of wall time.
  * ``maybe_sample(now)`` — the hot-path hook (scheduler/service._cycle
    and the periodic flush).  Disarmed cost is one module-global read;
    armed, it refreshes the device memory gauges (obs/devprof), appends
    one snapshot, and lets the armed SLO evaluator (obs/slo) judge the
    fresh window.
  * ``series_window`` / ``state_payload`` — flatten ring samples into
    per-series point lists for ``/debug/timeseries`` (counters carry a
    reset-aware windowed delta; histograms flatten to ``_count`` /
    ``_sum`` series) and the ``karmadactl top`` dashboard.

Armed by ``serve --telemetry[=RING]`` (cli), ``bench.py --soak --slo``,
and directly in tests via ``configure()``.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from karmada_tpu.utils.metrics import REGISTRY, Registry

SAMPLES_TOTAL = REGISTRY.counter(
    "karmada_telemetry_samples_total",
    "Metric-registry snapshots appended to the telemetry ring",
)
RING_DROPPED = REGISTRY.counter(
    "karmada_telemetry_ring_dropped_total",
    "Telemetry ring samples evicted by the capacity bound (oldest first)",
)


class MetricRing:
    """Bounded ring of (t, snapshot) samples over one Registry."""

    def __init__(self, capacity: int = 512, registry: Registry = REGISTRY,
                 min_interval_s: float = 0.0) -> None:
        self.capacity = max(2, int(capacity))
        self.registry = registry
        self.min_interval_s = float(min_interval_s)
        self._lock = threading.Lock()
        # guarded-by: _lock; mutators: sample
        self._ring: deque = deque(maxlen=self.capacity)
        self._dropped = 0      # guarded-by: _lock; mutators: sample
        self._out_of_order = 0  # guarded-by: _lock; mutators: sample
        self._last_t: Optional[float] = None  # guarded-by: _lock; mutators: sample

    def sample(self, now: float, force: bool = False,
               prepare=None) -> bool:
        """Append one snapshot stamped `now`.  Respects min_interval_s
        (on the SAMPLING clock, so virtual-time soaks pace on virtual
        time) unless `force`; returns whether a sample was taken.
        `prepare` runs only AFTER the throttle admits the sample and
        before the snapshot (per-sample refresh work — e.g. the memory
        gauges — must not be paid on throttled cycles).  The snapshot
        itself is taken OUTSIDE the ring lock — family locks already
        make it consistent, and a slow dashboard read of the ring must
        not stall the scheduler's cycle worker here."""
        with self._lock:
            if (not force and self._last_t is not None
                    and self.min_interval_s > 0
                    and now - self._last_t < self.min_interval_s):
                return False
            self._last_t = now
        if prepare is not None:
            prepare()
        snap = self.registry.snapshot()
        with self._lock:
            if self._ring and float(now) < self._ring[-1][0]:
                # two threads (cycle worker + periodic flush) can pass
                # the throttle concurrently and finish their snapshots
                # out of order; appending the stale one would break the
                # ring's time monotonicity and read as a counter reset
                # to counter_delta (inflating window deltas and burn
                # rates).  Drop the late arrival — the newer snapshot
                # already covers it.
                self._out_of_order += 1
                return False
            if len(self._ring) == self._ring.maxlen:
                self._dropped += 1
                RING_DROPPED.inc()
            self._ring.append((float(now), snap))
        SAMPLES_TOTAL.inc()
        return True

    def samples(self, n: Optional[int] = None) -> List[Tuple[float, dict]]:
        """The most recent n samples (all when n is None), oldest first.
        n=0 really means zero — never the whole-ring [-0:] surprise."""
        with self._lock:
            out = list(self._ring)
        if n is None:
            return out
        n = int(n)
        return out[-n:] if n > 0 else []

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    @property
    def out_of_order(self) -> int:
        with self._lock:
            return self._out_of_order

    def window(self) -> Tuple[Optional[float], Optional[float], int]:
        """(t_first, t_last, count) of the retained window."""
        with self._lock:
            if not self._ring:
                return None, None, 0
            return self._ring[0][0], self._ring[-1][0], len(self._ring)


def counter_delta(points: Sequence[Tuple[float, float]]) -> float:
    """Windowed increase of a counter series, reset-aware: a restarted
    process re-registers its counters at 0, so a drop between adjacent
    points is a reset and the post-reset value is all increase — the
    window delta never goes negative and never swallows pre-reset
    growth (the Prometheus increase() contract)."""
    delta = 0.0
    prev: Optional[float] = None
    for _, v in points:
        if prev is not None:
            delta += v if v < prev else v - prev
        prev = v
    return delta


def _key(name: str, label_names: Sequence[str],
         label_values: Sequence[str]) -> str:
    if not label_names:
        return name
    inner = ",".join(f'{n}="{v}"' for n, v in zip(label_names, label_values))
    return f"{name}{{{inner}}}"


def series_window(samples: Sequence[Tuple[float, dict]],
                  prefix: Optional[str] = None) -> Dict[str, dict]:
    """Flatten ring samples into per-series point lists:

        {series_key: {"type": ..., "points": [[t, v], ...],
                      "delta": windowed increase   # counters
                      "last": last value}}         # gauges

    Histogram families flatten to their ``<name>_count`` and
    ``<name>_sum`` derived series (both counter-semantics).  A series
    absent from early samples (labels born mid-window) starts at its
    first appearance.  `prefix` filters family names."""
    series: Dict[str, dict] = {}
    for t, snap in samples:
        for name, fam in snap.items():
            if prefix and not name.startswith(prefix):
                continue
            ftype = fam["type"]
            for s in fam["samples"]:
                if ftype == "histogram":
                    pairs = ((f"{name}_count", float(s["count"]), "counter"),
                             (f"{name}_sum", float(s["sum"]), "counter"))
                else:
                    pairs = ((name, float(s["value"]), ftype),)
                for sname, val, stype in pairs:
                    k = _key(sname, fam["labels"], s["labels"])
                    rec = series.setdefault(
                        k, {"type": stype, "points": []})
                    rec["points"].append([round(t, 6), val])
    for rec in series.values():
        if rec["type"] == "counter":
            rec["delta"] = round(counter_delta(rec["points"]), 6)
        else:
            rec["last"] = rec["points"][-1][1]
    return series


# -- the process-wide sampler -------------------------------------------------
_ACTIVE: Optional[MetricRing] = None  # guarded-by: _ACTIVE_LOCK
_ACTIVE_LOCK = threading.Lock()


def configure(capacity: int = 512, registry: Registry = REGISTRY,
              min_interval_s: float = 0.0) -> MetricRing:
    """Arm the process-wide telemetry ring (serve --telemetry)."""
    global _ACTIVE
    ring = MetricRing(capacity, registry, min_interval_s)
    with _ACTIVE_LOCK:
        _ACTIVE = ring
    return ring


def active() -> Optional[MetricRing]:
    with _ACTIVE_LOCK:
        return _ACTIVE


def disarm() -> None:
    global _ACTIVE
    with _ACTIVE_LOCK:
        _ACTIVE = None
    from karmada_tpu.obs import slo as obs_slo

    obs_slo.disarm()


def maybe_sample(now: float) -> bool:
    """The scheduler hot-path hook: one module-global read when
    disarmed; armed, refresh the per-device memory gauges (devprof —
    the "per guarded cycle" contract), append one ring sample, and run
    the armed SLO evaluator over the fresh window."""
    # lock-free read on the hot path (an atomic reference in CPython):
    # the disarmed serve cycle must pay one global read, not a lock
    # acquisition — the same discipline as the chaos plane's seams
    ring = _ACTIVE
    if ring is None:
        return False
    from karmada_tpu.obs import devprof, slo as obs_slo

    # the memory refresh rides the ring's throttle (prepare runs only
    # on admitted samples): a plane cycling every few ms must not poll
    # jax.devices()/memory_stats() per cycle when the ring keeps one
    # sample per --telemetry-interval
    took = ring.sample(now, prepare=devprof.refresh_memory_gauges)
    if took:
        ev = obs_slo.active()
        if ev is not None:
            ev.evaluate(ring)
    return took


def state_payload(n: Optional[int] = None,
                  prefix: Optional[str] = None,
                  include_points: bool = True) -> dict:
    """The /debug/timeseries payload.  include_points=False (the
    ?points=0 query, what `karmadactl top` polls) strips the per-series
    point lists and keeps only the window aggregates (delta / last) —
    a dashboard summary must not serialize the whole ring per poll."""
    ring = active()
    if ring is None:
        return {"enabled": False, "samples": 0, "series": {}}
    samples = ring.samples(n)
    t0, t1, count = ring.window()
    series = series_window(samples, prefix=prefix)
    if not include_points:
        for rec in series.values():
            rec.pop("points", None)
    return {
        "enabled": True,
        "capacity": ring.capacity,
        "min_interval_s": ring.min_interval_s,
        "samples": count,
        "returned_samples": len(samples),
        "dropped": ring.dropped,
        "out_of_order": ring.out_of_order,
        "window_s": (round(t1 - t0, 6)
                     if t0 is not None and t1 is not None else 0.0),
        "t_first": t0,
        "t_last": t1,
        "series": series,
    }


# -- the `karmadactl top` dashboard ------------------------------------------

def _fmt_rate(delta: float, window_s: float, unit: str = "/s") -> str:
    if window_s <= 0:
        return "-"
    return f"{delta / window_s:.1f}{unit}"


def render_top(ts_payload: dict, slo_payload: Optional[dict] = None) -> str:
    """One-screen live dashboard over a /debug/timeseries payload (+ the
    optional /debug/slo verdict): queue depths, the cycle budget
    breakdown (where a second of scheduling goes, from the per-step
    latency histogram), the h2d binding-field counter, and shed /
    eviction rates over the retained window."""
    if not ts_payload.get("enabled"):
        return ("telemetry plane is disabled on the server "
                "(serve --telemetry to arm the ring sampler)")
    series = ts_payload.get("series") or {}
    window = float(ts_payload.get("window_s") or 0.0)
    lines = [
        f"telemetry window {window:.3f}s "
        f"({ts_payload.get('samples')} sample(s), "
        f"{len(series)} series, dropped {ts_payload.get('dropped')})",
    ]

    def gauge(key):
        rec = series.get(key)
        return rec.get("last") if rec else None

    def delta(key) -> float:
        rec = series.get(key)
        return float(rec.get("delta") or 0.0) if rec else 0.0

    depths = {q: gauge(f'karmada_scheduler_queue_depth{{queue="{q}"}}')
              for q in ("active", "backoff", "unschedulable")}
    lines.append("  queue depth  " + "  ".join(
        f"{q}={int(v) if v is not None else '-'}"
        for q, v in depths.items()))
    # cycle budget: per-step solve-time share over the window
    steps = ("Encode", "H2D", "Solve", "D2H", "Decode", "Serial")
    step_d = {
        st: delta("karmada_scheduler_scheduling_algorithm_duration_seconds"
                  f'_sum{{schedule_step="{st}"}}')
        for st in steps}
    total = sum(step_d.values())
    if total > 0:
        lines.append("  cycle budget " + "  ".join(
            f"{st}={d / total:.0%}" for st, d in step_d.items() if d > 0))
    else:
        lines.append("  cycle budget (no solver traffic in window)")
    attempts = delta("karmada_scheduler_schedule_attempts_total"
                     '{result="scheduled",schedule_type="reconcile"}')
    lines.append(
        f"  scheduled {int(attempts)} ({_fmt_rate(attempts, window)}); "
        f"h2d binding fields "
        f"{int(delta('karmada_solver_h2d_binding_fields_total'))}")
    shed = delta('karmada_scheduler_admission_total{decision="shed"}')
    admitted = delta('karmada_scheduler_admission_total{decision="admitted"}')
    evict = sum(rec.get("delta") or 0.0 for k, rec in series.items()
                if k.startswith("karmada_rebalance_evictions_total"))
    lines.append(f"  admission admitted={int(admitted)} shed={int(shed)} "
                 f"({_fmt_rate(shed, window)}); "
                 f"rebalance evictions={int(evict)}")
    if slo_payload and slo_payload.get("enabled"):
        for obj in slo_payload.get("objectives", []):
            mark = {True: "OK ", False: "BURN", None: "n/a "}[
                obj.get("healthy")]
            lines.append(
                f"  slo [{mark}] {obj['name']}: "
                f"burn short={obj.get('burn_rate', {}).get('short')} "
                f"long={obj.get('burn_rate', {}).get('long')} "
                f"budget {obj.get('budget_remaining')}")
        watchdog = slo_payload.get("regression")
        if watchdog:
            lines.append(
                f"  regression watchdog: tripped={watchdog.get('tripped')} "
                f"live={watchdog.get('live_bps')} bindings/s "
                f"floor={watchdog.get('floor_bps')}")
    return "\n".join(lines)
