"""Lifecycle ledger: causal per-object event timelines across every plane.

The reference control plane answers "what happened to this object" with
Kubernetes Events — reasons enumerated in pkg/events/events.go, recorded
by every controller and surfaced via `kubectl describe`.  This module is
that journal grown into a first-class plane: a bounded, coalescing,
thread-safe ledger with a per-object timeline index, where every event
carries ``{type, reason, message, origin, cycle_id, trace_id,
decision_id}`` so an event is one click from its trace waterfall
(/debug/traces/{trace_id}) and its explain verdict
(/debug/explain/{ns}/{name}).

Emitters:

  * ``EventRecorder`` — the controllers' classic surface
    (``recorder.event(obj, type_, reason, message)``).  A bare
    ``EventRecorder()`` binds the PROCESS ledger, so every controller's
    events land on one unified timeline; constructing it with explicit
    ``capacity``/``now`` yields a private ledger (test isolation).
  * ``emit(ref, ...)`` / ``emit_key(key, ...)`` — module-level hot-path
    emitters for planes with no recorder handle (the admission gate, the
    chaos plane, the rebalance drain).  Disarmed cost is one list read
    (the chaos-seam contract); the ledger is ARMED by default — events
    are the reference's always-on surface, and the ledger is bounded.

Coalescing is per-timeline-tail: re-recording the tail event's exact
(type, reason, message) bumps its count/last_timestamp instead of
appending, so a hot repeated event cannot flood the ring while the
timeline stays gap-free and causally ordered.  Eviction is
globally-oldest-first, which prunes timeline HEADS — the newest history
always survives.

The clock is injectable (``set_clock``): compressed loadgen soaks point
it at their VirtualClock (loadgen/driver._install), the same way the
telemetry ring samples on the queue clock, so event timestamps order
correctly against the virtual timeline instead of wall time.

Every ``reason`` at a ``record``/``emit`` call site must be one of the
``REASON_*`` constants below — enforced by the ``event-reasons`` vet
pass (analysis/event_reasons.py), which also requires each constant to
appear in the docs/OBSERVABILITY.md reason catalog.
"""

from __future__ import annotations

import threading
import time
from collections import Counter as _Counter
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from karmada_tpu.utils.locks import VetLock
from karmada_tpu.utils.metrics import REGISTRY

TYPE_NORMAL = "Normal"
TYPE_WARNING = "Warning"

# -- the reason taxonomy ------------------------------------------------------
# pkg/events/events.go reasons used by this framework's controllers
REASON_SCHEDULE_BINDING_SUCCEED = "ScheduleBindingSucceed"
REASON_SCHEDULE_BINDING_FAILED = "ScheduleBindingFailed"
REASON_SYNC_WORKLOAD_SUCCEED = "SyncSucceed"
REASON_SYNC_WORKLOAD_FAILED = "SyncFailed"
REASON_WORK_DISPATCHING = "WorkDispatching"
REASON_TAINT_CLUSTER_SUCCEED = "TaintClusterSucceed"
REASON_UNTAINT_CLUSTER_SUCCEED = "UntaintClusterSucceed"
REASON_EVICT_WORKLOAD_FROM_CLUSTER = "EvictWorkloadFromCluster"
REASON_APPLY_POLICY_SUCCEED = "ApplyPolicySucceed"
REASON_REFLECT_STATUS_FAILED = "ReflectStatusFailed"
REASON_CLUSTER_NOT_READY = "ClusterNotReady"
REASON_CLUSTER_READY = "ClusterReady"
REASON_CLUSTER_STATUS_UNKNOWN = "ClusterStatusUnknown"
# admission gate (scheduler/queue.py)
REASON_BINDING_ENQUEUED = "BindingEnqueued"
REASON_BINDING_SHED = "BindingShed"
REASON_BINDING_DISPLACED = "BindingDisplaced"
# batch formation / overload / backend lifecycle (scheduler/service.py)
REASON_BATCH_FORMED = "BatchFormed"
REASON_OVERLOAD_ENTERED = "OverloadEntered"
REASON_OVERLOAD_EXITED = "OverloadExited"
REASON_BACKEND_DEGRADED = "BackendDegraded"
REASON_BACKEND_REARMED = "BackendRearmed"
REASON_CYCLE_FAULT = "CycleFaultContained"
# graceful eviction chain (controllers/failover.py)
REASON_EVICTION_PENDING = "EvictionPending"
REASON_EVICTION_DEFERRED = "EvictionDeferred"
REASON_EVICTION_TASK_DRAINED = "EvictionTaskDrained"
# rebalance plane (karmada_tpu/rebalance)
REASON_REBALANCE_EVICTED = "RebalanceEvicted"
REASON_EVICTION_BUDGET_DENIED = "EvictionBudgetDenied"
# FederatedHPA fast path (e2e.ControlPlane._hpa_fast_path)
REASON_HPA_FAST_PATH = "HpaFastPathPush"
# chaos plane (karmada_tpu/chaos)
REASON_CHAOS_FAULT_INJECTED = "ChaosFaultInjected"
# chaos safety auditor (chaos/audit.py) — keyed by violated invariant
REASON_SAFETY_VIOLATION = "SafetyViolation"
# incident plane (obs/incidents.py)
REASON_INCIDENT_CAPTURED = "IncidentCaptured"

REASON_SHORTLIST_FALLBACK = "ShortlistFallback"
REASON_SHORTLIST_TRUNCATE = "ShortlistTruncate"

# incremental steady-state solve (scheduler/incremental.py)
REASON_INCREMENTAL_FULL_SOLVE = "IncrementalFullSolve"
REASON_INCREMENTAL_AUDIT_MISMATCH = "IncrementalAuditMismatch"

# facade plane (karmada_tpu/facade): per-caller outcome events, stamped
# with the coalesced batch id so a caller's timeline names the shared
# device dispatch it rode
REASON_FACADE_ASSIGNED = "FacadeAssigned"
REASON_FACADE_REJECTED = "FacadeRejected"

EVENTS_TOTAL = REGISTRY.counter(
    "karmada_events_total",
    "Lifecycle-ledger events recorded (coalesced repeats count each "
    "occurrence), by event type and reason",
    ("type", "reason"),
)

EVENTS_DROPPED = REGISTRY.counter(
    "karmada_events_dropped_total",
    "Lifecycle-ledger events evicted by the capacity bound (globally "
    "oldest first — timeline heads prune, the newest history survives)",
)


@dataclass
class ObjectRef:
    kind: str = ""
    namespace: str = ""
    name: str = ""


#: the scheduler's own (cycle-level) timeline: batch formation, overload
#: transitions, backend degrade/re-arm, contained cycle faults
SCHEDULER_REF = ObjectRef(kind="Scheduler", namespace="", name="scheduler")


@dataclass
class LedgerEvent:
    """One coalesced event.  Field names keep the classic RecordedEvent
    surface (type/reason/message/count/first_timestamp/last_timestamp)
    plus the lifecycle-ledger causal links."""

    id: int
    ref: ObjectRef
    type: str = TYPE_NORMAL
    reason: str = ""
    message: str = ""
    origin: str = ""
    cycle_id: Optional[int] = None
    trace_id: Optional[str] = None
    decision_id: Optional[int] = None
    count: int = 1
    first_timestamp: float = 0.0
    last_timestamp: float = 0.0
    # monotone ACTIVITY sequence, bumped on every record touching this
    # event (coalesced repeats included) — the `?since=` watch cursor
    # filters on this, not `id`, so a storm coalescing onto one tail
    # event still surfaces in `karmadactl events --watch`
    last_seq: int = 0

    def to_dict(self) -> dict:
        return {
            "id": self.id,
            "kind": self.ref.kind,
            "namespace": self.ref.namespace,
            "name": self.ref.name,
            "type": self.type,
            "reason": self.reason,
            "message": self.message,
            "origin": self.origin,
            "cycle_id": self.cycle_id,
            "trace_id": self.trace_id,
            "decision_id": self.decision_id,
            "count": self.count,
            "first_timestamp": round(self.first_timestamp, 6),
            "last_timestamp": round(self.last_timestamp, 6),
            "last_seq": self.last_seq,
        }


def _ambient_trace_id() -> Optional[str]:
    """The enclosing flight-recorder trace id, if tracing is armed —
    the event -> waterfall link costs one contextvar read when armed,
    one attribute read when not."""
    from karmada_tpu import obs

    if not obs.TRACER.enabled:
        return None
    sp = obs.TRACER.current()
    return sp.trace.trace_id if sp is not None else None


class EventLedger:
    """Bounded, coalescing, thread-safe journal with a per-object
    timeline index."""

    def __init__(self, capacity: int = 16384,
                 now: Callable[[], float] = time.time,
                 export_metrics: bool = False) -> None:
        # only the PROCESS ledger exports karmada_events_* (configure()
        # passes True): a private recorder's traffic — bench harnesses,
        # test isolation — must not pollute the scrape surface
        self.capacity = max(1, int(capacity))
        self.now = now
        self.export_metrics = bool(export_metrics)
        self._lock = VetLock("obs.events")
        # guarded-by: _lock; mutators: record,link_decision
        self._events: Dict[int, LedgerEvent] = {}
        # guarded-by: _lock — global FIFO of event ids (eviction order)
        self._order: deque = deque()
        # guarded-by: _lock — (kind, ns, name) -> deque of event ids in
        # record order (ids ascend within a timeline)
        self._timelines: Dict[Tuple[str, str, str], deque] = {}
        self._seq = 0           # guarded-by: _lock — event ids
        self._act_seq = 0       # guarded-by: _lock — activity cursor
        self._recorded = 0      # guarded-by: _lock — record() occurrences
        self._coalesced = 0     # guarded-by: _lock — tail bumps
        self._evicted = 0       # guarded-by: _lock — capacity evictions
        self._by_reason: _Counter = _Counter()  # guarded-by: _lock

    def set_clock(self, now: Callable[[], float]) -> Callable[[], float]:
        """Repoint the ledger clock (compressed soaks pass their
        VirtualClock); returns the previous clock so callers restore."""
        prev = self.now
        self.now = now
        return prev

    # -- record --------------------------------------------------------------
    def record(self, ref, type_: str, reason: str, message: str,
               origin: str = "", cycle_id: Optional[int] = None,
               trace_id: Optional[str] = None,
               decision_id: Optional[int] = None) -> int:
        """Record one event for ``ref`` (an ObjectRef or any typed store
        object exposing KIND/namespace/name); returns the event id (the
        coalesced tail's id when the record was a repeat)."""
        if not isinstance(ref, ObjectRef):
            ref = ObjectRef(kind=ref.KIND, namespace=ref.namespace,
                            name=ref.name)
        if trace_id is None:
            trace_id = _ambient_trace_id()
        ts = self.now()
        tlkey = (ref.kind, ref.namespace, ref.name)
        with self._lock:
            self._recorded += 1
            self._act_seq += 1
            self._by_reason[reason] += 1
            timeline = self._timelines.get(tlkey)
            if timeline:
                tail = self._events[timeline[-1]]
                if (tail.type == type_ and tail.reason == reason
                        and tail.message == message):
                    # coalesce at the timeline tail: repeats bump the
                    # count, ordering stays gap-free
                    tail.count += 1
                    tail.last_timestamp = ts
                    tail.last_seq = self._act_seq
                    if cycle_id is not None:
                        tail.cycle_id = cycle_id
                    if trace_id is not None:
                        tail.trace_id = trace_id
                    self._coalesced += 1
                    eid = tail.id
                    if self.export_metrics:
                        EVENTS_TOTAL.inc(type=type_, reason=reason)
                    return eid
            self._seq += 1
            eid = self._seq
            ev = LedgerEvent(id=eid, ref=ref, type=type_, reason=reason,
                             message=message, origin=origin,
                             cycle_id=cycle_id, trace_id=trace_id,
                             decision_id=decision_id,
                             first_timestamp=ts, last_timestamp=ts,
                             last_seq=self._act_seq)
            self._events[eid] = ev
            self._order.append(eid)
            if timeline is None:
                timeline = deque()
                self._timelines[tlkey] = timeline
            timeline.append(eid)
            evicted = 0
            while len(self._order) > self.capacity:
                old_id = self._order.popleft()
                old = self._events.pop(old_id, None)
                evicted += 1
                if old is None:
                    continue
                okey = (old.ref.kind, old.ref.namespace, old.ref.name)
                tl = self._timelines.get(okey)
                if tl:
                    # ids ascend within a timeline and eviction is
                    # globally-oldest-first, so the victim is the head
                    if tl[0] == old_id:
                        tl.popleft()
                    else:  # pragma: no cover — defensive
                        try:
                            tl.remove(old_id)
                        except ValueError:
                            pass
                    if not tl:
                        self._timelines.pop(okey, None)
            self._evicted += evicted
        if self.export_metrics:
            EVENTS_TOTAL.inc(type=type_, reason=reason)
            if evicted:
                EVENTS_DROPPED.inc(evicted)
        return eid

    def link_decision(self, event_id: int, decision_id: int) -> None:
        """Stamp the explain-plane decision id onto an event (the
        scheduled/unschedulable outcome events cross-reference their
        Decision record; obs/decisions stamps the event id back)."""
        with self._lock:
            ev = self._events.get(event_id)
            if ev is not None:
                ev.decision_id = decision_id

    # -- read ----------------------------------------------------------------
    def list(self, kind: Optional[str] = None, namespace: Optional[str] = None,
             name: Optional[str] = None) -> List[LedgerEvent]:
        """Filtered events in record order (the classic recorder list)."""
        with self._lock:
            return [
                self._events[i] for i in self._order
                if (kind is None or self._events[i].ref.kind == kind)
                and (namespace is None
                     or self._events[i].ref.namespace == namespace)
                and (name is None or self._events[i].ref.name == name)
            ]

    def timeline(self, kind: str, namespace: str, name: str) -> List[dict]:
        """One object's ordered event timeline as dicts."""
        with self._lock:
            ids = list(self._timelines.get((kind, namespace, name), ()))
            return [self._events[i].to_dict() for i in ids
                    if i in self._events]

    def recent(self, n: int = 64, since: Optional[int] = None) -> List[dict]:
        """The most recent ``n`` events (record order), optionally only
        those with ACTIVITY after ``since`` (`last_seq > since` — the
        `karmadactl events --watch` cursor; a coalesced repeat bumps the
        tail event's last_seq, so a storm collapsing onto one entry
        still surfaces on every poll).  With a cursor, the OLDEST ``n``
        matches return (the client pages forward by advancing its
        cursor — returning the newest slice would skip everything the
        bound cut off, permanently); without one, the newest ``n``.
        n=0 really means zero events (the MetricRing.samples contract),
        never the whole-ring [-0:] surprise."""
        with self._lock:
            out = []
            for i in self._order:
                ev = self._events.get(i)
                if ev is None:
                    continue
                if since is not None and ev.last_seq <= since:
                    continue
                out.append(ev.to_dict())
        n = max(0, int(n))
        if n == 0:
            return []
        return out[:n] if since is not None else out[-n:]

    def counters(self) -> dict:
        """Lifetime tallies (the /debug/state `events` section and the
        soak reports' delta baseline)."""
        with self._lock:
            return {
                "recorded": self._recorded,
                "coalesced": self._coalesced,
                "evicted": self._evicted,
                # the activity cursor (last_seq high-water mark): soak
                # baselines use it to scope timeline walks to ONE run
                "seq": self._act_seq,
                "retained": len(self._order),
                "objects": len(self._timelines),
                "capacity": self.capacity,
                "by_reason": dict(self._by_reason),
            }


class EventRecorder:
    """The framework's record.EventRecorder equivalent.

    A bare ``EventRecorder()`` is a view over the PROCESS ledger (every
    controller's events land on one unified timeline and respect the
    global arm state); passing ``capacity``/``now``/``ledger`` binds a
    private ledger that always records (test isolation)."""

    def __init__(self, capacity: Optional[int] = None,
                 now: Optional[Callable[[], float]] = None,
                 ledger: Optional[EventLedger] = None) -> None:
        if ledger is not None:
            self._ledger: Optional[EventLedger] = ledger
        elif capacity is not None or now is not None:
            self._ledger = EventLedger(capacity=capacity or 16384,
                                       now=now or time.time)
        else:
            self._ledger = None  # resolve the process ledger per call

    @property
    def private(self) -> bool:
        return self._ledger is not None

    def _resolve(self) -> EventLedger:
        return self._ledger if self._ledger is not None else ledger()

    def event(self, obj, type_: str, reason: str, message: str,
              origin: str = "", cycle_id: Optional[int] = None,
              trace_id: Optional[str] = None,
              decision_id: Optional[int] = None) -> Optional[int]:
        """Record one event; returns its ledger id (None when the
        process ledger is disarmed and this recorder is the global
        view)."""
        if self._ledger is None and not _ARMED[0]:
            return None
        return self._resolve().record(
            obj, type_, reason, message, origin=origin, cycle_id=cycle_id,
            trace_id=trace_id, decision_id=decision_id)

    def link_decision(self, event_id: Optional[int],
                      decision_id: Optional[int]) -> None:
        if event_id is None or decision_id is None:
            return
        self._resolve().link_decision(event_id, decision_id)

    def list(self, kind: Optional[str] = None, namespace: Optional[str] = None,
             name: Optional[str] = None) -> List[LedgerEvent]:
        return self._resolve().list(kind=kind, namespace=namespace, name=name)


# -- the process ledger -------------------------------------------------------
# guarded by convention, not a lock: configure()/disarm() happen at test
# setup / bench install; emitters read one list cell (the chaos-plane
# pattern), so the disarmed hot path pays a single global read
_ARMED = [True]
_LEDGER: List[EventLedger] = [EventLedger(export_metrics=True)]


def ledger() -> EventLedger:
    return _LEDGER[0]


def armed() -> bool:
    return _ARMED[0]


def arm() -> None:
    _ARMED[0] = True


def disarm() -> None:
    """Stop recording through the process-ledger emitters (perf bench
    legs; private recorders are unaffected).  The retained journal stays
    readable."""
    _ARMED[0] = False


def configure(capacity: int = 16384,
              now: Callable[[], float] = time.time) -> EventLedger:
    """Install a fresh process ledger (tests wanting isolation; serve
    keeps the default).  Re-arms recording."""
    led = EventLedger(capacity=capacity, now=now, export_metrics=True)
    _LEDGER[0] = led
    _ARMED[0] = True
    return led


def set_clock(now: Callable[[], float]) -> Callable[[], float]:
    """Repoint the process ledger's clock; returns the previous clock.
    Compressed loadgen soaks pass their VirtualClock here (the same
    plumbing obs_timeseries.maybe_sample gets via the queue clock) so
    event timestamps order against the virtual timeline."""
    return _LEDGER[0].set_clock(now)


def emit(ref, type_: str, reason: str, message: str, **kw) -> Optional[int]:
    """Module-level emitter for planes with no recorder handle.  One
    list read when disarmed."""
    if not _ARMED[0]:
        return None
    return _LEDGER[0].record(ref, type_, reason, message, **kw)


def emit_key(key, type_: str, reason: str, message: str,
             **kw) -> Optional[int]:
    """``emit`` keyed by the scheduler queues' ``(namespace, name)``
    binding key."""
    if not _ARMED[0]:
        return None
    if isinstance(key, tuple) and len(key) == 2:
        ref = ObjectRef(kind="ResourceBinding", namespace=str(key[0]),
                        name=str(key[1]))
    else:
        ref = ObjectRef(kind="Object", namespace="", name=str(key))
    return _LEDGER[0].record(ref, type_, reason, message, **kw)


def state_payload(n: int = 64, since: Optional[int] = None) -> dict:
    """/debug/events: counters + per-reason tallies + the recent ring."""
    led = _LEDGER[0]
    counters = led.counters()
    return {
        "enabled": True,
        "armed": _ARMED[0],
        "stats": counters,
        "recent": led.recent(n=n, since=since),
    }


def timeline_payload(namespace: str, name: str,
                     kind: str = "ResourceBinding") -> dict:
    """/debug/events/{ns}/{name}: one object's gap-free timeline."""
    led = _LEDGER[0]
    events = led.timeline(kind, namespace, name)
    return {
        "key": f"{namespace}/{name}",
        "kind": kind,
        "events": events,
        "count": len(events),
    }
