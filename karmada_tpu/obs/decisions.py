"""Explain plane: per-binding placement Decision records.

The flight recorder (obs/trace) answers *when* a cycle ran and the
metrics registry answers *how much*; this module answers *why* — why a
binding landed on cluster Y, why it was rejected everywhere, which
spread constraint ate its replicas.  Armed via `karmadactl serve
--explain[=RATE]` / `Scheduler(explain=...)`, the batched solver emits
per-(binding, cluster) filter-verdict bitmasks, a score/capacity
breakdown, and a per-binding outcome code from a separate jit variant
(ops/solver, `dispatch_compact(explain=True)`); they are decoded here
into bounded, JSON-ready Decision dicts linked to the owning trace id
and served through /debug/explain (utils/httpserve) and `karmadactl
explain <namespace>/<binding>` (cli).

This module is the single authority for the verdict BIT LAYOUT.  Bit k
set means filter stage k REJECTED the cluster for that binding, and the
bit order IS the serial reference's first-rejection-wins plugin order
(ops/serial.FILTER_PLUGINS, then registry plugins), so the lowest set
bit of a mask equals the reason serial's diagnosis reports — the parity
contract tests/test_explain.py checks bit for bit.  Kept import-light
on purpose (no jax, no ops): the CLI renders decisions client-side.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional, Sequence

from karmada_tpu.utils.metrics import REGISTRY

# -- verdict bitmask layout ---------------------------------------------------
# Bits 0..5 mirror the serial filter chain's evaluation order; bits 6..8
# are device-path stages with no serial-diagnosis equivalent (capacity
# shortfalls surface as UnschedulableError there, deleting clusters are
# skipped, and selection trims are silent).
VERDICT_BIT_API_ENABLEMENT = 0   # APIEnablement
VERDICT_BIT_TOLERATION = 1       # TaintToleration
VERDICT_BIT_AFFINITY = 2         # ClusterAffinity
VERDICT_BIT_SPREAD_PROP = 3      # SpreadConstraint property filter
VERDICT_BIT_EVICTION = 4         # ClusterEviction (graceful eviction)
VERDICT_BIT_PLUGIN = 5           # out-of-tree registry filter
VERDICT_BIT_CAPACITY = 6         # estimator: zero replicas fit
VERDICT_BIT_NOT_SELECTED = 7     # feasible but eliminated by spread
                                 # selection / division trimming
VERDICT_BIT_CLUSTER_GONE = 8     # deleting cluster / padding lane

VERDICT_API_ENABLEMENT = 1 << VERDICT_BIT_API_ENABLEMENT
VERDICT_TOLERATION = 1 << VERDICT_BIT_TOLERATION
VERDICT_AFFINITY = 1 << VERDICT_BIT_AFFINITY
VERDICT_SPREAD_PROP = 1 << VERDICT_BIT_SPREAD_PROP
VERDICT_EVICTION = 1 << VERDICT_BIT_EVICTION
VERDICT_PLUGIN = 1 << VERDICT_BIT_PLUGIN
VERDICT_CAPACITY = 1 << VERDICT_BIT_CAPACITY
VERDICT_NOT_SELECTED = 1 << VERDICT_BIT_NOT_SELECTED
VERDICT_CLUSTER_GONE = 1 << VERDICT_BIT_CLUSTER_GONE

N_VERDICT_BITS = 9
#: the stages serial's FitError diagnosis can name (parity compares these)
VERDICT_FILTER_MASK = (VERDICT_API_ENABLEMENT | VERDICT_TOLERATION
                       | VERDICT_AFFINITY | VERDICT_SPREAD_PROP
                       | VERDICT_EVICTION | VERDICT_PLUGIN)

#: bit index -> canonical reason name (the reason taxonomy the queue's
#: unschedulable map and karmada_schedule_unschedulable_total share)
VERDICT_BIT_NAMES = (
    "api_enablement", "toleration", "affinity", "spread_property",
    "eviction", "plugin_filter", "capacity", "not_selected", "cluster_gone",
)

#: classifier-only reasons (no per-cluster bit): group-DFS shortfalls and
#: everything the heuristics cannot place
REASON_SPREAD_SELECTION = "spread_selection"
REASON_UNKNOWN = "unknown"

#: reason name -> operator-facing phrase for the kube-scheduler-style
#: one-liner ("0/5 clusters are available: 3 insufficient capacity, ...")
REASON_LABEL = {
    "api_enablement": "API not enabled",
    "toleration": "untolerated taint",
    "affinity": "affinity mismatch",
    "spread_property": "missing spread topology property",
    "eviction": "eviction in progress",
    "plugin_filter": "rejected by plugin filter",
    "capacity": "insufficient capacity",
    "not_selected": "eliminated by spread selection",
    "cluster_gone": "cluster deleting",
    REASON_SPREAD_SELECTION: "spread group selection failed",
    REASON_UNKNOWN: "unschedulable",
}

#: outcome-code low byte (ops/tensors STATUS_*) -> outcome name
OUTCOME_NAMES = {0: "scheduled", 1: "no_fit", 2: "unschedulable",
                 3: "no_cluster"}

#: per-decision cluster-table bound: assigned clusters are always kept,
#: rejected ones up to this many (full per-reason counts are always kept)
MAX_DECISION_CLUSTERS = 128

DECISIONS_TOTAL = REGISTRY.counter(
    "karmada_explain_decisions_total",
    "Explain-plane placement decisions recorded, by outcome",
    ("outcome",),
)


def first_reason(mask: int) -> Optional[str]:
    """The serial-priority reason of a verdict mask: its LOWEST set bit
    (bit order == serial first-rejection-wins order), or None when the
    cluster passed every stage."""
    if not mask:
        return None
    return VERDICT_BIT_NAMES[(mask & -mask).bit_length() - 1]


def reasons_of(mask: int) -> List[str]:
    """Every stage a verdict mask names, in priority order."""
    return [name for k, name in enumerate(VERDICT_BIT_NAMES)
            if mask & (1 << k)]


def split_outcome(code: int) -> tuple:
    """(status, dominant reason name | None) of a per-binding outcome
    code: low byte is the solver STATUS_*, bits 8+ hold 1 + the dominant
    rejection stage's bit index (0 = no rejected clusters)."""
    status = int(code) & 0xFF
    dom = int(code) >> 8
    return status, (VERDICT_BIT_NAMES[dom - 1] if dom else None)


# substring -> bit, in the order the serial filter messages are probed;
# every in-tree reason string (ops/serial.filter_*) maps here, anything
# else is an out-of-tree plugin's reason
_SERIAL_REASON_BITS = (
    ("did not have the API resource", VERDICT_BIT_API_ENABLEMENT),
    ("untolerated taint", VERDICT_BIT_TOLERATION),
    ("cluster affinity constraint", VERDICT_BIT_AFFINITY),
    ("did not have provider property", VERDICT_BIT_SPREAD_PROP),
    ("did not have region property", VERDICT_BIT_SPREAD_PROP),
    ("did not have zones property", VERDICT_BIT_SPREAD_PROP),
    ("did not have spread label", VERDICT_BIT_SPREAD_PROP),
    ("process of eviction", VERDICT_BIT_EVICTION),
)


def bit_for_serial_reason(msg: str) -> int:
    """Map one serial filter diagnosis string to its verdict bit index
    (unrecognized reasons are out-of-tree plugin rejections)."""
    for sub, bit in _SERIAL_REASON_BITS:
        if sub in msg:
            return bit
    return VERDICT_BIT_PLUGIN


def classify_unschedulable(exc: Exception) -> str:
    """Dominant reason of an UnschedulableError for the queue's
    unschedulable map and karmada_schedule_unschedulable_total.  An
    explain-armed decode attaches the solver's dominant reason as
    `exc.reason`; otherwise the known message shapes classify."""
    r = getattr(exc, "reason", None)
    if r:
        return str(r)
    msg = str(exc)
    # the capacity shapes: the device/native decodes ("insufficient
    # capacity (batched|native)"), the serial selection swap-loop ("no
    # enough resource when selecting N clusters"), and the serial
    # divider ("Clusters available replicas N are not enough to
    # schedule.", ops/serial._dynamic_divide)
    if ("insufficient capacity" in msg or "no enough resource" in msg
            or "not enough to schedule" in msg):
        return "capacity"
    if "MinGroups" in msg or "spread" in msg.lower():
        return REASON_SPREAD_SELECTION
    return REASON_UNKNOWN


class DecisionRecorder:
    """Bounded storage for Decision dicts, mirroring obs/recorder: a ring
    of the most recent `capacity` decisions plus an always-retained shelf
    of the latest unschedulable/no-fit decision per binding (bounded to
    `unsched_keep` bindings, oldest evicted) — the decision an operator
    actually wants (why is X still pending?) survives a ring full of
    healthy scheduled ones.  Truncation is never silent (`dropped`)."""

    def __init__(self, capacity: int = 256, unsched_keep: int = 64) -> None:
        self.capacity = max(1, int(capacity))
        self.unsched_keep = max(0, int(unsched_keep))
        # guarded-by: _lock
        self._ring: "collections.deque[dict]" = collections.deque(
            maxlen=self.capacity)
        # guarded-by: _lock (key -> latest failed decision, insertion order)
        self._failed: "collections.OrderedDict[str, dict]" = \
            collections.OrderedDict()
        self._dropped = 0  # guarded-by: _lock
        self._next_id = 0  # guarded-by: _lock — per-recorder Decision ids
        self._lock = threading.Lock()

    def record(self, decision: dict) -> None:
        with self._lock:
            self._next_id += 1
            # the Decision's identity for the lifecycle ledger's
            # cross-reference (events carry decision_id, decisions carry
            # event_id once the outcome event links back)
            decision["id"] = self._next_id
            if len(self._ring) == self.capacity:
                self._dropped += 1
            self._ring.append(decision)
            if self.unsched_keep and decision["outcome"] != "scheduled":
                self._failed.pop(decision["key"], None)
                self._failed[decision["key"]] = decision
                while len(self._failed) > self.unsched_keep:
                    self._failed.popitem(last=False)
        DECISIONS_TOTAL.inc(outcome=decision["outcome"])

    def recent(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def unschedulable(self) -> List[dict]:
        """Newest-first shelf of the latest failed decision per binding."""
        with self._lock:
            return list(reversed(self._failed.values()))

    def get(self, key: str) -> Optional[dict]:
        """The most recent decision for one `namespace/name` binding."""
        with self._lock:
            for d in reversed(self._ring):
                if d["key"] == key:
                    return d
            return self._failed.get(key)

    def link_event(self, key: str, event_id: int) -> Optional[int]:
        """Stamp the lifecycle-ledger event id onto the latest decision
        for `key`; returns that decision's id so the caller can stamp it
        back onto the event (obs/events.link_decision) — the timeline
        and /debug/explain/{ns}/{name} then cross-reference."""
        with self._lock:
            target = None
            for d in reversed(self._ring):
                if d["key"] == key:
                    target = d
                    break
            if target is None:
                target = self._failed.get(key)
            if target is None:
                return None
            target["event_id"] = event_id
            return target.get("id")

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def stats(self) -> dict:
        with self._lock:
            by_reason: Dict[str, int] = {}
            for d in self._failed.values():
                r = d.get("reason") or REASON_UNKNOWN
                by_reason[r] = by_reason.get(r, 0) + 1
            return {"recent": len(self._ring), "capacity": self.capacity,
                    "unschedulable_kept": len(self._failed),
                    "unschedulable_by_reason": by_reason,
                    "dropped": self._dropped}


# the process-wide recorder `serve --explain` arms (None = disarmed); the
# list cell keeps reads race-free without a lock
_RECORDER: List[Optional[DecisionRecorder]] = [None]


def configure(capacity: int = 256, unsched_keep: int = 64,
              recorder: Optional[DecisionRecorder] = None) -> DecisionRecorder:
    """Arm the process-wide decision ring (idempotent: an already-armed
    recorder is kept unless an explicit one is injected)."""
    if recorder is not None:
        _RECORDER[0] = recorder
    elif _RECORDER[0] is None:
        _RECORDER[0] = DecisionRecorder(capacity=capacity,
                                        unsched_keep=unsched_keep)
    return _RECORDER[0]


def disable() -> None:
    _RECORDER[0] = None


def recorder() -> Optional[DecisionRecorder]:
    return _RECORDER[0]


# -- decision builders --------------------------------------------------------


def _one_liner(outcome: str, reason_counts: Dict[str, int], n_clusters: int,
               targets: Sequence) -> str:
    """The kube-scheduler-style summary line."""
    if outcome == "scheduled":
        where = ", ".join(f"{t['name']}({t['replicas']})" for t in targets)
        return (f"scheduled to {len(targets)}/{n_clusters} cluster(s)"
                + (f": {where}" if where else ""))
    parts = [f"{n} {REASON_LABEL.get(r, r)}"
             for r, n in sorted(reason_counts.items(),
                                key=lambda kv: (-kv[1], kv[0]))]
    detail = "; ".join(parts) if parts else REASON_LABEL.get(outcome, outcome)
    return f"0/{n_clusters} clusters are available: {detail}."


def _base(key: str, outcome: str, reason: Optional[str],
          trace_id: Optional[str], backend: str) -> dict:
    return {"key": key, "outcome": outcome, "reason": reason,
            "trace_id": trace_id, "backend": backend,
            "ts": round(time.time(), 3)}


def decision_from_planes(
    key: str,
    cluster_names: Sequence[str],
    verdict_row,
    score_row,
    avail_row,
    outcome_code: int,
    result,
    trace_id: Optional[str] = None,
    backend: str = "device",
    static_w_row=None,
    plugin_row=None,
) -> dict:
    """One binding's Decision from the solver's dense explain planes.

    `result` is the decoded List[TargetCluster] | Exception for the row;
    the per-cluster table is bounded (MAX_DECISION_CLUSTERS) but the
    per-reason rejection counts always cover the whole fleet."""
    status, dom = split_outcome(int(outcome_code))
    outcome = OUTCOME_NAMES.get(status, str(status))
    targets = ([] if isinstance(result, Exception) or result is None
               else [{"name": t.name, "replicas": t.replicas}
                     for t in result])
    by_name = {t["name"]: t["replicas"] for t in targets}
    reason_counts: Dict[str, int] = {}
    rows: List[dict] = []
    omitted = 0
    for i, name in enumerate(cluster_names):
        mask = int(verdict_row[i])
        r = first_reason(mask)
        if r is not None:
            reason_counts[r] = reason_counts.get(r, 0) + 1
        row = {"name": name, "verdict": mask,
               "reasons": reasons_of(mask),
               "score": int(score_row[i]) if score_row is not None else None,
               "avail": int(avail_row[i]) if avail_row is not None else None,
               "replicas": by_name.get(name, 0)}
        if static_w_row is not None:
            row["static_weight"] = int(static_w_row[i])
        if plugin_row is not None:
            row["plugin_score"] = int(plugin_row[i])
        rows.append(row)
    if len(rows) > MAX_DECISION_CLUSTERS:
        # assigned/feasible clusters always make the table; rejected ones
        # fill the remaining budget (big fleets: the per-reason counts
        # stay exact, only rows truncate)
        keep = [r for r in rows if r["replicas"] > 0 or r["verdict"] == 0]
        rest = [r for r in rows if not (r["replicas"] > 0 or r["verdict"] == 0)]
        budget = max(MAX_DECISION_CLUSTERS - len(keep), 0)
        omitted = max(len(rest) - budget, 0)
        rows = keep + rest[:budget]
    d = _base(key, outcome, dom, trace_id, backend)
    d.update({
        "status": status,
        "clusters": rows,
        "clusters_total": len(cluster_names),
        "clusters_omitted": omitted,
        "reason_counts": reason_counts,
        "targets": targets,
        "message": _one_liner(outcome, reason_counts, len(cluster_names),
                              targets),
    })
    return d


def decision_from_result(key: str, result, n_clusters: int,
                         trace_id: Optional[str] = None,
                         backend: str = "device",
                         diagnosis: Optional[Dict[str, str]] = None) -> dict:
    """Outcome-level Decision for rows without dense explain planes (big
    lane tier, spread group-DFS failures, the serial host path).  A
    FitError's per-cluster diagnosis maps onto the same verdict bitmask
    (bit_for_serial_reason), so serial decisions stay parity-comparable."""
    diagnosis = diagnosis if diagnosis is not None else \
        getattr(result, "diagnosis", None)
    reason_counts: Dict[str, int] = {}
    rows: List[dict] = []
    if isinstance(result, Exception):
        exc_name = type(result).__name__
        if "FitError" in exc_name:
            outcome, status = "no_fit", 1
        elif "NoClusterAvailable" in exc_name:
            outcome, status = "no_cluster", 3
        else:
            outcome, status = "unschedulable", 2
        targets: List[dict] = []
        if diagnosis:
            for name, msg in diagnosis.items():
                bit = bit_for_serial_reason(msg)
                r = VERDICT_BIT_NAMES[bit]
                reason_counts[r] = reason_counts.get(r, 0) + 1
                if len(rows) < MAX_DECISION_CLUSTERS:
                    rows.append({"name": name, "verdict": 1 << bit,
                                 "reasons": [r], "detail": msg,
                                 "replicas": 0})
        reason = (classify_unschedulable(result) if outcome == "unschedulable"
                  else (max(reason_counts, key=reason_counts.get)
                        if reason_counts else None))
    else:
        outcome, status, reason = "scheduled", 0, None
        targets = [{"name": t.name, "replicas": t.replicas}
                   for t in (result or [])]
        rows = [{"name": t["name"], "verdict": 0, "reasons": [],
                 "replicas": t["replicas"]} for t in targets]
    d = _base(key, outcome, reason, trace_id, backend)
    d.update({
        "status": status,
        "clusters": rows,
        "clusters_total": n_clusters,
        "clusters_omitted": max((len(diagnosis) if diagnosis else 0)
                                - len(rows), 0) if isinstance(result, Exception)
        else 0,
        "reason_counts": reason_counts,
        "targets": targets,
        "message": (str(result) if isinstance(result, Exception)
                    else _one_liner(outcome, reason_counts, n_clusters,
                                    targets)),
    })
    return d


def default_key(spec) -> str:
    """The `namespace/name` identity of a binding spec's workload — used
    when the caller (bench) has no ResourceBinding names to offer."""
    ref = spec.resource
    return f"{ref.namespace or 'default'}/{ref.name}"
