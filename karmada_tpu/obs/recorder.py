"""Bounded storage for finished traces.

Two retention tiers, both bounded so a long-lived serve process can leave
tracing on indefinitely:

  * a ring of the most recent `capacity` traces (deque append/evict under
    one short lock — "lock-free-ish": record() never blocks on readers
    longer than a list copy), and
  * a "slowest N" shelf that always retains the worst cycles ever seen,
    so the trace an operator actually wants (the 30 s outlier from last
    night) survives a ring full of healthy 10 ms cycles.

Truncation is never silent: every ring eviction increments `dropped`,
exported through stats() into /debug/state and /debug/traces.
"""

from __future__ import annotations

import collections
import threading

from karmada_tpu.utils.locks import VetLock
from typing import List, Optional


class TraceRecorder:
    def __init__(self, capacity: int = 256, slow_keep: int = 8) -> None:
        self.capacity = max(1, int(capacity))
        self.slow_keep = max(0, int(slow_keep))
        # guarded-by: _lock
        self._ring: "collections.deque[dict]" = collections.deque(
            maxlen=self.capacity)
        # guarded-by: _lock (ascending duration; [0] is fastest)
        self._slow: List[dict] = []
        self._dropped = 0  # guarded-by: _lock
        self._lock = VetLock("obs.recorder")

    def record(self, trace: dict) -> None:
        with self._lock:
            if len(self._ring) == self.capacity:
                self._dropped += 1  # counted eviction, never silent
            self._ring.append(trace)
            if self.slow_keep:
                self._slow.append(trace)
                self._slow.sort(key=lambda t: t["duration_s"])
                if len(self._slow) > self.slow_keep:
                    del self._slow[0]

    def recent(self) -> List[dict]:
        """Oldest-first list of the retained ring."""
        with self._lock:
            return list(self._ring)

    def slowest(self) -> List[dict]:
        """Slowest-first list of the always-retained shelf."""
        with self._lock:
            return list(reversed(self._slow))

    def get(self, trace_id: str) -> Optional[dict]:
        with self._lock:
            for tr in reversed(self._ring):
                if tr["trace_id"] == trace_id:
                    return tr
            for tr in self._slow:
                if tr["trace_id"] == trace_id:
                    return tr
        return None

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def stats(self) -> dict:
        with self._lock:
            return {"recent": len(self._ring), "capacity": self.capacity,
                    "slow_kept": len(self._slow),
                    "slow_keep": self.slow_keep, "dropped": self._dropped}
