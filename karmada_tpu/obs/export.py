"""Trace export: JSON payloads, the text waterfall, stage aggregates.

Traces arrive here as the plain dicts Trace finalization produced (see
obs/trace.py) — everything is already JSON-able; this module only shapes
and renders.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional


def summarize(trace: dict) -> dict:
    """One list row for /debug/traces and `karmadactl trace`."""
    return {
        "trace_id": trace["trace_id"],
        "root": trace["root"],
        "start_unix": trace["start_unix"],
        "duration_ms": round(trace["duration_s"] * 1e3, 3),
        "spans": len(trace["spans"]),
        "cancelled": trace["cancelled"],
    }


def to_json(trace: dict, indent: Optional[int] = None) -> str:
    return json.dumps(trace, indent=indent, default=str)


def stage_summary(trace: dict, prefix: str = "pipeline.") -> Dict[str, dict]:
    """Aggregate a trace's spans by name (default: the pipeline stage
    spans): count / total / max seconds per stage.  This is what the
    bench embeds into BENCH_*.json so a perf regression can be attributed
    to a stage, not just a total."""
    agg: Dict[str, dict] = {}
    for s in trace["spans"]:
        if prefix and not s["name"].startswith(prefix):
            continue
        d = s["end_s"] - s["start_s"]
        a = agg.setdefault(s["name"], {"count": 0, "total_s": 0.0,
                                       "max_s": 0.0})
        a["count"] += 1
        a["total_s"] += d
        a["max_s"] = max(a["max_s"], d)
    for a in agg.values():
        a["total_s"] = round(a["total_s"], 6)
        a["max_s"] = round(a["max_s"], 6)
    return agg


def latest_pipeline_timeline(recorder, root: str = "pipeline.cycle"
                             ) -> Optional[dict]:
    """The most recent trace containing a `root` span, reduced to its
    per-stage timeline (bench payload helper)."""
    if recorder is None:
        return None
    for tr in reversed(recorder.recent()):
        if tr["root"] == root or any(s["name"] == root
                                     for s in tr["spans"]):
            return {
                "trace_id": tr["trace_id"],
                "duration_s": round(tr["duration_s"], 6),
                "cancelled": tr["cancelled"],
                "stages": stage_summary(tr),
            }
    return None


def _fmt_attrs(attrs: dict, limit: int = 3) -> str:
    shown = []
    for k, v in attrs.items():
        if isinstance(v, float):
            v = round(v, 4)
        shown.append(f"{k}={v}")
        if len(shown) >= limit:
            break
    return " ".join(shown)


def render_waterfall(trace: dict, width: int = 48,
                     label_width: int = 26) -> str:
    """Text waterfall of one trace: spans in tree order, each with a bar
    positioned on the shared [0, duration] timeline.  Overlap is visible
    directly — under the pipelined executor, chunk k+1's encode bar sits
    INSIDE chunk k's bar (host encode hiding behind device solve)."""
    spans = trace["spans"]
    dur = max(trace["duration_s"], 1e-9)
    children: Dict[Optional[int], List[dict]] = {}
    for s in spans:
        children.setdefault(s["parent_id"], []).append(s)
    for kids in children.values():
        kids.sort(key=lambda s: (s["start_s"], s["span_id"]))

    lines = [
        f"trace {trace['trace_id']} root={trace['root']} "
        f"duration={dur * 1e3:.2f}ms spans={len(spans)} "
        f"cancelled={trace['cancelled']}"
    ]

    emitted = set()

    def emit(s: dict, depth: int) -> None:
        if s["span_id"] in emitted:
            return
        emitted.add(s["span_id"])
        lo = int(round(s["start_s"] / dur * width))
        hi = int(round(s["end_s"] / dur * width))
        hi = min(max(hi, lo + 1), width)
        bar = " " * lo + "#" * (hi - lo) + " " * (width - hi)
        label = ("  " * depth + s["name"])[:label_width].ljust(label_width)
        ms = (s["end_s"] - s["start_s"]) * 1e3
        extra = _fmt_attrs(s["attrs"])
        lines.append(f"{label} |{bar}| {ms:9.3f}ms"
                     + (f"  {extra}" if extra else ""))
        for kid in children.get(s["span_id"], []):
            emit(kid, depth + 1)

    for root in children.get(None, []):
        emit(root, 0)
    # orphans (parent record missing): render flat so nothing hides
    for s in spans:
        emit(s, 0)
    return "\n".join(lines)
