/* Native decode hot loop (ops/tensors.decode_compact).
 *
 * Sibling of encode_fast.c, one tier deeper: where encode_fast.c's
 * decode_fast helper consumed PRE-SPLIT row bounds and skipped wide rows,
 * this extension consumes the raw d2h COO triple exactly as
 * ops/solver.finalize_compact hands it over — int32 idx/val planes
 * (ascending row-major, -1 fill) plus the int32 status plane, ideally as
 * zero-copy dlpack views of the jit outputs — performs the row split
 * natively, and builds every per-binding TargetCluster list in one pass:
 *
 *   - rows are rank-sorted natively (insertion sort for narrow rows,
 *     qsort on packed (rank << 32 | pos) keys for wide Duplicated /
 *     full-fleet rows the old path punted to Python's timsort);
 *   - TargetCluster instances are constructed via cls.__new__(cls) +
 *     setattr, skipping the dataclass __init__ Python frame that
 *     dominated the old decode (~5us/object measured);
 *   - with the explain plane armed, the outcome verdict plane rides the
 *     same pass: the dominant rejection reason is attached to the error
 *     objects Python pre-filled (`exc.reason`, obs/decisions bit layout).
 *
 * Behavior is defined by ONE implementation: the Python loop in
 * tensors.decode_compact; a parity fuzz test asserts bit-exact results
 * and the Python path remains the fallback when this extension is
 * absent.  ABI dtypes are declared in ops/tensors.NATIVE_ABI_DTYPES and
 * checked by the dtype-contract vet pass.
 *
 * Build: gcc -O2 -shared -fPIC -I<python-include> (native/__init__.py).
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <stdlib.h>

static PyObject *s_name, *s_replicas, *s_new, *s_reason;
static PyObject *empty_args; /* cached () for direct tp_new calls */

/* packed sort key: (name rank << 32) | row position — unique positions
 * make the order total, so qsort needs no stability */
static int cmp_i64(const void *a, const void *b) {
  int64_t x = *(const int64_t *)a, y = *(const int64_t *)b;
  return (x > y) - (x < y);
}

/* decode_coo(idx, val, status, C, n_clusters, name_rank, names,
 *            non_workload, empty_prop, tc_type, out[, outcome,
 *            reason_names])
 *
 * idx/val/status: int32 buffers (read-only views accepted); idx is the
 * flat binding*C+cluster index plane, -1 fill, ascending among its >= 0
 * in-range entries (row-major — solver._compact_of's contract).
 * name_rank: int64[C] ascending-name permutation.  names: list[str].
 * non_workload: uint8[>= nb].  out: list[nb] whose non-None slots
 * (Python's pre-filled error objects) are left alone; every None slot is
 * filled with a name-sorted List[TargetCluster].  outcome (optional):
 * int32[>= nb] explain outcome plane — rows whose `out` slot is an
 * exception get reason_names[(outcome >> 8) - 1] attached as `.reason`.
 *
 * Returns the number of rows built natively, or -1 when the input
 * violates the ascending contract (caller falls back to the Python
 * path, which owns the diagnostic assert).
 */
static PyObject *decode_coo(PyObject *self, PyObject *args) {
  PyObject *a_idx, *a_val, *a_status, *a_rank, *names, *a_nw;
  PyObject *tc_type, *out, *a_outcome = Py_None, *reason_names = Py_None;
  long C = 0, n_clusters = 0;
  int empty_prop = 0;
  if (!PyArg_ParseTuple(args, "OOOllOOOpOO|OO", &a_idx, &a_val, &a_status,
                        &C, &n_clusters, &a_rank, &names, &a_nw,
                        &empty_prop, &tc_type, &out, &a_outcome,
                        &reason_names))
    return NULL;
  if (C <= 0) {
    PyErr_SetString(PyExc_ValueError, "decode_coo: C must be positive");
    return NULL;
  }

  Py_buffer b_idx, b_val, b_status, b_rank, b_nw, b_outcome;
  memset(&b_outcome, 0, sizeof(b_outcome));
  int have_outcome = (a_outcome != Py_None && reason_names != Py_None);
  if (PyObject_GetBuffer(a_idx, &b_idx, PyBUF_SIMPLE) < 0) return NULL;
  if (PyObject_GetBuffer(a_val, &b_val, PyBUF_SIMPLE) < 0) goto fail1;
  if (PyObject_GetBuffer(a_status, &b_status, PyBUF_SIMPLE) < 0) goto fail2;
  if (PyObject_GetBuffer(a_rank, &b_rank, PyBUF_SIMPLE) < 0) goto fail3;
  if (PyObject_GetBuffer(a_nw, &b_nw, PyBUF_SIMPLE) < 0) goto fail4;
  if (have_outcome &&
      PyObject_GetBuffer(a_outcome, &b_outcome, PyBUF_SIMPLE) < 0)
    goto fail5;

  const int32_t *idx = (const int32_t *)b_idx.buf;
  const int32_t *val = (const int32_t *)b_val.buf;
  const int32_t *status = (const int32_t *)b_status.buf;
  const int64_t *rank = (const int64_t *)b_rank.buf;
  const uint8_t *nw = (const uint8_t *)b_nw.buf;
  const int32_t *outcome = have_outcome ? (const int32_t *)b_outcome.buf
                                        : NULL;
  Py_ssize_t n_entries = b_idx.len / (Py_ssize_t)sizeof(int32_t);
  Py_ssize_t nb = PyList_GET_SIZE(out);

  PyObject *new_func = NULL, *result = NULL;
  int64_t *row = NULL;      /* packed (rank << 32 | pos) keys */
  int32_t *row_c = NULL, *row_v = NULL;
  Py_ssize_t row_cap = 256;
  Py_ssize_t handled = 0;

  /* direct tp_new when the class keeps object.__new__ (the Python side
   * guards with tc_new_is_plain()); the attr call is the general path */
  PyTypeObject *tp = PyType_Check(tc_type) ? (PyTypeObject *)tc_type : NULL;
  int direct_new = (tp != NULL && tp->tp_new != NULL);
  if (!direct_new) {
    new_func = PyObject_GetAttr(tc_type, s_new);
    if (new_func == NULL) goto done;
  }
  row = (int64_t *)PyMem_Malloc(sizeof(int64_t) * (size_t)row_cap);
  row_c = (int32_t *)PyMem_Malloc(sizeof(int32_t) * (size_t)row_cap);
  row_v = (int32_t *)PyMem_Malloc(sizeof(int32_t) * (size_t)row_cap);
  if (row == NULL || row_c == NULL || row_v == NULL) {
    PyErr_NoMemory();
    goto done;
  }

  Py_ssize_t e = 0;
  int64_t prev_b = -1;
  for (Py_ssize_t b = 0; b < nb; b++) {
    /* gather row b's in-range entries (rows are contiguous: ascending) */
    Py_ssize_t m = 0;
    while (e < n_entries) {
      int32_t ix = idx[e];
      if (ix < 0) {
        e++;
        continue; /* extraction-cap fill */
      }
      int64_t bb = (int64_t)ix / C;
      int64_t cc = (int64_t)ix - bb * C;
      if (cc >= n_clusters) {
        e++;
        continue; /* padded cluster lane: dropped before the order check */
      }
      if (bb >= nb) {
        e = n_entries; /* padded binding rows: nothing real follows */
        break;
      }
      if (bb < prev_b) {
        handled = -1; /* ascending contract violated: Python's assert owns */
        goto build_result;
      }
      if (bb > b) break; /* row finished (possibly empty rows to fill) */
      prev_b = bb;
      if (m == row_cap) {
        Py_ssize_t cap2 = row_cap * 2;
        int64_t *r2 = (int64_t *)PyMem_Realloc(
            row, sizeof(int64_t) * (size_t)cap2);
        int32_t *c2 = (int32_t *)PyMem_Realloc(
            row_c, sizeof(int32_t) * (size_t)cap2);
        int32_t *v2 = (int32_t *)PyMem_Realloc(
            row_v, sizeof(int32_t) * (size_t)cap2);
        if (r2) row = r2;
        if (c2) row_c = c2;
        if (v2) row_v = v2;
        if (!r2 || !c2 || !v2) {
          PyErr_NoMemory();
          goto done;
        }
        row_cap = cap2;
      }
      row[m] = ((int64_t)rank[cc] << 32) | (int64_t)m;
      row_c[m] = (int32_t)cc;
      row_v[m] = val[e];
      m++;
      e++;
    }

    if (have_outcome && PyList_GET_ITEM(out, b) != Py_None) {
      /* explain plane: attach the dominant rejection reason to the
       * pre-filled error object (obs/decisions split_outcome layout:
       * bits 8+ hold 1 + the dominant stage's bit index) */
      int64_t dom = (int64_t)outcome[b] >> 8;
      PyObject *slot = PyList_GET_ITEM(out, b); /* borrowed */
      if (dom > 0 && dom <= PySequence_Length(reason_names) &&
          PyObject_IsInstance(slot, PyExc_Exception)) {
        PyObject *nm = PySequence_GetItem(reason_names, dom - 1);
        if (nm == NULL) goto done;
        int rc = PyObject_SetAttr(slot, s_reason, nm);
        Py_DECREF(nm);
        if (rc < 0) goto done;
      }
    }
    if (PyList_GET_ITEM(out, b) != Py_None) continue; /* error: Python's */

    /* rank-sort the row: tiny rows insertion-sort, wide rows qsort */
    if (m <= 32) {
      for (Py_ssize_t j = 1; j < m; j++) {
        int64_t key = row[j];
        Py_ssize_t i = j - 1;
        while (i >= 0 && row[i] > key) {
          row[i + 1] = row[i];
          i--;
        }
        row[i + 1] = key;
      }
    } else {
      qsort(row, (size_t)m, sizeof(int64_t), cmp_i64);
    }

    PyObject *targets = PyList_New(0);
    if (targets == NULL) goto done;
    int is_nw = nw[b];
    int32_t st = status[b];
    (void)st; /* status only gates via the pre-filled error slots */
    for (Py_ssize_t j = 0; j < m; j++) {
      Py_ssize_t pos = (Py_ssize_t)(row[j] & 0xFFFFFFFF);
      int32_t cc = row_c[pos];
      int32_t v = row_v[pos];
      long out_rep;
      if (is_nw) {
        out_rep = 0;
      } else if (v > 0) {
        out_rep = (long)v;
      } else if (empty_prop && v == 0) {
        out_rep = 0;
      } else {
        continue;
      }
      /* cls.__new__(cls) + setattr: identical instance to the dataclass
       * __init__ (which only assigns these two fields) without its
       * Python frame — the parity fuzz gate guards this equivalence */
      PyObject *tc = direct_new
          ? tp->tp_new(tp, empty_args, NULL)
          : PyObject_CallFunctionObjArgs(new_func, tc_type, NULL);
      if (tc == NULL) {
        Py_DECREF(targets);
        goto done;
      }
      PyObject *rep = PyLong_FromLong(out_rep);
      if (rep == NULL ||
          PyObject_SetAttr(tc, s_name, PyList_GET_ITEM(names, cc)) < 0 ||
          PyObject_SetAttr(tc, s_replicas, rep) < 0 ||
          PyList_Append(targets, tc) < 0) {
        Py_XDECREF(rep);
        Py_DECREF(tc);
        Py_DECREF(targets);
        goto done;
      }
      Py_DECREF(rep);
      Py_DECREF(tc);
    }
    if (PyList_SetItem(out, b, targets) < 0) goto done; /* steals targets */
    handled++;
  }

build_result:
  result = PyLong_FromSsize_t(handled);

done:
  PyMem_Free(row_v);
  PyMem_Free(row_c);
  PyMem_Free(row);
  Py_XDECREF(new_func);
  if (have_outcome) PyBuffer_Release(&b_outcome);
fail5:
  PyBuffer_Release(&b_nw);
fail4:
  PyBuffer_Release(&b_rank);
fail3:
  PyBuffer_Release(&b_status);
fail2:
  PyBuffer_Release(&b_val);
fail1:
  PyBuffer_Release(&b_idx);
  return result; /* NULL when an exception is set */
}

static PyMethodDef methods[] = {
    {"decode_coo", decode_coo, METH_VARARGS,
     "Native COO decode: row split + rank-sorted TargetCluster lists."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef module = {
    PyModuleDef_HEAD_INIT, "_decode_fast", NULL, -1, methods,
};

PyMODINIT_FUNC PyInit__decode_fast(void) {
  s_name = PyUnicode_InternFromString("name");
  s_replicas = PyUnicode_InternFromString("replicas");
  s_new = PyUnicode_InternFromString("__new__");
  s_reason = PyUnicode_InternFromString("reason");
  empty_args = PyTuple_New(0);
  return PyModule_Create(&module);
}
