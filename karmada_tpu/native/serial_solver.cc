// Native serial scheduling control — C++ implementation of the reference
// scheduler's algorithmic core, mirroring ops/serial.py step for step:
//
//     findClustersThatFit -> prioritizeClusters -> SelectClusters -> AssignReplicas
//     (reference pkg/scheduler/core/generic_scheduler.go:71-116)
//
// Purpose: BASELINE.md's >=50x north star is measured against a *Go-equivalent*
// serial path.  The Python control in ops/serial.py understates that bar by the
// Python/Go gap; this -O2 compiled control is the honest stand-in.  bench.py
// uses it for the serial throughput number when the shared library builds.
//
// Scope (exactly the classes ops/serial.py supports on the summary path):
//   * filters: APIEnablement / TaintToleration / ClusterAffinity /
//     SpreadConstraint / ClusterEviction (placement-level predicates arrive
//     precomputed as per-placement reason masks — snapshot-side data, same
//     amortization the device path's EncoderCache performs)
//   * score: ClusterLocality
//   * capacity: GeneralEstimator summary math
//     (pkg/estimator/client/general.go:56-94,294-334)
//   * grouping + selection: cluster sort, region group scores, the
//     findFeasiblePaths DFS (pkg/scheduler/core/spreadconstraint/select_groups.go:102-230),
//     select-by-cluster swap loop (select_clusters_by_cluster.go:25-105)
//   * assignment: Duplicated / StaticWeight / DynamicWeight / Aggregated with
//     Steady scale-up/down and Fresh modes (assignment.go, division_algorithm.go)
//     over the quantized-integer Webster dispenser (ops/webster.py semantics,
//     reference pkg/util/helper/webstermethod.go:112).
//
// Out of scope (callers mark such bindings unsupported before the call):
// resource-model histograms, multi-component sets, weights >= 2^31.
//
// Build: g++ -O2 -shared -fPIC (see karmada_tpu/native/__init__.py).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <unordered_map>
#include <vector>

namespace {

constexpr int64_t kMaxInt32 = 2147483647LL;
constexpr int kPriorityQBits = 28;  // ops/webster.py PRIORITY_QBITS

// status codes (mirrors the wrapper's STATUS_* constants)
constexpr int32_t kOk = 0;
constexpr int32_t kFitError = 1;
constexpr int32_t kUnschedulable = 2;
constexpr int32_t kNoClusterAvailable = 3;
constexpr int32_t kUnsupported = 4;
constexpr int32_t kOutputOverflow = 5;

// strategy enum (wrapper STRATEGY_*)
constexpr int32_t kDuplicated = 0;
constexpr int32_t kStaticWeight = 1;
constexpr int32_t kDynamicWeight = 2;
constexpr int32_t kAggregated = 3;

// spread field enum (wrapper FIELD_*)
constexpr int32_t kFieldNone = -1;
constexpr int32_t kFieldCluster = 0;
constexpr int32_t kFieldRegion = 1;

constexpr int kWeightUnit = 1000;  // spreadconstraint/group_clusters.go:139
constexpr int64_t kInvalidReplicas = -1;

// Python floor division (rounds toward negative infinity).
inline int64_t py_floordiv(int64_t a, int64_t b) {
  int64_t q = a / b;
  if ((a % b != 0) && ((a < 0) != (b < 0))) --q;
  return q;
}

// k8s Quantity.Value(): whole units rounded up == -((-m) // 1000) in Python.
inline int64_t ceil_units(int64_t milli) { return -py_floordiv(-milli, 1000); }

struct Snapshot {
  int32_t nC, nR, nG, nP, nQ;
  const int32_t* name_rank;
  const uint8_t* deleting;
  const uint8_t* has_summary;
  const int32_t* region_id;      // -1 == none
  const int32_t* region_rank;    // [n_regions] lexicographic rank of region name
  int32_t n_regions;
  const int64_t* pods_allowed;   // [C]
  const uint8_t* res_is_cpu;     // [R]
  const int64_t* avail_milli;    // [C*R]; <0 covers both missing + exhausted
  const uint8_t* gvk_enabled;    // [G*C]
  const uint8_t* p_taint;        // [P*C] untolerated NoSchedule/NoExecute taint
  const uint8_t* p_reason;       // [P*C] 0 pass / 1 affinity / 3 spread-field
  const int32_t* p_strategy;     // [P]
  const uint8_t* p_ignore_spread;  // [P] should_ignore_spread_constraint
  const uint8_t* p_has_weights;  // [P]
  const int64_t* p_weights;      // [P*C]
  const int32_t* p_spread;       // [P*6] field,min,max x2
  const int64_t* p_extra_score;  // [P*C] out-of-tree plugin score sums
};

struct Binding {
  int32_t placement, gvk, klass;
  int64_t replicas;
  bool fresh, uid_desc, workload, zero_shortcut;
  const int32_t* prev_idx;
  const int64_t* prev_val;
  int32_t n_prev;
  const int32_t* evict_idx;
  int32_t n_evict;
};

struct ClusterDetail {  // serial.py ClusterDetailInfo
  int32_t idx;
  int64_t score;
  int64_t available;    // estimator output + previously-assigned replicas
  int64_t allocatable;  // estimator output alone
};

struct Target {
  int32_t idx;
  int64_t replicas;
};

// ---------------------------------------------------------------------------
// Webster (Sainte-Lague) dispenser — ops/webster.py allocate_webster_seats
// ---------------------------------------------------------------------------

struct HeapEntry {
  int64_t prio;
  int64_t seats;
  int32_t rank;   // lexicographic name rank
  int32_t party;  // index into the parties vector
};

inline int64_t priority_quantized(int64_t votes, int64_t seats) {
  int64_t v = votes < 0 ? 0 : votes;
  return (v << kPriorityQBits) / (2 * seats + 1);
}

// `true` when a should pop AFTER b (a is worse): max-heap on
// (prio asc-inverted, seats desc-inverted, name order).
struct HeapWorse {
  bool desc;
  bool operator()(const HeapEntry& a, const HeapEntry& b) const {
    if (a.prio != b.prio) return a.prio < b.prio;
    if (a.seats != b.seats) return a.seats > b.seats;
    return desc ? a.rank < b.rank : a.rank > b.rank;
  }
};

// Allocates `n` seats among parties (votes, seats start at 0); fills seats[].
void webster_allocate(int64_t n, const std::vector<int32_t>& party_cluster,
                      const std::vector<int64_t>& votes, const Snapshot& S,
                      bool desc, std::vector<int64_t>* seats) {
  size_t P = votes.size();
  seats->assign(P, 0);
  std::vector<HeapEntry> heap;
  heap.reserve(P);
  for (size_t i = 0; i < P; ++i) {
    heap.push_back({priority_quantized(votes[i], 0), 0,
                    S.name_rank[party_cluster[i]], static_cast<int32_t>(i)});
  }
  HeapWorse cmp{desc};
  std::make_heap(heap.begin(), heap.end(), cmp);
  for (int64_t k = 0; k < n; ++k) {
    std::pop_heap(heap.begin(), heap.end(), cmp);
    HeapEntry e = heap.back();
    heap.pop_back();
    int64_t s = ++(*seats)[e.party];
    e.seats = s;
    e.prio = priority_quantized(votes[e.party], s);
    heap.push_back(e);
    std::push_heap(heap.begin(), heap.end(), cmp);
  }
}

// dispense_by_weight with init=None (the only form serial.py uses): returns
// name->seats over the weighted parties; zero weight sum -> empty.
void dispense_by_weight(int64_t n, const std::vector<int32_t>& party_cluster,
                        const std::vector<int64_t>& votes, const Snapshot& S,
                        bool desc, std::vector<Target>* out) {
  out->clear();
  int64_t wsum = 0;
  for (int64_t v : votes) wsum += v;
  if (wsum == 0) return;
  std::vector<int64_t> seats;
  webster_allocate(n, party_cluster, votes, S, desc, &seats);
  out->reserve(votes.size());
  for (size_t i = 0; i < votes.size(); ++i)
    out->push_back({party_cluster[i], seats[i]});
  // serial.py: sorted(result.items()) — ascending name
  std::sort(out->begin(), out->end(), [&S](const Target& a, const Target& b) {
    return S.name_rank[a.idx] < S.name_rank[b.idx];
  });
}

// ---------------------------------------------------------------------------
// GeneralEstimator summary math (general.go:56-94, 294-334)
// ---------------------------------------------------------------------------

int64_t estimator_max_replicas(const Snapshot& S, const int64_t* class_req,
                               int32_t c, int32_t klass) {
  if (!S.has_summary[c]) return 0;
  int64_t maximum = S.pods_allowed[c];
  if (maximum <= 0) return 0;
  if (klass < 0) return std::min(maximum, kMaxInt32);
  const int64_t* req = class_req + static_cast<int64_t>(klass) * S.nR;
  int64_t num = INT64_MAX;  // max_replicas_from_summary
  for (int32_t r = 0; r < S.nR; ++r) {
    int64_t requested = req[r];
    if (requested <= 0) continue;
    int64_t am = S.avail_milli[static_cast<int64_t>(c) * S.nR + r];
    if (am < 0) return 0;  // allocatable missing / exhausted
    int64_t available = S.res_is_cpu[r] ? am : ceil_units(am);
    if (available <= 0) return 0;
    num = std::min(num, available / requested);
  }
  return std::min(std::min(num, maximum), kMaxInt32);
}

// make_cal_available leftover clamp (core/util.go:104-109): MAX_INT32 means
// "no estimator authenticated" -> clamp to spec.replicas.
inline int64_t cal_available_one(const Snapshot& S, const int64_t* class_req,
                                 const Binding& b, int32_t c) {
  if (b.zero_shortcut) return kMaxInt32;  // returned pre-clamp in serial.py
  int64_t v = estimator_max_replicas(S, class_req, c, b.klass);
  if (v == kMaxInt32) return b.replicas;
  return v;
}

// ---------------------------------------------------------------------------
// Spread grouping + selection (spreadconstraint/)
// ---------------------------------------------------------------------------

struct SpreadC {
  int32_t field = kFieldNone;
  int64_t min_groups = 0, max_groups = 0;
};

struct PlacementView {
  int32_t strategy;
  bool has_weights;
  bool ignores_spread;  // select_clusters.go:57-69 (precomputed host-side)
  SpreadC sc[2];
  int n_sc = 0;
  const SpreadC* find(int32_t field) const {
    for (int i = 0; i < n_sc; ++i)
      if (sc[i].field == field) return &sc[i];
    return nullptr;
  }
};

inline bool ignore_spread(const PlacementView& p) { return p.ignores_spread; }
// select_clusters.go:71-80 — Duplicated ignores capacity.
inline bool ignore_available(const PlacementView& p) {
  return p.strategy == kDuplicated;
}
inline bool topology_ignored(const PlacementView& p) {
  if (p.n_sc == 0 || (p.n_sc == 1 && p.sc[0].field == kFieldCluster))
    return true;
  return ignore_spread(p);
}

// spreadconstraint/util.go sortClusters: score desc, available desc, name asc.
void sort_clusters(std::vector<ClusterDetail>* v, const Snapshot& S) {
  std::sort(v->begin(), v->end(),
            [&S](const ClusterDetail& a, const ClusterDetail& b) {
              if (a.score != b.score) return a.score > b.score;
              if (a.available != b.available) return a.available > b.available;
              return S.name_rank[a.idx] < S.name_rank[b.idx];
            });
}

// group_clusters.go:141-218 (clusters pre-sorted score desc).
int64_t calc_group_score_duplicate(const std::vector<ClusterDetail>& cs,
                                   int64_t target) {
  int64_t sum_score = 0, valid = 0;
  for (const auto& c : cs)
    if (c.available >= target) {
      sum_score += c.score;
      ++valid;
    }
  if (valid == 0) return 0;
  return valid * kWeightUnit + sum_score / valid;
}

// group_clusters.go:220-333.
int64_t calc_group_score(const std::vector<ClusterDetail>& cs,
                         const PlacementView& p, int64_t replicas,
                         int64_t min_groups) {
  if (p.strategy == kDuplicated) return calc_group_score_duplicate(cs, replicas);
  // ceil(replicas / min_groups)
  int64_t target = min_groups ? -py_floordiv(-replicas, min_groups) : replicas;
  int64_t cluster_min = 0;
  if (const SpreadC* c = p.find(kFieldCluster)) cluster_min = c->min_groups;
  cluster_min = std::max(cluster_min, min_groups);
  int64_t sum_available = 0, sum_score = 0, valid = 0;
  for (const auto& c : cs) {
    sum_available += c.available;
    sum_score += c.score;
    ++valid;
    if (valid >= cluster_min && sum_available >= target) break;
  }
  if (sum_available < target)
    return sum_available * kWeightUnit +
           sum_score / static_cast<int64_t>(cs.size());
  return target * kWeightUnit + sum_score / valid;
}

// --- findFeasiblePaths DFS (select_groups.go:102-224) ----------------------

struct DfsGroup {
  int32_t region;   // region id (name order via region_rank)
  int64_t value;    // number of clusters in the region
  int64_t weight;   // group score
};

struct DfsPath {
  int32_t id;
  std::vector<DfsGroup> groups;
  int64_t weight, value;
};

struct DfsCtx {
  const std::vector<DfsGroup>* groups;
  const Snapshot* S;
  int64_t min_c, max_c, target;
  std::vector<DfsPath> paths;
  std::vector<DfsGroup> current;
  int32_t next_id = 0;

  void record() {
    DfsPath p;
    p.id = ++next_id;
    p.groups = current;
    // sorted(current, key=(-weight, name))
    const Snapshot& s = *S;
    std::sort(p.groups.begin(), p.groups.end(),
              [&s](const DfsGroup& a, const DfsGroup& b) {
                if (a.weight != b.weight) return a.weight > b.weight;
                return s.region_rank[a.region] < s.region_rank[b.region];
              });
    p.weight = 0;
    p.value = 0;
    for (const auto& g : p.groups) {
      p.weight += g.weight;
      p.value += g.value;
    }
    paths.push_back(std::move(p));
  }

  void dfs(int64_t total, size_t begin) {
    int64_t cur = static_cast<int64_t>(current.size());
    if (total >= target && min_c <= cur && cur <= max_c) {
      record();
      return;
    }
    if (cur >= max_c) return;
    for (size_t i = begin; i < groups->size(); ++i) {
      current.push_back((*groups)[i]);
      dfs(total + (*groups)[i].value, i + 1);
      if (static_cast<int64_t>(groups->size()) == min_c) break;
      current.pop_back();
    }
  }
};

bool match_sub_path(const DfsPath& path, const DfsPath& sub) {
  if (sub.groups.size() >= path.groups.size()) return false;
  for (size_t i = 0; i < sub.groups.size(); ++i)
    if (path.groups[i].region != sub.groups[i].region) return false;
  return true;
}

// Port of selectGroups/findFeasiblePaths/prioritizePaths.
std::vector<DfsGroup> select_groups(std::vector<DfsGroup> groups,
                                    const Snapshot& S, int64_t min_c,
                                    int64_t max_c, int64_t target) {
  if (groups.empty()) return {};
  std::sort(groups.begin(), groups.end(),
            [&S](const DfsGroup& a, const DfsGroup& b) {
              if (a.value != b.value) return a.value < b.value;
              if (a.weight != b.weight) return a.weight > b.weight;
              return S.region_rank[a.region] < S.region_rank[b.region];
            });
  DfsCtx ctx;
  ctx.groups = &groups;
  ctx.S = &S;
  ctx.min_c = min_c;
  ctx.max_c = max_c;
  ctx.target = target;
  ctx.dfs(0, 0);
  if (ctx.paths.empty()) return {};
  if (ctx.paths.size() == 1) return ctx.paths[0].groups;
  std::sort(ctx.paths.begin(), ctx.paths.end(),
            [](const DfsPath& a, const DfsPath& b) {
              if (a.weight != b.weight) return a.weight > b.weight;
              if (a.value != b.value) return a.value > b.value;
              return a.id < b.id;
            });
  const DfsPath* final_p = &ctx.paths[0];
  for (size_t i = 1; i < ctx.paths.size(); ++i)
    if (match_sub_path(*final_p, ctx.paths[i])) final_p = &ctx.paths[i];
  return final_p->groups;
}

// select_clusters_by_cluster.go:32-105 swap loop.
bool select_by_available_resource(std::vector<ClusterDetail>* ret,
                                  std::vector<ClusterDetail>* rest,
                                  int64_t need_replicas) {
  auto total = [](const std::vector<ClusterDetail>& v) {
    int64_t s = 0;
    for (const auto& c : v) s += c.available;
    return s;
  };
  int64_t update_id = static_cast<int64_t>(ret->size()) - 1;
  while (total(*ret) < need_replicas && update_id >= 0) {
    int64_t best_id = -1, best_avail = (*ret)[update_id].available;
    for (size_t i = 0; i < rest->size(); ++i)
      if ((*rest)[i].available > best_avail) {
        best_id = static_cast<int64_t>(i);
        best_avail = (*rest)[i].available;
      }
    if (best_id == -1) {
      --update_id;
      continue;
    }
    std::swap((*ret)[update_id], (*rest)[best_id]);
    --update_id;
  }
  return total(*ret) >= need_replicas;
}

}  // namespace

extern "C" {

// Returns 0 on success (per-binding failures land in out_status), nonzero on
// a structural error.  All array contracts documented in native/__init__.py.
int serial_schedule_batch(
    // clusters
    int32_t nC, const int32_t* name_rank, const uint8_t* deleting,
    const uint8_t* has_summary, const int32_t* region_id,
    const int32_t* region_rank, int32_t n_regions, const int64_t* pods_allowed,
    // capacity
    int32_t nR, const uint8_t* res_is_cpu, const int64_t* avail_milli,
    // api enablement
    int32_t nG, const uint8_t* gvk_enabled,
    // placements
    int32_t nP, const uint8_t* p_taint, const uint8_t* p_reason,
    const int32_t* p_strategy, const uint8_t* p_ignore_spread,
    const uint8_t* p_has_weights, const int64_t* p_weights,
    const int32_t* p_spread, const int64_t* p_extra_score,
    // request classes
    int32_t nQ, const int64_t* class_req,
    // bindings
    int32_t nB, const int32_t* b_placement, const int32_t* b_gvk,
    const int64_t* b_replicas, const int32_t* b_class, const uint8_t* b_fresh,
    const uint8_t* b_uid_desc, const uint8_t* b_workload,
    const uint8_t* b_zero_shortcut, const uint8_t* b_unsupported,
    const int32_t* prev_off, const int32_t* prev_idx, const int64_t* prev_val,
    const int32_t* evict_off, const int32_t* evict_idx,
    // outputs
    int32_t* out_status, int32_t* out_off, int32_t* out_idx, int64_t* out_val,
    int32_t out_cap) {
  Snapshot S{nC, nR, nG, nP, nQ,       name_rank, deleting,
             has_summary, region_id,   region_rank, n_regions,
             pods_allowed, res_is_cpu, avail_milli, gvk_enabled,
             p_taint,      p_reason,   p_strategy, p_ignore_spread,
             p_has_weights, p_weights, p_spread,   p_extra_score};
  (void)nQ;
  int32_t cursor = 0;
  out_off[0] = 0;

  // scratch, reused across bindings
  std::vector<ClusterDetail> details, candidates, rest;
  std::vector<Target> scheduled, available, result, dispensed;
  std::vector<int32_t> party_cluster;
  std::vector<int64_t> votes;
  std::unordered_map<int32_t, int64_t> prev_map;

  for (int32_t b = 0; b < nB; ++b) {
    out_status[b] = kOk;
    result.clear();

    Binding bd{b_placement[b], b_gvk[b],  b_class[b],
               b_replicas[b],  b_fresh[b] != 0, b_uid_desc[b] != 0,
               b_workload[b] != 0, b_zero_shortcut[b] != 0,
               prev_idx + prev_off[b], prev_val + prev_off[b],
               prev_off[b + 1] - prev_off[b], evict_idx + evict_off[b],
               evict_off[b + 1] - evict_off[b]};
    if (b_unsupported[b]) {
      out_status[b] = kUnsupported;
      out_off[b + 1] = cursor;
      continue;
    }

    prev_map.clear();
    for (int32_t j = 0; j < bd.n_prev; ++j) prev_map[bd.prev_idx[j]] = bd.prev_val[j];
    bool has_prev = bd.n_prev > 0;

    const uint8_t* taint_row = p_taint + static_cast<int64_t>(bd.placement) * nC;
    const uint8_t* reason_row = p_reason + static_cast<int64_t>(bd.placement) * nC;
    const uint8_t* enable_row = gvk_enabled + static_cast<int64_t>(bd.gvk) * nC;

    PlacementView pv;
    pv.strategy = p_strategy[bd.placement];
    pv.has_weights = p_has_weights[bd.placement] != 0;
    pv.ignores_spread = p_ignore_spread[bd.placement] != 0;
    const int32_t* sp = p_spread + static_cast<int64_t>(bd.placement) * 6;
    for (int k = 0; k < 2; ++k) {
      if (sp[k * 3] == kFieldNone) continue;
      pv.sc[pv.n_sc].field = sp[k * 3];
      pv.sc[pv.n_sc].min_groups = sp[k * 3 + 1];
      pv.sc[pv.n_sc].max_groups = sp[k * 3 + 2];
      ++pv.n_sc;
    }

    // ---- findClustersThatFit (generic_scheduler.go:119-152) --------------
    details.clear();
    int32_t n_diagnosed = 0;
    for (int32_t c = 0; c < nC; ++c) {
      if (deleting[c]) continue;
      bool targeted = prev_map.count(c) != 0;
      const char* why = nullptr;
      if (!targeted && !enable_row[c]) why = "api";          // APIEnablement
      if (!why && !targeted && taint_row[c]) why = "taint";  // TaintToleration
      if (!why && reason_row[c] == 1) why = "affinity";      // ClusterAffinity
      if (!why && reason_row[c] == 3) why = "spreadfield";   // SpreadConstraint
      if (!why && reason_row[c] == 4) why = "plugin";        // out-of-tree
      if (!why) {                                            // ClusterEviction
        for (int32_t j = 0; j < bd.n_evict; ++j)
          if (bd.evict_idx[j] == c) {
            why = "evicting";
            break;
          }
      }
      if (why) {
        ++n_diagnosed;
        continue;
      }
      // prioritizeClusters: ClusterLocality + out-of-tree plugin sums
      // (pre-clamped on the Python side, scheduler/plugins.py)
      int64_t score = ((has_prev && prev_map.count(c)) ? 100 : 0) +
                      S.p_extra_score[static_cast<int64_t>(bd.placement) * S.nC + c];
      details.push_back({c, score, 0, 0});
    }
    if (details.empty()) {
      out_status[b] = kFitError;
      out_off[b + 1] = cursor;
      (void)n_diagnosed;
      continue;
    }

    // ---- group_clusters_with_score: capacity + sort ----------------------
    for (auto& d : details) {
      d.allocatable = cal_available_one(S, class_req, bd, d.idx);
      auto it = prev_map.find(d.idx);
      d.available = d.allocatable + (it == prev_map.end() ? 0 : it->second);
    }
    sort_clusters(&details, S);

    // region groups (only when topology participates)
    // regions map: region id -> member details, in sorted-cluster order
    std::vector<std::vector<ClusterDetail>> region_members;
    std::vector<int32_t> region_ids_present;
    if (!topology_ignored(pv) && pv.find(kFieldRegion) != nullptr) {
      std::unordered_map<int32_t, size_t> rpos;
      for (const auto& d : details) {
        int32_t r = region_id[d.idx];
        if (r < 0) continue;
        auto it = rpos.find(r);
        if (it == rpos.end()) {
          rpos[r] = region_members.size();
          region_ids_present.push_back(r);
          region_members.emplace_back();
          region_members.back().push_back(d);
        } else {
          region_members[it->second].push_back(d);
        }
      }
    }

    // ---- SelectClusters (select_clusters*.go) ----------------------------
    candidates.clear();
    bool unschedulable = false;
    if (pv.n_sc == 0 || ignore_spread(pv)) {
      candidates = details;
    } else {
      int64_t need = ignore_available(pv) ? kInvalidReplicas : bd.replicas;
      const SpreadC* rsc = pv.find(kFieldRegion);
      const SpreadC* csc = pv.find(kFieldCluster);
      if (rsc != nullptr) {
        // select_clusters_by_region.go:27-118
        if (static_cast<int64_t>(region_members.size()) < rsc->min_groups) {
          unschedulable = true;
        } else {
          int64_t rep = bd.replicas;
          int64_t rmin = rsc->min_groups;
          std::vector<DfsGroup> groups;
          for (size_t g = 0; g < region_members.size(); ++g) {
            int64_t w = calc_group_score(region_members[g], pv, rep, rmin);
            groups.push_back({region_ids_present[g],
                              static_cast<int64_t>(region_members[g].size()), w});
          }
          SpreadC cdef;  // zero-valued when absent (go zero value semantics)
          const SpreadC& cc = csc ? *csc : cdef;
          std::vector<DfsGroup> chosen = select_groups(
              groups, S, rsc->min_groups, rsc->max_groups, cc.min_groups);
          if (chosen.empty()) {
            unschedulable = true;
          } else {
            std::unordered_map<int32_t, size_t> pos;
            for (size_t g = 0; g < region_ids_present.size(); ++g)
              pos[region_ids_present[g]] = g;
            rest.clear();
            for (const auto& g : chosen) {
              const auto& members = region_members[pos[g.region]];
              candidates.push_back(members[0]);
              for (size_t i = 1; i < members.size(); ++i)
                rest.push_back(members[i]);
            }
            int64_t need_cnt =
                static_cast<int64_t>(rest.size() + candidates.size());
            if (need_cnt > cc.max_groups) need_cnt = cc.max_groups;
            int64_t extra = need_cnt - static_cast<int64_t>(candidates.size());
            if (extra > 0) {
              sort_clusters(&rest, S);
              for (int64_t i = 0; i < extra && i < static_cast<int64_t>(rest.size()); ++i)
                candidates.push_back(rest[i]);
            }
          }
        }
      } else if (csc != nullptr) {
        // select_clusters_by_cluster.go:25-105
        int64_t total = static_cast<int64_t>(details.size());
        if (total < csc->min_groups) {
          unschedulable = true;
        } else {
          int64_t need_cnt = total >= csc->max_groups ? csc->max_groups : total;
          if (need == kInvalidReplicas) {
            for (int64_t i = 0; i < need_cnt; ++i) candidates.push_back(details[i]);
          } else {
            candidates.assign(details.begin(),
                              details.begin() + static_cast<size_t>(need_cnt));
            rest.assign(details.begin() + static_cast<size_t>(need_cnt),
                        details.end());
            if (!select_by_available_resource(&candidates, &rest, need)) {
              unschedulable = true;
              candidates.clear();
            }
          }
        }
      } else {
        unschedulable = true;  // "just support cluster and region spread constraint"
      }
    }
    if (unschedulable) {
      out_status[b] = kUnschedulable;
      out_off[b + 1] = cursor;
      continue;
    }
    if (candidates.empty()) {
      out_status[b] = kNoClusterAvailable;
      out_off[b + 1] = cursor;
      continue;
    }

    // ---- AssignReplicas (assignment.go / division_algorithm.go) ----------
    bool drop_zeros = true;
    bool fresh = bd.fresh;
    int32_t strat = pv.strategy;
    if (!bd.workload) {
      // non-workloads & multi-component: propagate to ALL candidates with
      // zero replicas (assign_replicas early return — NOT subject to the
      // strategy paths' replicas>0 drop)
      for (const auto& c : candidates) result.push_back({c.idx, 0});
      drop_zeros = false;
      goto emit;
    }

    if (strat == kDuplicated) {
      for (const auto& c : candidates) result.push_back({c.idx, bd.replicas});
    } else if (strat == kStaticWeight) {
      party_cluster.clear();
      votes.clear();
      const int64_t* wrow =
          p_weights + static_cast<int64_t>(bd.placement) * nC;
      int64_t wsum = 0;
      if (pv.has_weights) {
        for (const auto& c : candidates) {
          int64_t w = wrow[c.idx];
          if (w > 0) {
            party_cluster.push_back(c.idx);
            votes.push_back(w);
            wsum += w;
          }
        }
      }
      if (!pv.has_weights || wsum == 0) {
        // defaulting: all candidates weight 1 (assignment.go:196-198 +
        // getStaticWeightInfoList zero-sum fallback)
        party_cluster.clear();
        votes.clear();
        for (const auto& c : candidates) {
          party_cluster.push_back(c.idx);
          votes.push_back(1);
        }
      }
      dispense_by_weight(bd.replicas, party_cluster, votes, S, bd.uid_desc,
                         &result);
    } else if (strat == kDynamicWeight || strat == kAggregated) {
      // assignByDynamicStrategy (assignment.go:207-238)
      scheduled.clear();
      int64_t assigned = 0;
      {
        std::unordered_map<int32_t, char> cand_set;
        for (const auto& c : candidates) cand_set[c.idx] = 1;
        for (int32_t j = 0; j < bd.n_prev; ++j)
          if (cand_set.count(bd.prev_idx[j])) {
            scheduled.push_back({bd.prev_idx[j], bd.prev_val[j]});
            assigned += bd.prev_val[j];
          }
      }
      int64_t target;
      available.clear();
      if (fresh) {
        // division_algorithm.go:139-166
        target = bd.replicas;
        std::unordered_map<int32_t, int64_t> sched_map;
        for (const auto& t : scheduled) sched_map[t.idx] = t.replicas;
        for (const auto& c : candidates) {
          auto it = sched_map.find(c.idx);
          available.push_back(
              {c.idx, c.allocatable + (it == sched_map.end() ? 0 : it->second)});
        }
        scheduled.clear();
      } else if (assigned > bd.replicas) {
        // scale down: previous result becomes the weights (:103-119)
        target = bd.replicas;
        scheduled.clear();
        for (int32_t j = 0; j < bd.n_prev; ++j)
          available.push_back({bd.prev_idx[j], bd.prev_val[j]});
      } else if (assigned < bd.replicas) {
        // scale up (:121-136)
        target = bd.replicas - assigned;
        for (const auto& c : candidates)
          available.push_back({c.idx, c.allocatable});
      } else {
        for (const auto& t : scheduled) result.push_back(t);
        goto emit;
      }
      {
        // _sort_by_replicas_desc: (-replicas, name)
        std::sort(available.begin(), available.end(),
                  [&S](const Target& a, const Target& b) {
                    if (a.replicas != b.replicas) return a.replicas > b.replicas;
                    return S.name_rank[a.idx] < S.name_rank[b.idx];
                  });
        int64_t avail_sum = 0;
        for (const auto& t : available) avail_sum += t.replicas;
        if (avail_sum < target) {
          out_status[b] = kUnschedulable;
          out_off[b + 1] = cursor;
          continue;
        }
        if (strat == kAggregated) {
          // resort_available (assignment.go:145-172): prior clusters first
          std::unordered_map<int32_t, char> prior;
          for (const auto& t : scheduled)
            if (t.replicas > 0) prior[t.idx] = 1;
          if (!prior.empty()) {
            std::vector<Target> pr, lf;
            for (const auto& t : available)
              (prior.count(t.idx) ? pr : lf).push_back(t);
            available.clear();
            available.insert(available.end(), pr.begin(), pr.end());
            available.insert(available.end(), lf.begin(), lf.end());
          }
          int64_t total = 0;
          size_t cut = available.size();
          for (size_t i = 0; i < available.size(); ++i) {
            total += available[i].replicas;
            if (total >= target) {
              cut = i + 1;
              break;
            }
          }
          available.resize(cut);
        }
        party_cluster.clear();
        votes.clear();
        for (const auto& t : available) {
          party_cluster.push_back(t.idx);
          votes.push_back(t.replicas);
        }
        dispense_by_weight(target, party_cluster, votes, S, bd.uid_desc,
                           &dispensed);
        // merge_target_clusters(scheduled, new): old order first, sums
        result.clear();
        std::unordered_map<int32_t, size_t> rpos;
        for (const auto& t : scheduled) {
          auto it = rpos.find(t.idx);
          if (it == rpos.end()) {
            rpos[t.idx] = result.size();
            result.push_back(t);
          } else {
            result[it->second].replicas += t.replicas;
          }
        }
        for (const auto& t : dispensed) {
          auto it = rpos.find(t.idx);
          if (it == rpos.end()) {
            rpos[t.idx] = result.size();
            result.push_back(t);
          } else {
            result[it->second].replicas += t.replicas;
          }
        }
      }
    } else {
      out_status[b] = kUnschedulable;  // unsupported strategy
      out_off[b + 1] = cursor;
      continue;
    }

  emit:
    for (const auto& t : result) {
      if (drop_zeros && t.replicas <= 0) continue;  // strategy paths drop zeros
      if (cursor >= out_cap) {
        out_status[b] = kOutputOverflow;
        return 1;
      }
      out_idx[cursor] = t.idx;
      out_val[cursor] = t.replicas;
      ++cursor;
    }
    out_off[b + 1] = cursor;
  }
  return 0;
}

}  // extern "C"
