"""Native (C++) serial scheduling control — ctypes host binding.

Compiles ``serial_solver.cc`` with g++ on first use (cached beside the
source, rebuilt when the source is newer) and exposes
:func:`schedule_batch_native`, a drop-in batch equivalent of running
``ops/serial.schedule`` over a list of bindings.  bench.py uses it as the
honest Go-equivalent control for the ``vs_baseline`` speedup; tests golden-
verify it against the Python serial path binding for binding.

Marshaling contract: everything derived from the *snapshot* (cluster name
ranks, availability matrix, per-placement filter masks and static-weight
rows) is precomputed host-side once per snapshot — the same amortization
the device path's EncoderCache performs, and the moral equivalent of the
reference scheduler reading informer-fed caches.  All *per-binding* work
(filtering, capacity division, spread grouping/DFS, Webster dispensing)
happens inside the C++ control.

Unsupported inputs (resource-model histograms, multi-component sets,
vanished previous clusters, weights >= 2^31) are marked per binding and
reported as ``STATUS_UNSUPPORTED`` rather than silently mis-scheduled.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from karmada_tpu.models.cluster import API_ENABLED, Cluster
from karmada_tpu.models.policy import (
    SPREAD_BY_FIELD_CLUSTER,
    SPREAD_BY_FIELD_PROVIDER,
    SPREAD_BY_FIELD_REGION,
    SPREAD_BY_FIELD_ZONE,
    Placement,
)
from karmada_tpu.models.work import (
    ResourceBindingSpec,
    ResourceBindingStatus,
    TargetCluster,
)
from karmada_tpu.ops import serial
from karmada_tpu.ops.webster import tiebreak_descending_by_uid
from karmada_tpu.utils.quantity import RESOURCE_CPU, resource_request_value

STATUS_OK = 0
STATUS_FIT_ERROR = 1
STATUS_UNSCHEDULABLE = 2
STATUS_NO_CLUSTER = 3
STATUS_UNSUPPORTED = 4
STATUS_OVERFLOW = 5

_STRATEGY_CODE = {
    serial.DUPLICATED: 0,
    serial.STATIC_WEIGHT: 1,
    serial.DYNAMIC_WEIGHT: 2,
    serial.AGGREGATED: 3,
}
_FIELD_CODE = {
    SPREAD_BY_FIELD_CLUSTER: 0,
    SPREAD_BY_FIELD_REGION: 1,
    SPREAD_BY_FIELD_ZONE: 2,
    SPREAD_BY_FIELD_PROVIDER: 3,
}

_W_CAP = (1 << 31) - 1  # int32-class weights only (matches reference MaxInt32)

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "serial_solver.cc")
_SO = os.path.join(_DIR, "_serial_solver.so")
_ENC_SRC = os.path.join(_DIR, "encode_fast.c")
_DEC_SRC = os.path.join(_DIR, "decode_fast.c")
# ABI-tagged filename: a CPython-API extension must never be loaded into a
# different interpreter version than the one that built it
_ENC_SO = os.path.join(
    _DIR, f"_encode_fast.{__import__('sys').implementation.cache_tag}.so")
_DEC_SO = os.path.join(
    _DIR, f"_decode_fast.{__import__('sys').implementation.cache_tag}.so")

_lib = None
_lib_lock = threading.Lock()
_build_error: Optional[str] = None


def _build() -> Optional[str]:
    """g++ -O2 build, cached on mtime.  Returns an error string or None."""
    try:
        if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
            return None
        r = subprocess.run(
            ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", _SO + ".tmp", _SRC],
            capture_output=True, text=True, timeout=180,
        )
        if r.returncode != 0:
            return f"g++ failed: {r.stderr[-800:]}"
        os.replace(_SO + ".tmp", _SO)
        return None
    # vet: ignore[exception-hygiene] toolchain absence is a supported state; error kept in _build_error
    except Exception as e:  # noqa: BLE001 — toolchain absence is a supported state
        return f"native build unavailable: {e!r}"


def load() -> Optional[ctypes.CDLL]:
    """The shared library, building it if needed; None when unavailable."""
    global _lib, _build_error
    with _lib_lock:
        if _lib is not None:
            return _lib
        if _build_error is not None:
            return None
        _build_error = _build()
        if _build_error is not None:
            return None
        lib = ctypes.CDLL(_SO)
        lib.serial_schedule_batch.restype = ctypes.c_int
        _lib = lib
        return _lib


def build_error() -> Optional[str]:
    return _build_error


def available() -> bool:
    return load() is not None


# -- encode fast path (CPython extension) ------------------------------------

_enc_mod = None
_enc_error: Optional[str] = None


def load_encode_fast():
    """The _encode_fast extension module, building it on demand; None when
    the toolchain or headers are unavailable (callers fall back to the
    Python loop)."""
    global _enc_mod, _enc_error
    with _lib_lock:
        if _enc_mod is not None:
            return _enc_mod
        if _enc_error is not None:
            return None
        try:
            import sysconfig

            if (not os.path.exists(_ENC_SO)
                    or os.path.getmtime(_ENC_SO) < os.path.getmtime(_ENC_SRC)):
                inc = sysconfig.get_path("include")
                r = subprocess.run(
                    ["gcc", "-O2", "-shared", "-fPIC", f"-I{inc}",
                     "-o", _ENC_SO + ".tmp", _ENC_SRC],
                    capture_output=True, text=True, timeout=180,
                )
                if r.returncode != 0:
                    _enc_error = f"gcc failed: {r.stderr[-800:]}"
                    return None
                os.replace(_ENC_SO + ".tmp", _ENC_SO)
            import importlib.util

            spec = importlib.util.spec_from_file_location(
                "karmada_tpu.native._encode_fast", _ENC_SO)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            _enc_mod = mod
            return _enc_mod
        # vet: ignore[exception-hygiene] optional acceleration; the build error is retained for report
        except Exception as e:  # noqa: BLE001 — optional acceleration only
            _enc_error = f"encode_fast unavailable: {e!r}"
            return None


def encode_fast_error() -> Optional[str]:
    return _enc_error


# -- decode fast path (CPython extension) -------------------------------------

_dec_mod = None
_dec_error: Optional[str] = None


def load_decode_fast():
    """The _decode_fast extension module (native COO decode,
    decode_fast.c), building it on demand; None when the toolchain or
    headers are unavailable (ops/tensors.decode_compact falls back to
    the Python builder, which stays the behavior-defining parity
    control)."""
    global _dec_mod, _dec_error
    with _lib_lock:
        if _dec_mod is not None:
            return _dec_mod
        if _dec_error is not None:
            return None
        try:
            import sysconfig

            if (not os.path.exists(_DEC_SO)
                    or os.path.getmtime(_DEC_SO) < os.path.getmtime(_DEC_SRC)):
                inc = sysconfig.get_path("include")
                r = subprocess.run(
                    ["gcc", "-O2", "-shared", "-fPIC", f"-I{inc}",
                     "-o", _DEC_SO + ".tmp", _DEC_SRC],
                    capture_output=True, text=True, timeout=180,
                )
                if r.returncode != 0:
                    _dec_error = f"gcc failed: {r.stderr[-800:]}"
                    return None
                os.replace(_DEC_SO + ".tmp", _DEC_SO)
            import importlib.util

            spec = importlib.util.spec_from_file_location(
                "karmada_tpu.native._decode_fast", _DEC_SO)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            _dec_mod = mod
            return _dec_mod
        # vet: ignore[exception-hygiene] optional acceleration; the build error is retained for report
        except Exception as e:  # noqa: BLE001 — optional acceleration only
            _dec_error = f"decode_fast unavailable: {e!r}"
            return None


def decode_fast_error() -> Optional[str]:
    return _dec_error


# ---------------------------------------------------------------------------
# Snapshot marshaling
# ---------------------------------------------------------------------------


def _i64(a) -> np.ndarray:
    return np.ascontiguousarray(a, dtype=np.int64)


def _i32(a) -> np.ndarray:
    return np.ascontiguousarray(a, dtype=np.int32)


def _u8(a) -> np.ndarray:
    return np.ascontiguousarray(a, dtype=np.uint8)


class NativeSnapshot:
    """Cluster-side tensors for one scheduling snapshot (reusable across
    chunks of the same cycle, like tensors.EncoderCache)."""

    def __init__(self, clusters: Sequence[Cluster], res_names: Sequence[str]):
        from karmada_tpu.estimator.general import _available, allowed_pod_number

        self.clusters = list(clusters)
        self.index: Dict[str, int] = {c.name: i for i, c in enumerate(clusters)}
        nC = len(clusters)
        order = sorted(range(nC), key=lambda i: clusters[i].name)
        # vet: ignore[dtype-contract] int32 C++ ABI rank, not the SolverBatch field
        self.name_rank = np.zeros(nC, np.int32)
        for rank, i in enumerate(order):
            self.name_rank[i] = rank

        self.deleting = _u8([c.metadata.deleting for c in clusters])
        self.has_summary = _u8(
            [c.status.resource_summary is not None for c in clusters]
        )
        self.unsupported_modeling = any(
            c.status.resource_summary is not None
            and c.status.resource_summary.allocatable_modelings
            for c in clusters
        )

        regions: Dict[str, int] = {}
        self.region_id = np.full(nC, -1, np.int32)
        for i, c in enumerate(clusters):
            r = c.spec.region
            if not r:
                continue
            if r not in regions:
                regions[r] = len(regions)
            self.region_id[i] = regions[r]
        rnames = sorted(regions, key=lambda n: n)
        self.region_rank = np.zeros(max(len(regions), 1), np.int32)
        for rank, name in enumerate(rnames):
            self.region_rank[regions[name]] = rank
        self.n_regions = len(regions)

        self.res_names = list(res_names)
        self.res_is_cpu = _u8([n == RESOURCE_CPU for n in self.res_names])
        nR = max(len(self.res_names), 1)
        self.pods_allowed = np.zeros(nC, np.int64)
        self.avail_milli = np.full((nC, nR), -1, np.int64)
        for i, c in enumerate(clusters):
            s = c.status.resource_summary
            if s is None:
                continue
            self.pods_allowed[i] = allowed_pod_number(s)
            for r, name in enumerate(self.res_names):
                self.avail_milli[i, r] = _available(s, name)

        self.gvk_rows: Dict[Tuple[str, str], int] = {}
        self.gvk_enabled: List[np.ndarray] = []
        self.placement_rows: Dict[str, int] = {}
        self.p_taint: List[np.ndarray] = []
        self.p_reason: List[np.ndarray] = []
        self.p_strategy: List[int] = []
        self.p_ignore_spread: List[int] = []
        self.p_has_weights: List[int] = []
        self.p_weights: List[np.ndarray] = []
        self.p_spread: List[np.ndarray] = []
        self.p_extra_score: List[np.ndarray] = []  # out-of-tree plugin sums
        self.p_unsupported: List[bool] = []

    def gvk_id(self, api_version: str, kind: str) -> int:
        key = (api_version, kind)
        gid = self.gvk_rows.get(key)
        if gid is not None:
            return gid
        row = _u8([
            c.api_enablement(api_version, kind) == API_ENABLED
            for c in self.clusters
        ])
        self.gvk_rows[key] = len(self.gvk_enabled)
        self.gvk_enabled.append(row)
        return self.gvk_rows[key]

    def placement_id(self, placement: Placement) -> int:
        key = serial_placement_key(placement)
        pid = self.placement_rows.get(key)
        if pid is not None:
            return pid

        from karmada_tpu.scheduler.plugins import (
            REGISTRY as _PLUGINS,
            eval_filters,
            eval_scores,
        )

        nC = len(self.clusters)
        taint = np.zeros(nC, np.uint8)
        reason = np.zeros(nC, np.uint8)
        extra = np.zeros(nC, np.int64)
        plug_filters = _PLUGINS.enabled_filters()
        plug_scores = _PLUGINS.enabled_scores()
        # evaluate the placement-level filter predicates per cluster, in the
        # serial plugin order (taint, affinity, spread-field presence,
        # out-of-tree registry filters)
        dummy_spec = ResourceBindingSpec(placement=placement)
        dummy_status = ResourceBindingStatus()
        for i, c in enumerate(self.clusters):
            if serial.filter_taint_toleration(dummy_spec, dummy_status, c):
                taint[i] = 1
            if serial.filter_cluster_affinity(dummy_spec, dummy_status, c):
                reason[i] = 1
            elif serial.filter_spread_constraint(dummy_spec, dummy_status, c):
                reason[i] = 3
            elif plug_filters and eval_filters(plug_filters, placement, c):
                reason[i] = 4
            if plug_scores:
                extra[i] = eval_scores(plug_scores, placement, c)

        strategy = serial.strategy_type(
            ResourceBindingSpec(placement=placement, replicas=1)
        )
        scode = _STRATEGY_CODE.get(strategy, -1)
        unsupported = scode < 0

        weights = np.zeros(nC, np.int64)
        has_weights = 0
        rs = placement.replica_scheduling
        wp = rs.weight_preference if rs is not None else None
        if strategy == serial.STATIC_WEIGHT and wp is not None and wp.static_weight_list:
            has_weights = 1
            for i, c in enumerate(self.clusters):
                w = 0
                for rule in wp.static_weight_list:
                    if rule.target_cluster.matches(c):
                        w = max(w, rule.weight)
                if w > _W_CAP:
                    unsupported = True
                weights[i] = w

        spread = np.full(6, -1, np.int32)
        scs = placement.spread_constraints
        if len(scs) > 2 or any(sc.spread_by_label for sc in scs):
            unsupported = True
        for k, sc in enumerate(scs[:2]):
            spread[k * 3] = _FIELD_CODE.get(sc.spread_by_field, -1)
            spread[k * 3 + 1] = sc.min_groups
            spread[k * 3 + 2] = sc.max_groups
            if spread[k * 3] < 0:
                unsupported = True

        self.placement_rows[key] = len(self.p_strategy)
        self.p_taint.append(taint)
        self.p_reason.append(reason)
        self.p_strategy.append(max(scode, 0))
        self.p_ignore_spread.append(
            1 if serial.should_ignore_spread_constraint(placement) else 0
        )
        self.p_has_weights.append(has_weights)
        self.p_weights.append(weights)
        self.p_spread.append(spread)
        self.p_extra_score.append(extra)
        self.p_unsupported.append(unsupported)
        return self.placement_rows[key]


def serial_placement_key(placement: Placement) -> str:
    """Identity key for memoizing placement rows (repr of the dataclass
    tree is stable for our frozen-ish models; collisions only merge
    identical placements)."""
    return repr(placement)


def collect_res_names(
    items: Sequence[Tuple[ResourceBindingSpec, ResourceBindingStatus]],
) -> List[str]:
    names: Dict[str, None] = {}
    for spec, _ in items:
        rr = spec.replica_requirements
        if rr is not None:
            for n in rr.resource_request:
                names.setdefault(n, None)
    return list(names)


class NativeBatch:
    """Marshaled per-binding arrays, ready for the C call (input prep is
    separated from the solver call so bench.py can time the control's
    scheduling work alone, symmetrically with the batched path whose
    encode IS included in its own timing)."""

    def __init__(self) -> None:
        self.arrays: Dict[str, np.ndarray] = {}
        self.out_cap = 0
        self.n_bindings = 0


def marshal_batch(
    items: Sequence[Tuple[ResourceBindingSpec, ResourceBindingStatus]],
    snapshot: NativeSnapshot,
) -> NativeBatch:
    nB = len(items)
    nC = len(snapshot.clusters)

    b_placement = np.zeros(nB, np.int32)
    b_gvk = np.zeros(nB, np.int32)
    b_replicas = np.zeros(nB, np.int64)
    b_class = np.full(nB, -1, np.int32)
    b_fresh = np.zeros(nB, np.uint8)
    b_uid_desc = np.zeros(nB, np.uint8)
    b_workload = np.zeros(nB, np.uint8)
    b_zero_shortcut = np.zeros(nB, np.uint8)
    b_unsupported = np.zeros(nB, np.uint8)

    classes: Dict[Tuple, int] = {}
    class_rows: List[np.ndarray] = []
    nR = max(len(snapshot.res_names), 1)
    res_index = {n: r for r, n in enumerate(snapshot.res_names)}

    prev_off = np.zeros(nB + 1, np.int32)
    evict_off = np.zeros(nB + 1, np.int32)
    prev_idx_l: List[int] = []
    prev_val_l: List[int] = []
    evict_idx_l: List[int] = []

    for b, (spec, status) in enumerate(items):
        placement = _effective_placement(spec, status)
        pid = snapshot.placement_id(placement)
        b_placement[b] = pid
        b_gvk[b] = snapshot.gvk_id(spec.resource.api_version, spec.resource.kind)
        b_replicas[b] = min(spec.replicas, _W_CAP)
        if spec.replicas > _W_CAP:
            b_unsupported[b] = 1
        b_fresh[b] = serial.reschedule_required(spec, status)
        b_uid_desc[b] = tiebreak_descending_by_uid(spec.resource.uid)
        rr = spec.replica_requirements
        b_workload[b] = (
            (spec.replicas > 0 or rr is not None) and len(spec.components) <= 1
        )
        b_zero_shortcut[b] = spec.replicas == 0 and not spec.components
        if snapshot.p_unsupported[pid] or len(spec.components) > 1:
            b_unsupported[b] = 1
        if snapshot.unsupported_modeling:
            b_unsupported[b] = 1

        if rr is not None and rr.resource_request:
            ck = tuple(sorted((n, q.milli) for n, q in rr.resource_request.items()))
            cid = classes.get(ck)
            if cid is None:
                row = np.zeros(nR, np.int64)
                for n, q in rr.resource_request.items():
                    row[res_index[n]] = resource_request_value(n, q)
                cid = classes[ck] = len(class_rows)
                class_rows.append(row)
            b_class[b] = cid

        seen: Dict[int, int] = {}
        for tc in spec.clusters:
            ci = snapshot.index.get(tc.name)
            if ci is None:
                b_unsupported[b] = 1  # vanished prev cluster: serial-only path
                continue
            seen[ci] = tc.replicas  # duplicate names: last wins
            if tc.replicas > _W_CAP:
                b_unsupported[b] = 1
        for ci, r in seen.items():
            prev_idx_l.append(ci)
            prev_val_l.append(r)
        prev_off[b + 1] = len(prev_idx_l)

        for task in spec.graceful_eviction_tasks:
            ci = snapshot.index.get(task.from_cluster)
            if ci is not None:
                evict_idx_l.append(ci)
        evict_off[b + 1] = len(evict_idx_l)

    nP = max(len(snapshot.p_strategy), 1)
    nG = max(len(snapshot.gvk_enabled), 1)
    nQ = max(len(class_rows), 1)

    def stack(rows: List[np.ndarray], n: int, width: int, dtype) -> np.ndarray:
        if not rows:
            return np.zeros((n, width), dtype)
        return np.ascontiguousarray(np.stack(rows), dtype)

    p_taint = stack(snapshot.p_taint, nP, nC, np.uint8)
    p_reason = stack(snapshot.p_reason, nP, nC, np.uint8)
    p_weights = stack(snapshot.p_weights, nP, nC, np.int64)
    p_spread = stack(snapshot.p_spread, nP, 6, np.int32)
    p_extra = stack(snapshot.p_extra_score, nP, nC, np.int64)
    p_strategy = _i32(snapshot.p_strategy or [0])
    p_ignore = _u8(snapshot.p_ignore_spread or [0])
    p_has_w = _u8(snapshot.p_has_weights or [0])
    gvk_enabled = stack(snapshot.gvk_enabled, nG, nC, np.uint8)
    class_req = stack(class_rows, nQ, nR, np.int64)

    prev_idx = _i32(prev_idx_l or [0])
    prev_val = _i64(prev_val_l or [0])
    evict_idx = _i32(evict_idx_l or [0])

    # tight output bound: Webster-divided results have at most
    # min(replicas + |prev|, nC) positive lanes; Duplicated at most the
    # placement's affinity-passing cluster count.
    pass_count = [
        nC - int(np.count_nonzero(row)) for row in snapshot.p_reason
    ] or [nC]
    out_cap = 1
    for b in range(nB):
        if not b_workload[b] or snapshot.p_strategy[b_placement[b]] == 0:
            # non-workload zero-propagation and Duplicated both emit one
            # entry per feasible candidate
            out_cap += pass_count[b_placement[b]]
        else:
            out_cap += int(
                min(b_replicas[b] + (prev_off[b + 1] - prev_off[b]), nC)
            )

    nb = NativeBatch()
    nb.n_bindings = nB
    nb.out_cap = out_cap
    nb.arrays = {
        "nC": nC, "nR": nR, "nG": nG, "nP": nP, "nQ": nQ,
        "gvk_enabled": gvk_enabled, "p_taint": p_taint, "p_reason": p_reason,
        "p_strategy": p_strategy, "p_ignore": p_ignore, "p_has_w": p_has_w,
        "p_weights": p_weights, "p_spread": p_spread, "p_extra": p_extra,
        "class_req": class_req,
        "b_placement": b_placement, "b_gvk": b_gvk, "b_replicas": b_replicas,
        "b_class": b_class, "b_fresh": b_fresh, "b_uid_desc": b_uid_desc,
        "b_workload": b_workload, "b_zero_shortcut": b_zero_shortcut,
        "b_unsupported": b_unsupported, "prev_off": prev_off,
        "prev_idx": prev_idx, "prev_val": prev_val, "evict_off": evict_off,
        "evict_idx": evict_idx,
    }
    return nb


def run_marshaled(
    nb: NativeBatch, snapshot: NativeSnapshot
) -> List[Tuple[int, List[TargetCluster]]]:
    """Run the C++ control over a marshaled batch."""
    lib = load()
    if lib is None:
        raise RuntimeError(f"native solver unavailable: {_build_error}")
    a = nb.arrays
    nB = nb.n_bindings
    out_status = np.zeros(nB, np.int32)
    out_off = np.zeros(nB + 1, np.int32)
    out_idx = np.zeros(nb.out_cap, np.int32)
    out_val = np.zeros(nb.out_cap, np.int64)

    c = ctypes
    p = lambda arr: arr.ctypes.data_as(c.c_void_p)  # noqa: E731
    # bind to a local so the pointer outlives the call even if a future
    # change makes avail_milli a non-contiguous view
    avail_milli = np.ascontiguousarray(snapshot.avail_milli)
    rc = lib.serial_schedule_batch(
        c.c_int32(a["nC"]), p(snapshot.name_rank), p(snapshot.deleting),
        p(snapshot.has_summary), p(snapshot.region_id), p(snapshot.region_rank),
        c.c_int32(snapshot.n_regions), p(snapshot.pods_allowed),
        c.c_int32(a["nR"]), p(snapshot.res_is_cpu),
        p(avail_milli),
        c.c_int32(a["nG"]), p(a["gvk_enabled"]),
        c.c_int32(a["nP"]), p(a["p_taint"]), p(a["p_reason"]),
        p(a["p_strategy"]), p(a["p_ignore"]), p(a["p_has_w"]),
        p(a["p_weights"]), p(a["p_spread"]), p(a["p_extra"]),
        c.c_int32(a["nQ"]), p(a["class_req"]),
        c.c_int32(nB), p(a["b_placement"]), p(a["b_gvk"]), p(a["b_replicas"]),
        p(a["b_class"]), p(a["b_fresh"]), p(a["b_uid_desc"]),
        p(a["b_workload"]), p(a["b_zero_shortcut"]), p(a["b_unsupported"]),
        p(a["prev_off"]), p(a["prev_idx"]), p(a["prev_val"]),
        p(a["evict_off"]), p(a["evict_idx"]),
        p(out_status), p(out_off), p(out_idx), p(out_val),
        c.c_int32(nb.out_cap),
    )
    if rc != 0:
        raise RuntimeError("native solver output overflow")

    results: List[Tuple[int, List[TargetCluster]]] = []
    names = [cl.name for cl in snapshot.clusters]
    for b in range(nB):
        status = int(out_status[b])
        targets: List[TargetCluster] = []
        if status == STATUS_OK:
            for j in range(out_off[b], out_off[b + 1]):
                targets.append(
                    TargetCluster(name=names[out_idx[j]], replicas=int(out_val[j]))
                )
        results.append((status, targets))
    return results


def schedule_batch_native(
    items: Sequence[Tuple[ResourceBindingSpec, ResourceBindingStatus]],
    snapshot: NativeSnapshot,
) -> List[Tuple[int, List[TargetCluster]]]:
    """Schedule every binding through the C++ control.

    Returns ``[(status, targets), ...]`` aligned with ``items``;
    ``targets`` is meaningful only when status is ``STATUS_OK``.
    """
    return run_marshaled(marshal_batch(items, snapshot), snapshot)


def _effective_placement(
    spec: ResourceBindingSpec, status: ResourceBindingStatus
) -> Placement:
    """The placement the filters see — single shared resolution so
    out-of-tree plugins get the identical object on every backend."""
    return serial.effective_placement(spec, status)
