/* Fast path for the per-binding encode loop (ops/tensors.encode_batch).
 *
 * The Python loop costs ~7us per binding after caching; this extension
 * walks the same (spec, status) objects through the CPython C API at
 * ~1us per binding for the COMMON shape:
 *
 *   - placement is spec.placement, already registered (identity-keyed);
 *   - GVK and request-class already in the call's vocabulary dicts;
 *   - no components, no previous assignment, no eviction tasks;
 *   - no ClusterAffinities needing per-binding resolution.
 *
 * Anything else goes through `miss_cb(b)` — the Python slow path for that
 * single binding (which also registers new vocabulary entries so later
 * bindings hit). Behavior is defined by ONE implementation: the Python
 * loop; a golden test asserts the fast path produces identical tensors.
 *
 * Build: gcc -O2 -shared -fPIC -I<python-include> (native/__init__.py).
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>

/* interned attribute names, set up in module init */
static PyObject *s_placement, *s_resource, *s_api_version, *s_kind, *s_uid;
static PyObject *s_replicas, *s_replica_requirements, *s_resource_request;
static PyObject *s_milli, *s_components, *s_clusters, *s_gets, *s_reschedule;
static PyObject *s_cluster_affinity, *s_cluster_affinities;

static uint32_t fnv32a(const char *data, Py_ssize_t len) {
  uint32_t h = 0x811C9DC5u;
  for (Py_ssize_t i = 0; i < len; i++) {
    h ^= (unsigned char)data[i];
    h *= 0x01000193u;
  }
  return h;
}

/* Returns a BORROWED int value from a dict lookup of an owned key; -1 if
 * absent. Steals nothing. */
static long dict_lookup_long(PyObject *dict, PyObject *key) {
  PyObject *v = PyDict_GetItem(dict, key); /* borrowed */
  if (v == NULL) return -1;
  return PyLong_AsLong(v);
}

/* encode_fast(items, pid_route_by_id, gvk_ids, class_ids,
 *             placement_id, gvk_id, class_id, replicas, uid_desc, fresh,
 *             non_workload, nw_shortcut, route, miss_cb)
 *
 * Array arguments are writable 1-D numpy arrays exposed via the buffer
 * protocol with dtypes int32/int64/bool as noted below.  Returns the
 * number of bindings handled by the fast path.
 */
static PyObject *encode_fast(PyObject *self, PyObject *args) {
  PyObject *items, *pid_route_by_id, *gvk_ids, *class_ids, *miss_cb;
  PyObject *a_pid, *a_gvk, *a_cls, *a_rep, *a_uid, *a_fresh, *a_nw, *a_nws,
      *a_route;
  long replica_cap = 0;
  if (!PyArg_ParseTuple(args, "OOOOOOOOOOOOOlO", &items, &pid_route_by_id,
                        &gvk_ids, &class_ids, &a_pid, &a_gvk, &a_cls, &a_rep,
                        &a_uid, &a_fresh, &a_nw, &a_nws, &a_route,
                        &replica_cap, &miss_cb))
    return NULL;

  Py_buffer b_pid, b_gvk, b_cls, b_rep, b_uid, b_fresh, b_nw, b_nws, b_route;
  memset(&b_pid, 0, sizeof(b_pid));
  if (PyObject_GetBuffer(a_pid, &b_pid, PyBUF_WRITABLE) < 0) return NULL;
  if (PyObject_GetBuffer(a_gvk, &b_gvk, PyBUF_WRITABLE) < 0) goto fail1;
  if (PyObject_GetBuffer(a_cls, &b_cls, PyBUF_WRITABLE) < 0) goto fail2;
  if (PyObject_GetBuffer(a_rep, &b_rep, PyBUF_WRITABLE) < 0) goto fail3;
  if (PyObject_GetBuffer(a_uid, &b_uid, PyBUF_WRITABLE) < 0) goto fail4;
  if (PyObject_GetBuffer(a_fresh, &b_fresh, PyBUF_WRITABLE) < 0) goto fail5;
  if (PyObject_GetBuffer(a_nw, &b_nw, PyBUF_WRITABLE) < 0) goto fail6;
  if (PyObject_GetBuffer(a_nws, &b_nws, PyBUF_WRITABLE) < 0) goto fail7;
  if (PyObject_GetBuffer(a_route, &b_route, PyBUF_WRITABLE) < 0) goto fail8;

  int32_t *pid_arr = (int32_t *)b_pid.buf;
  int32_t *gvk_arr = (int32_t *)b_gvk.buf;
  int32_t *cls_arr = (int32_t *)b_cls.buf;
  int64_t *rep_arr = (int64_t *)b_rep.buf;
  uint8_t *uid_arr = (uint8_t *)b_uid.buf;
  uint8_t *fresh_arr = (uint8_t *)b_fresh.buf;
  uint8_t *nw_arr = (uint8_t *)b_nw.buf;
  uint8_t *nws_arr = (uint8_t *)b_nws.buf;
  int32_t *route_arr = (int32_t *)b_route.buf;

  Py_ssize_t n = PySequence_Length(items);
  Py_ssize_t handled = 0;
  PyObject *fast_items = PySequence_Fast(items, "items must be a sequence");
  if (fast_items == NULL) goto fail9;

  for (Py_ssize_t b = 0; b < n; b++) {
    PyObject *pair = PySequence_Fast_GET_ITEM(fast_items, b); /* borrowed */
    if (!PyTuple_Check(pair) || PyTuple_GET_SIZE(pair) != 2) {
      /* list pairs etc. work on the Python path; route them there */
      PyObject *r = PyObject_CallFunction(miss_cb, "n", b);
      if (r == NULL) goto loop_error;
      Py_DECREF(r);
      continue;
    }
    PyObject *spec = PyTuple_GET_ITEM(pair, 0); /* borrowed */

    int slow = 0;
    PyObject *placement = NULL, *resource = NULL, *rr = NULL;

    /* ---- placement: identity-keyed fast lookup ---- */
    placement = PyObject_GetAttr(spec, s_placement);
    if (placement == NULL) goto item_error;
    long pid = -1, route = -1;
    if (placement == Py_None) {
      slow = 1;
    } else {
      /* ClusterAffinities needing resolution -> slow path */
      PyObject *aff = PyObject_GetAttr(placement, s_cluster_affinity);
      if (aff == NULL) goto item_error;
      int aff_none = (aff == Py_None);
      Py_DECREF(aff);
      if (aff_none) {
        PyObject *affs = PyObject_GetAttr(placement, s_cluster_affinities);
        if (affs == NULL) goto item_error;
        Py_ssize_t n_affs = PySequence_Length(affs);
        Py_DECREF(affs);
        if (n_affs != 0) slow = 1;
      }
      if (!slow) {
        PyObject *key = PyLong_FromVoidPtr(placement);
        if (key == NULL) goto item_error;
        PyObject *entry = PyDict_GetItem(pid_route_by_id, key); /* borrowed */
        Py_DECREF(key);
        if (entry == NULL) {
          slow = 1;
        } else {
          /* entry = (placement_obj, pid, route); verify identity so a
           * recycled id() can never alias a dead placement */
          if (PyTuple_GET_ITEM(entry, 0) != placement) {
            slow = 1;
          } else {
            pid = PyLong_AsLong(PyTuple_GET_ITEM(entry, 1));
            route = PyLong_AsLong(PyTuple_GET_ITEM(entry, 2));
          }
        }
      }
    }

    /* ---- components / prev clusters / evictions: any -> slow ---- */
    if (!slow) {
      PyObject *comps = PyObject_GetAttr(spec, s_components);
      if (comps == NULL) goto item_error;
      Py_ssize_t n_comps = PySequence_Length(comps);
      Py_DECREF(comps);
      PyObject *prev = PyObject_GetAttr(spec, s_clusters);
      if (prev == NULL) goto item_error;
      Py_ssize_t n_prev = PySequence_Length(prev);
      Py_DECREF(prev);
      PyObject *gets = PyObject_GetAttr(spec, s_gets);
      if (gets == NULL) goto item_error;
      Py_ssize_t n_gets = PySequence_Length(gets);
      Py_DECREF(gets);
      if (n_comps != 0 || n_prev != 0 || n_gets != 0) slow = 1;
    }

    /* ---- fresh: reschedule_triggered_at must be None for the fast path
     * (a set trigger needs the status comparison -> slow) ---- */
    if (!slow) {
      PyObject *rta = PyObject_GetAttr(spec, s_reschedule);
      if (rta == NULL) goto item_error;
      int rta_none = (rta == Py_None);
      Py_DECREF(rta);
      if (!rta_none) slow = 1;
    }

    /* ---- gvk vocabulary ---- */
    long gid = -1;
    if (!slow) {
      resource = PyObject_GetAttr(spec, s_resource);
      if (resource == NULL) goto item_error;
      PyObject *av = PyObject_GetAttr(resource, s_api_version);
      PyObject *kd = av ? PyObject_GetAttr(resource, s_kind) : NULL;
      if (kd == NULL) {
        Py_XDECREF(av);
        goto item_error;
      }
      PyObject *gkey = PyTuple_Pack(2, av, kd);
      Py_DECREF(av);
      Py_DECREF(kd);
      if (gkey == NULL) goto item_error;
      gid = dict_lookup_long(gvk_ids, gkey);
      Py_DECREF(gkey);
      if (gid < 0) slow = 1;
    }

    /* ---- request class vocabulary ---- */
    long cid = -1;
    long replicas = 0;
    if (!slow) {
      PyObject *rep_obj = PyObject_GetAttr(spec, s_replicas);
      if (rep_obj == NULL) goto item_error;
      int overflow = 0;
      replicas = PyLong_AsLongAndOverflow(rep_obj, &overflow);
      Py_DECREF(rep_obj);
      if (replicas == -1 && !overflow && PyErr_Occurred()) goto item_error;
      /* replica counts beyond the device kernel's cap take the
       * arbitrary-precision host route (ROUTE_HUGE_REPLICAS) — the Python
       * path owns that decision */
      if (overflow || replicas > replica_cap) slow = 1;

      rr = PyObject_GetAttr(spec, s_replica_requirements);
      if (rr == NULL) goto item_error;
      if (rr != Py_None) {
        PyObject *req = PyObject_GetAttr(rr, s_resource_request);
        if (req == NULL) goto item_error;
        int is_dict = PyDict_Check(req);
        if (!is_dict || PyDict_Size(req) == 0) {
          Py_DECREF(req);
          if (!is_dict) slow = 1; /* unusual shape: slow path */
          /* empty request: class stays -1 */
        } else {
          /* build the canonical sorted (name, milli) tuple key */
          Py_ssize_t sz = PyDict_Size(req);
          PyObject *lst = PyList_New(0);
          if (lst == NULL) {
            Py_DECREF(req);
            goto item_error;
          }
          PyObject *k, *v;
          Py_ssize_t pos = 0;
          int ok = 1;
          while (PyDict_Next(req, &pos, &k, &v)) {
            PyObject *milli = PyObject_GetAttr(v, s_milli);
            if (milli == NULL) {
              ok = 0;
              break;
            }
            PyObject *pairk = PyTuple_Pack(2, k, milli);
            Py_DECREF(milli);
            if (pairk == NULL || PyList_Append(lst, pairk) < 0) {
              Py_XDECREF(pairk);
              ok = 0;
              break;
            }
            Py_DECREF(pairk);
          }
          Py_DECREF(req);
          if (!ok) {
            Py_DECREF(lst);
            goto item_error;
          }
          if (sz > 1 && PyList_Sort(lst) < 0) {
            Py_DECREF(lst);
            goto item_error;
          }
          PyObject *ckey = PyList_AsTuple(lst);
          Py_DECREF(lst);
          if (ckey == NULL) goto item_error;
          cid = dict_lookup_long(class_ids, ckey);
          Py_DECREF(ckey);
          if (cid < 0) slow = 1;
        }
      }
    }

    if (slow) {
      Py_XDECREF(placement);
      Py_XDECREF(resource);
      Py_XDECREF(rr);
      PyObject *r = PyObject_CallFunction(miss_cb, "n", b);
      if (r == NULL) goto loop_error;
      Py_DECREF(r);
      continue;
    }

    /* ---- fnv32a tiebreak over the uid ---- */
    PyObject *uid = PyObject_GetAttr(resource, s_uid);
    if (uid == NULL) goto item_error;
    int desc = 0;
    if (PyUnicode_Check(uid)) {
      Py_ssize_t ulen = 0;
      const char *udata = PyUnicode_AsUTF8AndSize(uid, &ulen);
      if (udata == NULL) {
        Py_DECREF(uid);
        goto item_error;
      }
      if (ulen > 0) desc = fnv32a(udata, ulen) & 1;
    }
    Py_DECREF(uid);

    int is_workload = (replicas > 0) || (rr != Py_None);

    pid_arr[b] = (int32_t)pid;
    gvk_arr[b] = (int32_t)gid;
    cls_arr[b] = (int32_t)cid;
    rep_arr[b] = (int64_t)replicas;
    uid_arr[b] = (uint8_t)desc;
    fresh_arr[b] = 0; /* reschedule_triggered_at is None on this path */
    nw_arr[b] = (uint8_t)(!is_workload);
    nws_arr[b] = (uint8_t)(replicas == 0); /* no components on this path */
    route_arr[b] = (int32_t)route;
    handled++;

    Py_DECREF(placement);
    Py_DECREF(resource);
    Py_DECREF(rr);
    continue;

  item_error:
    Py_XDECREF(placement);
    Py_XDECREF(resource);
    Py_XDECREF(rr);
    goto loop_error;
  }

  Py_DECREF(fast_items);
  PyBuffer_Release(&b_route);
  PyBuffer_Release(&b_nws);
  PyBuffer_Release(&b_nw);
  PyBuffer_Release(&b_fresh);
  PyBuffer_Release(&b_uid);
  PyBuffer_Release(&b_rep);
  PyBuffer_Release(&b_cls);
  PyBuffer_Release(&b_gvk);
  PyBuffer_Release(&b_pid);
  return PyLong_FromSsize_t(handled);

loop_error:
  Py_DECREF(fast_items);
fail9:
  PyBuffer_Release(&b_route);
fail8:
  PyBuffer_Release(&b_nws);
fail7:
  PyBuffer_Release(&b_nw);
fail6:
  PyBuffer_Release(&b_fresh);
fail5:
  PyBuffer_Release(&b_uid);
fail4:
  PyBuffer_Release(&b_rep);
fail3:
  PyBuffer_Release(&b_cls);
fail2:
  PyBuffer_Release(&b_gvk);
fail1:
  PyBuffer_Release(&b_pid);
  return NULL;
}

/* decode_fast(bounds, c_arr, vv, name_rank, names, non_workload, status,
 *             tc_type, empty_prop, out)
 *
 * Builds the per-binding TargetCluster lists for every binding whose
 * status is 0 and whose out[] slot is still None (errors are Python's).
 * bounds: int64[nb+1] row boundaries into c_arr/vv (row-major COO);
 * name_rank orders construction so each list is name-sorted without a
 * Python sort. Returns None.
 */
static PyObject *decode_fast(PyObject *self, PyObject *args) {
  PyObject *a_bounds, *a_c, *a_v, *a_rank, *names, *a_nw, *a_status;
  PyObject *tc_type, *out;
  int empty_prop = 0;
  if (!PyArg_ParseTuple(args, "OOOOOOOOpO", &a_bounds, &a_c, &a_v, &a_rank,
                        &names, &a_nw, &a_status, &tc_type, &empty_prop,
                        &out))
    return NULL;

  Py_buffer b_bounds, b_c, b_v, b_rank, b_nw, b_status;
  if (PyObject_GetBuffer(a_bounds, &b_bounds, PyBUF_SIMPLE) < 0) return NULL;
  if (PyObject_GetBuffer(a_c, &b_c, PyBUF_SIMPLE) < 0) goto dfail1;
  if (PyObject_GetBuffer(a_v, &b_v, PyBUF_SIMPLE) < 0) goto dfail2;
  if (PyObject_GetBuffer(a_rank, &b_rank, PyBUF_SIMPLE) < 0) goto dfail3;
  if (PyObject_GetBuffer(a_nw, &b_nw, PyBUF_SIMPLE) < 0) goto dfail4;
  if (PyObject_GetBuffer(a_status, &b_status, PyBUF_SIMPLE) < 0) goto dfail5;

  const int64_t *bounds = (const int64_t *)b_bounds.buf;
  const int64_t *c_arr = (const int64_t *)b_c.buf;
  const int64_t *v_arr = (const int64_t *)b_v.buf;
  const int64_t *rank = (const int64_t *)b_rank.buf;
  const uint8_t *nw = (const uint8_t *)b_nw.buf;
  const int32_t *status = (const int32_t *)b_status.buf;
  Py_ssize_t nb = PyList_GET_SIZE(out);

  for (Py_ssize_t b = 0; b < nb; b++) {
    if (status[b] != 0) continue;               /* error: Python's slot */
    if (PyList_GET_ITEM(out, b) != Py_None) continue;
    int64_t lo = bounds[b], hi = bounds[b + 1];
    int64_t m = hi - lo;
    /* wide rows (fleet-wide Duplicated / non-workload selections) would
     * make the insertion sort quadratic — Python's timsort owns them */
    if (m > 256) continue;
    PyObject *targets = PyList_New(0);
    if (targets == NULL) goto dloop_error;

    /* insertion-sort the row by name rank (rows are tiny) */
    int64_t order[64];
    int use_stack = (m <= 64);
    int64_t *ord = order;
    if (!use_stack) {
      ord = (int64_t *)PyMem_Malloc(sizeof(int64_t) * (size_t)m);
      if (ord == NULL) {
        Py_DECREF(targets);
        goto dloop_error;
      }
    }
    for (int64_t j = 0; j < m; j++) ord[j] = lo + j;
    for (int64_t j = 1; j < m; j++) {
      int64_t key = ord[j];
      int64_t kr = rank[c_arr[key]];
      int64_t i = j - 1;
      while (i >= 0 && rank[c_arr[ord[i]]] > kr) {
        ord[i + 1] = ord[i];
        i--;
      }
      ord[i + 1] = key;
    }

    int is_nw = nw[b];
    int ok = 1;
    for (int64_t j = 0; j < m && ok; j++) {
      int64_t e = ord[j];
      int64_t v = v_arr[e];
      long out_rep;
      if (is_nw) {
        out_rep = 0;
      } else if (v > 0) {
        out_rep = (long)v;
      } else if (empty_prop) {
        out_rep = 0;
      } else {
        continue;
      }
      PyObject *name = PyList_GET_ITEM(names, c_arr[e]); /* borrowed */
      PyObject *rep = PyLong_FromLong(out_rep);
      if (rep == NULL) {
        ok = 0;
        break;
      }
      PyObject *tc = PyObject_CallFunctionObjArgs(tc_type, name, rep, NULL);
      Py_DECREF(rep);
      if (tc == NULL || PyList_Append(targets, tc) < 0) {
        Py_XDECREF(tc);
        ok = 0;
        break;
      }
      Py_DECREF(tc);
    }
    if (!use_stack) PyMem_Free(ord);
    if (!ok) {
      Py_DECREF(targets);
      goto dloop_error;
    }
    if (PyList_SetItem(out, b, targets) < 0) goto dloop_error; /* steals */
  }

  PyBuffer_Release(&b_status);
  PyBuffer_Release(&b_nw);
  PyBuffer_Release(&b_rank);
  PyBuffer_Release(&b_v);
  PyBuffer_Release(&b_c);
  PyBuffer_Release(&b_bounds);
  Py_RETURN_NONE;

dloop_error:
  PyBuffer_Release(&b_status);
dfail5:
  PyBuffer_Release(&b_nw);
dfail4:
  PyBuffer_Release(&b_rank);
dfail3:
  PyBuffer_Release(&b_v);
dfail2:
  PyBuffer_Release(&b_c);
dfail1:
  PyBuffer_Release(&b_bounds);
  return NULL;
}

static PyMethodDef methods[] = {
    {"encode_fast", encode_fast, METH_VARARGS,
     "Fast per-binding encode loop; returns count handled."},
    {"decode_fast", decode_fast, METH_VARARGS,
     "Fast per-binding result-list construction."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef module = {
    PyModuleDef_HEAD_INIT, "_encode_fast", NULL, -1, methods,
};

PyMODINIT_FUNC PyInit__encode_fast(void) {
  s_placement = PyUnicode_InternFromString("placement");
  s_resource = PyUnicode_InternFromString("resource");
  s_api_version = PyUnicode_InternFromString("api_version");
  s_kind = PyUnicode_InternFromString("kind");
  s_uid = PyUnicode_InternFromString("uid");
  s_replicas = PyUnicode_InternFromString("replicas");
  s_replica_requirements = PyUnicode_InternFromString("replica_requirements");
  s_resource_request = PyUnicode_InternFromString("resource_request");
  s_milli = PyUnicode_InternFromString("milli");
  s_components = PyUnicode_InternFromString("components");
  s_clusters = PyUnicode_InternFromString("clusters");
  s_gets = PyUnicode_InternFromString("graceful_eviction_tasks");
  s_reschedule = PyUnicode_InternFromString("reschedule_triggered_at");
  s_cluster_affinity = PyUnicode_InternFromString("cluster_affinity");
  s_cluster_affinities = PyUnicode_InternFromString("cluster_affinities");
  return PyModule_Create(&module);
}
