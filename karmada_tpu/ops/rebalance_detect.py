"""Rebalance detect kernel: per-cluster overcommit + spread divergence.

The rebalance plane (karmada_tpu/rebalance) closes the control loop the
reference runs in pkg/descheduler: every rebalance interval it scores the
FLEET — how overcommitted is each cluster against its capacity, and how
far does the committed-replica share diverge from the capacity share —
and selects drain candidates.  The scoring is one small jitted kernel
over [C] tensors (the same dense shape discipline as ops/solver.py): on
an accelerator the resident cluster tensors are already device-side, so
the per-interval detect costs one tiny dispatch, not a host scan.

All math is int64 in milli units (ratios x1000) — no float anywhere, so
the drain plan is bit-deterministic across backends and replays exactly
in virtual-clock soaks.

Outputs per cluster:
  drain_need   replicas to shed to get back inside the thresholds
               (max of the overcommit need and the gated spread need)
  over_milli   committed/capacity ratio x1000 (capacity 0 with load
               reports OVER_SATURATED)
  div_milli    committed-share minus capacity-share, x1000 (positive =
               this cluster carries more than its fair share)
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_enable_x64", True)

#: over_milli sentinel for "committed load on a cluster with zero
#: usable capacity" — saturated beyond any finite ratio
OVER_SATURATED = np.int64(1) << 30


@partial(jax.jit, static_argnames=("threshold_milli", "spread_tol_milli"))
def score_kernel(committed, capacity, valid,
                 threshold_milli: int, spread_tol_milli: int):
    """committed/capacity int64 [C], valid bool [C]; thresholds static
    milli ints (they change only by operator reconfig, like `waves`)."""
    cap = jnp.where(valid, jnp.maximum(capacity, 0), 0)
    com = jnp.where(valid, jnp.maximum(committed, 0), 0)
    sat = jnp.asarray(OVER_SATURATED, dtype=jnp.int64)
    over_milli = jnp.where(
        cap > 0, com * 1000 // jnp.maximum(cap, 1),
        jnp.where(com > 0, sat, 0))
    # overcommit: drain down to floor(threshold * capacity)
    allowed = cap * threshold_milli // 1000
    over_need = jnp.maximum(com - allowed, 0)
    # spread divergence: committed share vs capacity share of the fleet
    total_com = jnp.sum(com)
    total_cap = jnp.sum(cap)
    share_milli = jnp.where(total_com > 0,
                            com * 1000 // jnp.maximum(total_com, 1), 0)
    fair_milli = jnp.where(total_cap > 0,
                           cap * 1000 // jnp.maximum(total_cap, 1), 0)
    div_milli = share_milli - fair_milli
    # spread need only gates in when divergence exceeds the tolerance:
    # drain down to (fair share + tolerance) of the committed total
    spread_allowed = (fair_milli + spread_tol_milli) * total_com // 1000
    spread_need = jnp.where(div_milli > spread_tol_milli,
                            jnp.maximum(com - spread_allowed, 0), 0)
    drain_need = jnp.where(valid, jnp.maximum(over_need, spread_need), 0)
    return drain_need, over_milli, div_milli


def score(committed: np.ndarray, capacity: np.ndarray, valid: np.ndarray,
          threshold_milli: int, spread_tol_milli: int):
    """Host wrapper: int64/bool device round-trip of the detect kernel,
    results back as numpy (the drain planner is host-side)."""
    drain_need, over_milli, div_milli = score_kernel(
        np.ascontiguousarray(committed, dtype=np.int64),
        np.ascontiguousarray(capacity, dtype=np.int64),
        np.ascontiguousarray(valid, dtype=bool),
        threshold_milli=int(threshold_milli),
        spread_tol_milli=int(spread_tol_milli))
    return (np.asarray(drain_need), np.asarray(over_milli),
            np.asarray(div_milli))
