"""AOT executable plane: persistent compile cache + warm-start pre-compiles.

A fresh serve plane used to pay the full jit compile warmup (~100s of
`compile_warmup_s` in BENCH_r02) because the persistent compilation cache
lived only in bench.py and nothing pre-compiled the solver executables
before the first real cycle.  This module owns both halves of the fix:

* ``enable()`` — the ONE place the jax persistent compilation cache is
  armed (bench.py's three call sites and ``serve --aot-cache`` all land
  here).  The cache directory is keyed by platform, host CPU features,
  jax version and the configured mesh topology so an artifact compiled
  on one host/layout is never loaded on an incompatible one (XLA:CPU
  executables are host-feature-specific — observed SIGILL risk), while
  accelerator executables (which target the chip, not the host) share
  one dir across hosts.  Arming also registers a jax monitoring listener
  that feeds ``karmada_solver_compile_cache_{hits,misses}_total`` — the
  cold-start story is measured, not guessed.

* ``warm_executables()`` — AOT pre-compile of the compact-solve
  executables for every pow2 batch shape x jit variant the pipeline can
  dispatch (plain / explain / carry / donated, mesh-placed when a solver
  mesh is active) via the pjit ``.lower().compile()`` surface.  Nothing
  executes: lowering runs from abstract ShapeDtypeStructs, so warming
  never touches the device-transfer cache, never donates a real buffer,
  and never produces a result to discard.  With the persistent cache
  armed the compiles land on disk, so the FIRST real dispatch of a
  warmed shape (and every later process) pays deserialization instead
  of compilation.  ``start_background_warmup()`` runs it on a daemon
  thread under a ``solver.warmup`` flight-recorder span — the serve
  plane schedules its first cycle while the warm set compiles behind it.

``state_payload()`` serves the ``aot`` section of ``/debug/state``.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from karmada_tpu.utils.metrics import REGISTRY

#: jit variants of the compact dispatch the pipeline can reach
#: (scheduler/pipeline.py): plain single-chunk cycles, the explain jit
#: variant of sampled cycles, the with_used carry chain of multi-chunk
#: cycles, and its buffer-donated form.
VARIANT_PLAIN = "plain"
VARIANT_EXPLAIN = "explain"
VARIANT_CARRY = "carry"
VARIANT_DONATED = "donated"
#: the fused resident-gather executable (ops/resident_gather) — not a
#: solver variant (the solver jit signature is identical for fused
#: dispatches: same avals, device-placed operands), but its OWN jit that
#: must be warm per pow2 batch shape or the first fused cycle mid-soak
#: eats a silent compile
VARIANT_FUSED = "fused"
#: the shortlist tier (ops/shortlist): TWO executables per shape — the
#: tier-1 candidate kernel at (B, C, k), and the tier-2 [B, C'] solver
#: over the sub-vocabulary width the first shortlisted chunks will
#: dispatch.  Without both the first shortlisted cycle mid-soak eats a
#: silent compile exactly like the fused/explain variants used to.
VARIANT_SHORTLIST = "shortlist"
ALL_VARIANTS = (VARIANT_PLAIN, VARIANT_EXPLAIN, VARIANT_CARRY,
                VARIANT_DONATED)

COMPILE_CACHE_HITS = REGISTRY.counter(
    "karmada_solver_compile_cache_hits_total",
    "Solver executables served from the persistent compilation cache",
)
COMPILE_CACHE_MISSES = REGISTRY.counter(
    "karmada_solver_compile_cache_misses_total",
    "Solver compilations the persistent compilation cache could not serve",
)

# guarded-by: _LOCK; mutators: enable,disable_for_tests,_set_warm,_listener
_STATE: Dict[str, object] = {
    "armed": False,
    "cache_dir": None,
    "key": None,
    # per-(shape, variant) warm ledger: "B{b}xC{c}:{variant}" ->
    # {"state": pending|compiling|done|error|skipped, "seconds": float}
    "warmup": {},
    "warmup_thread": None,  # "running" | "done" | "error: ..." | None
}
_LOCK = threading.Lock()
_LISTENER_ARMED = False

_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_MISS_EVENT = "/jax/compilation_cache/cache_misses"


def _listener(event: str, **_kw) -> None:
    """jax monitoring tap: count persistent-cache hits/misses as they
    happen (every jit compile in the process flows through here once
    the cache is armed)."""
    if event == _HIT_EVENT:
        COMPILE_CACHE_HITS.inc()
    elif event == _MISS_EVENT:
        COMPILE_CACHE_MISSES.inc()


def machine_tag() -> str:
    """Short stable fingerprint of this host's CPU feature set.

    XLA:CPU executables are compiled FOR the build host's CPU features;
    loading one on a host with a different feature set risks SIGILL.
    Unknown layouts (non-x86/arm, unreadable /proc) fall back to the full
    uname PLUS a marker so those hosts at least never share a dir with a
    feature-fingerprinted one."""
    keys = ("flags", "Features", "model name", "vendor_id", "cpu family",
            "CPU implementer", "CPU part")
    ident: List[str] = []
    try:
        with open("/proc/cpuinfo") as f:
            seen = set()
            for ln in f:
                k = ln.split(":", 1)[0].strip()
                if k in keys and k not in seen:
                    seen.add(k)
                    ident.append(ln.strip())
    except OSError:
        pass
    if not ident:
        import platform

        ident = ["nocpuinfo", *platform.uname()]
    return hashlib.sha1("|".join(ident).encode()).hexdigest()[:12]


def cache_key(platform_hint: str = "cpu", mesh=None) -> str:
    """The cache-dir key: platform (accelerator executables target the
    CHIP and share one dir across hosts; CPU artifacts are host-feature
    bound), jax version (serialized executables are not stable across
    jax/jaxlib upgrades), and the configured solver-mesh topology (a
    sharded program is a different executable family — keeping them in
    separate dirs keeps each dir's working set tight)."""
    import jax

    base = "accel-shared" if platform_hint == "accel" else machine_tag()
    key = f"{base}-jax{jax.__version__}"
    if mesh:
        shape = mesh if isinstance(mesh, str) else "x".join(
            str(int(d)) for d in mesh)
        key += f"-mesh{shape}"
    return key


def default_cache_root() -> str:
    """<repo root>/.jax_compile_cache — the same root bench.py always
    used, shared by every entry point on this checkout."""
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))),
        ".jax_compile_cache")


def enable(cache_dir: Optional[str] = None, *, platform_hint: str = "cpu",
           mesh=None, min_compile_time_s: float = 1.0) -> Dict[str, object]:
    """Arm the persistent compilation cache (must precede the first jit).

    cache_dir None uses ``default_cache_root()/<cache_key()>``; an
    explicit dir is used verbatim (the two-process cold-start bench
    points both children at one tmp dir).  min_compile_time_s below
    jax's default of 1.0 persists even trivial compiles — what the
    cold-start measurement needs to assert ZERO misses on a warm cache.
    Returns the state payload.  Failure to arm (older jax) degrades to
    the unarmed behavior: the cache is an optimization only."""
    global _LISTENER_ARMED
    import jax

    key = cache_key(platform_hint, mesh)
    if cache_dir is None:
        cache_dir = os.path.join(default_cache_root(), key)
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_time_s))
    # vet: ignore[exception-hygiene] older jax: the persistent cache is an optimization only
    except Exception:  # noqa: BLE001 — older jax: cache is optional
        return state_payload()
    try:
        # jax memoizes the is-cache-used decision at the FIRST compile: a
        # process that already jitted anything before enable() (tests, a
        # plane that armed late) would otherwise silently never use the
        # dir; reset_cache() makes it re-evaluate against the new config
        from jax._src import compilation_cache as _cc  # noqa: SLF001

        _cc.reset_cache()
    # vet: ignore[exception-hygiene] private surface varies by jax version; fresh processes don't need the reset
    except Exception:  # noqa: BLE001 — best-effort re-evaluation
        pass
    try:
        if not _LISTENER_ARMED:
            from jax._src import monitoring  # noqa: SLF001 — no public surface

            monitoring.register_event_listener(_listener)
            _LISTENER_ARMED = True
    # vet: ignore[exception-hygiene] hit/miss attribution degrades to the warm ledger only
    except Exception:  # noqa: BLE001 — attribution unavailable on this jax
        pass
    with _LOCK:
        _STATE["armed"] = True
        _STATE["cache_dir"] = cache_dir
        _STATE["key"] = key
    return state_payload()


def disable_for_tests() -> None:
    """Point jax back at no cache dir and clear the armed state (tests
    that measure cold behavior)."""
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", None)
        from jax._src import compilation_cache as _cc  # noqa: SLF001

        # drop the initialized cache object too (it holds the old dir)
        _cc.reset_cache()
    # vet: ignore[exception-hygiene] best-effort teardown in tests
    except Exception:  # noqa: BLE001 — config shape differs on older jax
        pass
    with _LOCK:
        _STATE["armed"] = False
        _STATE["cache_dir"] = None
        _STATE["key"] = None
        _STATE["warmup"] = {}
        _STATE["warmup_thread"] = None


def counters() -> Tuple[int, int]:
    """(hits, misses) of the persistent compilation cache so far."""
    return int(COMPILE_CACHE_HITS.value()), int(COMPILE_CACHE_MISSES.value())


def state_payload() -> Dict[str, object]:
    """The ``aot`` section of /debug/state: cache dir + key, hit/miss
    counters, and the per-shape warm ledger."""
    hits, misses = counters()
    with _LOCK:
        return {
            "armed": bool(_STATE["armed"]),
            "cache_dir": _STATE["cache_dir"],
            "key": _STATE["key"],
            "hits": hits,
            "misses": misses,
            "warmup": dict(_STATE["warmup"]),  # shallow: values replaced whole
            "warmup_thread": _STATE["warmup_thread"],
        }


def _set_warm(label: str, state: str, seconds: Optional[float] = None,
              cost: Optional[dict] = None) -> None:
    with _LOCK:
        rec: Dict[str, object] = {"state": state}
        if seconds is not None:
            rec["seconds"] = round(seconds, 3)
        if cost:
            # device cost attribution (obs/devprof): the executable's
            # cost_analysis() harvest rides in the ledger so
            # /debug/state's aot section shows flops/bytes per
            # shape x variant
            rec["cost"] = dict(cost)
        _STATE["warmup"][label] = rec


# -- synthetic warm workload --------------------------------------------------


def synth_items(n: int):
    """(spec, status) pairs for warm encodes: the loadgen shape —
    Duplicated placement over every feasible cluster, one replica — so
    the encoded batch routes ROUTE_DEVICE and exercises the same compact
    executable real traffic does."""
    from karmada_tpu.models.policy import (
        REPLICA_SCHEDULING_DUPLICATED,
        Placement,
        ReplicaSchedulingStrategy,
    )
    from karmada_tpu.models.work import (
        ObjectReference,
        ResourceBindingSpec,
        ResourceBindingStatus,
    )

    placement = Placement(replica_scheduling=ReplicaSchedulingStrategy(
        replica_scheduling_type=REPLICA_SCHEDULING_DUPLICATED))
    items = []
    for i in range(n):
        spec = ResourceBindingSpec(
            resource=ObjectReference(
                api_version="apps/v1", kind="Deployment",
                namespace="karmada-warmup", name=f"aot-warm-{i}",
                uid=f"aot-warm-uid-{i}"),
            replicas=1,
            placement=placement,
        )
        items.append((spec, ResourceBindingStatus()))
    return items


def warm_shapes(batch_window: int, pipeline_chunk: int) -> Tuple[int, ...]:
    """Every pow2 binding-axis bucket a serve cycle can dispatch: the
    pipelined executor cuts cycles into pipeline_chunk-sized chunks, and
    encode_batch pads B UP to the next pow2 (min 8) — so the top bucket
    is the pow2 ceiling of min(batch_window, pipeline_chunk), not its
    floor (a 1000-binding chunk encodes as B=1024 and must be warmed)."""
    cap = max(8, min(int(batch_window), int(pipeline_chunk)))
    shapes = []
    b = 8
    while b < cap:
        shapes.append(b)
        b *= 2
    shapes.append(b)  # the pow2 ceiling bucket full chunks pad into
    return tuple(shapes)


def variants_for(explain_rate: float, multi_chunk: bool,
                 fused: bool = False,
                 shortlist: bool = False) -> Tuple[str, ...]:
    """The jit-variant set THIS scheduler configuration can actually
    dispatch (warming more would spend background compile time on
    programs that never run): plain always; explain only when the
    explain plane samples; carry + donated only when cycles can span
    multiple chunks (batch_window > pipeline_chunk); the fused
    resident-gather executable only when the fused resident path is
    armed (Scheduler resident_fused); the shortlist tier pair only when
    the two-tier solve is armed (Scheduler shortlist_k)."""
    variants = [VARIANT_PLAIN]
    if explain_rate and explain_rate > 0:
        variants.append(VARIANT_EXPLAIN)
    if multi_chunk:
        variants += [VARIANT_CARRY, VARIANT_DONATED]
    if fused:
        variants.append(VARIANT_FUSED)
    if shortlist:
        variants.append(VARIANT_SHORTLIST)
    return tuple(variants)


def _resident_slot_cap() -> int:
    """The active resident plane's slot-store capacity (the fused gather
    jit signature includes it), else the smallest geometry (64) — distinct
    requested caps re-warm lazily as the store grows."""
    from karmada_tpu import resident

    state = resident.active()
    if state is not None and state.plane is not None:
        return int(state.plane.placement_id.shape[0])
    return 64


def warm_executables(
    clusters: Sequence,
    estimator,
    *,
    shapes: Iterable[int] = (8, 16, 32, 64),
    variants: Sequence[str] = ALL_VARIANTS,
    waves: int = 8,
    keep_sel: bool = False,
    cancelled: Optional[threading.Event] = None,
    resident_cap: Optional[int] = None,
    shortlist_k: Optional[int] = None,
) -> Dict[str, object]:
    """AOT pre-compile the compact dispatch for every (pow2 shape x jit
    variant) against THIS cluster fleet via ``.lower().compile()``
    (ops/solver.aot_warm_compile).  Synthetic bindings only feed the
    ENCODER (host-side numpy) — nothing executes on device, and with the
    persistent cache armed every compile lands on disk for later
    processes.  Mesh-placed variants are compiled when a solver mesh is
    active at call time.  Returns {label: seconds|error} plus totals;
    the per-shape ledger also lands in state_payload()."""
    from karmada_tpu import obs
    from karmada_tpu.ops import solver, tensors

    t_all = time.perf_counter()
    results: Dict[str, object] = {}
    compiled = 0
    compile_s_total = 0.0
    lower_s_total = 0.0
    span = (obs.TRACER.start_span(obs.SPAN_WARMUP,
                                  shapes=list(shapes),
                                  variants=list(variants))
            if obs.TRACER.enabled else None)
    try:
        cindex = tensors.ClusterIndex.build(list(clusters))
        cache = tensors.EncoderCache()
        for n in shapes:
            if cancelled is not None and cancelled.is_set():
                break
            # one explain-encoded batch serves every variant: pl_fail_bits
            # rides along unused by the disarmed signatures (the disarmed
            # program is byte-identical with or without it — PR-5 gate)
            cache.reset_for_cycle()
            batch = tensors.encode_batch(synth_items(n), cindex, estimator,
                                         cache=cache, explain=True)
            for variant in variants:
                if variant == VARIANT_FUSED:
                    # the fused gather's signature is (B, slot cap, sparse
                    # widths), not (B, C): label it by its own geometry so
                    # a grown slot store re-warms under a fresh key
                    cap = (int(resident_cap) if resident_cap
                           else _resident_slot_cap())
                    label = f"B{batch.B}xS{cap}:{variant}"
                elif variant == VARIANT_SHORTLIST:
                    sk = int(shortlist_k or 64)
                    label = f"B{batch.B}xC{batch.C}:k{sk}:{variant}"
                else:
                    label = f"B{batch.B}xC{batch.C}:{variant}"
                with _LOCK:
                    prior = _STATE["warmup"].get(label)
                if prior is not None and prior.get("state") == "done":
                    # distinct requested sizes can pad to one pow2 bucket;
                    # one compile per (shape x variant) is enough
                    results[label] = "already-warm"
                    continue
                if cancelled is not None and cancelled.is_set():
                    _set_warm(label, "skipped")
                    continue
                _set_warm(label, "compiling")
                t0 = time.perf_counter()
                try:
                    if variant == VARIANT_FUSED:
                        from karmada_tpu.ops import meshing, resident_gather

                        timings = resident_gather.aot_warm(
                            batch.B, cap=cap,
                            Kp=batch.prev_idx.shape[1],
                            Ke=batch.evict_idx.shape[1],
                            plan=meshing.active())
                    elif variant == VARIANT_SHORTLIST:
                        from karmada_tpu.ops import meshing, shortlist
                        from karmada_tpu.ops import tensors as _T

                        timings = shortlist.aot_warm(
                            batch, k=min(sk, batch.C),
                            plan=meshing.active())
                        # the tier-2 [B, C'] solver over the most likely
                        # sub-vocabulary bucket (pow2 ceiling of 2k —
                        # wider unions re-warm lazily at dispatch):
                        # encode the synth items against a truncated
                        # fleet so the warmed aval set IS a sub-shape
                        sub_n = min(len(cindex.clusters),
                                    _T._next_pow2(2 * sk, 8))  # noqa: SLF001
                        sub_cindex = _T.ClusterIndex.build(
                            cindex.clusters[:sub_n])
                        sub_batch = _T.encode_batch(
                            synth_items(n), sub_cindex, estimator,
                            explain=True)
                        t2 = solver.aot_warm_compile(
                            sub_batch, waves=waves, keep_sel=keep_sel,
                            variant=VARIANT_PLAIN)
                        timings = dict(timings)
                        timings["tier2"] = {
                            "shape": f"B{sub_batch.B}xC{sub_batch.C}",
                            **t2}
                        timings["compile_s"] = (timings["compile_s"]
                                                + t2["compile_s"])
                        timings["lower_s"] = (timings["lower_s"]
                                              + t2["lower_s"])
                    else:
                        timings = solver.aot_warm_compile(
                            batch, waves=waves, keep_sel=keep_sel,
                            variant=variant)
                    dt = time.perf_counter() - t0
                    cost = timings.get("cost")
                    _set_warm(label, "done", dt, cost=cost)
                    from karmada_tpu.obs import devprof

                    devprof.record_cost(label, cost)
                    results[label] = {"seconds": round(dt, 3), **timings}
                    compile_s_total += timings["compile_s"]
                    lower_s_total += timings["lower_s"]
                    compiled += 1
                # vet: ignore[exception-hygiene] warm is best-effort; the error is kept in the ledger
                except Exception as e:  # noqa: BLE001 — warm must never kill serve
                    _set_warm(label, f"error: {e!r:.200}")
                    results[label] = f"error: {e!r:.200}"
    finally:
        if span is not None:
            span.end(compiled=compiled,
                     seconds=round(time.perf_counter() - t_all, 3))
    hits, misses = counters()
    results["_totals"] = {"compiled": compiled,
                          "seconds": round(time.perf_counter() - t_all, 3),
                          # the XLA-compile share (what the persistent
                          # cache serves) vs tracing (paid every process)
                          "compile_s": round(compile_s_total, 3),
                          "lower_s": round(lower_s_total, 3),
                          "hits": hits, "misses": misses}
    return results


def start_background_warmup(
    clusters_fn: Callable[[], Sequence],
    estimator,
    *,
    shapes: Iterable[int],
    variants: Sequence[str],
    waves: int = 8,
    keep_sel: bool = False,
    resident_cap: Optional[int] = None,
    shortlist_k: Optional[int] = None,
) -> threading.Thread:
    """Run warm_executables on a daemon thread (serve: the plane takes
    traffic immediately; warmed shapes stop paying compiles as they
    land).  clusters_fn is called ON the thread so warmup sees the
    store's state at warm time, not at arm time."""

    def run() -> None:
        with _LOCK:
            _STATE["warmup_thread"] = "running"
        try:
            clusters = list(clusters_fn())
            if not clusters:
                with _LOCK:
                    _STATE["warmup_thread"] = "done (no clusters)"
                return
            warm_executables(clusters, estimator, shapes=shapes,
                             variants=variants, waves=waves,
                             keep_sel=keep_sel, resident_cap=resident_cap,
                             shortlist_k=shortlist_k)
            with _LOCK:
                _STATE["warmup_thread"] = "done"
        # vet: ignore[exception-hygiene] background warm must never kill serve; state kept for /debug/state
        except Exception as e:  # noqa: BLE001 — warm is best-effort
            with _LOCK:
                _STATE["warmup_thread"] = f"error: {e!r:.200}"

    t = threading.Thread(target=run, daemon=True, name="solver-aot-warmup")
    t.start()
    return t
