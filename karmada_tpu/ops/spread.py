"""Topology spread on device (SURVEY §2.9 masked tensor search).

Reference: pkg/scheduler/core/spreadconstraint/ — group clusters by region
with scores + available replicas (group_clusters.go:220-333), pick the
best region combination by DFS (select_groups.go:102-230), then pick
clusters within the chosen regions (select_clusters_by_region.go:27-118).

Device split: the O(C) per-cluster work — grouping, the sorted-prefix
group-score walk, and the final cluster pick — runs as one vmapped jitted
program over the dense batch; ONLY the DFS over G group-level scalars runs
on host, and it IS serial.select_groups itself, so path prioritization and
the sub-path rule match the golden path by construction.

The group axis is GENERIC: region spread uses the fleet's region ids;
spread-by-label placements use a per-label-key vocabulary of label VALUES
(tensors.encode_batch builds both), with identical group math — the
framework's extension beyond the reference, whose scheduler never
implemented SpreadByLabel (select_clusters.go:55 fails it).  Group math is
SEGMENTED (a (group, sort-key) lexicographic sort + segment reductions),
so memory is O(B x C) regardless of the group count — there is no
[B, G, C] membership plane and no fixed group-lane cap (the r4 design's
MAX_DEVICE_REGIONS=16 ceiling is retired; VERDICT r4 item 3).

Flow (ops.spread.solve_spread):
  phase A (device)  group scalars per binding: score/avail/value [B_s, G]
  host              serial.select_groups over G scalars -> chosen groups
  phase B (device)  ONE fused jit: cluster pick inside chosen groups ->
                    placement mask -> solver._schedule_core assignment
                    (tier "std" or "big" — bindings beyond the tier-1
                    compact caps run the big lane tier instead of falling
                    to host) -> compact COO extraction.  Only [B, G]
                    scalars and the compact result ever cross the device
                    boundary — a remote-attached backend ships every jit
                    output to the host, so plane-sized outputs are the
                    cost (see solver.schedule_compact).
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from karmada_tpu.ops import serial
from karmada_tpu.ops.solver import (
    MAX_INT32,
    MAX_INT64,
    _AVAIL_BITS,
    _AVAIL_CAP,
    _LANE_BITS,
    _capacity_estimates,
    _compact_of,
    _explain_outcome,
    _explain_verdict,
    _locality_score,
    _schedule_core,
    _use_extra,
)

WEIGHT_UNIT = serial.WEIGHT_UNIT  # 1000 (group_clusters.go:139)
_BIG = jnp.int64(MAX_INT64)  # larger than any real packed key


def _sort_key(score, avail, name_rank, feasible):
    """The spreadconstraint sortClusters order: score desc, avail desc,
    name asc (util.go) — same packing as the solver's selection key."""
    avail_c = jnp.clip(avail, 0, _AVAIL_CAP)
    key = (
        ((200 - score).astype(jnp.int64) << (_AVAIL_BITS + _LANE_BITS))
        | ((_AVAIL_CAP - avail_c) << _LANE_BITS)
        | name_rank
    )
    return jnp.where(feasible, key, _BIG)


def _group_info_one(
    feasible, avail_sel, score, name_rank, group_id,
    replicas, region_min, cluster_min, duplicated, G: int,
):
    """Group tensors for ONE binding: (score_g, avail_g, value_g).

    Ports _calc_group_score / _calc_group_score_duplicate
    (group_clusters.go:141-333).  The per-group sorted-prefix walk runs as
    SEGMENTED scans over a (group, sort-key) lexicographically ordered
    cluster axis: O(C) working set plus [G] segment reductions — no [G, C]
    membership plane, so the group axis scales to arbitrarily many
    regions / label values.
    """
    C = feasible.shape[0]
    key = _sort_key(score, avail_sel, name_rank, feasible)
    gid = jnp.where(feasible & (group_id >= 0), group_id.astype(jnp.int32), G)
    # lexicographic (group asc, key asc): stable argsort by key, then by
    # group — within a group, clusters stay in sortClusters order
    order1 = jnp.argsort(key)
    order = order1[jnp.argsort(gid[order1], stable=True)]
    seg = gid[order]
    f = feasible[order] & (seg < G)
    av = jnp.where(f, avail_sel[order], 0)
    sc = jnp.where(f, score[order], 0)
    cnt = f.astype(jnp.int64)
    pos = jnp.arange(C, dtype=jnp.int64)
    boundary = jnp.concatenate([jnp.ones((1,), bool), seg[1:] != seg[:-1]])
    start = lax.cummax(jnp.where(boundary, pos, 0))

    def seg_cum(x):
        t = jnp.cumsum(x)
        return t - t[start] + x[start]

    cum_avail, cum_cnt, cum_score = seg_cum(av), seg_cum(cnt), seg_cum(sc)
    nseg = G + 1  # segment G collects infeasible / group-less lanes
    value_g = jax.ops.segment_sum(cnt, seg, num_segments=nseg)[:G]
    avail_g = jax.ops.segment_sum(av, seg, num_segments=nseg)[:G]
    score_sum_g = jax.ops.segment_sum(sc, seg, num_segments=nseg)[:G]

    # Divided score (group_clusters.go:220-333): walk the group's clusters
    # in sorted order until >= cluster_min members AND >= target available
    mg = jnp.maximum(region_min, 1)
    target_d = -(-replicas // mg)  # ceil, matches math.ceil(replicas/min)
    target_d = jnp.where(region_min > 0, target_d, replicas)
    cmin = jnp.maximum(cluster_min, region_min)
    ok = f & (cum_cnt >= cmin) & (cum_avail >= target_d)
    first = jax.ops.segment_min(
        jnp.where(ok, pos, C), seg, num_segments=nseg)[:G]
    has = first < C
    fc = jnp.minimum(first, C - 1)
    valid = cum_cnt[fc]
    # exhausted-walk semantics (group_clusters.go:300-308): only
    # INSUFFICIENT AVAILABLE demotes the score; a group that merely has
    # fewer than cluster_min members still scores target*UNIT with the
    # whole group as `valid`
    div_score = jnp.where(
        has,
        target_d * WEIGHT_UNIT + cum_score[fc] // jnp.maximum(valid, 1),
        jnp.where(
            avail_g >= target_d,
            target_d * WEIGHT_UNIT + score_sum_g // jnp.maximum(value_g, 1),
            avail_g * WEIGHT_UNIT + score_sum_g // jnp.maximum(value_g, 1),
        ),
    )

    # Duplicated score (group_clusters.go:141-218)
    fits = f & (av >= replicas)
    n_fit = jax.ops.segment_sum(
        fits.astype(jnp.int64), seg, num_segments=nseg)[:G]
    fit_score = jax.ops.segment_sum(
        jnp.where(fits, sc, 0), seg, num_segments=nseg)[:G]
    dup_score = jnp.where(
        n_fit > 0, n_fit * WEIGHT_UNIT + fit_score // jnp.maximum(n_fit, 1), 0
    )

    score_g = jnp.where(duplicated, dup_score, div_score)
    score_g = jnp.where(value_g > 0, score_g, 0)
    return score_g, avail_g, value_g


_group_info_vmap = jax.vmap(
    _group_info_one, in_axes=(0, 0, 0, None, None, 0, 0, 0, 0, None)
)


def _spread_planes(
    cluster_valid, deleting, pods_allowed, has_summary, avail_milli,
    has_alloc, api_ok, req_milli, req_is_cpu, req_pods, est_override,
    pl_mask, pl_tol_bypass, pl_extra_score, placement_id, gvk_id, class_id,
    replicas, nw_shortcut, prev_idx, prev_val, evict_idx,
):
    """The [B, C] feasibility/availability/score planes both phases need.
    Traced INSIDE each phase's jit (phase B recomputes them rather than
    shipping ~600 MB of plane outputs over the host link)."""
    B = placement_id.shape[0]
    C = cluster_valid.shape[0]
    Q = req_milli.shape[0]

    est_q = _capacity_estimates(
        req_milli, req_is_cpu, req_pods, avail_milli, has_alloc,
        pods_allowed, has_summary,
    )
    est_q = est_q.at[:Q].set(jnp.where(est_override >= 0, est_override, est_q[:Q]))
    cid = jnp.where(class_id >= 0, class_id, Q)
    est_b = est_q[cid]
    avail_cal = jnp.where(est_b == MAX_INT32, replicas[:, None], est_b)
    avail_cal = jnp.where(nw_shortcut[:, None], MAX_INT32, avail_cal)

    bidx = jnp.arange(B, dtype=jnp.int32)[:, None]
    pmask = prev_idx >= 0
    pic = jnp.where(pmask, prev_idx, 0)
    prev_rep = (
        jnp.zeros((B, C), jnp.int64)
        .at[bidx, pic]
        .add(jnp.where(pmask, prev_val, 0).astype(jnp.int64))
    )
    prev_present = (
        jnp.zeros((B, C), jnp.int32).at[bidx, pic].add(pmask.astype(jnp.int32)) > 0
    )
    emask = evict_idx >= 0
    eic = jnp.where(emask, evict_idx, 0)
    evict = (
        jnp.zeros((B, C), jnp.int32).at[bidx, eic].add(emask.astype(jnp.int32)) > 0
    )

    lanes_ok = cluster_valid[None, :] & ~deleting[None, :]
    feasible = (
        lanes_ok
        & pl_mask[placement_id]
        & (pl_tol_bypass[placement_id] | prev_present)
        & (api_ok[gvk_id] | prev_present)
        & ~evict
    )
    score = _locality_score(prev_present,
                            jnp.asarray(pl_extra_score, jnp.int64)[placement_id])
    # group availability includes already-assigned replicas
    # (group_clusters_with_score: tc.replicas + assigned)
    avail_sel = avail_cal + prev_rep * prev_present
    return feasible, avail_sel, score, avail_cal, prev_present, evict


@partial(jax.jit, static_argnames=("G",))
def spread_group_info(
    # cluster axis
    cluster_valid, deleting, name_rank, pods_allowed, has_summary,
    avail_milli, has_alloc, api_ok, group_id,
    # request classes
    req_milli, req_is_cpu, req_pods, est_override,
    # placement rows
    pl_mask, pl_tol_bypass, pl_extra_score,
    # per spread-binding rows
    placement_id, gvk_id, class_id, replicas, region_min, cluster_min,
    duplicated, nw_shortcut, prev_idx, prev_val, evict_idx,
    *, G: int,
):
    """Phase A: per-binding region-group scalars [B, G] + a feasibility
    flag [B] — the ONLY outputs; the planes stay on device."""
    feasible, avail_sel, score, _, _, _ = _spread_planes(
        cluster_valid, deleting, pods_allowed, has_summary, avail_milli,
        has_alloc, api_ok, req_milli, req_is_cpu, req_pods, est_override,
        pl_mask, pl_tol_bypass, pl_extra_score, placement_id, gvk_id,
        class_id, replicas, nw_shortcut, prev_idx, prev_val, evict_idx,
    )
    score_g, avail_g, value_g = _group_info_vmap(
        feasible, avail_sel, score, name_rank, group_id,
        replicas, region_min, cluster_min, duplicated, G,
    )
    return score_g, avail_g, value_g, jnp.any(feasible, axis=1)


def _pick_one(order, feasible, group_id, chosen, cluster_max, G: int):
    """Phase B for ONE binding (select_clusters_by_region.go:27-118):
    the FIRST cluster of each chosen group is selected; remaining chosen-
    group clusters are candidates taken in sorted order up to cluster_max
    total (0 when the cluster constraint is absent).  Segmented: first-of-
    group via a [G] segment_min over sorted positions — no [G, C] plane."""
    C = order.shape[0]
    sorted_feasible = feasible[order]
    gid = group_id[order].astype(jnp.int32)
    seg = jnp.where(sorted_feasible & (gid >= 0), gid, G)
    chosen_ext = jnp.concatenate([chosen, jnp.zeros((1,), bool)])
    in_chosen = chosen_ext[seg]
    pos = jnp.arange(C, dtype=jnp.int64)
    first_g = jax.ops.segment_min(
        jnp.where(in_chosen, pos, C), seg, num_segments=G + 1)[:G]
    any_g = first_g < C
    # .max: memberless groups contribute False without clobbering a True
    # another group scattered to the same (clamped) position
    is_first = jnp.zeros((C,), bool).at[jnp.minimum(first_g, C - 1)].max(any_g)
    n_selected = jnp.sum(any_g)
    total = jnp.sum(in_chosen)
    need_cnt = jnp.minimum(total, cluster_max)
    rest_cnt = jnp.maximum(need_cnt - n_selected, 0)
    cand = in_chosen & ~is_first
    cand_rank = jnp.cumsum(cand.astype(jnp.int64)) - 1
    take = cand & (cand_rank < rest_cnt)
    sel_sorted = is_first | take
    # back to cluster-lane order
    sel = jnp.zeros((C,), bool).at[order].set(sel_sorted)
    return sel


_pick_vmap = jax.vmap(_pick_one, in_axes=(0, 0, None, 0, 0, None))


@partial(jax.jit, static_argnames=("G", "waves", "max_nnz", "keep_sel",
                                   "use_extra", "with_used", "tier",
                                   "shard_mesh", "explain"))
def spread_assign_compact(
    # cluster axis
    cluster_valid, deleting, name_rank, pods_allowed, has_summary,
    avail_milli, has_alloc, api_ok, group_id,
    # request classes
    req_milli, req_is_cpu, req_pods, est_override,
    # placement rows
    pl_mask, pl_tol_bypass, pl_extra_score,
    # per live-binding rows
    placement_id, gvk_id, class_id, replicas, nw_shortcut,
    prev_idx, prev_val, evict_idx,
    chosen, cluster_max,
    strategy, static_w, ignore_avail, uid_desc, fresh, non_workload, b_valid,
    used0_milli=None, used0_pods=None, used0_sets=None,
    pl_fail_bits=None,
    *, G: int, waves: int, max_nnz: int, keep_sel: bool = False,
    use_extra: bool = True, with_used: bool = False, tier: str = "std",
    shard_mesh=None, explain: bool = False,
):
    """Phase B + assignment, FUSED: recompute the planes, pick clusters in
    the chosen groups, and run the main assignment kernel with the pick as
    the placement mask — one jit whose only outputs are the compact COO
    result (the per-binding [B, C] pick mask never leaves the device).
    `tier` selects the assignment kernel's compact lane budget ("big" for
    bindings beyond the tier-1 caps — VERDICT r4 item 3).  `shard_mesh`
    (static) pins the wave scan's stacked outputs when the inputs are
    mesh-sharded — see ops/solver._schedule_core; the production spread
    sub-solves run single-device (their sub-batches are small) and leave
    it None."""
    B = placement_id.shape[0]
    C = cluster_valid.shape[0]
    feasible, avail_sel, score, avail_cal, prev_present, evict = \
        _spread_planes(
            cluster_valid, deleting, pods_allowed, has_summary, avail_milli,
            has_alloc, api_ok, req_milli, req_is_cpu, req_pods, est_override,
            pl_mask, pl_tol_bypass, pl_extra_score, placement_id, gvk_id,
            class_id, replicas, nw_shortcut, prev_idx, prev_val, evict_idx,
        )
    key = _sort_key(score, avail_sel, name_rank[None, :], feasible)
    order = jnp.argsort(key, axis=1)
    sel = _pick_vmap(order, feasible, group_id, chosen, cluster_max, G)
    extra_b = jnp.asarray(pl_extra_score, jnp.int64)[placement_id]  # [B, C]
    core = _schedule_core(
        cluster_valid, deleting, name_rank, pods_allowed, has_summary,
        avail_milli, has_alloc, api_ok,
        req_milli, req_is_cpu, req_pods, est_override,
        sel,                             # pl_mask: row i is binding i's pick
        jnp.ones((B, C), bool),          # tolerations folded into the pick
        strategy, static_w,
        jnp.zeros((B,), bool),           # cluster spread consumed by the pick
        jnp.zeros((B,), jnp.int32), jnp.zeros((B,), jnp.int32),
        ignore_avail,
        extra_b,                         # plugin scores, per-binding rows
        b_valid, jnp.arange(B, dtype=jnp.int32), gvk_id, class_id,
        replicas, uid_desc, fresh, non_workload, nw_shortcut,
        prev_idx, prev_val, evict_idx,
        used0_milli, used0_pods, used0_sets,
        waves=waves, use_extra=use_extra, with_used=with_used, tier=tier,
        shard_mesh=shard_mesh,
    )
    if with_used:
        rep, selected, status, used = core
    else:
        rep, selected, status = core
    compact = _compact_of(rep, selected, status, non_workload, max_nnz,
                          keep_sel=keep_sel)
    if with_used:
        compact = compact + tuple(used)
    if explain:
        # spread-path verdict plane: the static fail bits are the REAL
        # placement's (gathered per binding by the caller), the pick
        # eliminations surface as NOT_SELECTED (feasible & ~sel — the
        # group DFS / max-groups trim "ate" those clusters), and
        # toleration/api/eviction recompute from the same planes the
        # phase math used.  Assignment-level trims inside the core (its
        # pl_mask IS the pick) fold into the same NOT_SELECTED bit via
        # the core's `selected`.
        fb = (pl_fail_bits if pl_fail_bits is not None
              else jnp.zeros((B, C), jnp.int32))
        lanes_ok = cluster_valid[None, :] & ~deleting[None, :]
        verdict = _explain_verdict(
            fb, pl_tol_bypass[placement_id] | prev_present,
            api_ok[gvk_id] | prev_present, evict, lanes_ok,
            avail_cal, feasible, sel & selected,
            ~non_workload & ~nw_shortcut, b_valid, status)
        ex_score = jnp.clip(score, 0, MAX_INT32).astype(jnp.int32)
        ex_avail = jnp.clip(avail_cal, 0, MAX_INT32).astype(jnp.int32)
        outcome = _explain_outcome(verdict, status, cluster_valid)
        compact = compact + (verdict, ex_score, ex_avail, outcome)
    return compact


def solve_spread(
    batch,
    items: Sequence,
    spread_idx: Sequence[int],
    waves: int = 1,
    enable_empty_workload_propagation: bool = False,
    collect_used: bool = False,
    used0=None,
    axis: str = "",
    tier: str = "std",
    explain: bool = False,
    explain_cb=None,
):
    """Schedule the ROUTE_DEVICE_SPREAD(_BIG) bindings of one chunk.

    `explain` dispatches the armed jit variant of the fused assignment
    (spread_assign_compact(explain=True)) and hands each live binding's
    explain rows to `explain_cb(binding_index, verdict_row, score_row,
    avail_row, outcome_code)` — rows are numpy [C] slices in cluster-lane
    order.  Bindings the group DFS failed before assignment never reach
    the cb; their serial-classed errors in the result dict carry the
    whole story (the pipeline builds outcome-level decisions for them).

    `axis` names the group axis: "" = region (batch.region_id), else a
    label key from batch.label_axes (spread-by-label grouping — group ids
    are label VALUES, same group math).  `tier` selects the assignment
    kernel's lane budget; route ROUTE_DEVICE_SPREAD_BIG bindings with
    tier="big".  Callers group spread bindings by (axis, tier) — see
    tensors.spread_axis_of.

    Returns {binding_index: List[TargetCluster] | Exception} in the same
    result vocabulary as tensors.decode_* (serial error classes); with
    collect_used, returns (out, used|None) where used = (um, up, usets)
    numpy accumulators of the spread bindings' consumption; used0 carries
    a previous batch's consumption into the ASSIGNMENT kernel (the phase-A
    group scoring and the in-group pick still see the raw snapshot —
    selection order is score-driven, assignment is the capacity-honest
    step).
    """
    from karmada_tpu.analysis import guards as _guards
    from karmada_tpu.ops import tensors as T

    if not len(spread_idx):
        return ({}, None) if collect_used else {}
    if _guards.armed():
        _guards.check_batch(batch, "solve-spread")
    if axis == "":
        group_id_arr, group_names = batch.region_id, batch.region_names
    else:
        group_id_arr, group_names = batch.label_axes[axis]
    # pad the phase A batch axis so jit signatures stay stable as the
    # spread-binding count varies chunk to chunk (row 0 repeats as inert
    # padding: its results are simply never read back)
    n_spread = len(spread_idx)
    Bp = T._next_pow2(n_spread, 8)  # noqa: SLF001
    idx = np.asarray(list(spread_idx) + [spread_idx[0]] * (Bp - n_spread),
                     np.int64)
    n_groups = len(group_names)
    # pow2-bucketed group axis: a fleet gaining one region/label value must
    # not recompile phase A (segments beyond n_groups are empty)
    G = T._next_pow2(max(n_groups, 1), 8)  # noqa: SLF001

    pid = batch.placement_id[idx]
    duplicated = batch.pl_strategy[pid] == T.STRAT_DUPLICATED
    region_min = batch.pl_region_min[pid]
    region_max = batch.pl_region_max[pid]
    cluster_min = batch.pl_sc_min[pid]
    cluster_max = np.where(batch.pl_has_cluster_sc[pid], batch.pl_sc_max[pid], 0)

    score_g, avail_g, value_g, feas_any = spread_group_info(
        batch.cluster_valid, batch.deleting, batch.name_rank,
        batch.pods_allowed, batch.has_summary, batch.avail_milli,
        batch.has_alloc, batch.api_ok, group_id_arr,
        batch.req_milli, batch.req_is_cpu, batch.req_pods,
        batch.est_override,
        batch.pl_mask, batch.pl_tol_bypass, batch.pl_extra_score,
        pid, batch.gvk_id[idx], batch.class_id[idx],
        batch.replicas[idx], region_min, cluster_min, duplicated,
        batch.nw_shortcut[idx],
        batch.prev_idx[idx], batch.prev_val[idx], batch.evict_idx[idx],
        G=G,
    )
    score_g = np.asarray(score_g)
    avail_g = np.asarray(avail_g)
    value_g = np.asarray(value_g)
    feas_any = np.asarray(feas_any)

    # -- host DFS over G-level scalars: serial.select_groups itself --------
    out = {}
    chosen = np.zeros((len(idx), G), bool)
    for row in range(n_spread):
        b = idx[row]
        if not feas_any[row]:
            _, diagnosis = serial.find_clusters_that_fit(
                items[b][0], items[b][1], batch.cluster_index.clusters
            )
            out[int(b)] = serial.FitError(diagnosis)
            continue
        groups = [
            serial._DfsGroup(  # noqa: SLF001 — deliberate reuse of the golden DFS
                name=group_names[g],
                value=int(value_g[row, g]),
                weight=int(score_g[row, g]),
            )
            for g in range(n_groups)
            if value_g[row, g] > 0
        ]
        if len(groups) < int(region_min[row]):
            out[int(b)] = serial.UnschedulableError(
                "the number of feasible region is less than spreadConstraint.MinGroups"
            )
            continue
        picked = serial.select_groups(
            groups, int(region_min[row]), int(region_max[row]),
            int(cluster_min[row]),
        )
        if not picked:
            out[int(b)] = serial.UnschedulableError(
                "the number of clusters is less than the cluster spreadConstraint.MinGroups"
            )
            continue
        names = {g.name for g in picked}
        for g in range(n_groups):
            chosen[row, g] = group_names[g] in names

    live = [r for r in range(n_spread) if int(idx[r]) not in out]
    if not live:
        return (out, None) if collect_used else out
    # pad the fused phase's batch axis too (same jit-signature stability)
    n_live = len(live)
    Bs = T._next_pow2(n_live, 8)  # noqa: SLF001
    C = batch.C
    live_np = np.asarray(live + [live[0]] * (Bs - n_live), np.int64)
    lidx = idx[live_np]
    lpid = pid[live_np]
    b_valid = np.zeros(Bs, bool)
    b_valid[:n_live] = True
    use_extra = _use_extra(batch)  # one shared predicate, hoisted off retries

    if explain:
        assert batch.explain, \
            "explain spread solve needs a batch encoded with explain=True"
    fail_b = batch.pl_fail_bits[lpid] if explain else None  # [Bs, C] rows

    def assign(max_nnz):
        return spread_assign_compact(
            batch.cluster_valid, batch.deleting, batch.name_rank,
            batch.pods_allowed, batch.has_summary, batch.avail_milli,
            batch.has_alloc, batch.api_ok, group_id_arr,
            batch.req_milli, batch.req_is_cpu, batch.req_pods,
            batch.est_override,
            batch.pl_mask, batch.pl_tol_bypass, batch.pl_extra_score,
            lpid, batch.gvk_id[lidx], batch.class_id[lidx],
            batch.replicas[lidx], batch.nw_shortcut[lidx],
            batch.prev_idx[lidx], batch.prev_val[lidx], batch.evict_idx[lidx],
            chosen[live_np], cluster_max[live_np].astype(np.int64),
            batch.pl_strategy[lpid], batch.pl_static_w[lpid],
            batch.pl_ignore_avail[lpid], batch.uid_desc[lidx],
            batch.fresh[lidx], batch.non_workload[lidx], b_valid,
            used0[0] if used0 is not None else None,
            used0[1] if used0 is not None else None,
            used0[2] if used0 is not None else None,
            fail_b,
            G=G, waves=waves, max_nnz=max_nnz,
            keep_sel=enable_empty_workload_propagation,
            use_extra=use_extra, with_used=collect_used, tier=tier,
            explain=explain,
        )

    max_nnz = (Bs * C if enable_empty_workload_propagation
               else min(max(Bs * 16, 1 << 12), Bs * C))
    res = assign(max_nnz)
    while int(res[3]) > max_nnz and max_nnz < Bs * C:
        max_nnz = min(max_nnz * 4, Bs * C)
        res = assign(max_nnz)
    cidx, cval, status, nnz = res[:4]
    used = (tuple(np.asarray(u) for u in res[4:7]) if collect_used else None)
    if explain and explain_cb is not None:
        off = 7 if collect_used else 4
        everdict, escore, eavail, eoutcome = (
            np.asarray(a) for a in res[off:off + 4])
        nc = batch.n_clusters
        for row in range(n_live):
            b = int(lidx[row])
            explain_cb(b, everdict[row, :nc], escore[row, :nc],
                       eavail[row, :nc], int(eoutcome[row]))

    # remap the sub-batch COO rows onto the chunk's binding axis and reuse
    # the one shared decoder (tensors.decode_compact, incl. its native fast
    # path).  lidx ascends (spread_idx and `live` both preserve chunk
    # order), so the remap keeps the decoder's row-major contract.
    cidx = np.asarray(cidx)
    cval = np.asarray(cval)
    status = np.asarray(status)
    keep = (cidx >= 0) & (cidx // C < n_live)  # drop -1 pads and padded rows
    rows = cidx[keep] // C
    remapped_idx = (lidx[rows] * C + cidx[keep] % C).astype(np.int64)
    status_full = np.zeros((batch.n_bindings,), np.int32)
    status_full[lidx[:n_live]] = status[:n_live]
    decoded = T.decode_compact(
        batch, remapped_idx, cval[keep], status_full,
        enable_empty_workload_propagation=enable_empty_workload_propagation,
        items=items,
    )
    for b in lidx[:n_live]:
        out[int(b)] = decoded[int(b)]
    return (out, used) if collect_used else out
