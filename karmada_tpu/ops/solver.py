"""Batched TPU solver kernels (JAX/XLA).

This module is the point of the whole framework: the reference scheduler's
per-binding hot loop (reference pkg/scheduler/core/generic_scheduler.go:71-116
-- filter, score, spread-constraint selection, replica division) re-designed
as one vmapped, jit-compiled program over dense (bindings x clusters) tensors,
sharded over a TPU mesh on the cluster/binding axes.

Golden contract: for every supported input class, kernels here produce
bit-identical results to the serial control path (ops/serial.py /
ops/webster.py), which is itself a faithful port of the reference Go
algorithms.  Priorities are computed in IEEE float64 in both paths, so
equality is exact, not approximate.

Requires jax x64 (int64 weights/cross-products, float64 priorities); enabled
at import.  On TPU, f64/s64 are emulated -- acceptable because the solver is
elementwise/sort-bound, not matmul-bound, and the batch axis provides the
parallelism.
"""

from __future__ import annotations

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402

MAX_INT32 = (1 << 31) - 1
MAX_INT64 = (1 << 63) - 1


# ---------------------------------------------------------------------------
# Webster (Sainte-Lague) divisor allocation
# ---------------------------------------------------------------------------
#
# Reference semantics (pkg/util/helper/webstermethod.go:112 AllocateWebsterSeats
# + binding.go:70-144 Dispenser/UID tiebreak), as ported in ops/webster.py:
# award `n` seats one at a time to the party maximising float64 priority
# w/(2s+1); ties by fewer current seats, then name order (ascending, or
# descending when fnv32a(uid) is odd).
#
# Kernel insight: the candidate "s-th seat of party i" is awarded when party i
# holds exactly s seats, so each candidate has a STATIC key
# (priority(w_i, s) desc, s asc, rank_i asc) and the serial result is exactly
# the top-n candidates under that order.  We fast-forward with a divisor
# bisection (float64 threshold T; seats awarded ~= candidates with priority
# above T) and then run a small correction loop that awards / removes / swaps
# whole tie-blocks until the awarded set is the true top-n.  The correction
# uses the same float64 priorities and integer tiebreaks as the serial heap,
# so the final seat vector is bit-identical.


def _priority(w: jnp.ndarray, s: jnp.ndarray) -> jnp.ndarray:
    """float64 Webster priority w/(2s+1), matching the serial/Go float math."""
    return w.astype(jnp.float64) / (2.0 * s.astype(jnp.float64) + 1.0)


def webster_divide(
    n: jnp.ndarray,
    w: jnp.ndarray,
    s0: jnp.ndarray,
    active: jnp.ndarray,
    rank: jnp.ndarray,
    max_iters: int = 0,
) -> jnp.ndarray:
    """Allocate `n` new seats among parties; returns total seats per party.

    Args:
      n: int scalar -- number of new seats to award (<=0 awards none).
      w: int64[C] votes (weights); negative treated as 0.
      s0: int64[C] initial seats (kept; never removed).
      active: bool[C] party-exists mask (inactive lanes are padding).
      rank: int32[C] tiebreak order; MUST be a permutation-like strict order
        (distinct values) among active lanes, pre-flipped for descending UID
        tiebreak by the caller.
      max_iters: correction-loop bound; 0 means C + 64.

    Matches ops/webster.py allocate_webster_seats / dispense_by_weight:
    a zero total weight awards nothing (seats stay s0).
    """
    C = w.shape[0]
    if max_iters <= 0:
        max_iters = C + 64

    n = jnp.asarray(n, jnp.int64)
    w = jnp.where(active, jnp.maximum(jnp.asarray(w, jnp.int64), 0), 0)
    s0 = jnp.where(active, jnp.asarray(s0, jnp.int64), 0)
    rank = jnp.asarray(rank, jnp.int64)
    totw = jnp.sum(w)
    n_eff = jnp.where(totw > 0, jnp.maximum(n, 0), 0)
    nf = n_eff.astype(jnp.float64)

    # -- 1. divisor bisection: T s.t. #[candidates with priority > T] <= n --
    def count(T: jnp.ndarray) -> jnp.ndarray:
        x = w.astype(jnp.float64) / T
        # clamp AFTER subtracting s0 (to n new seats); the pre-cast clamp at
        # nf + s0 only guards the float->int64 cast against overflow
        cnt0 = jnp.minimum(
            jnp.maximum(jnp.ceil((x - 1.0) * 0.5), 0.0),
            nf + s0.astype(jnp.float64),
        )
        c = jnp.minimum(jnp.maximum(cnt0.astype(jnp.int64) - s0, 0), n_eff)
        return jnp.where(active & (w > 0), c, 0)

    def bis(state, _):
        lo, hi = state
        mid = 0.5 * (lo + hi)
        over = jnp.sum(count(mid)) > n_eff
        return (jnp.where(over, mid, lo), jnp.where(over, hi, mid)), None

    lo0 = jnp.float64(1e-30)
    hi0 = jnp.max(w).astype(jnp.float64) + 1.0
    (_, hi), _ = lax.scan(bis, (lo0, hi0), None, length=80)
    s = s0 + count(hi)  # total <= n_eff awarded; correction loop finishes

    # -- 2. correction loop: block award / remove / swap to the exact top-n --
    NEG_INF = jnp.float64(-jnp.inf)
    POS_INF = jnp.float64(jnp.inf)
    BIG = jnp.int64(1) << 62

    def positions(packed: jnp.ndarray) -> jnp.ndarray:
        """pos[i] = rank of lane i when sorting `packed` ascending."""
        order = jnp.argsort(packed)
        return jnp.zeros((C,), jnp.int64).at[order].set(jnp.arange(C, dtype=jnp.int64))

    def body(state):
        s, it = state
        awarded = jnp.sum(s - s0)
        deficit = n_eff - awarded

        # candidate keys
        p_next = jnp.where(active, _priority(w, s), NEG_INF)
        removable = active & (s > s0)
        p_last = jnp.where(removable, _priority(w, s - 1), POS_INF)

        # best next candidate (award order: p desc, seats asc, rank asc)
        m1 = jnp.max(p_next)
        tie_a = active & (p_next == m1)
        pk_a = jnp.where(tie_a, s * C + rank, BIG)  # (seats, rank) packed
        pos_a = positions(pk_a)

        # worst awarded candidate (removal: p asc, then seats desc, rank desc)
        m2 = jnp.min(p_last)
        tie_r = removable & (p_last == m2)
        pk_r = jnp.where(tie_r, -((s - 1) * C + rank), BIG)
        pos_r = positions(pk_r)

        def do_award(s):
            r = jnp.minimum(deficit, jnp.sum(tie_a))
            return s + jnp.where(tie_a & (pos_a < r), 1, 0)

        def do_remove(s):
            r = jnp.minimum(-deficit, jnp.sum(tie_r))
            return s - jnp.where(tie_r & (pos_r < r), 1, 0)

        def do_swap(s):
            # profitable iff best-next key < worst-last key (strict):
            #   (-m1, s_a, rank_a) < (-m2, s_r - 1, rank_r) lexicographic
            a_i = jnp.argmin(pk_a)
            r_i = jnp.argmin(pk_r)
            ka = s[a_i] * C + rank[a_i]
            kr = (s[r_i] - 1) * C + rank[r_i]
            better = (m1 > m2) | ((m1 == m2) & (ka < kr))
            swap = jnp.where(better & (jnp.sum(tie_a) > 0) & (jnp.sum(tie_r) > 0), 1, 0)
            return (
                s
                + jnp.zeros((C,), jnp.int64).at[a_i].add(swap)
                - jnp.zeros((C,), jnp.int64).at[r_i].add(swap)
            )

        s = lax.cond(
            deficit > 0,
            do_award,
            lambda s: lax.cond(deficit < 0, do_remove, do_swap, s),
            s,
        )
        return s, it + 1

    def cond(state):
        s, it = state
        awarded = jnp.sum(s - s0)
        deficit = n_eff - awarded
        p_next = jnp.where(active, _priority(w, s), NEG_INF)
        removable = active & (s > s0)
        p_last = jnp.where(removable, _priority(w, s - 1), POS_INF)
        m1 = jnp.max(p_next)
        m2 = jnp.min(p_last)
        tie_a = active & (p_next == m1)
        tie_r = removable & (p_last == m2)
        pk_a = jnp.where(tie_a, s * C + rank, BIG)
        pk_r = jnp.where(tie_r, -((s - 1) * C + rank), BIG)
        a_i = jnp.argmin(pk_a)
        r_i = jnp.argmin(pk_r)
        ka = s[a_i] * C + rank[a_i]
        kr = (s[r_i] - 1) * C + rank[r_i]
        has_a = jnp.sum(tie_a) > 0
        has_r = jnp.sum(tie_r) > 0
        profitable = has_a & has_r & ((m1 > m2) | ((m1 == m2) & (ka < kr)))
        return ((deficit != 0) | profitable) & (it < max_iters)

    s, _ = lax.while_loop(cond, body, (s, jnp.int64(0)))
    return jnp.where(active, s, 0)


# vmapped over a batch of problems: n[B], w[B,C], s0[B,C], active[B,C], rank[B,C]
webster_divide_batch = jax.vmap(webster_divide, in_axes=(0, 0, 0, 0, 0, None))


# ---------------------------------------------------------------------------
# Batched scheduling pipeline
# ---------------------------------------------------------------------------
#
# One jitted program per scheduling cycle over the dense SolverBatch encoding
# (ops/tensors.py): filter masks -> locality scores -> GeneralEstimator
# capacity math (pkg/estimator/client/general.go:294) -> cluster-field spread
# selection (select_clusters_by_cluster.go:25) -> replica division strategies
# (assignment.go / division_algorithm.go) via the Webster kernel above.

# strategy / status ids shared with the encoder/decoder
from karmada_tpu.ops.tensors import (  # noqa: E402
    STATUS_FIT_ERROR,
    STATUS_NO_CLUSTER,
    STATUS_OK,
    STATUS_UNSCHEDULABLE,
    STRAT_AGGREGATED,
    STRAT_DUPLICATED,
    STRAT_DYNAMIC,
    STRAT_STATIC,
)

_AVAIL_BITS = 34  # avail values clamped below 2^34 for key packing
_AVAIL_CAP = (1 << _AVAIL_BITS) - 1


def _capacity_estimates(
    req_milli, req_is_cpu, req_pods, avail_milli, has_alloc, pods_allowed,
    has_summary
):
    """est[Q+1, C]: GeneralEstimator summary math (general.go:56-94,294-334),
    including component-SET classes (maxAvailableComponentSets general.go:
    106-160) whose pod bound divides by pods-per-set.

    Row Q is the requirements==None row: min(allowed pods, MaxInt32).
    """
    Q, R = req_milli.shape
    C = avail_milli.shape[0]
    # per-resource available in request units: cpu keeps milli, others ceil
    unit_avail = jnp.where(
        req_is_cpu[None, :], avail_milli, -((-avail_milli) // 1000)
    )  # [C, R]
    req = req_milli[:, None, :]  # [Q, 1, R]
    avail = unit_avail[None, :, :]  # [1, C, R]
    ok = has_alloc[None, :, :] & (avail > 0)
    cnt = jnp.where(ok, avail // jnp.maximum(req, 1), 0)  # [Q, C, R]
    cnt = jnp.where(req > 0, cnt, MAX_INT64)  # unrequested resources inert
    est = jnp.min(cnt, axis=2)  # [Q, C]
    pods_bound = pods_allowed[None, :] // jnp.maximum(req_pods[:, None], 1)
    est = jnp.minimum(est, pods_bound)
    est = jnp.where(has_summary[None, :] & (pods_allowed[None, :] > 0), est, 0)
    est = jnp.minimum(jnp.maximum(est, 0), MAX_INT32)
    none_row = jnp.where(
        has_summary & (pods_allowed > 0), jnp.minimum(pods_allowed, MAX_INT32), 0
    )
    return jnp.concatenate([est, none_row[None, :]], axis=0)  # [Q+1, C]


def _positions(key: jnp.ndarray) -> jnp.ndarray:
    C = key.shape[0]
    order = jnp.argsort(key)
    return jnp.zeros((C,), jnp.int64).at[order].set(jnp.arange(C, dtype=jnp.int64))


def _select_by_cluster(
    feasible, score, avail, name_rank, n_need, sc_min, sc_max, ignore_avail
):
    """Port of select_clusters_by_cluster.go:25-105 as masked tensor ops.

    Returns (selected mask, unschedulable flag).  Selection is by the packed
    key (score desc, available desc, name asc); when capacity matters, the
    swap loop replaces low-ranked picks with higher-capacity leftovers
    exactly like _select_by_available_resource in ops/serial.py.
    """
    C = feasible.shape[0]
    BIG = jnp.int64(1) << 62
    fcount = jnp.sum(feasible)
    avail_c = jnp.clip(avail, 0, _AVAIL_CAP)
    key = (
        ((200 - score).astype(jnp.int64) << 47)
        | ((_AVAIL_CAP - avail_c) << 13)
        | name_rank
    )
    key = jnp.where(feasible, key, BIG)
    pos = _positions(key)
    order = jnp.argsort(key)
    need_cnt = jnp.minimum(jnp.asarray(sc_max, jnp.int64), fcount)
    sel0 = feasible & (pos < need_cnt)

    def swap_loop(args):
        in_sel, rest_pos, update_id = args

        def cond(st):
            in_sel, _, update_id = st
            total = jnp.sum(jnp.where(in_sel, avail, 0))
            return (total < n_need) & (update_id >= 0)

        def body(st):
            in_sel, rest_pos, update_id = st
            cur = order[update_id]
            rest = feasible & ~in_sel
            # max avail, ties to smallest rest position (serial list order)
            cand = jnp.where(
                rest, (avail_c << 13) | (8191 - jnp.clip(rest_pos, 0, 8191)), -1
            )
            best = jnp.argmax(cand)
            found = (cand[best] >= 0) & (avail[best] > avail[cur])
            in_sel = jnp.where(
                found,
                in_sel.at[best].set(True).at[cur].set(False),
                in_sel,
            )
            rest_pos = jnp.where(
                found, rest_pos.at[cur].set(rest_pos[best]), rest_pos
            )
            return in_sel, rest_pos, update_id - 1

        return lax.while_loop(cond, body, (in_sel, rest_pos, update_id))

    in_sel, _, _ = lax.cond(
        ignore_avail,
        lambda a: a,
        swap_loop,
        (sel0, pos, need_cnt.astype(jnp.int64) - 1),
    )
    total = jnp.sum(jnp.where(in_sel, avail, 0))
    unsched = (fcount < sc_min) | (~ignore_avail & (total < n_need))
    return in_sel, unsched


def _schedule_one(
    feasible, avail_cal, prev_present, prev_rep, name_rank,
    n, strategy, has_sc, sc_min, sc_max, ignore_avail,
    static_w, uid_desc, fresh, non_workload, valid,
):
    """One binding against [C] cluster lanes; vmapped over the batch."""
    C = feasible.shape[0]
    i64 = lambda x: jnp.asarray(x, jnp.int64)
    n = i64(n)

    fcount = jnp.sum(feasible)
    has_prev = jnp.any(prev_present)
    score = jnp.where(has_prev & prev_present, 100, 0).astype(jnp.int64)

    # ---- selection -------------------------------------------------------
    sel_sc, unsched_sel = _select_by_cluster(
        feasible, score, avail_cal + prev_rep * prev_present, name_rank,
        n, i64(sc_min), i64(sc_max), ignore_avail,
    )
    sel = jnp.where(has_sc, sel_sc, feasible)
    unsched_sel = has_sc & unsched_sel
    sel_count = jnp.sum(sel)

    # ---- assignment ------------------------------------------------------
    rank_eff = jnp.where(uid_desc, C - 1 - name_rank, name_rank)
    scheduled_rep = jnp.where(sel & prev_present, prev_rep, 0)
    assigned = jnp.sum(scheduled_rep)

    is_dynamic = (strategy == STRAT_DYNAMIC) | (strategy == STRAT_AGGREGATED)
    scale_down = is_dynamic & ~fresh & (assigned > n)
    scale_up = is_dynamic & ~fresh & (assigned < n)
    steady_eq = is_dynamic & ~fresh & (assigned == n)
    is_fresh = is_dynamic & fresh

    # webster problem per strategy (selected branchlessly)
    static_eff = static_w * sel
    static_eff = jnp.where(jnp.sum(static_eff) > 0, static_eff, sel.astype(jnp.int64))

    w = jnp.zeros((C,), jnp.int64)
    w = jnp.where(strategy == STRAT_STATIC, static_eff, w)
    w = jnp.where(is_fresh, avail_cal * sel + scheduled_rep, w)
    w = jnp.where(scale_up, avail_cal * sel, w)
    w = jnp.where(scale_down, jnp.where(prev_present, prev_rep, 0), w)

    active = sel
    active = jnp.where(scale_down, prev_present, active)

    target = jnp.where(strategy == STRAT_STATIC, n, 0)
    target = jnp.where(is_fresh | scale_down, n, target)
    target = jnp.where(scale_up, n - assigned, target)

    base = jnp.where(scale_up | steady_eq, scheduled_rep, 0)

    avail_sum = jnp.sum(w)
    unsched_div = is_dynamic & (avail_sum < target)

    # Aggregated: trim to the capacity-descending prefix reaching target
    # (division_algorithm.go:80-90 + resortAvailableClusters assignment.go:145)
    prior = scale_up & (scheduled_rep > 0)
    wc = jnp.clip(w, 0, _AVAIL_CAP)
    agg_key = (
        (jnp.where(prior, 0, 1).astype(jnp.int64) << 48)
        | ((_AVAIL_CAP - wc) << 13)
        | name_rank
    )
    agg_key = jnp.where(active, agg_key, (jnp.int64(1) << 62))
    agg_pos = _positions(agg_key)
    w_sorted = jnp.zeros((C,), jnp.int64).at[agg_pos].set(jnp.where(active, w, 0))
    cum_excl = jnp.cumsum(w_sorted) - w_sorted
    include_sorted = cum_excl < target
    inc = include_sorted[agg_pos]
    use_prefix = (strategy == STRAT_AGGREGATED) & (is_fresh | scale_up | scale_down)
    w = jnp.where(use_prefix, jnp.where(inc, w, 0), w)
    active = jnp.where(use_prefix, active & inc, active)

    run_webster = (
        valid
        & ~non_workload
        & (
            (strategy == STRAT_STATIC)
            | ((is_fresh | scale_up | scale_down) & ~unsched_div)
        )
    )
    seats = webster_divide(
        jnp.where(run_webster, target, 0), w, jnp.zeros((C,), jnp.int64),
        active & run_webster, rank_eff,
    )

    rep = base + seats
    rep = jnp.where(strategy == STRAT_DUPLICATED, n * sel, rep)
    rep = jnp.where(non_workload, 0, rep)

    status = jnp.where(
        fcount == 0,
        STATUS_FIT_ERROR,
        jnp.where(
            unsched_sel | unsched_div,
            STATUS_UNSCHEDULABLE,
            jnp.where(sel_count == 0, STATUS_NO_CLUSTER, STATUS_OK),
        ),
    )
    status = jnp.where(valid, status, STATUS_OK).astype(jnp.int32)
    rep = jnp.where((status == STATUS_OK) & valid, rep, 0)
    sel = sel & (status == STATUS_OK) & valid
    return rep, sel, status


_schedule_vmap = jax.vmap(
    _schedule_one,
    in_axes=(0, 0, 0, 0, None, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0),
)


@jax.jit
def schedule_batch(
    # cluster axis
    cluster_valid, deleting, name_rank, pods_allowed, has_summary,
    avail_milli, has_alloc, api_ok,
    # request classes
    req_milli, req_is_cpu, req_pods, est_override,
    # placements
    pl_mask, pl_tol_bypass, pl_strategy, pl_static_w,
    pl_has_cluster_sc, pl_sc_min, pl_sc_max, pl_ignore_avail,
    # bindings
    b_valid, placement_id, gvk_id, class_id, replicas, uid_desc, fresh,
    non_workload, nw_shortcut, prev_rep, prev_present, evict,
):
    """The full cycle: returns (rep[B,C] int64, selected[B,C] bool, status[B])."""
    est_q = _capacity_estimates(
        req_milli, req_is_cpu, req_pods, avail_milli, has_alloc, pods_allowed,
        has_summary
    )
    Q = req_milli.shape[0]
    est_q = est_q.at[:Q].set(jnp.where(est_override >= 0, est_override, est_q[:Q]))

    # per-binding gathers
    cid = jnp.where(class_id >= 0, class_id, Q)
    est_b = est_q[cid]  # [B, C]
    # calAvailableReplicas (util.go:104): clamp leftover MaxInt32 to replicas,
    # EXCEPT the non-workload shortcut, which early-returns unclamped
    avail_cal = jnp.where(est_b == MAX_INT32, replicas[:, None], est_b)
    avail_cal = jnp.where(nw_shortcut[:, None], MAX_INT32, avail_cal)

    lanes_ok = cluster_valid[None, :] & ~deleting[None, :]
    feasible = (
        lanes_ok
        & pl_mask[placement_id]
        & (pl_tol_bypass[placement_id] | prev_present)
        & (api_ok[gvk_id] | prev_present)
        & ~evict
    )

    rep, sel, status = _schedule_vmap(
        feasible, avail_cal, prev_present, prev_rep, name_rank,
        replicas, pl_strategy[placement_id], pl_has_cluster_sc[placement_id],
        pl_sc_min[placement_id], pl_sc_max[placement_id],
        pl_ignore_avail[placement_id], pl_static_w[placement_id],
        uid_desc, fresh, non_workload, b_valid,
    )
    return rep, sel, status


def solve(batch):
    """Run schedule_batch over an ops/tensors.SolverBatch; numpy results."""
    import numpy as np

    # packed sort keys reserve 13 bits for the cluster lane
    assert batch.C <= 8192, "cluster axis must be <= 8192 per solve call"

    rep, sel, status = schedule_batch(
        batch.cluster_valid, batch.deleting, batch.name_rank,
        batch.pods_allowed, batch.has_summary, batch.avail_milli,
        batch.has_alloc, batch.api_ok,
        batch.req_milli, batch.req_is_cpu, batch.req_pods, batch.est_override,
        batch.pl_mask, batch.pl_tol_bypass, batch.pl_strategy,
        batch.pl_static_w, batch.pl_has_cluster_sc, batch.pl_sc_min,
        batch.pl_sc_max, batch.pl_ignore_avail,
        batch.b_valid, batch.placement_id, batch.gvk_id, batch.class_id,
        batch.replicas, batch.uid_desc, batch.fresh, batch.non_workload,
        batch.nw_shortcut, batch.prev_rep, batch.prev_present, batch.evict,
    )
    return np.asarray(rep), np.asarray(sel), np.asarray(status)
