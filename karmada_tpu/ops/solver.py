"""Batched TPU solver kernels (JAX/XLA).

This module is the point of the whole framework: the reference scheduler's
per-binding hot loop (reference pkg/scheduler/core/generic_scheduler.go:71-116
-- filter, score, spread-constraint selection, replica division) re-designed
as one vmapped, jit-compiled program over dense (bindings x clusters) tensors,
sharded over a TPU mesh on the cluster/binding axes.

Golden contract: for every supported input class, kernels here produce
bit-identical results to the serial control path (ops/serial.py /
ops/webster.py), which is itself a faithful port of the reference Go
algorithms.  Priorities are computed in IEEE float64 in both paths, so
equality is exact, not approximate.

Requires jax x64 (int64 weights/cross-products, float64 priorities); enabled
at import.  On TPU, f64/s64 are emulated -- acceptable because the solver is
elementwise/sort-bound, not matmul-bound, and the batch axis provides the
parallelism.
"""

from __future__ import annotations

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402

MAX_INT32 = (1 << 31) - 1
MAX_INT64 = (1 << 63) - 1


# ---------------------------------------------------------------------------
# Webster (Sainte-Lague) divisor allocation
# ---------------------------------------------------------------------------
#
# Reference semantics (pkg/util/helper/webstermethod.go:112 AllocateWebsterSeats
# + binding.go:70-144 Dispenser/UID tiebreak), as ported in ops/webster.py:
# award `n` seats one at a time to the party maximising float64 priority
# w/(2s+1); ties by fewer current seats, then name order (ascending, or
# descending when fnv32a(uid) is odd).
#
# Kernel insight: the candidate "s-th seat of party i" is awarded when party i
# holds exactly s seats, so each candidate has a STATIC key
# (priority(w_i, s) desc, s asc, rank_i asc) and the serial result is exactly
# the top-n candidates under that order.  We fast-forward with a divisor
# bisection (float64 threshold T; seats awarded ~= candidates with priority
# above T) and then run a small correction loop that awards / removes / swaps
# whole tie-blocks until the awarded set is the true top-n.  The correction
# uses the same float64 priorities and integer tiebreaks as the serial heap,
# so the final seat vector is bit-identical.


def _priority(w: jnp.ndarray, s: jnp.ndarray) -> jnp.ndarray:
    """float64 Webster priority w/(2s+1), matching the serial/Go float math."""
    return w.astype(jnp.float64) / (2.0 * s.astype(jnp.float64) + 1.0)


def webster_divide(
    n: jnp.ndarray,
    w: jnp.ndarray,
    s0: jnp.ndarray,
    active: jnp.ndarray,
    rank: jnp.ndarray,
    max_iters: int = 0,
) -> jnp.ndarray:
    """Allocate `n` new seats among parties; returns total seats per party.

    Args:
      n: int scalar -- number of new seats to award (<=0 awards none).
      w: int64[C] votes (weights); negative treated as 0.
      s0: int64[C] initial seats (kept; never removed).
      active: bool[C] party-exists mask (inactive lanes are padding).
      rank: int32[C] tiebreak order; MUST be a permutation-like strict order
        (distinct values) among active lanes, pre-flipped for descending UID
        tiebreak by the caller.
      max_iters: correction-loop bound; 0 means C + 64.

    Matches ops/webster.py allocate_webster_seats / dispense_by_weight:
    a zero total weight awards nothing (seats stay s0).
    """
    C = w.shape[0]
    if max_iters <= 0:
        max_iters = C + 64

    n = jnp.asarray(n, jnp.int64)
    w = jnp.where(active, jnp.maximum(jnp.asarray(w, jnp.int64), 0), 0)
    s0 = jnp.where(active, jnp.asarray(s0, jnp.int64), 0)
    rank = jnp.asarray(rank, jnp.int64)
    totw = jnp.sum(w)
    n_eff = jnp.where(totw > 0, jnp.maximum(n, 0), 0)
    nf = n_eff.astype(jnp.float64)

    # -- 1. divisor bisection: T s.t. #[candidates with priority > T] <= n --
    def count(T: jnp.ndarray) -> jnp.ndarray:
        x = w.astype(jnp.float64) / T
        cnt0 = jnp.minimum(jnp.maximum(jnp.ceil((x - 1.0) * 0.5), 0.0), nf)
        c = jnp.maximum(cnt0.astype(jnp.int64) - s0, 0)
        return jnp.where(active & (w > 0), c, 0)

    def bis(state, _):
        lo, hi = state
        mid = 0.5 * (lo + hi)
        over = jnp.sum(count(mid)) > n_eff
        return (jnp.where(over, mid, lo), jnp.where(over, hi, mid)), None

    lo0 = jnp.float64(1e-30)
    hi0 = jnp.max(w).astype(jnp.float64) + 1.0
    (_, hi), _ = lax.scan(bis, (lo0, hi0), None, length=80)
    s = s0 + count(hi)  # total <= n_eff awarded; correction loop finishes

    # -- 2. correction loop: block award / remove / swap to the exact top-n --
    NEG_INF = jnp.float64(-jnp.inf)
    POS_INF = jnp.float64(jnp.inf)
    BIG = jnp.int64(1) << 62

    def positions(packed: jnp.ndarray) -> jnp.ndarray:
        """pos[i] = rank of lane i when sorting `packed` ascending."""
        order = jnp.argsort(packed)
        return jnp.zeros((C,), jnp.int64).at[order].set(jnp.arange(C, dtype=jnp.int64))

    def body(state):
        s, it = state
        awarded = jnp.sum(s - s0)
        deficit = n_eff - awarded

        # candidate keys
        p_next = jnp.where(active, _priority(w, s), NEG_INF)
        removable = active & (s > s0)
        p_last = jnp.where(removable, _priority(w, s - 1), POS_INF)

        # best next candidate (award order: p desc, seats asc, rank asc)
        m1 = jnp.max(p_next)
        tie_a = active & (p_next == m1)
        pk_a = jnp.where(tie_a, s * C + rank, BIG)  # (seats, rank) packed
        pos_a = positions(pk_a)

        # worst awarded candidate (removal: p asc, then seats desc, rank desc)
        m2 = jnp.min(p_last)
        tie_r = removable & (p_last == m2)
        pk_r = jnp.where(tie_r, -((s - 1) * C + rank), BIG)
        pos_r = positions(pk_r)

        def do_award(s):
            r = jnp.minimum(deficit, jnp.sum(tie_a))
            return s + jnp.where(tie_a & (pos_a < r), 1, 0)

        def do_remove(s):
            r = jnp.minimum(-deficit, jnp.sum(tie_r))
            return s - jnp.where(tie_r & (pos_r < r), 1, 0)

        def do_swap(s):
            # profitable iff best-next key < worst-last key (strict):
            #   (-m1, s_a, rank_a) < (-m2, s_r - 1, rank_r) lexicographic
            a_i = jnp.argmin(pk_a)
            r_i = jnp.argmin(pk_r)
            ka = s[a_i] * C + rank[a_i]
            kr = (s[r_i] - 1) * C + rank[r_i]
            better = (m1 > m2) | ((m1 == m2) & (ka < kr))
            swap = jnp.where(better & (jnp.sum(tie_a) > 0) & (jnp.sum(tie_r) > 0), 1, 0)
            return (
                s
                + jnp.zeros((C,), jnp.int64).at[a_i].add(swap)
                - jnp.zeros((C,), jnp.int64).at[r_i].add(swap)
            )

        s = lax.cond(
            deficit > 0,
            do_award,
            lambda s: lax.cond(deficit < 0, do_remove, do_swap, s),
            s,
        )
        return s, it + 1

    def cond(state):
        s, it = state
        awarded = jnp.sum(s - s0)
        deficit = n_eff - awarded
        p_next = jnp.where(active, _priority(w, s), NEG_INF)
        removable = active & (s > s0)
        p_last = jnp.where(removable, _priority(w, s - 1), POS_INF)
        m1 = jnp.max(p_next)
        m2 = jnp.min(p_last)
        tie_a = active & (p_next == m1)
        tie_r = removable & (p_last == m2)
        pk_a = jnp.where(tie_a, s * C + rank, BIG)
        pk_r = jnp.where(tie_r, -((s - 1) * C + rank), BIG)
        a_i = jnp.argmin(pk_a)
        r_i = jnp.argmin(pk_r)
        ka = s[a_i] * C + rank[a_i]
        kr = (s[r_i] - 1) * C + rank[r_i]
        has_a = jnp.sum(tie_a) > 0
        has_r = jnp.sum(tie_r) > 0
        profitable = has_a & has_r & ((m1 > m2) | ((m1 == m2) & (ka < kr)))
        return ((deficit != 0) | profitable) & (it < max_iters)

    s, _ = lax.while_loop(cond, body, (s, jnp.int64(0)))
    return jnp.where(active, s, 0)


# vmapped over a batch of problems: n[B], w[B,C], s0[B,C], active[B,C], rank[B,C]
webster_divide_batch = jax.vmap(webster_divide, in_axes=(0, 0, 0, 0, 0, None))
