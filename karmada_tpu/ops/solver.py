"""Batched TPU solver kernels (JAX/XLA).

This module is the point of the whole framework: the reference scheduler's
per-binding hot loop (reference pkg/scheduler/core/generic_scheduler.go:71-116
-- filter, score, spread-constraint selection, replica division) re-designed
as one vmapped, jit-compiled program over dense (bindings x clusters) tensors.
When a device mesh is active (ops/meshing.activate — `serve --mesh BxC`,
`bench.py --mesh`), every dispatch places its operands with the
(bindings, clusters) NamedShardings from ops/meshing and XLA partitions
the program across the mesh (cluster tensors model-parallel, binding rows
data-parallel); with no active mesh the single-device dispatch below is
byte-for-byte the pre-mesh path.

Golden contract: for every supported input class, kernels here produce
bit-identical results to the serial control path (ops/serial.py /
ops/webster.py).  The Webster priority is the quantized integer
(votes << 28) // (2*seats+1) in BOTH paths (see ops/webster.py docstring),
so equality is exact with zero floating point in either path.

TPU shape: the hot path is pure int32/int64 elementwise + reductions — no
float64 anywhere (f64 is software-emulated on TPU), no sort inside any loop
(the only argsorts left run once per binding: selection setup + Aggregated
prefix), and the Webster allocation is CLOSED FORM: a logarithmic integer
threshold bisection plus a one-shot tie-block award, both fixed-depth
lax.while_loops of cheap elementwise ops.  jax x64 stays enabled for int64
arrays (int64 lowers to int32 pairs on TPU, ~2-4x int32 cost — measured
acceptable; f64 emulation, the real cliff, is gone).

Within-batch capacity contention: schedule_batch runs the chunk as `waves`
sequential waves (lax.scan) carrying a consumed-capacity accumulator;
bindings in wave k see the snapshot minus everything waves <k consumed
(milli resources, pods, and same-class accurate-estimator counts).
waves=B reproduces the reference's one-binding-at-a-time semantics exactly
(SURVEY §7 "Hard parts": sequential-equivalent ordering); the production
default trades that for throughput and documents the divergence: bindings
WITHIN one wave price against the same snapshot (the reference has the same
race across its status-update interval).
"""

from __future__ import annotations

from functools import partial

import jax
import numpy as _onp

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402

from karmada_tpu.analysis import guards as _guards  # noqa: E402
from karmada_tpu.ops.webster import PRIORITY_QBITS  # noqa: E402
from karmada_tpu.utils.metrics import REGISTRY  # noqa: E402

MAX_INT32 = (1 << 31) - 1
MAX_INT64 = (1 << 63) - 1

_W_CAP = (1 << 34) - 1  # weights clamped so (w << QBITS) fits int64
_N_CAP = (1 << 25) - 1  # seat targets clamped (2^25 replicas per binding)


# ---------------------------------------------------------------------------
# Webster (Sainte-Lague) divisor allocation — closed form
# ---------------------------------------------------------------------------
#
# Reference semantics (pkg/util/helper/webstermethod.go:112 AllocateWebsterSeats
# + binding.go:70-144 Dispenser/UID tiebreak), as ported in ops/webster.py:
# award `n` seats one at a time to the party maximising the quantized priority
# q(w, s) = (w << QBITS) // (2s+1); ties by fewer current seats, then name
# order (ascending, or descending when fnv32a(uid) is odd).
#
# Kernel insight: the candidate "s-th seat of party i" has the STATIC key
# (q(w_i, s) desc, s asc, rank_i asc) and the serial result is exactly the
# top-n candidates under that order (the standard divisor-method argument:
# within a party candidates are awarded in seat order, and across parties
# the heap always pops the globally best remaining candidate).  So:
#
#   1. bisect the integer threshold t* = smallest t with
#      #[candidates q > t] <= n          (while_loop, ~log2(max w<<28) steps,
#                                         one int64 divide per lane per step)
#   2. fully award every candidate with q > t*;
#   3. award the remaining r seats among the q == t* tie block, ordered by
#      (seat, rank): candidate keys are seat*C + rank with distinct values,
#      so a second bisection on the key value yields the exact r smallest
#      (one-shot block award — no correction loop, no sorts).


def _count_above(wq, s0, pos_mask, n_eff, t):
    """Per-party count of candidates (seat index >= s0) with priority > t.

    q(w, s) > t  <=>  wq // (2s+1) >= t+1  <=>  2s+1 <= wq // (t+1),
    so #{s >= 0} = (wq // (t+1) + 1) >> 1, clamped per party to n_eff
    (a single party can absorb at most the whole target).
    """
    m = ((wq // (t + 1)) + 1) >> 1
    return jnp.where(pos_mask, jnp.clip(m - s0, 0, n_eff), 0)


def webster_divide(
    n: jnp.ndarray,
    w: jnp.ndarray,
    s0: jnp.ndarray,
    active: jnp.ndarray,
    rank: jnp.ndarray,
) -> jnp.ndarray:
    """Allocate `n` new seats among parties; returns total seats per party.

    Args:
      n: int scalar -- number of new seats to award (<=0 awards none).
      w: int64[C] votes (weights); negative treated as 0, clamped to 2^34.
      s0: int64[C] initial seats (kept; never removed).
      active: bool[C] party-exists mask (inactive lanes are padding).
      rank: int[C] tiebreak order; MUST hold distinct values among active
        lanes, pre-flipped for descending UID tiebreak by the caller.

    Matches ops/webster.py allocate_webster_seats / dispense_by_weight:
    a zero total weight awards nothing (seats stay s0).
    """
    C = w.shape[0]
    n = jnp.asarray(n, jnp.int64)
    w = jnp.where(active, jnp.clip(jnp.asarray(w, jnp.int64), 0, _W_CAP), 0)
    s0 = jnp.where(active, jnp.clip(jnp.asarray(s0, jnp.int64), 0, _N_CAP), 0)
    rank = jnp.asarray(rank, jnp.int64)
    totw = jnp.sum(w)
    n_eff = jnp.where(totw > 0, jnp.clip(n, 0, _N_CAP), 0)

    wq = w << PRIORITY_QBITS
    pos_mask = active & (w > 0)

    # -- 1. threshold bisection: smallest t >= 0 with cnt(t) <= n_eff -------
    # Invariant maintained: cnt(hi) <= n_eff < cnt(lo) (when cnt(0) > n_eff;
    # otherwise the result is overridden to t* = 0 below, where the award is
    # exact because every positive-weight party already absorbs its clamp).
    def cnt(t):
        return jnp.sum(_count_above(wq, s0, pos_mask, n_eff, t))

    hi0 = jnp.maximum(jnp.max(wq), jnp.int64(1))

    def bis_cond(st):
        lo, hi = st
        return hi - lo > 1

    def bis_body(st):
        lo, hi = st
        mid = (lo + hi) >> 1
        over = cnt(mid) > n_eff
        return (jnp.where(over, mid, lo), jnp.where(over, hi, mid))

    _, hi = lax.while_loop(bis_cond, bis_body, (jnp.int64(0), hi0))
    t_star = jnp.where(cnt(jnp.int64(0)) <= n_eff, jnp.int64(0), hi)

    # -- 2. full award above the threshold ----------------------------------
    full = _count_above(wq, s0, pos_mask, n_eff, t_star)
    r = n_eff - jnp.sum(full)

    # -- 3. one-shot tie-block award at q == t* -----------------------------
    # Tie candidates of party i occupy seat indices base_i .. base_i+k_i-1
    # with static keys seat*C + rank_i (all distinct).  The r serial awards
    # are exactly the r smallest keys (merge argument over per-party
    # ascending key streams), found by bisecting the key value.
    tm1 = jnp.maximum(t_star - 1, jnp.int64(0))
    k = jnp.where(t_star > 0, _count_above(wq, s0, pos_mask, n_eff, tm1) - full, 0)
    base = s0 + full

    def cnt_key(K):
        c = ((K - 1 - rank) // C) - base + 1
        return jnp.clip(c, 0, k)

    KHI = jnp.int64((1 << 27) * C)  # keys < (s0_cap + n_cap + 1) * C

    def kb_cond(st):
        lo, hi = st
        return hi - lo > 1

    def kb_body(st):
        lo, hi = st
        mid = (lo + hi) >> 1
        ge = jnp.sum(cnt_key(mid)) >= r
        return (jnp.where(ge, lo, mid), jnp.where(ge, mid, hi))

    _, k_star = lax.while_loop(kb_cond, kb_body, (jnp.int64(0), KHI))
    award = jnp.where(r > 0, cnt_key(k_star), 0)

    s = s0 + full + award
    return jnp.where(active, s, 0)


# vmapped over a batch of problems: n[B], w[B,C], s0[B,C], active[B,C], rank[B,C]
webster_divide_batch = jax.vmap(webster_divide, in_axes=(0, 0, 0, 0, 0))


# ---------------------------------------------------------------------------
# Batched scheduling pipeline
# ---------------------------------------------------------------------------
#
# One jitted program per scheduling cycle over the dense SolverBatch encoding
# (ops/tensors.py): filter masks -> locality scores -> GeneralEstimator
# capacity math (pkg/estimator/client/general.go:294) -> cluster-field spread
# selection (select_clusters_by_cluster.go:25) -> replica division strategies
# (assignment.go / division_algorithm.go) via the Webster kernel above.

# strategy / status ids shared with the encoder/decoder
from karmada_tpu.ops.tensors import (  # noqa: E402
    STATUS_FIT_ERROR,
    STATUS_NO_CLUSTER,
    STATUS_OK,
    STATUS_UNSCHEDULABLE,
    STRAT_AGGREGATED,
    STRAT_DUPLICATED,
    STRAT_DYNAMIC,
    STRAT_STATIC,
)

# explain-plane verdict bit layout (obs/decisions is the single authority;
# pure int constants — no runtime dependency rides in)
from karmada_tpu.obs.decisions import (  # noqa: E402
    N_VERDICT_BITS,
    VERDICT_API_ENABLEMENT,
    VERDICT_BIT_CAPACITY,
    VERDICT_CAPACITY,
    VERDICT_CLUSTER_GONE,
    VERDICT_EVICTION,
    VERDICT_NOT_SELECTED,
    VERDICT_TOLERATION,
)


def _explain_verdict(fail_static, tol_ok, api_ok_b, evict, lanes_ok,
                     avail_cal, feasible, sel, workload, b_valid, status):
    """The per-(binding, cluster) filter-verdict bitmask (int32 [B, C])
    from the stage predicates the kernel already evaluates.  Bits are
    INDEPENDENT — a cluster failing several stages carries them all; the
    serial-parity contract (obs/decisions.first_reason) reads the lowest
    set bit, which is the serial chain's first-rejection-wins reason.

    On an UNSCHEDULABLE row (aggregate capacity shortfall in selection /
    division) every feasible cluster carries CAPACITY: the binding's
    demand exceeded what they offer TOGETHER, which is the kube-style
    "insufficient capacity" story — NOT_SELECTED is reserved for trims
    of a schedulable binding (spread max-groups, aggregated prefix)."""
    v = fail_static.astype(jnp.int32)
    v = v | jnp.where(tol_ok, 0, VERDICT_TOLERATION).astype(jnp.int32)
    v = v | jnp.where(api_ok_b, 0, VERDICT_API_ENABLEMENT).astype(jnp.int32)
    v = v | jnp.where(evict, VERDICT_EVICTION, 0).astype(jnp.int32)
    v = v | jnp.where(lanes_ok, 0, VERDICT_CLUSTER_GONE).astype(jnp.int32)
    unsched = (status == STATUS_UNSCHEDULABLE)[:, None]
    v = v | jnp.where(((avail_cal <= 0) | (unsched & feasible))
                      & workload[:, None],
                      VERDICT_CAPACITY, 0).astype(jnp.int32)
    v = v | jnp.where(feasible & ~sel & ~unsched, VERDICT_NOT_SELECTED,
                      0).astype(jnp.int32)
    return jnp.where(b_valid[:, None], v, 0).astype(jnp.int32)


def _explain_outcome(verdict, status, cluster_valid):
    """Per-binding outcome code (int32 [B]): low byte is the solver
    STATUS_*, bits 8+ hold 1 + the bit index of the DOMINANT rejection
    stage — the stage that is the first-set (serial-priority) reason on
    the most real clusters; ties break toward the higher-priority stage
    (argmax returns the first maximum).  A capacity-shortfall
    UNSCHEDULABLE status always classifies as capacity."""
    low = verdict & (-verdict)  # lowest set bit per lane (0 when clean)
    counts = jnp.stack(
        [jnp.sum(((low == (1 << k)) & cluster_valid[None, :])
                 .astype(jnp.int32), axis=1)
         for k in range(N_VERDICT_BITS)], axis=1)  # [B, n_bits]
    dom = jnp.argmax(counts, axis=1).astype(jnp.int32)
    any_rej = jnp.max(counts, axis=1) > 0
    dom_code = jnp.where(any_rej, dom + 1, 0).astype(jnp.int32)
    dom_code = jnp.where(status == STATUS_UNSCHEDULABLE,
                         jnp.int32(VERDICT_BIT_CAPACITY + 1), dom_code)
    return (status.astype(jnp.int32) | (dom_code << 8)).astype(jnp.int32)

_AVAIL_BITS = 34  # avail values clamped below 2^34 for key packing
_AVAIL_CAP = (1 << _AVAIL_BITS) - 1

# Cluster-lane index bits in the packed sort keys.  The selection key packs
# (score[8b] | avail[34b] | lane[21b]) = 63 bits, so int64 admits fleets up
# to 2^21 clusters per solve call (the r3 design packed 13 bits / 8192
# lanes, which capped real-world fleets; VERDICT r3 item 2).
_LANE_BITS = 21
_LANE_MASK = (1 << _LANE_BITS) - 1
MAX_CLUSTER_LANES = 1 << _LANE_BITS


def _capacity_estimates(
    req_milli, req_is_cpu, req_pods, avail_milli, has_alloc, pods_allowed,
    has_summary
):
    """est[Q+1, C]: GeneralEstimator summary math (general.go:56-94,294-334),
    including component-SET classes (maxAvailableComponentSets general.go:
    106-160) whose pod bound divides by pods-per-set.

    Row Q is the requirements==None row: min(allowed pods, MaxInt32).
    """
    Q, R = req_milli.shape
    C = avail_milli.shape[0]
    # per-resource available in request units: cpu keeps milli, others ceil
    unit_avail = jnp.where(
        req_is_cpu[None, :], avail_milli, -((-avail_milli) // 1000)
    )  # [C, R]
    req = req_milli[:, None, :]  # [Q, 1, R]
    avail = unit_avail[None, :, :]  # [1, C, R]
    ok = has_alloc[None, :, :] & (avail > 0)
    cnt = jnp.where(ok, avail // jnp.maximum(req, 1), 0)  # [Q, C, R]
    cnt = jnp.where(req > 0, cnt, MAX_INT64)  # unrequested resources inert
    est = jnp.min(cnt, axis=2)  # [Q, C]
    pods_bound = pods_allowed[None, :] // jnp.maximum(req_pods[:, None], 1)
    est = jnp.minimum(est, pods_bound)
    est = jnp.where(has_summary[None, :] & (pods_allowed[None, :] > 0), est, 0)
    est = jnp.minimum(jnp.maximum(est, 0), MAX_INT32)
    none_row = jnp.where(
        has_summary & (pods_allowed > 0), jnp.minimum(pods_allowed, MAX_INT32), 0
    )
    return jnp.concatenate([est, none_row[None, :]], axis=0)  # [Q+1, C]


def _positions(key: jnp.ndarray) -> jnp.ndarray:
    C = key.shape[0]
    order = jnp.argsort(key)
    return jnp.zeros((C,), jnp.int64).at[order].set(jnp.arange(C, dtype=jnp.int64))


def _select_by_cluster(
    feasible, score, avail, name_rank, n_need, sc_min, sc_max, ignore_avail
):
    """Port of select_clusters_by_cluster.go:25-105 as masked tensor ops.

    Returns (selected mask, unschedulable flag).  Selection is by the packed
    key (score desc, available desc, name asc); when capacity matters, the
    swap loop replaces low-ranked picks with higher-capacity leftovers
    exactly like _select_by_available_resource in ops/serial.py.
    """
    C = feasible.shape[0]
    BIG = jnp.int64(MAX_INT64)  # larger than any real packed key
    fcount = jnp.sum(feasible)
    avail_c = jnp.clip(avail, 0, _AVAIL_CAP)
    key = (
        ((200 - score).astype(jnp.int64) << (_AVAIL_BITS + _LANE_BITS))
        | ((_AVAIL_CAP - avail_c) << _LANE_BITS)
        | name_rank
    )
    key = jnp.where(feasible, key, BIG)
    pos = _positions(key)
    order = jnp.argsort(key)
    need_cnt = jnp.minimum(jnp.asarray(sc_max, jnp.int64), fcount)
    sel0 = feasible & (pos < need_cnt)

    def swap_loop(args):
        in_sel, rest_pos, update_id = args

        def cond(st):
            in_sel, _, update_id = st
            total = jnp.sum(jnp.where(in_sel, avail, 0))
            return (total < n_need) & (update_id >= 0)

        def body(st):
            in_sel, rest_pos, update_id = st
            cur = order[update_id]
            rest = feasible & ~in_sel
            # max avail, ties to smallest rest position (serial list order)
            cand = jnp.where(
                rest,
                (avail_c << _LANE_BITS)
                | (_LANE_MASK - jnp.clip(rest_pos, 0, _LANE_MASK)),
                -1,
            )
            best = jnp.argmax(cand)
            found = (cand[best] >= 0) & (avail[best] > avail[cur])
            in_sel = jnp.where(
                found,
                in_sel.at[best].set(True).at[cur].set(False),
                in_sel,
            )
            rest_pos = jnp.where(
                found, rest_pos.at[cur].set(rest_pos[best]), rest_pos
            )
            return in_sel, rest_pos, update_id - 1

        return lax.while_loop(cond, body, (in_sel, rest_pos, update_id))

    in_sel, _, _ = lax.cond(
        ignore_avail,
        lambda a: a,
        swap_loop,
        (sel0, pos, need_cnt.astype(jnp.int64) - 1),
    )
    total = jnp.sum(jnp.where(in_sel, avail, 0))
    unsched = (fcount < sc_min) | (~ignore_avail & (total < n_need))
    return in_sel, unsched


def _locality_score(prev_present, extra_score) -> jnp.ndarray:
    """Cluster score along the last axis: in-tree locality (100 on previous
    clusters when any exist — generic_scheduler.go ClusterLocality) plus the
    pre-clamped out-of-tree plugin sum (<=100, scheduler/plugins.py); total
    <= 200 fits the packed sort keys' score bits."""
    has_prev = jnp.any(prev_present, axis=-1, keepdims=True)
    return (jnp.where(has_prev & prev_present, 100, 0).astype(jnp.int64)
            + jnp.asarray(extra_score, jnp.int64))


def _assign_lanes(
    feasible, avail_cal, prev_present, prev_rep, extra_score, name_rank,
    rank_webster,
    n, strategy, has_sc, sc_min, sc_max, ignore_avail,
    static_w, uid_desc, fresh, non_workload, valid,
):
    """One binding against its lane axis (full [C] or a compact top-K
    gather — the math is lane-count agnostic).  rank_webster is a
    DENSIFIED 0..L-1 rank in rank_eff order (Webster's tie-key packing
    seat*L + rank requires rank < L); name_rank keeps original values for
    the _LANE_BITS-wide lane field of the packed sort keys."""
    C = feasible.shape[0]
    i64 = lambda x: jnp.asarray(x, jnp.int64)
    n = i64(n)

    fcount = jnp.sum(feasible)
    score = _locality_score(prev_present, extra_score)

    # ---- selection -------------------------------------------------------
    sel_sc, unsched_sel = _select_by_cluster(
        feasible, score, avail_cal + prev_rep * prev_present, name_rank,
        n, i64(sc_min), i64(sc_max), ignore_avail,
    )
    sel = jnp.where(has_sc, sel_sc, feasible)
    unsched_sel = has_sc & unsched_sel
    sel_count = jnp.sum(sel)

    # ---- assignment ------------------------------------------------------
    scheduled_rep = jnp.where(sel & prev_present, prev_rep, 0)
    assigned = jnp.sum(scheduled_rep)

    is_dynamic = (strategy == STRAT_DYNAMIC) | (strategy == STRAT_AGGREGATED)
    scale_down = is_dynamic & ~fresh & (assigned > n)
    scale_up = is_dynamic & ~fresh & (assigned < n)
    steady_eq = is_dynamic & ~fresh & (assigned == n)
    is_fresh = is_dynamic & fresh

    # webster problem per strategy (selected branchlessly)
    static_eff = static_w * sel
    static_eff = jnp.where(jnp.sum(static_eff) > 0, static_eff, sel.astype(jnp.int64))

    w = jnp.zeros((C,), jnp.int64)
    w = jnp.where(strategy == STRAT_STATIC, static_eff, w)
    w = jnp.where(is_fresh, avail_cal * sel + scheduled_rep, w)
    w = jnp.where(scale_up, avail_cal * sel, w)
    w = jnp.where(scale_down, jnp.where(prev_present, prev_rep, 0), w)

    active = sel
    active = jnp.where(scale_down, prev_present, active)

    target = jnp.where(strategy == STRAT_STATIC, n, 0)
    target = jnp.where(is_fresh | scale_down, n, target)
    target = jnp.where(scale_up, n - assigned, target)

    base = jnp.where(scale_up | steady_eq, scheduled_rep, 0)

    avail_sum = jnp.sum(w)
    unsched_div = is_dynamic & (avail_sum < target)

    # Aggregated: trim to the capacity-descending prefix reaching target
    # (division_algorithm.go:80-90 + resortAvailableClusters assignment.go:145)
    prior = scale_up & (scheduled_rep > 0)
    wc = jnp.clip(w, 0, _AVAIL_CAP)
    agg_key = (
        (jnp.where(prior, 0, 1).astype(jnp.int64) << (_AVAIL_BITS + _LANE_BITS))
        | ((_AVAIL_CAP - wc) << _LANE_BITS)
        | name_rank
    )
    agg_key = jnp.where(active, agg_key, jnp.int64(MAX_INT64))
    agg_pos = _positions(agg_key)
    w_sorted = jnp.zeros((C,), jnp.int64).at[agg_pos].set(jnp.where(active, w, 0))
    cum_excl = jnp.cumsum(w_sorted) - w_sorted
    include_sorted = cum_excl < target
    inc = include_sorted[agg_pos]
    use_prefix = (strategy == STRAT_AGGREGATED) & (is_fresh | scale_up | scale_down)
    w = jnp.where(use_prefix, jnp.where(inc, w, 0), w)
    active = jnp.where(use_prefix, active & inc, active)

    run_webster = (
        valid
        & ~non_workload
        & (
            (strategy == STRAT_STATIC)
            | ((is_fresh | scale_up | scale_down) & ~unsched_div)
        )
    )
    seats = webster_divide(
        jnp.where(run_webster, target, 0), w, jnp.zeros((C,), jnp.int64),
        active & run_webster, rank_webster,
    )

    rep = base + seats
    rep = jnp.where(strategy == STRAT_DUPLICATED, n * sel, rep)
    rep = jnp.where(non_workload, 0, rep)

    status = jnp.where(
        fcount == 0,
        STATUS_FIT_ERROR,
        jnp.where(
            unsched_sel | unsched_div,
            STATUS_UNSCHEDULABLE,
            jnp.where(sel_count == 0, STATUS_NO_CLUSTER, STATUS_OK),
        ),
    )
    status = jnp.where(valid, status, STATUS_OK).astype(jnp.int32)
    rep = jnp.where((status == STATUS_OK) & valid, rep, 0)
    sel = sel & (status == STATUS_OK) & valid
    return rep, sel, status


# ---------------------------------------------------------------------------
# Compact lanes: the division/selection math per binding only ever involves
# a bounded set of lanes, so at large C it runs on a top-K gather instead of
# the full cluster axis (the while-loop passes were ~97% of kernel volume at
# C=8192).  Exactness argument, per sub-algorithm with target/sc_max <= 64
# (the encoder routes bigger bindings to the serial host path):
#   * Webster: a lane wins a seat only if its first-seat priority clears the
#     award threshold; at most `target` lanes outrank the marginal weight,
#     and tie awards go to the first r lanes in rank_eff order — so the top
#     128 by (w desc, rank_eff asc) contain every possible winner.
#   * Aggregated prefix: <= target lanes, ties by name ASC — top 128 by
#     (w desc, name asc).
#   * Selection + swap loop: keyed (score, avail, name asc); score>0 only on
#     prev lanes (all gathered), swaps take max-avail candidates — top 128
#     by (avail_sel desc, name asc).
#   * scale-down / Steady seats: previous-assignment lanes, all gathered.
# Duplicated-without-spread and non-workload selection are wide formulas
# computed outside the gather (they touch no expensive loop).

from karmada_tpu.ops.tensors import (  # noqa: E402
    COMPACT_DIVISION_CAP,
    COMPACT_DIVISION_CAP_BIG,
    COMPACT_LANES,
    COMPACT_LANES_BIG,
    COMPACT_PREV_CAP,
    COMPACT_PREV_CAP_BIG,
    COMPACT_SELECTION_CAP,
    COMPACT_SELECTION_CAP_BIG,
)

_G_PREV, _G_TOPK = COMPACT_PREV_CAP, 2 * COMPACT_DIVISION_CAP
assert COMPACT_LANES == _G_PREV + 4 * _G_TOPK, "lane geometry out of sync"
# the selection path consumes up to sc_max picks + sc_max swap-ins from the
# avail-ordered gather; its cap must not outgrow the division-derived budget
assert COMPACT_SELECTION_CAP <= COMPACT_DIVISION_CAP, "selection cap too big"

# gather geometry per compile tier: (g_prev, g_topk, direct_max).  The
# "big" tier serves ROUTE_DEVICE_BIG sub-solves (caps 8x tier-1); its
# exactness argument is the same, scaled.
_TIERS = {
    "std": (_G_PREV, _G_TOPK, COMPACT_LANES),
    "big": (COMPACT_PREV_CAP_BIG, 2 * COMPACT_DIVISION_CAP_BIG,
            COMPACT_LANES_BIG),
}
assert COMPACT_LANES_BIG == COMPACT_PREV_CAP_BIG + 8 * COMPACT_DIVISION_CAP_BIG
assert COMPACT_SELECTION_CAP_BIG <= COMPACT_DIVISION_CAP_BIG


def _gather_lanes(feasible, avail_sel, w_gather, prev_present, score,
                  name_rank, rank_eff, use_extra: bool,
                  g_prev: int = _G_PREV, g_topk: int = _G_TOPK):
    """The union-of-top-K lane set for one binding: indices[K] plus a
    validity mask (duplicates and junk lanes disabled).  The score-keyed
    5th gather covers selection order under out-of-tree score plugins;
    without them (use_extra=False, the common case — statically known per
    compile) score > 0 only on prev lanes, which the prev gather already
    covers, so the kernel keeps the 4-group lane volume."""
    C = feasible.shape[0]
    nr = jnp.asarray(name_rank, jnp.int64)
    wq = jnp.clip(w_gather, 0, _AVAIL_CAP) << _LANE_BITS
    aq = jnp.clip(avail_sel, 0, _AVAIL_CAP) << _LANE_BITS
    NEG = jnp.int64(-1)
    key_prev = jnp.where(prev_present, (_LANE_MASK - nr), NEG)
    key_w_rank = jnp.where(feasible, wq | (_LANE_MASK - rank_eff), NEG)
    key_w_name = jnp.where(feasible, wq | (_LANE_MASK - nr), NEG)
    key_a_name = jnp.where(feasible, aq | (_LANE_MASK - nr), NEG)
    _, ip = lax.top_k(key_prev, g_prev)
    _, iw = lax.top_k(key_w_rank, g_topk)
    _, inm = lax.top_k(key_w_name, g_topk)
    _, ia = lax.top_k(key_a_name, g_topk)
    groups = [ip, iw, inm, ia]
    if use_extra:
        # the selection sort key itself: score desc, avail desc, name asc
        key_sel = jnp.where(
            feasible,
            (jnp.clip(score, 0, 255) << (_AVAIL_BITS + _LANE_BITS))
            | aq | (_LANE_MASK - nr),
            NEG,
        )
        _, isel = lax.top_k(key_sel, g_topk)
        groups.append(isel)
    lanes = jnp.concatenate(groups)  # [K]
    lanes = jnp.sort(lanes)
    dup = jnp.concatenate(
        [jnp.zeros((1,), bool), lanes[1:] == lanes[:-1]])
    return lanes, ~dup


def _schedule_one(
    feasible, avail_cal, prev_present, prev_rep, extra_score, name_rank,
    n, strategy, has_sc, sc_min, sc_max, ignore_avail,
    static_w, uid_desc, fresh, non_workload, valid,
    *, use_extra: bool = True, tier: str = "std",
):
    """One binding; vmapped over the batch.  Small cluster axes run the
    lane math directly; large ones gather the tier's lane budget first."""
    g_prev, g_topk, direct_max = _TIERS[tier]
    C = feasible.shape[0]
    rank_eff = jnp.where(uid_desc, C - 1 - name_rank, name_rank)
    if C <= direct_max:
        return _assign_lanes(
            feasible, avail_cal, prev_present, prev_rep, extra_score,
            name_rank, rank_eff,
            n, strategy, has_sc, sc_min, sc_max, ignore_avail,
            static_w, uid_desc, fresh, non_workload, valid,
        )

    avail_sel = avail_cal + prev_rep * prev_present
    w_gather = jnp.where(strategy == STRAT_STATIC, static_w, avail_sel)
    score_full = _locality_score(prev_present, extra_score)
    lanes, lane_ok = _gather_lanes(
        feasible, avail_sel, w_gather, prev_present, score_full, name_rank,
        rank_eff, use_extra, g_prev, g_topk)
    g = lambda a: a[lanes]
    feas_k = g(feasible) & lane_ok
    rank_eff_k = g(rank_eff)
    # densify rank_eff to 0..K-1 preserving order (Webster's tie-key
    # packing needs rank < lane count)
    rank_webster = _positions(jnp.where(lane_ok, rank_eff_k,
                                        (jnp.int64(1) << 40) + lanes))
    rep_k, sel_k, status = _assign_lanes(
        feas_k, g(avail_cal), g(prev_present) & lane_ok, g(prev_rep),
        g(extra_score), g(name_rank), rank_webster,
        n, strategy, has_sc, sc_min, sc_max, ignore_avail,
        g(static_w), uid_desc, fresh, non_workload, valid,
    )
    rep = jnp.zeros((C,), jnp.int64).at[lanes].add(
        jnp.where(lane_ok, rep_k, 0))
    sel_scatter = jnp.zeros((C,), bool).at[lanes].max(sel_k & lane_ok)
    ok = (status == STATUS_OK) & valid
    # wide formulas for the pieces whose result legitimately spans the
    # full feasible set (no expensive loop involved)
    dup_wide = (strategy == STRAT_DUPLICATED) & ~has_sc
    rep = jnp.where(dup_wide & ok, jnp.asarray(n, jnp.int64) * feasible, rep)
    rep = jnp.where(non_workload, 0, rep)
    sel = jnp.where(has_sc, sel_scatter, feasible & ok)
    return rep, sel, status


def _schedule_vmap_for(use_extra: bool, tier: str):
    """vmapped kernel per static (plugin-score mode, lane tier) pair —
    the common no-plugin std variant keeps the 4-group/528-lane volume."""
    return jax.vmap(
        partial(_schedule_one, use_extra=use_extra, tier=tier),
        in_axes=(0, 0, 0, 0, 0, None, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0),
    )


_SCHEDULE_VMAPS = {
    (ue, tier): _schedule_vmap_for(ue, tier)
    for ue in (True, False) for tier in _TIERS
}


def _schedule_core(
    # cluster axis
    cluster_valid, deleting, name_rank, pods_allowed, has_summary,
    avail_milli, has_alloc, api_ok,
    # request classes
    req_milli, req_is_cpu, req_pods, est_override,
    # placements
    pl_mask, pl_tol_bypass, pl_strategy, pl_static_w,
    pl_has_cluster_sc, pl_sc_min, pl_sc_max, pl_ignore_avail,
    pl_extra_score,
    # bindings
    b_valid, placement_id, gvk_id, class_id, replicas, uid_desc, fresh,
    non_workload, nw_shortcut, prev_idx, prev_val, evict_idx,
    used0_milli=None, used0_pods=None, used0_sets=None,
    pl_fail_bits=None,
    *, waves: int = 1, use_extra: bool = True, with_used: bool = False,
    tier: str = "std", shard_mesh=None, explain: bool = False,
):
    """The full cycle: returns (rep[B,C] int64, selected[B,C] bool, status[B]).

    `explain` (static) is a SEPARATE jit variant emitting the explain
    plane alongside: a per-(binding, cluster) filter-verdict bitmask, the
    selection-score and estimator-capacity breakdown planes, and a
    per-binding outcome code — all int32, appended to the return as one
    (verdict[B,C], score[B,C], avail[B,C], outcome[B]) tuple.
    `pl_fail_bits` carries the encoder's static per-placement failure
    bits in (tensors.encode_batch(explain=True)); disarmed calls pass
    neither and compile byte-identically to the pre-explain program.

    `waves` splits the chunk (in its queue-priority order) into sequential
    capacity-contention waves: wave k prices against the snapshot minus what
    waves <k consumed.  waves == B is exactly the reference's serial
    one-at-a-time semantics; waves == 1 prices the whole chunk against the
    unmodified snapshot.

    Previous assignments / eviction tasks arrive SPARSE (prev_idx/prev_val
    [B, Kp], evict_idx [B, Ke], -1 padded) and are scattered to dense [B, C]
    lanes here: the dense forms are ~hundreds of MB per chunk and would be
    transfer-bound over the host<->TPU link.

    `shard_mesh` (static; the active ops/meshing Mesh, None single-device)
    pins the wave scan's stacked outputs to explicit (bindings, clusters)
    shardings.  Without the pin the SPMD partitioner picks shardings for
    the scan's stacking dynamic-update-slice itself and (observed on this
    jaxlib, multi-wave + fused extraction) emits a mixed s64/s32 offset
    compare the HLO verifier rejects; the pin keeps it on the well-trodden
    partition-along-data-axes path and states the intended placement
    anyway.
    """
    B = b_valid.shape[0]
    C = cluster_valid.shape[0]
    Q = req_milli.shape[0]
    # clamp to the nearest divisor of B at or below the requested count
    # (B is pow2 when padded, arbitrary otherwise) — a configured waves=8
    # on a tiny 4-binding cycle must degrade, not crash.  _effective_waves
    # is the single authority: the dispatch-level mesh policy (_plan_for)
    # relies on computing the same Bw before tracing.
    waves = _effective_waves(B, waves)
    Bw = B // waves

    # scatter sparse prev/evict to dense device lanes (additive: -1 padding
    # rows collapse onto lane 0 contributing zero, so duplicates are safe)
    bidx = jnp.arange(B, dtype=jnp.int32)[:, None]
    pmask = prev_idx >= 0
    pic = jnp.where(pmask, prev_idx, 0)
    prev_rep = (
        jnp.zeros((B, C), jnp.int64)
        .at[bidx, pic]
        .add(jnp.where(pmask, prev_val, 0).astype(jnp.int64))
    )
    prev_present = (
        jnp.zeros((B, C), jnp.int32).at[bidx, pic].add(pmask.astype(jnp.int32)) > 0
    )
    emask = evict_idx >= 0
    eic = jnp.where(emask, evict_idx, 0)
    evict = (
        jnp.zeros((B, C), jnp.int32).at[bidx, eic].add(emask.astype(jnp.int32)) > 0
    )

    lanes_ok = cluster_valid[None, :] & ~deleting[None, :]
    # consumption per replica, in avail_milli units (cpu rows are stored in
    # milli; every other resource row is stored in whole units -> x1000)
    req_consume = req_milli * jnp.where(req_is_cpu[None, :], 1, 1000)  # [Q, R]
    # class gather rows padded with a "no requirements" row Q: zero resource
    # consumption, one pod per replica
    req_consume_ext = jnp.concatenate(
        [req_consume, jnp.zeros((1,) + req_consume.shape[1:], req_consume.dtype)]
    )
    req_pods_ext = jnp.concatenate([req_pods, jnp.ones((1,), req_pods.dtype)])

    def wave_step(carry, xs):
        used_milli, used_pods, used_sets = carry
        (b_valid_w, placement_id_w, gvk_id_w, class_id_w, replicas_w,
         uid_desc_w, fresh_w, non_workload_w, nw_shortcut_w, prev_rep_w,
         prev_present_w, evict_w) = xs

        avail_eff = avail_milli - used_milli
        pods_eff = jnp.maximum(pods_allowed - used_pods, 0)
        est_q = _capacity_estimates(
            req_milli, req_is_cpu, req_pods, avail_eff, has_alloc, pods_eff,
            has_summary,
        )
        # accurate-estimator overrides decrement by same-class consumption
        # (cross-class coupling rides the general milli math above)
        ovr = jnp.maximum(est_override - used_sets, 0)
        est_q = est_q.at[:Q].set(jnp.where(est_override >= 0, ovr, est_q[:Q]))

        cid = jnp.where(class_id_w >= 0, class_id_w, Q)
        est_b = est_q[cid]  # [Bw, C]
        # calAvailableReplicas (util.go:104): clamp leftover MaxInt32 to
        # replicas, EXCEPT the non-workload shortcut (early-return unclamped)
        avail_cal = jnp.where(est_b == MAX_INT32, replicas_w[:, None], est_b)
        avail_cal = jnp.where(nw_shortcut_w[:, None], MAX_INT32, avail_cal)

        feasible = (
            lanes_ok
            & pl_mask[placement_id_w]
            & (pl_tol_bypass[placement_id_w] | prev_present_w)
            & (api_ok[gvk_id_w] | prev_present_w)
            & ~evict_w
        )

        rep, sel, status = _SCHEDULE_VMAPS[(use_extra, tier)](
            feasible, avail_cal, prev_present_w, prev_rep_w,
            pl_extra_score[placement_id_w], name_rank,
            replicas_w, pl_strategy[placement_id_w],
            pl_has_cluster_sc[placement_id_w], pl_sc_min[placement_id_w],
            pl_sc_max[placement_id_w], pl_ignore_avail[placement_id_w],
            pl_static_w[placement_id_w],
            uid_desc_w, fresh_w, non_workload_w, b_valid_w,
        )
        expl = ()
        if explain:
            pidw = placement_id_w
            verdict = _explain_verdict(
                pl_fail_bits[pidw], pl_tol_bypass[pidw] | prev_present_w,
                api_ok[gvk_id_w] | prev_present_w, evict_w, lanes_ok,
                avail_cal, feasible, sel,
                ~non_workload_w & ~nw_shortcut_w, b_valid_w, status)
            sc_pl = _locality_score(prev_present_w,
                                    pl_extra_score[pidw])
            ex_score = jnp.clip(sc_pl, 0, MAX_INT32).astype(jnp.int32)
            ex_avail = jnp.clip(avail_cal, 0, MAX_INT32).astype(jnp.int32)
            outcome = _explain_outcome(verdict, status, cluster_valid)
            expl = (verdict, ex_score, ex_avail, outcome)
        if shard_mesh is not None and waves > 1:
            # pin the scan's stacked per-wave outputs (see docstring)
            from karmada_tpu.ops import meshing

            rep_s, sel_s, st_s = meshing.wave_output_shardings(
                shard_mesh, Bw, C)
            rep = lax.with_sharding_constraint(rep, rep_s)
            sel = lax.with_sharding_constraint(sel, sel_s)
            status = lax.with_sharding_constraint(status, st_s)
            if explain:
                # the explain planes stack through the same scan DUS —
                # same partitioner hazard, same pin
                expl = (lax.with_sharding_constraint(expl[0], rep_s),
                        lax.with_sharding_constraint(expl[1], rep_s),
                        lax.with_sharding_constraint(expl[2], rep_s),
                        lax.with_sharding_constraint(expl[3], st_s))

        if waves > 1 or with_used:
            # New consumption only: replicas KEPT from the previous
            # assignment are already reflected in the snapshot's
            # allocated/allocating totals (cluster_status controller), so
            # charging full rep would double-count steady-state bindings.
            # Shrinks are not credited back either — pods terminate
            # asynchronously, so freed capacity is not instantly available.
            delta = jnp.maximum(rep - prev_rep_w, 0)
            # s64 dot_general is unsupported on TPU; these contractions are
            # tiny (R, Q axes), so broadcast-multiply-reduce / segment_sum
            req_b = req_consume_ext[cid]  # [Bw, R]
            used_milli = used_milli + jnp.sum(
                delta[:, :, None] * req_b[:, None, :], axis=0
            )
            used_pods = used_pods + jnp.sum(delta * req_pods_ext[cid][:, None], axis=0)
            used_sets = used_sets + jax.ops.segment_sum(
                delta, cid, num_segments=Q + 1
            )[:Q]
        return (used_milli, used_pods, used_sets), (rep, sel, status) + expl

    xs = jax.tree.map(
        lambda a: a.reshape((waves, Bw) + a.shape[1:]),
        (b_valid, placement_id, gvk_id, class_id, replicas, uid_desc, fresh,
         non_workload, nw_shortcut, prev_rep, prev_present, evict),
    )
    # carry-in: a previous batch of the SAME cycle already consumed this
    # much (scheduler second-pass repack / cross-batch continuity)
    carry0 = (
        (jnp.asarray(used0_milli, avail_milli.dtype) if used0_milli is not None
         else jnp.zeros_like(avail_milli)),                       # [C, R]
        (jnp.asarray(used0_pods, pods_allowed.dtype) if used0_pods is not None
         else jnp.zeros_like(pods_allowed)),                      # [C]
        (jnp.asarray(used0_sets, est_override.dtype) if used0_sets is not None
         else jnp.zeros_like(est_override)),                      # [Q, C]
    )
    if waves == 1:
        used, ys = wave_step(carry0, jax.tree.map(lambda a: a[0], xs))
        out = ys[:3]
        if with_used:
            out = out + (used,)
        if explain:
            out = out + (ys[3:7],)
        return out
    used, ys = lax.scan(wave_step, carry0, xs)
    rep, sel, status = ys[:3]
    C = rep.shape[-1]
    rep, sel, status = rep.reshape(B, C), sel.reshape(B, C), status.reshape(B)
    expl = ()
    if explain:
        verdict, ex_score, ex_avail, outcome = ys[3:7]
        expl = (verdict.reshape(B, C), ex_score.reshape(B, C),
                ex_avail.reshape(B, C), outcome.reshape(B))
    if shard_mesh is not None:
        # pin the reshaped results too: without it the partitioner can
        # back-propagate a bindings sharding of [B] through the reshape
        # onto the scan's stacking (index) dimension when Bw doesn't
        # divide — the same broken partitioned-DUS path (see docstring)
        from karmada_tpu.ops import meshing

        rep_s, sel_s, st_s = meshing.scan_result_shardings(
            shard_mesh, B, Bw, C)
        rep = lax.with_sharding_constraint(rep, rep_s)
        sel = lax.with_sharding_constraint(sel, sel_s)
        status = lax.with_sharding_constraint(status, st_s)
        if expl:
            expl = (lax.with_sharding_constraint(expl[0], rep_s),
                    lax.with_sharding_constraint(expl[1], rep_s),
                    lax.with_sharding_constraint(expl[2], rep_s),
                    lax.with_sharding_constraint(expl[3], st_s))
    out = (rep, sel, status)
    if with_used:
        out = out + (used,)
    if explain:
        out = out + (expl,)
    return out


# Dense-output entry point (tests, small callers).  The PRODUCTION path is
# schedule_compact below: a remote-attached backend (the tunnel this
# environment runs) materializes every jit OUTPUT to the host, so returning
# the dense [B, C] planes costs ~300 MB of D2H per chunk regardless of what
# the caller reads — measured as the entire chunk budget at 4096x8192.
schedule_batch = partial(
    jax.jit,
    static_argnames=("waves", "use_extra", "with_used",
                     "tier", "shard_mesh", "explain"))(_schedule_core)


def _mesh_plan():
    """The process-wide active solver mesh (ops/meshing), or None — the
    single-device fallback, in which case every placement below is the
    identical pre-mesh dispatch (no device_put with shardings, no new jit
    signatures)."""
    from karmada_tpu.ops import meshing

    return meshing.active()


def _effective_waves(B: int, waves: int) -> int:
    """The wave clamp (nearest divisor of B at or below the requested
    count) — the ONE implementation both _schedule_core (at trace time)
    and the dispatch-level mesh policy (_plan_for, before tracing) use:
    the policy's Bw must equal the kernel's or a sharded dispatch could
    reach the partitioner path _schedule_core's pin exists to avoid."""
    waves = max(1, min(waves, B))
    while B % waves:
        waves -= 1
    return waves


def _plan_for(batch, waves: int):
    """The mesh plan THIS dispatch should use, or None.

    Chunks whose per-wave row count Bw does not divide the bindings mesh
    axis dispatch unsharded: sharding a handful of rows per wave buys
    nothing (the cluster tensors are what scale), and with Bw below the
    axis size the SPMD partitioner must shard the wave scan's stacking
    dimension — the broken partitioned-DUS path the shard_mesh pin
    avoids (see _schedule_core).  Sharded and single-device dispatch are
    bit-identical, so mixing per chunk is sound."""
    plan = _mesh_plan()
    if plan is None:
        return None
    Bw = batch.B // _effective_waves(batch.B, waves)
    if Bw % plan.shape[0] != 0:
        return None
    return plan


def _compact_of(rep, sel, status, non_workload, max_nnz: int,
                keep_sel: bool = False):
    """Selected-but-zero lanes are extracted only where a consumer exists:
    non-workload bindings always (their targets ARE the selection), every
    binding only under empty-workload propagation (keep_sel).  A plain
    Divided binding's selection is its whole feasible set — extracting it
    unconditionally degenerates the 'compact' result to dense size on
    full-fleet placements (measured: ~12M entries at 100k x 5k, escalating
    the extraction cap to a ~270 MB D2H per chunk)."""
    wanted_sel = sel if keep_sel else (sel & non_workload[:, None])
    mask = (wanted_sel | (rep > 0)).ravel()
    nnz = jnp.sum(mask.astype(jnp.int32))
    (idx,) = jnp.nonzero(mask, size=max_nnz, fill_value=-1)
    val = jnp.where(idx >= 0, rep.ravel()[jnp.maximum(idx, 0)], 0)
    return (idx.astype(jnp.int32), val.astype(jnp.int32),
            status.astype(jnp.int32), nnz)


# positional index of the non_workload arg in _schedule_core's signature
# (schedule_compact receives the same tuple via *args)
_NON_WORKLOAD_ARG = 28


# flight-recorder compile attribution: a dispatch is a "miss" exactly when
# jax.jit's own specialization cache grew across the call — correct even
# when the signature was warmed before tracing was armed (the bench warms
# every chunk shape untraced, then measures traced).
def _jit_cache_size():
    try:  # noqa: SLF001 — jax API
        n = schedule_compact._cache_size()
    # vet: ignore[exception-hygiene] older jax: compile attribution degrades to None
    except Exception:  # noqa: BLE001 — older jax: attribution unavailable
        return None
    try:
        n += schedule_compact_donated._cache_size()  # noqa: SLF001
    # vet: ignore[exception-hygiene] donated variant optional; the base count stands
    except Exception:  # noqa: BLE001 — donated variant is an optimization
        pass
    return n


def _trace_span():
    """The ambient flight-recorder span, or None when tracing is off."""
    from karmada_tpu import obs

    return obs.TRACER.current() if obs.TRACER.enabled else None


def _schedule_compact_impl(*args, pl_fail_bits=None, waves: int, max_nnz: int,
                           keep_sel: bool = False, use_extra: bool = True,
                           with_used: bool = False, tier: str = "std",
                           shard_mesh=None, explain: bool = False):
    """The full cycle with the sparse COO extraction FUSED into one jitted
    program: the dense [B, C] result planes never become jit outputs, so
    only idx/val/status/nnz (~max_nnz ints) ever leave the device.
    with_used additionally returns the consumed-capacity accumulators
    (used_milli [C,R], used_pods [C], used_sets [Q,C]) — the carry for a
    second-pass repack or a later batch of the same cycle.  explain (a
    static: its own jit variant, so the disarmed program is untouched)
    appends the dense explain plane — verdict/score/avail [B,C] + outcome
    [B], all int32 — which finalize_compact d2h's alongside the COO."""
    core = _schedule_core(*args, pl_fail_bits=pl_fail_bits, waves=waves,
                          use_extra=use_extra, with_used=with_used,
                          tier=tier, shard_mesh=shard_mesh, explain=explain)
    rep, sel, status = core[:3]
    compact = _compact_of(rep, sel, status, args[_NON_WORKLOAD_ARG], max_nnz,
                          keep_sel=keep_sel)
    if with_used:
        compact = compact + tuple(core[3])
    if explain:
        compact = compact + tuple(core[4 if with_used else 3])
    return compact


_COMPACT_STATICS = ("waves", "max_nnz", "keep_sel", "use_extra", "with_used",
                    "tier", "shard_mesh", "explain")
schedule_compact = partial(
    jax.jit, static_argnames=_COMPACT_STATICS)(_schedule_compact_impl)

# positions of the used0_milli/used0_pods/used0_sets carry operands in the
# *args tuple (they follow the 33 batch fields; meshing.BATCH_FIELDS is the
# canonical order)
_USED0_ARGNUMS = (33, 34, 35)

# Donated variant of the compact dispatch: the carry used0 operands alias
# into the used-out outputs, so the chunk-to-chunk carry updates in place
# instead of allocating (and on narrow links, copying) a fresh accumulator
# generation per chunk.  Donation deletes the input buffers after the call,
# so dispatch_compact only selects this variant when the nnz-overflow
# escalation re-solve (which would need those buffers back) is provably
# impossible — see _nnz_bound.
schedule_compact_donated = partial(
    jax.jit, static_argnames=_COMPACT_STATICS,
    donate_argnums=_USED0_ARGNUMS)(_schedule_compact_impl)

DONATED_DISPATCHES = REGISTRY.counter(
    "karmada_solver_donated_dispatches_total",
    "Compact dispatches whose carry used0 operands were buffer-donated",
)


def _nnz_bound(batch) -> int:
    """A sound host-side upper bound on the compact extraction's nnz for
    keep_sel=False: wide rows (Duplicated strategies, whose result can
    span every feasible cluster, and non-workload rows, whose selection is
    extracted) count the full cluster axis; every other valid row's rep>0
    lanes are bounded by its OWN replica target (every division mode
    awards at most `replicas` seats, each on a distinct lane, clamped to
    C) plus the sparse prev-assignment width (scale-up/steady keep prev
    lanes).  Per-row replicas — not a tier cap — because small fleets
    (C <= COMPACT_LANES, encoded compact=False) route Divided rows of ANY
    replica count to the device.  When the bound fits max_nnz the
    escalation re-solve can never trigger, which is exactly the
    precondition for buffer donation (a donated dispatch cannot re-run:
    its inputs are gone).

    A fused resident-gather batch (ops/resident_gather) carries its
    binding-axis fields as live device arrays; the resident plane
    computes the identical bound host-side from the slot-store masters
    at assemble time (nnz_bound_hint) so this function never forces a
    device->host read of solver operands."""
    hint = getattr(batch, "nnz_bound_hint", None)
    if hint is not None:
        return int(hint)
    strat = batch.pl_strategy[batch.placement_id]
    valid = batch.b_valid.astype(bool)
    wide = valid & ((strat == STRAT_DUPLICATED)
                    | batch.non_workload.astype(bool))
    n_wide = int(_onp.sum(wide))
    rest = valid & ~wide
    per_row = _onp.minimum(batch.replicas, batch.C) + batch.prev_idx.shape[1]
    return n_wide * batch.C + int(_onp.sum(per_row[rest]))


# Single-generation device-transfer cache for the chunk-stable cluster-side
# tensors: the encoder hands back the SAME (frozen) numpy objects across
# chunks of a cycle (EncoderCache.assembled), so their device copies upload
# once per cycle instead of once per chunk (~5MB/chunk over a 36MB/s link).
# One slot only — keyed by the identity of the whole arg tuple's first
# member and holding the numpy refs so a GC'd id can never alias — so a
# long-running service retains exactly one stale-free generation per
# PLACEMENT: keyed by the active mesh plan's generation (0 = unsharded), so
# a cycle that mixes sharded chunks with per-chunk mesh fallbacks (tiny
# tail chunks, _plan_for) keeps BOTH device copies instead of thrashing
# one slot with re-uploads; generations of retired meshes are evicted.
_DEVICE_SLOT: dict = {}  # mesh_gen -> (cluster_np_tuple, cluster_dev_tuple)

_CLUSTER_FIELDS = (
    "cluster_valid", "deleting", "name_rank", "pods_allowed", "has_summary",
    "avail_milli", "has_alloc", "api_ok",
    "req_milli", "req_is_cpu", "req_pods", "est_override",
    "pl_mask", "pl_tol_bypass", "pl_strategy", "pl_static_w",
    "pl_has_cluster_sc", "pl_sc_min", "pl_sc_max", "pl_ignore_avail",
    "pl_extra_score",
)


def _put(field, arr, plan):
    """Place one solver operand: NamedSharding from the meshing spec table
    when a mesh is active, plain default placement otherwise."""
    if plan is None:
        return jax.device_put(arr)
    from karmada_tpu.ops import meshing

    return jax.device_put(
        arr, meshing.sharding_for(plan.mesh, field, arr.shape))


def prime_cluster_slot(np_args, dev_args, gen: int = 0) -> bool:
    """Pre-populate the device-transfer cache with already-placed cluster
    tensors (the resident-state plane, karmada_tpu/resident): a dispatch
    whose batch carries these exact numpy objects then skips the ~5MB
    cluster-side upload entirely.  `np_args`/`dev_args` follow the
    _CLUSTER_FIELDS order; `gen` is the mesh plan generation the device
    copies were placed for (0 = unsharded).  Refuses mutable arrays —
    the identity check must never serve a stale device copy."""
    np_args = tuple(np_args)
    if len(np_args) != len(_CLUSTER_FIELDS):
        return False
    if any(
        isinstance(a, _onp.ndarray) and a.flags.writeable for a in np_args
    ):
        return False
    _DEVICE_SLOT[gen] = (np_args, tuple(dev_args))
    active = _mesh_plan()
    keep = {0, gen, active.generation if active is not None else 0}
    for g in [g for g in _DEVICE_SLOT if g not in keep]:
        del _DEVICE_SLOT[g]
    return True


def _cluster_args(batch, plan=None):
    np_args = tuple(getattr(batch, f) for f in _CLUSTER_FIELDS)
    gen = plan.generation if plan is not None else 0
    slot = _DEVICE_SLOT.get(gen)
    if slot is not None and all(a is b for a, b in zip(slot[0], np_args)):
        return slot[1]
    dev = tuple(_put(f, a, plan) for f, a in zip(_CLUSTER_FIELDS, np_args))
    # only cache FROZEN arrays (encode_batch(cache=...) sets writeable=False):
    # a mutable array could be modified in place between solves and the
    # identity check would then serve a stale device copy
    if all(
        not (isinstance(a, _onp.ndarray) and a.flags.writeable) for a in np_args
    ):
        _DEVICE_SLOT[gen] = (np_args, dev)
        # retain only the live placements: the unsharded slot plus the
        # ACTIVE plan's — a retired mesh's copies are never served again
        active = _mesh_plan()
        keep = {0, active.generation if active is not None else 0}
        for g in [g for g in _DEVICE_SLOT if g not in keep]:
            del _DEVICE_SLOT[g]
    return dev


def _use_extra(batch) -> bool:
    """Static per-compile plugin-score mode: the encoder's extra-score rows
    are all-zero unless an out-of-tree score plugin is registered."""
    return bool(batch.pl_extra_score.any())


_BINDING_FIELDS = (
    "b_valid", "placement_id", "gvk_id", "class_id", "replicas", "uid_desc",
    "fresh", "non_workload", "nw_shortcut", "prev_idx", "prev_val",
    "evict_idx",
)

H2D_BINDING_FIELDS = REGISTRY.counter(
    "karmada_solver_h2d_binding_fields_total",
    "Binding-axis SolverBatch operands shipped host->device at dispatch; "
    "the fused resident-gather path (ops/resident_gather) hands live "
    "device arrays instead, so its steady-state cycles add zero here "
    "(bench --delta asserts exactly that)",
)


def _batch_args(batch, plan=None):
    cluster = _cluster_args(batch, plan)
    rows = tuple(getattr(batch, f) for f in _BINDING_FIELDS)
    # transfer accounting: every numpy operand here crosses the
    # host->device boundary this dispatch (jit moves it, or _put does);
    # live device arrays — the fused resident-gather outputs — do not
    n_np = sum(1 for a in rows if isinstance(a, _onp.ndarray))
    if n_np:
        H2D_BINDING_FIELDS.inc(n_np)
    if plan is None:
        # binding-axis tensors change every chunk: no caching value, and
        # jit moves raw numpy for free on the single-device path
        return cluster + rows
    return cluster + tuple(
        _put(f, a, plan) for f, a in zip(_BINDING_FIELDS, rows))


def solve(batch, waves: int = 1, tier: str = "std"):
    """Run schedule_batch over an ops/tensors.SolverBatch; dense numpy
    results (rep[B,C], sel[B,C], status[B]).  Tests and small callers; the
    hot path uses solve_compact to avoid the dense D2H transfer."""
    import numpy as np

    # packed sort keys reserve _LANE_BITS bits for the cluster lane
    assert batch.C <= MAX_CLUSTER_LANES, \
        f"cluster axis must be <= {MAX_CLUSTER_LANES} per solve call"
    if _guards.armed():
        _guards.check_batch(batch, "solve-entry")
    plan = _plan_for(batch, waves)
    rep, sel, status = schedule_batch(
        *_batch_args(batch, plan), waves=waves, use_extra=_use_extra(batch),
        tier=tier, shard_mesh=plan.mesh if plan is not None else None)
    return np.asarray(rep), np.asarray(sel), np.asarray(status)


def dispatch_compact(batch, waves: int = 1, max_nnz: int = 0,
                     keep_sel: bool = False, with_used: bool = False,
                     used0=None, tier: str = "std",
                     donate_used0: bool = False, explain: bool = False):
    """Enqueue the fused device solve WITHOUT forcing the result (jax
    dispatch is async): returns an opaque handle for finalize_compact.
    Lets a caller overlap host work (encode of the next chunk, decode of
    the previous) with the device execution of this one.

    keep_sel extracts every selected lane (empty-workload propagation);
    leave False otherwise — see _compact_of.  with_used adds the consumed-
    capacity accumulators to the result; used0 (um, up, usets) carries a
    previous batch's consumption in.

    donate_used0=True requests buffer donation of the used0 operands into
    the used-out outputs (in-place chunk-to-chunk carry).  It is honored
    only when nnz overflow — whose escalation re-solve would need the
    donated buffers back — is provably impossible (_nnz_bound, or an
    extraction cap already at the dense ceiling); otherwise the dispatch
    silently stays undonated.  A donated dispatch's used0 numpy operands
    remain readable (jax copies host arrays before donating the device
    copy), but live jax arrays passed as used0 are DELETED — callers must
    not read them afterwards (the pipelined executor's donation policy
    guarantees this).

    explain=True dispatches the SEPARATE explain jit variant (the
    disarmed signature is untouched — no new outputs compile into it):
    finalize_compact then additionally returns the (verdict, score,
    avail, outcome) int32 planes.  Requires a batch encoded with
    tensors.encode_batch(explain=True) — its pl_fail_bits carry the
    host-decomposed static filter stages."""
    assert batch.C <= MAX_CLUSTER_LANES, \
        f"cluster axis must be <= {MAX_CLUSTER_LANES} per solve call"
    assert not explain or batch.explain, \
        "explain dispatch needs a batch encoded with explain=True"
    if _guards.armed():
        # armed invariant mode (serve --check-invariants): the host->device
        # boundary check — dtype/shape drift dies here, not in the SPMD
        # partitioner three layers down
        _guards.check_batch(batch, "dispatch-compact")
        _guards.check_used(used0, "dispatch-compact carry")
    dense_nnz = batch.B * batch.C
    if max_nnz <= 0:
        # keep_sel ships whole selections (feasible-set scale on full-fleet
        # placements): start at dense rather than guaranteeing escalation
        # re-solves + recompiles on every chunk
        max_nnz = dense_nnz if keep_sel else min(
            max(batch.B * 16, 1 << 14), dense_nnz)
    plan = _plan_for(batch, waves)
    args = _batch_args(batch, plan)
    if used0 is not None:
        if plan is not None:
            # place the carry-in (host numpy from the keyed store, or live
            # device arrays from the chain) with the same cluster-sharded
            # specs as the capacity tensors it offsets: the chain stays
            # mesh-resident with ONE stable input sharding per chunk
            # (device_put on an already-matching Array is a no-op)
            from karmada_tpu.ops import meshing

            shards = meshing.used_shardings(
                plan.mesh, tuple(_onp.shape(u) for u in used0))
            used0 = tuple(jax.device_put(u, s)
                          for u, s in zip(used0, shards))
        else:
            # a mesh-dispatched neighbor chunk may have handed sharded
            # accumulators to this UNSHARDED dispatch (per-chunk mesh
            # fallback, e.g. one-binding waves): gather them onto the
            # default device; single-device arrays pass through untouched
            def _gather(u):
                s = getattr(u, "sharding", None)
                if s is not None and len(s.device_set) > 1:
                    return jax.device_put(u, jax.devices()[0])
                return u

            used0 = tuple(_gather(u) for u in used0)
        args = args + tuple(used0)
    donated = bool(
        donate_used0 and used0 is not None and not keep_sel
        and (max_nnz >= dense_nnz or _nnz_bound(batch) <= max_nnz))
    fn = schedule_compact_donated if donated else schedule_compact
    use_extra = _use_extra(batch)
    shard_mesh = plan.mesh if plan is not None else None
    pl_fb = _put("pl_fail_bits", batch.pl_fail_bits, plan) if explain else None
    sp = _trace_span()
    before = _jit_cache_size() if sp is not None else None
    first = fn(*args, pl_fail_bits=pl_fb, waves=waves, max_nnz=max_nnz,
               keep_sel=keep_sel, use_extra=use_extra,
               with_used=with_used, tier=tier, shard_mesh=shard_mesh,
               explain=explain)
    if donated:
        DONATED_DISPATCHES.inc()
    if before is not None:
        after = _jit_cache_size()
        if after is not None:
            sp.set_attr(compile_cache="miss" if after > before else "hit")
        if plan is not None:
            sp.set_attr(mesh=plan.shape_str, mesh_devices=plan.n_devices)
    return (args, waves, keep_sel, first, max_nnz, dense_nnz, use_extra,
            with_used, tier, donated, shard_mesh, explain, pl_fb)


def aot_warm_compile(batch, *, waves: int = 8, keep_sel: bool = False,
                     variant: str = "plain", tier: str = "std") -> dict:
    """AOT-compile the compact dispatch executable for this batch's shape
    WITHOUT executing it: lowers from abstract ShapeDtypeStructs (never
    touching the device-transfer cache or donating a live buffer) and
    calls the pjit ``.lower().compile()`` surface, so with the persistent
    compilation cache armed (ops/aotcache.enable) the executable lands on
    disk and the first REAL dispatch of the shape — in this process or
    any later one — pays cache deserialization instead of an XLA compile.

    variant: "plain" (single-chunk cycle), "explain" (the explain jit
    variant; requires a batch encoded with explain=True), "carry" (the
    with_used chain of multi-chunk cycles), "donated" (its buffer-donated
    form).  Statics (max_nnz, use_extra, shard_mesh) are derived exactly
    the way dispatch_compact derives them, mesh placement included, so
    the warmed signature IS the dispatched one."""
    explain = variant == "explain"
    with_used = variant in ("carry", "donated")
    assert variant in ("plain", "explain", "carry", "donated"), variant
    assert not explain or batch.explain, \
        "explain warm needs a batch encoded with explain=True"
    dense_nnz = batch.B * batch.C
    max_nnz = dense_nnz if keep_sel else min(
        max(batch.B * 16, 1 << 14), dense_nnz)
    plan = _plan_for(batch, waves)

    def aval(field, arr):
        arr = _onp.asarray(arr)
        if plan is None:
            return jax.ShapeDtypeStruct(arr.shape, arr.dtype)
        from karmada_tpu.ops import meshing

        return jax.ShapeDtypeStruct(
            arr.shape, arr.dtype,
            sharding=meshing.sharding_for(plan.mesh, field, arr.shape))

    fields = _CLUSTER_FIELDS + _BINDING_FIELDS
    args = tuple(aval(f, getattr(batch, f)) for f in fields)
    if with_used:
        # the carry triple the chain's keyed store would render: zeros of
        # the accumulator dtypes (tensors.CARRY_DTYPES), shaped like the
        # capacity tensors they offset
        used0_np = (_onp.zeros_like(batch.avail_milli),
                    _onp.zeros_like(batch.pods_allowed),
                    _onp.zeros_like(batch.est_override))
        if plan is not None:
            from karmada_tpu.ops import meshing

            shards = meshing.used_shardings(
                plan.mesh, tuple(u.shape for u in used0_np))
            args = args + tuple(
                jax.ShapeDtypeStruct(u.shape, u.dtype, sharding=s)
                for u, s in zip(used0_np, shards))
        else:
            args = args + tuple(
                jax.ShapeDtypeStruct(u.shape, u.dtype) for u in used0_np)
    pl_fb = aval("pl_fail_bits", batch.pl_fail_bits) if explain else None
    fn = schedule_compact_donated if variant == "donated" else schedule_compact
    # lower (tracing — paid by every process, cache or not) timed apart
    # from compile (the XLA step the persistent cache serves): the
    # cold-start measurement compares compile_s across processes
    import time as _time

    t0 = _time.perf_counter()
    lowered = fn.lower(*args, pl_fail_bits=pl_fb, waves=waves,
                       max_nnz=max_nnz, keep_sel=keep_sel,
                       use_extra=_use_extra(batch), with_used=with_used,
                       tier=tier,
                       shard_mesh=plan.mesh if plan is not None else None,
                       explain=explain)
    t1 = _time.perf_counter()
    compiled = lowered.compile()
    t2 = _time.perf_counter()
    from karmada_tpu.obs import devprof

    # device cost attribution: flops / bytes-accessed of the executable
    # (telemetry plane, obs/devprof) — the chip-side price of one
    # dispatch, harvested once at warm time, zero cost on dispatch
    return {"lower_s": round(t1 - t0, 3), "compile_s": round(t2 - t1, 3),
            "cost": devprof.harvest_cost(compiled)}


def wait_compact(handle) -> None:
    """Block until a dispatch_compact handle's device work finishes WITHOUT
    copying anything to host: lets the scheduler service time the device
    solve separately from the D2H copy (finalize_compact).  The rare
    escalation re-solve (nnz overflow) still happens inside finalize and is
    accounted to the D2H stage there.

    Blocks on the compact COO outputs only: the used-out accumulators of a
    carried chunk may already have been buffer-donated into the NEXT
    chunk's dispatch (deleted handles), and every output of one executable
    completes at the same time anyway."""
    import jax

    jax.block_until_ready(handle[3][:4])


def dispatched_used(handle):
    """The consumed-capacity accumulators of a dispatch_compact(...,
    with_used=True) handle as LIVE device values (never materialized to
    host): (used_milli [C, R], used_pods [C], used_sets [Q, C]).

    The pipelined chunk executor (scheduler/pipeline.py) feeds these
    straight back as the NEXT chunk's used0 operands, so the carry chains
    device-side with no host synchronization.  Safe against the nnz
    escalation in finalize_compact: a re-solve with a larger extraction
    cap recomputes bit-identical accumulators (max_nnz only changes the
    COO cap), so a chunk dispatched against the first run's accumulators
    stays consistent."""
    assert handle[7], "handle was not dispatched with_used=True"
    return handle[3][4:7]


D2H_ZEROCOPY = REGISTRY.counter(
    "karmada_solver_d2h_zerocopy_total",
    "Device-to-host result planes handed over without a copy (dlpack)",
)


def _host_view(a):
    """Hand a jit output to the host WITHOUT a copy when possible: a
    single-device CPU jax array exports its buffer via dlpack and
    np.from_dlpack wraps it as a READ-ONLY numpy view (the capsule keeps
    the device buffer alive).  Anything else — a real accelerator
    buffer, a multi-device sharded output, an already-numpy array —
    falls back to np.asarray, exactly the old behavior.  Consumers
    (decode_compact, the d2h guard, the native decoder) only read."""
    import numpy as np

    try:
        devs = getattr(a, "devices", None)
        if callable(devs):
            ds = devs()
            if len(ds) == 1 and next(iter(ds)).platform == "cpu":
                out = np.from_dlpack(a)
                D2H_ZEROCOPY.inc()
                return out
    # vet: ignore[exception-hygiene] dlpack support varies by jax/platform; the copy path is always correct
    except Exception:  # noqa: BLE001 — zero-copy is an optimization only
        pass
    return np.asarray(a)


def finalize_compact(handle):
    """Force a dispatch_compact handle: (idx, val, status, nnz) numpy —
    plus (used_milli, used_pods, used_sets) when dispatched with_used.
    The used tuple is None when those accumulators were buffer-donated
    into a later dispatch (the carry chain consumed them in place; the
    pipelined executor never reads them from the finalize).

    nnz > max_nnz escalates by re-running the fused solve with a 4x larger
    extraction cap (one recompile + re-execute per new cap — rare: the
    default cap of 16 targets/binding only overflows on pathological
    every-binding-selects-most-clusters mixes).  A donated dispatch cannot
    escalate (its inputs are gone) — dispatch_compact only donates when
    _nnz_bound proves overflow impossible."""
    import numpy as np

    (args, waves, keep_sel, first, max_nnz, dense_nnz, use_extra,
     with_used, tier, donated, shard_mesh, explain, pl_fb) = handle
    res = first
    nnz = res[3]
    while int(nnz) > max_nnz and max_nnz < dense_nnz:
        assert not donated, (
            "donated compact dispatch overflowed its extraction cap "
            "(_nnz_bound unsound?)")
        max_nnz = min(max_nnz * 4, dense_nnz)
        # the rare overflow re-solve usually recompiles (new max_nnz
        # static): annotate the ambient span (the pipeline's d2h stage)
        sp = _trace_span()
        before = _jit_cache_size() if sp is not None else None
        res = schedule_compact(*args, pl_fail_bits=pl_fb, waves=waves,
                               max_nnz=max_nnz,
                               keep_sel=keep_sel, use_extra=use_extra,
                               with_used=with_used, tier=tier,
                               shard_mesh=shard_mesh, explain=explain)
        if sp is not None:
            sp.set_attr(escalated_nnz=max_nnz)
            after = _jit_cache_size()
            if before is not None and after is not None:
                sp.set_attr(
                    compile_cache="miss" if after > before else "hit")
        nnz = res[3]
    idx, val, st = res[0], res[1], res[2]
    # zero-copy handoff where the platform allows it (CPU buffers export
    # via dlpack): the COO triple — and the explain planes below — reach
    # decode as read-only views instead of copies
    out = (_host_view(idx), _host_view(val), _host_view(st), int(nnz))
    if _guards.armed():
        # the device->host boundary check: COO indices/values/status sanity
        _guards.check_d2h(out[0], out[1], out[2], dense_nnz,
                          "finalize-compact")
    if with_used:
        used = res[4:7]
        if any(getattr(u, "is_deleted", None) is not None and u.is_deleted()
               for u in used):
            # donated downstream: the chain already consumed them in place
            out = out + (None,)
        else:
            out = out + (tuple(np.asarray(u) for u in used),)
    if explain:
        off = 7 if with_used else 4
        out = out + (tuple(_host_view(a) for a in res[off:off + 4]),)
    return out


def solve_rows(items, idx_list, cindex, estimator, cache, *,
               route, tier: str = "std", waves: int = 1,
               enable_empty_workload_propagation: bool = False,
               collect_used: bool = False, used0=None, from_batch=None):
    """Solve an arbitrary subset of a chunk's bindings as their own
    sub-batch — the sub-batch pattern of ops/spread.solve_spread,
    parameterized on the route the rows carry (`route`) and the lane
    tier the compact solve runs on (`tier`).  Returns
    {original_index: List[TargetCluster] | Exception}.

    Carry (the pipelined executor's chunk accounting): `used0` carries a
    previous batch's consumption in — either an accumulator tuple in
    `from_batch`'s vocabulary (remapped here via tensors.remap_used) or
    a tensors.CarryState, whose keyed store renders into the sub-batch's
    vocabulary directly (the only lossless transport OUT of a
    shortlisted sub-vocabulary — remap_used cannot cross lane sets);
    with collect_used the return becomes (out, (sub_batch, used_out,
    used0_sub)) — the triple a caller feeds CarryState.absorb to fold
    the sub-batch's OWN consumption back into its keyed store."""
    from karmada_tpu.ops import tensors as T

    if not idx_list:
        return ({}, None) if collect_used else {}
    sub = [items[i] for i in idx_list]
    batch2 = T.encode_batch(sub, cindex, estimator, cache=cache)
    # in a parent batch these rows may be host-invalid; in THIS sub-batch
    # they are the payload (binding-axis arrays are fresh per encode:
    # writable)
    batch2.b_valid[:len(sub)] = batch2.route == route
    used0_sub = None
    if isinstance(used0, T.CarryState):
        used0_sub = used0.used0_for(batch2)
    elif used0 is not None and from_batch is not None:
        used0_sub = T.remap_used(used0, from_batch, batch2)
    res = solve_compact(
        batch2, waves=waves, tier=tier,
        keep_sel=enable_empty_workload_propagation,
        with_used=collect_used, used0=used0_sub)
    idx, val, st = res[0], res[1], res[2]
    decoded = T.decode_compact(
        batch2, idx, val, st,
        enable_empty_workload_propagation=enable_empty_workload_propagation,
        items=sub)
    out = {idx_list[j]: decoded[j] for j in range(len(sub))
           if batch2.route[j] == route}
    if collect_used:
        if used0_sub is None:
            used0_sub = tuple(
                _onp.zeros_like(a) for a in
                (batch2.avail_milli, batch2.pods_allowed,
                 batch2.est_override))
        return out, (batch2, res[4], used0_sub)
    return out


def solve_big(items, idx_list, cindex, estimator, cache, waves: int = 1,
              enable_empty_workload_propagation: bool = False,
              collect_used: bool = False, used0=None, from_batch=None):
    """Solve one chunk's ROUTE_DEVICE_BIG bindings (beyond the tier-1
    compact caps) as their own sub-batch on the big lane tier — the
    solve_rows pattern pinned to the big route/tier."""
    from karmada_tpu.ops import tensors as T

    return solve_rows(
        items, idx_list, cindex, estimator, cache,
        route=T.ROUTE_DEVICE_BIG, tier="big", waves=waves,
        enable_empty_workload_propagation=enable_empty_workload_propagation,
        collect_used=collect_used, used0=used0, from_batch=from_batch)


def solve_compact(batch, waves: int = 1, max_nnz: int = 0,
                  keep_sel: bool = False, with_used: bool = False,
                  used0=None, tier: str = "std", explain: bool = False):
    """Device-side solve + sparse result extraction: D2H ships only the
    (binding, cluster, replicas) nonzeros instead of the dense [B, C] int64
    plane (x100+ less traffic on realistic mixes).  Escalates max_nnz x4 on
    overflow, capped at B*C (== dense).  explain=True (armed explain
    plane) appends the (verdict, score, avail, outcome) tuple — see
    dispatch_compact."""
    return finalize_compact(dispatch_compact(batch, waves=waves,
                                             max_nnz=max_nnz,
                                             keep_sel=keep_sel,
                                             with_used=with_used,
                                             used0=used0, tier=tier,
                                             explain=explain))
