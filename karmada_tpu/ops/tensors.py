"""Snapshot encoder: clusters + pending bindings -> dense solver tensors.

The reference scheduler evaluates (binding, cluster) pairs one binding at a
time (pkg/scheduler/core/generic_scheduler.go:71).  The TPU path instead
encodes one scheduling cycle as dense arrays and solves every binding in one
jitted program (ops/solver.schedule_batch).  Encoding exploits the natural
dedup axes of the domain:

  * placements dedupe to P rows (bindings created by the same policy share
    affinity / toleration / spread / strategy configuration) -- all
    cluster-level predicates are evaluated host-side once per placement,
    O(P x C), not per binding;
  * replica requirements dedupe to Q request classes -- the capacity
    estimate est[Q, C] is computed once on device and gathered per binding;
  * clusters encode to capacity rows avail[C, R] (milli-units, int64) plus
    a host-computed override for clusters using resource-model histograms
    (pkg/estimator/client/general.go:336 math stays bit-equal via
    estimator/general.py).

Bindings the kernel cannot represent (provider/zone-only spread selection,
groupless topologies, vanished previous clusters, counts beyond every
compact tier's exactness caps) are routed back to the serial host path;
`route` marks them.  Region and spread-by-label topologies run the device
spread pipeline (ops/spread.py) with no group-count ceiling; bindings
beyond the tier-1 compact caps run the big lane tier (ROUTE_*_BIG).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from karmada_tpu.estimator.general import GeneralEstimator
from karmada_tpu.models.cluster import Cluster
from karmada_tpu.models.policy import (
    REPLICA_SCHEDULING_DUPLICATED,
    SPREAD_BY_FIELD_CLUSTER,
    SPREAD_BY_FIELD_PROVIDER,
    SPREAD_BY_FIELD_REGION,
    SPREAD_BY_FIELD_ZONE,
    Placement,
)
from karmada_tpu.models.work import (
    ResourceBindingSpec,
    ResourceBindingStatus,
    TargetCluster,
)
from karmada_tpu.obs.decisions import (  # explain bit layout (pure ints)
    VERDICT_AFFINITY,
    VERDICT_PLUGIN,
    VERDICT_SPREAD_PROP,
)
from karmada_tpu.ops import serial
from karmada_tpu.ops.webster import (
    fnv32a_batch_odd,
    tiebreak_descending_by_uid,
)
from karmada_tpu.utils.metrics import REGISTRY
from karmada_tpu.utils.quantity import RESOURCE_CPU, RESOURCE_PODS

MAX_INT32 = (1 << 31) - 1

# strategy ids (solver-side dispatch)
STRAT_DUPLICATED = 0
STRAT_STATIC = 1
STRAT_DYNAMIC = 2
STRAT_AGGREGATED = 3
STRAT_NON_WORKLOAD = 4

# route reasons
ROUTE_DEVICE = 0
ROUTE_TOPOLOGY_SPREAD = 1  # provider/zone-only spread, or no groups -> serial
ROUTE_UNSUPPORTED = 3  # (2 was ROUTE_MULTI_COMPONENT, retired in r4)
ROUTE_VANISHED_PREV = 4  # prev assignment names a cluster outside the snapshot
ROUTE_HUGE_REPLICAS = 5  # replica count beyond the kernel's 2^25 cap
ROUTE_DEVICE_SPREAD = 6  # region/label spread: device group math + host DFS
ROUTE_COMPACT_CAP = 7  # beyond EVERY compact tier's exactness caps -> host
ROUTE_DEVICE_BIG = 8  # beyond tier-1 caps: the big-tier device sub-solve
ROUTE_DEVICE_SPREAD_BIG = 9  # spread whose assignment needs the big tier

# the device kernel clamps seat targets at 2^25-1 (ops/solver._N_CAP) and
# Webster weights at 2^34-1 (ops/solver._W_CAP); bindings above either cap
# must take the arbitrary-precision host path
KERNEL_REPLICA_CAP = (1 << 25) - 1
KERNEL_WEIGHT_CAP = (1 << 34) - 1

# compact-lane geometry (ops/solver._schedule_one): above COMPACT_LANES
# clusters the kernel runs its division/selection loops on a top-K gather
# whose exactness holds only under these per-binding bounds; bindings
# exceeding them route to the serial host path (ROUTE_COMPACT_CAP)
COMPACT_LANES = 528  # prev(16) + 4 x top-K(128): w-rank, w-name, avail, sel-key
COMPACT_DIVISION_CAP = 64    # replicas (and thus any Webster target)
COMPACT_SELECTION_CAP = 64   # cluster spread-constraint MaxGroups
COMPACT_PREV_CAP = 16        # previous-assignment cluster count

# tier-2 ("big") geometry: bindings beyond the tier-1 caps run in a
# SEPARATE big-lane sub-solve (ROUTE_DEVICE_BIG, solver tier="big") with
# 8x the caps instead of falling to the serial host; only counts beyond
# the big caps route to host (ROUTE_COMPACT_CAP)
COMPACT_DIVISION_CAP_BIG = 512
COMPACT_SELECTION_CAP_BIG = 512
COMPACT_PREV_CAP_BIG = 128
COMPACT_LANES_BIG = 4224  # prev(128) + 4 x top-K(1024)

# result status codes (must match ops/solver.py)
STATUS_OK = 0
STATUS_FIT_ERROR = 1
STATUS_UNSCHEDULABLE = 2
STATUS_NO_CLUSTER = 3

# ---------------------------------------------------------------------------
# Canonical dtype / axis contract for SolverBatch tensors.
#
# THE single authority on what dtype every field carries: the static
# dtype-contract vet pass (karmada_tpu/analysis/dtype_contract.py) checks
# every construction site in ops/ against this table at vet time, and the
# armed runtime mode (analysis/guards.check_batch, serve --check-invariants)
# validates live batches at solver entry against the same table.  The PR-3
# s64/s32 wave-scan bug was exactly a drift this table now catches: an
# int32 array where the kernel contract says int64 is invisible on one
# device and an XLA SPMD verifier failure on a mesh.  Values are plain
# strings so the vet pass can read them from the AST without importing.
FIELD_DTYPES = {
    "cluster_valid": "bool", "deleting": "bool",
    "name_rank": "int64", "pods_allowed": "int64", "has_summary": "bool",
    "avail_milli": "int64", "has_alloc": "bool", "api_ok": "bool",
    "req_milli": "int64", "req_is_cpu": "bool", "req_pods": "int64",
    "est_override": "int64",
    "pl_mask": "bool", "pl_tol_bypass": "bool", "pl_strategy": "int32",
    "pl_static_w": "int64", "pl_has_cluster_sc": "bool",
    "pl_sc_min": "int32", "pl_sc_max": "int32", "pl_ignore_avail": "bool",
    "pl_extra_score": "int64",
    "b_valid": "bool", "placement_id": "int32", "gvk_id": "int32",
    "class_id": "int32", "replicas": "int64", "uid_desc": "bool",
    "fresh": "bool", "non_workload": "bool", "nw_shortcut": "bool",
    "prev_idx": "int32", "prev_val": "int32", "evict_idx": "int32",
    "route": "int32", "region_id": "int32",
    "pl_has_region_sc": "bool", "pl_region_min": "int32",
    "pl_region_max": "int32",
    "pl_fail_bits": "int32",
    # shortlist plane (ops/shortlist): the tier-1 kernel's candidate
    # outputs and the sub-vocabulary lane map the tier-2 remap carries
    "shortlist_idx": "int32", "shortlist_fcount": "int32",
    "sub_lanes": "int64",
}

# axis names per field (B/C extents are checked against the batch by the
# armed runtime mode; the other letters document dimensionality only)
FIELD_AXES = {
    "cluster_valid": ("C",), "deleting": ("C",), "name_rank": ("C",),
    "pods_allowed": ("C",), "has_summary": ("C",),
    "avail_milli": ("C", "R"), "has_alloc": ("C", "R"),
    "api_ok": ("G", "C"),
    "req_milli": ("Q", "R"), "req_is_cpu": ("R",), "req_pods": ("Q",),
    "est_override": ("Q", "C"),
    "pl_mask": ("P", "C"), "pl_tol_bypass": ("P", "C"),
    "pl_strategy": ("P",), "pl_static_w": ("P", "C"),
    "pl_has_cluster_sc": ("P",), "pl_sc_min": ("P",), "pl_sc_max": ("P",),
    "pl_ignore_avail": ("P",), "pl_extra_score": ("P", "C"),
    "b_valid": ("B",), "placement_id": ("B",), "gvk_id": ("B",),
    "class_id": ("B",), "replicas": ("B",), "uid_desc": ("B",),
    "fresh": ("B",), "non_workload": ("B",), "nw_shortcut": ("B",),
    "prev_idx": ("B", "Kp"), "prev_val": ("B", "Kp"),
    "evict_idx": ("B", "Ke"),
    "route": ("nB",), "region_id": ("C",),
    "pl_has_region_sc": ("P",), "pl_region_min": ("P",),
    "pl_region_max": ("P",),
    "pl_fail_bits": ("P", "C"),
    # shortlist plane: candidate lanes per binding [B, k], eligible-lane
    # counts [B], and the sub-vocabulary's full-vocab lane per sub lane
    "shortlist_idx": ("B", "k"), "shortlist_fcount": ("B",),
    "sub_lanes": ("sC",),
}

# the consumed-capacity carry triple (solver with_used / CarryState):
# used_milli [C, R], used_pods [C], used_sets [Q, C]
CARRY_DTYPES = {
    "used_milli": "int64", "used_pods": "int64", "used_sets": "int64",
}

# the native decode ABI (native/decode_fast.c): dtypes of every buffer
# crossing the d2h -> CPython-extension boundary.  The COO triple and the
# explain outcome plane arrive from solver.finalize_compact as int32 jit
# outputs (ideally zero-copy dlpack views); name_rank keeps the solver's
# int64 contract.  Construction sites naming these fields are checked by
# the dtype-contract vet pass exactly like SolverBatch fields — an s64
# array handed to the int32-reading C loop would decode garbage, not
# crash.
NATIVE_ABI_DTYPES = {
    "coo_idx": "int32", "coo_val": "int32", "coo_status": "int32",
    "outcome_plane": "int32", "verdict_plane": "int32",
    "decode_name_rank": "int64",
}

DECODE_NATIVE = REGISTRY.counter(
    "karmada_solver_decode_native_total",
    "Per-binding result rows decoded by the native COO decoder",
)


def tc_new_is_plain() -> bool:
    """True while TargetCluster construction via cls.__new__(cls) +
    setattr (what native/decode_fast.c does) is equivalent to calling the
    dataclass __init__: plain object.__new__, no __slots__, no
    __post_init__.  A subclass or monkeypatch that breaks the equivalence
    silently re-routes decode to the Python builder instead of producing
    divergent objects."""
    return (TargetCluster.__new__ is object.__new__
            and not hasattr(TargetCluster, "__post_init__")
            and not hasattr(TargetCluster, "__slots__"))


def _next_pow2(n: int, lo: int = 1) -> int:
    v = lo
    while v < n:
        v *= 2
    return v


@dataclass
class ClusterIndex:
    """Host-side cluster catalogue for one scheduling cycle."""

    clusters: List[Cluster]
    names: List[str]
    index: Dict[str, int]
    name_rank: np.ndarray  # int64[C]: position in ascending name sort

    @staticmethod
    def build(clusters: Sequence[Cluster]) -> "ClusterIndex":
        clusters = list(clusters)
        names = [c.name for c in clusters]
        order = sorted(range(len(names)), key=lambda i: names[i])
        rank = np.zeros(len(names), np.int64)
        for pos, i in enumerate(order):
            rank[i] = pos
        return ClusterIndex(clusters, names, {n: i for i, n in enumerate(names)}, rank)


@dataclass
class SolverBatch:
    """Dense pytree for ops/solver.schedule_batch (numpy; moved by jit)."""

    # shapes
    B: int  # padded bindings
    C: int  # padded clusters
    n_bindings: int
    n_clusters: int

    # cluster axis
    cluster_valid: np.ndarray  # bool[C]
    deleting: np.ndarray  # bool[C]
    name_rank: np.ndarray  # int64[C]
    pods_allowed: np.ndarray  # int64[C] (0 when no summary)
    has_summary: np.ndarray  # bool[C]
    avail_milli: np.ndarray  # int64[C, R] available milli per resource
    has_alloc: np.ndarray  # bool[C, R] allocatable present
    api_ok: np.ndarray  # bool[G, C]

    # request classes
    req_milli: np.ndarray  # int64[Q, R] requested (cpu: milli, other: units)
    req_is_cpu: np.ndarray  # bool[R]
    req_pods: np.ndarray  # int64[Q] pods per unit (1; pods-per-set for sets)
    est_override: np.ndarray  # int64[Q, C]; >=0 overrides device estimate

    # placements
    pl_mask: np.ndarray  # bool[P, C] affinity & toleration & spread-prop
    pl_tol_bypass: np.ndarray  # bool[P, C] passes api/taint WITHOUT prev bypass
    pl_strategy: np.ndarray  # int32[P]
    pl_static_w: np.ndarray  # int64[P, C]
    pl_has_cluster_sc: np.ndarray  # bool[P]
    pl_sc_min: np.ndarray  # int32[P]
    pl_sc_max: np.ndarray  # int32[P]
    pl_ignore_avail: np.ndarray  # bool[P] (duplicated: capacity ignored)

    # binding axis
    b_valid: np.ndarray  # bool[B]
    placement_id: np.ndarray  # int32[B]
    gvk_id: np.ndarray  # int32[B]
    class_id: np.ndarray  # int32[B] (-1: no requirements)
    replicas: np.ndarray  # int64[B]
    uid_desc: np.ndarray  # bool[B]
    fresh: np.ndarray  # bool[B]
    non_workload: np.ndarray  # bool[B]
    nw_shortcut: np.ndarray  # bool[B] replicas==0 and no components (cal fast path)
    # previous assignment / eviction, SPARSE: the dense [B, C] forms would
    # dominate host<->device transfer (hundreds of MB per chunk over a
    # skinny PCIe/tunnel link) for data that is ~8 entries per binding;
    # the kernel scatters them back to dense lanes on device.
    prev_idx: np.ndarray  # int32[B, Kp] cluster lane, -1 padding
    prev_val: np.ndarray  # int32[B, Kp] previous replicas
    evict_idx: np.ndarray  # int32[B, Ke] cluster lane, -1 padding

    # host-side routing / metadata
    route: np.ndarray = field(default=None)  # int32[n_bindings] ROUTE_*
    cluster_index: ClusterIndex = field(default=None)
    # group topology (device spread path, ops/spread.py)
    region_id: np.ndarray = field(default=None)  # int32[C]; -1 = no region
    region_names: List[str] = field(default=None)  # vocabulary
    # spread-by-label group axes: label key -> (group_id int32[C], values)
    label_axes: Dict[str, Tuple[np.ndarray, List[str]]] = field(default=None)
    pl_has_region_sc: np.ndarray = field(default=None)  # bool[P]
    # out-of-tree score-plugin contributions (scheduler/plugins.py),
    # pre-clamped sums per (placement, cluster)
    pl_extra_score: np.ndarray = field(default=None)  # int64[P, C]
    # axis vocabularies, for remapping carry-over capacity accumulators
    # between batches of one cycle (scheduler second-pass repack)
    res_names: List[str] = field(default=None)  # R-axis order
    class_keys: List = field(default=None)  # Q-axis order (canonical keys)
    pl_region_min: np.ndarray = field(default=None)  # int32[P]
    pl_region_max: np.ndarray = field(default=None)  # int32[P]
    # explain plane (obs/decisions bit layout): per-(placement, cluster)
    # static filter-failure bits — affinity | spread-property | plugin —
    # populated only by encode_batch(explain=True); all-zero otherwise
    # (the `explain` flag below distinguishes "no failures" from
    # "not computed" for dispatch-time validation)
    pl_fail_bits: np.ndarray = field(default=None)  # int32[P, C]
    explain: bool = False
    # vocabulary identities for the resident-state plane
    # (karmada_tpu/resident): the Placement objects per P row, the
    # (api_version, kind) keys per G row, and the request objects per Q
    # row — lets a consumer remap this batch's ids into a persistent
    # vocabulary by KEY instead of re-deriving them from the items
    placements: List = field(default=None)  # P-axis order
    gvk_keys: List[Tuple[str, str]] = field(default=None)  # G-axis order
    class_reqs: List = field(default=None)  # Q-axis order (rr | _SetClass)
    # fused resident-gather batches (ops/resident_gather via
    # resident/state.py): binding-axis fields are LIVE DEVICE arrays
    # gathered from the device slot store — never re-uploaded at
    # dispatch.  nnz_bound_hint carries the host-computed donation-
    # safety bound (solver._nnz_bound) so the solver derives it without
    # forcing a device->host read of its own operands.
    fused: bool = False
    nnz_bound_hint: Optional[int] = None
    # shortlist plane (ops/shortlist): a tier-2 sub-vocabulary batch —
    # the chunk's cluster planes gathered to the candidate union.
    # sub_lanes maps each sub lane to its FULL-vocabulary lane (-1 on
    # pow2 padding), sub_full_c is the full batch's padded C, and
    # sub_sig is the lane set's identity (the carry chain keys its
    # segments on it: two sub-batches with equal shapes but different
    # lane sets must never chain device arrays).  All three are
    # host-side bookkeeping — the dispatch ships the gathered planes,
    # never the map itself (meshing.HOST_ONLY_FIELDS).
    sub_lanes: np.ndarray = field(default=None)
    sub_full_c: Optional[int] = None
    sub_sig: Optional[int] = None
    # host copy of non_workload[:n] on fused batches (HOST_ONLY_FIELDS):
    # decode reads it per binding, and converting the device-resident
    # plane mid-pipeline can block behind the next chunk's solve on the
    # runtime's transfer path (measured ~170ms stalls on XLA:CPU)
    non_workload_host: np.ndarray = field(default=None)  # bool[n]
    # fused-source handle (resident/state._assemble_fused): the frozen
    # host slot-store masters, this chunk's slot vector, and the live
    # device slot mirrors — the shortlist's fused arming reads binding
    # fields host-side from the masters and gathers the device rows
    # straight into its sub-vocabulary (ops/resident_gather sub-gather).
    # Host bookkeeping only, never shipped.
    fused_src: Optional[Dict] = field(default=None)


def _effective_placement(
    spec: ResourceBindingSpec, status: ResourceBindingStatus
) -> Placement:
    """Resolve ClusterAffinities terms to the observed one (the scheduler
    service drives the failover loop; the kernel sees one affinity).
    Single implementation shared with the serial path so out-of-tree
    plugins see the identical placement object on every backend."""
    return serial.effective_placement(spec, status)


def _placement_key(p: Placement) -> str:
    return repr(p)


def _route_for(
    spec: ResourceBindingSpec, placement: Placement, n_regions: int = 0,
    compact: bool = False, label_axis_fn=None,
) -> int:
    scs = placement.spread_constraints
    big = False
    if scs and not serial.should_ignore_spread_constraint(placement):
        has_region = has_cluster = has_other_field = False
        cluster_max = region_max = label_max = 0
        label_key = None
        for sc in scs:
            if sc.spread_by_field in (
                SPREAD_BY_FIELD_PROVIDER,
                SPREAD_BY_FIELD_ZONE,
            ):
                # provider/zone constraints only FILTER (clusters missing
                # the property drop out — already encoded in pl_mask via
                # serial.filter_spread_constraint); selection itself is by
                # region, then cluster (select_clusters.go:44-55), so these
                # placements stay on device alongside region/cluster
                has_other_field = True
            if sc.spread_by_field == SPREAD_BY_FIELD_REGION:
                has_region = True
                region_max = max(region_max, sc.max_groups)
            if sc.spread_by_field == SPREAD_BY_FIELD_CLUSTER:
                has_cluster = True
                cluster_max = max(cluster_max, sc.max_groups)
            if sc.spread_by_label and label_key is None:
                # first label key is the group axis (ops/spread.py);
                # further label constraints filter only
                label_key = sc.spread_by_label
                label_max = sc.max_groups
        if has_region or label_key is not None:
            # grouped-topology selection (region axis wins over label)
            if has_region:
                n_groups, group_max = n_regions, region_max
            else:
                n_groups = label_axis_fn(label_key) if label_axis_fn else 0
                group_max = label_max
            # the pick selects first-of-each-chosen-group plus extras up to
            # the cluster constraint: its lane bound decides the tier
            sel_bound = max(cluster_max, min(group_max, n_groups))
            if compact and sel_bound > COMPACT_SELECTION_CAP_BIG:
                return ROUTE_COMPACT_CAP
            spread_big = compact and sel_bound > COMPACT_SELECTION_CAP
            if n_groups > 0 and len(spec.components) <= 1:
                return (ROUTE_DEVICE_SPREAD_BIG if spread_big
                        else ROUTE_DEVICE_SPREAD)
            return ROUTE_TOPOLOGY_SPREAD
        if compact and cluster_max > COMPACT_SELECTION_CAP:
            if cluster_max > COMPACT_SELECTION_CAP_BIG:
                return ROUTE_COMPACT_CAP
            big = True  # tier-2 selection: the big-lane sub-solve
        if has_other_field and not has_cluster:
            # provider/zone with NEITHER region nor cluster: the reference
            # fails these ('just support cluster and region spread
            # constraint', select_clusters.go:55) — serial host raises the
            # identical UnschedulableError, O(1)
            return ROUTE_TOPOLOGY_SPREAD
    rs = placement.replica_scheduling
    if rs is not None and rs.weight_preference is not None and any(
        w.weight > KERNEL_WEIGHT_CAP
        for w in rs.weight_preference.static_weight_list
    ):
        return ROUTE_HUGE_REPLICAS
    # multi-template scheduling (estimation.go:42-64): applicable shapes
    # encode component-set capacity as a request class (per-set aggregate +
    # pods-per-set divisor); non-applicable multi-component shapes estimate
    # per-replica with nil requirements (the allowed-pods row) and replicas
    # 0, which is exactly the kernel's non_workload selection path — both
    # run on device (VERDICT r3 item 4; ROUTE_MULTI_COMPONENT retired)
    return ROUTE_DEVICE_BIG if big else ROUTE_DEVICE


# spec-free probe for the placement-only route: _route_for reads only
# spec.components (empty here), so one call per distinct placement suffices
_ROUTE_PROBE_SPEC = ResourceBindingSpec()


def spread_groups(batch: "SolverBatch", items) -> Dict[Tuple[str, str], List[int]]:
    """Group a chunk's ROUTE_DEVICE_SPREAD(_BIG) bindings by (axis, tier)
    — the unit of one ops/spread.solve_spread call (the group-id plane
    differs per axis, the assignment lane budget per tier).  The single
    authority all callers (scheduler service, bench, tests) share."""
    groups: Dict[Tuple[str, str], List[int]] = {}
    for i in range(batch.n_bindings):
        r = batch.route[i]
        if r in (ROUTE_DEVICE_SPREAD, ROUTE_DEVICE_SPREAD_BIG):
            spec, status = items[i]
            axis = spread_axis_of(serial.effective_placement(spec, status)) or ""
            tier = "big" if r == ROUTE_DEVICE_SPREAD_BIG else "std"
            groups.setdefault((axis, tier), []).append(i)
    return groups


def spread_axis_of(placement: Placement) -> Optional[str]:
    """The group axis a ROUTE_DEVICE_SPREAD(_BIG) placement selects over:
    "" = region (batch.region_id), a label key = batch.label_axes[key],
    None = no grouped-topology selection.  Callers use it to group spread
    bindings per solve_spread call (the group-id plane differs per axis)."""
    scs = placement.spread_constraints
    if not scs or serial.should_ignore_spread_constraint(placement):
        return None
    label_key = None
    for sc in scs:
        if sc.spread_by_field == SPREAD_BY_FIELD_REGION:
            return ""
        if sc.spread_by_label and label_key is None:
            label_key = sc.spread_by_label
    return label_key


@dataclass
class _SetClass:
    """Request class for a multi-template workload: capacity is counted in
    whole component SETS (per-set aggregate requirement + pods-per-set)."""

    per_set: Dict[str, int]  # request units (cpu milli, others Value)
    pods_per_set: int


class EncoderCache:
    """Memoizes the cluster-and-placement side of the encoding across chunks.

    One scheduling cycle encodes many binding chunks against the SAME
    cluster snapshot; placement predicate rows (O(C) Python each) and the
    per-class estimator overrides are computed once per distinct
    placement/class, not once per chunk.
    """

    def __init__(self) -> None:
        self.placement_rows: Dict[str, Tuple[np.ndarray, np.ndarray, np.ndarray]] = {}
        # explain plane: per-placement static filter-failure bit rows
        # (obs/decisions layout), built only under encode_batch(explain=True),
        # plus the assembled [P, C] plane for one vocabulary.  Kept OUT of
        # `assembled` on purpose: explain sampling alternates armed and
        # disarmed cycles over one cache, and folding the plane into the
        # assembled slot would thrash it (and the solver's device-transfer
        # cache) on every toggle.
        self.fail_rows: Dict[str, np.ndarray] = {}
        self.fail_plane: Optional[Tuple[tuple, np.ndarray]] = None
        self.gvk_rows: Dict[Tuple[str, str], np.ndarray] = {}
        self.override_rows: Dict[Tuple, np.ndarray] = {}
        # id(placement) -> (placement, repr key): placements are shared
        # objects across a cycle's bindings, and repr() of the dataclass
        # tree dominates warm encode time without this.  The object itself
        # is pinned in the entry so a GC'd id can never alias a stale key.
        self.placement_keys: Dict[int, Tuple[object, str]] = {}
        # cluster lane -> allowed pod count (snapshot-stable per cycle)
        self.pods_allowed: Optional[np.ndarray] = None
        # cluster-axis bundle (cluster_valid, region_names, region_id,
        # deleting, has_summary, name_rank): snapshot-stable per cycle,
        # rebuilt once per cycle instead of once per chunk (the deleting/
        # region Python loops are O(C) each — ~15k iterations per 5000-
        # cluster chunk without this)
        self.cluster_axis: Optional[tuple] = None
        # spread-by-label group axes, keyed by label key (cluster labels
        # are part of the owner's cache signature — scheduler/service.py
        # builds a fresh cache when any cluster label changes)
        self.label_rows: Dict[str, Tuple[np.ndarray, List[str]]] = {}

        # assembled cluster/placement tensor set, reused VERBATIM (same
        # numpy objects) across chunks whose vocabulary matches — the
        # solver's device-put cache then skips re-transferring the ~5MB of
        # cluster-side tensors per chunk (they dominate per-chunk H2D)
        self.assembled_sig: Optional[tuple] = None
        self.assembled: Optional[Dict[str, np.ndarray]] = None
        # plugin-registry generation the memoized placement rows were
        # built against (encode_batch invalidates on change)
        self.plugins_gen: Optional[int] = None

    def reset_for_cycle(self) -> None:
        """Drop the STATUS-derived fields before a new cycle's snapshot:
        pod allowances and modeled-capacity override rows track live usage,
        and placement-key pins hold the previous cycle's objects.  The
        spec-derived rows (placement masks) and api-enablement rows survive
        — their owners invalidate them on their own signatures."""
        self.pods_allowed = None
        self.cluster_axis = None
        self.override_rows = {}
        self.placement_keys = {}
        self.assembled_sig = None
        self.assembled = None


def encode_batch(
    items: Sequence[Tuple[ResourceBindingSpec, ResourceBindingStatus]],
    cindex: ClusterIndex,
    estimator: Optional[GeneralEstimator] = None,
    pad_bindings: bool = True,
    cache: Optional[EncoderCache] = None,
    explain: bool = False,
) -> SolverBatch:
    """Encode one scheduling cycle.  `items` are (spec, status) pairs.

    Pass the same `cache` across chunks of one cycle to amortize the
    placement/cluster/override host work (cluster snapshot must not change
    between cached calls).

    `explain` additionally decomposes each placement's predicate row into
    per-stage failure bits (pl_fail_bits, obs/decisions layout) — the
    host-side half of the explain plane; the device solve emits the
    per-binding verdicts from them (ops/solver dispatch_compact(explain)).
    Disarmed encodes leave the plane all-zero and skip the extra filter
    evaluations entirely.
    """
    estimator = estimator or GeneralEstimator()
    from karmada_tpu.scheduler.plugins import REGISTRY as _PLUGINS

    if cache is not None and cache.plugins_gen != _PLUGINS.generation:
        # out-of-tree plugin set changed: every memoized placement row
        # (mask/score, and the explain fail-bit rows that fold plugin
        # rejections) is stale
        cache.placement_rows = {}
        cache.fail_rows = {}
        cache.fail_plane = None
        cache.assembled_sig = None
        cache.assembled = None
        cache.plugins_gen = _PLUGINS.generation
    clusters = cindex.clusters
    nC = len(clusters)
    C = _next_pow2(max(nC, 1), 8)
    nB = len(items)
    B = _next_pow2(max(nB, 1), 8) if pad_bindings else max(nB, 1)

    # ---- cluster axis (chunk-stable: built once per cycle) ----------------
    if cache is not None and cache.cluster_axis is not None:
        (cluster_valid, region_names, region_id, deleting, has_summary,
         name_rank) = cache.cluster_axis
    else:
        cluster_valid = np.zeros(C, bool)
        cluster_valid[:nC] = True
        # region vocabulary (device spread path routes on its size)
        region_names = []
        region_ids: Dict[str, int] = {}
        region_id = np.full(C, -1, np.int32)
        for i, c in enumerate(clusters):
            r = c.spec.region
            if not r:
                continue
            if r not in region_ids:
                region_ids[r] = len(region_names)
                region_names.append(r)
            region_id[i] = region_ids[r]
        deleting = np.zeros(C, bool)
        has_summary = np.zeros(C, bool)
        name_rank = np.full(C, 0, np.int64)
        name_rank[:nC] = cindex.name_rank
        # padding lanes need distinct ranks above real ones
        name_rank[nC:] = np.arange(nC, C)
        for i, c in enumerate(clusters):
            deleting[i] = c.metadata.deleting
            if c.status.resource_summary is not None:
                has_summary[i] = True
        if cache is not None:
            cache.cluster_axis = (cluster_valid, region_names, region_id,
                                  deleting, has_summary, name_rank)
    if cache is not None and cache.pods_allowed is not None:
        pods_allowed = cache.pods_allowed
    else:
        pods_allowed = np.zeros(C, np.int64)
        for i, c in enumerate(clusters):
            s = c.status.resource_summary
            if s is not None:
                pods_allowed[i] = _allowed_pods(s)
        if cache is not None:
            cache.pods_allowed = pods_allowed

    # resource vocabulary: everything any request mentions
    placements: List[Placement] = []
    pkeys: Dict[str, int] = {}
    gvks: Dict[Tuple[str, str], int] = {}
    classes: Dict[Tuple, int] = {}
    class_reqs: List = []
    res_names: Dict[str, int] = {}

    route = np.zeros(nB, np.int32)
    placement_id = np.zeros(B, np.int32)
    gvk_id = np.zeros(B, np.int32)
    class_id = np.full(B, -1, np.int32)
    replicas = np.zeros(B, np.int64)
    uid_desc = np.zeros(B, bool)
    fresh = np.zeros(B, bool)
    non_workload = np.zeros(B, bool)
    nw_shortcut = np.zeros(B, bool)
    b_valid = np.zeros(B, bool)
    b_valid[:nB] = True
    # sparse (most bindings carry no prev assignment / eviction tasks):
    # dict-of-rows keeps the per-chunk cost proportional to the rows that
    # HAVE entries instead of allocating B empty lists per chunk
    prev_entries: Dict[int, List[Tuple[int, int]]] = {}
    evict_entries: Dict[int, List[int]] = {}

    n_regions = len(region_names)
    # spread-by-label group axes, built lazily per label key (O(C) each,
    # memoized across chunks via the cache — cluster labels are stable
    # within a cycle's snapshot)
    label_axes: Dict[str, Tuple[np.ndarray, List[str]]] = {}

    def label_axis(key: str) -> int:
        entry = label_axes.get(key)
        if entry is None:
            entry = None if cache is None else cache.label_rows.get(key)
            if entry is None:
                gid = np.full(C, -1, np.int32)
                values: List[str] = []
                vids: Dict[str, int] = {}
                for ci_, c_ in enumerate(clusters):
                    v = c_.metadata.labels.get(key)
                    if not v:
                        continue
                    vid = vids.get(v)
                    if vid is None:
                        vid = vids[v] = len(values)
                        values.append(v)
                    gid[ci_] = vid
                entry = (gid, values)
                if cache is not None:
                    cache.label_rows[key] = entry
            label_axes[key] = entry
        return len(entry[1])

    # per-call pid -> placement-only route (spec-free: _route_for reads only
    # spec.components, empty on the common path)
    route_by_pid: Dict[int, int] = {}
    # id(placement) -> (placement, pid, route): the C fast path's identity
    # registry (entries pinned by holding the placement in the tuple);
    # populated only when the extension is driving (use_fast flag)
    pid_route_by_id: Dict[int, tuple] = {}
    use_fast = [False]
    uids: List[str] = []
    on_device = (ROUTE_DEVICE, ROUTE_DEVICE_SPREAD, ROUTE_DEVICE_BIG,
                 ROUTE_DEVICE_SPREAD_BIG)
    cindex_get = cindex.index.get
    compact = C > COMPACT_LANES
    rep_cap = COMPACT_DIVISION_CAP if compact else KERNEL_REPLICA_CAP

    def encode_one(b: int, set_uid: bool = True) -> None:
        """The full (slow) per-binding encoding — also the C fast path's
        miss callback, registering vocabulary so later bindings hit."""
        spec, status = items[b]
        placement = _effective_placement(spec, status)
        # only SHARED placement objects (placement is spec.placement) are
        # worth memoizing — _effective_placement builds fresh objects for
        # the affinity-resolution path, which would never hit and would pin
        # one entry per binding
        if cache is not None and placement is spec.placement:
            entry = cache.placement_keys.get(id(placement))
            if entry is not None and entry[0] is placement:
                key = entry[1]
            else:
                key = _placement_key(placement)
                cache.placement_keys[id(placement)] = (placement, key)
        else:
            key = _placement_key(placement)
        pid = pkeys.get(key)
        if pid is None:
            pid = pkeys[key] = len(placements)
            placements.append(placement)
            route_by_pid[pid] = _route_for(_ROUTE_PROBE_SPEC, placement,
                                           n_regions, compact, label_axis)
        if use_fast[0] and placement is spec.placement:
            pid_route_by_id[id(placement)] = (placement, pid, route_by_pid[pid])
        placement_id[b] = pid
        r = (route_by_pid[pid] if not spec.components
             else _route_for(spec, placement, n_regions, compact, label_axis))

        g = (spec.resource.api_version, spec.resource.kind)
        gid = gvks.get(g)
        if gid is None:
            gid = gvks[g] = len(gvks)
        gvk_id[b] = gid

        rr = spec.replica_requirements
        if (len(spec.components) > 1 and r == ROUTE_DEVICE
                and serial.is_multi_template_applicable(spec)):
            # multi-template: the request class is the per-set aggregate
            from karmada_tpu.estimator.general import (
                per_set_requirement,
                pods_in_set,
            )

            per_set = per_set_requirement(spec.components)
            pods_per_set = pods_in_set(spec.components)
            ck = ("__sets__", pods_per_set, tuple(sorted(per_set.items())))
            if ck not in classes:
                classes[ck] = len(classes)
                class_reqs.append(_SetClass(per_set, pods_per_set))
                for n in per_set:
                    if n not in res_names:
                        res_names[n] = len(res_names)
            class_id[b] = classes[ck]
        elif rr is not None and rr.resource_request:
            # canonical (sorted) key: permutations of the same request must
            # dedup into ONE class row, or the class axis inflates past pow2
            # boundaries (recompiles) and assembled_sig misses its cache
            ck = tuple(sorted((n, q.milli) for n, q in rr.resource_request.items()))
            cid = classes.get(ck)
            if cid is None:
                cid = classes[ck] = len(classes)
                class_reqs.append(rr)
                for n in rr.resource_request:
                    if n not in res_names:
                        res_names[n] = len(res_names)
            class_id[b] = cid

        nrep = spec.replicas
        replicas[b] = nrep
        if set_uid:
            uid_desc[b] = tiebreak_descending_by_uid(spec.resource.uid)
        else:
            uids.append(spec.resource.uid)
        fresh[b] = serial.reschedule_required(spec, status)
        is_workload = (nrep > 0 or rr is not None) and len(spec.components) <= 1
        non_workload[b] = not is_workload
        nw_shortcut[b] = nrep == 0 and not spec.components
        # prev entries naming clusters absent from the current snapshot
        # cannot be addressed by the dense encoding, and the reference CAN
        # re-assign to a vanished cluster during scale-down
        # (division_algorithm.go:103-119 weights by spec.clusters regardless
        # of snapshot membership) -- route those bindings to the serial host.
        # Duplicate names keep the LAST entry (serial paths build
        # {name: replicas} dicts, serial.py:658 -- last wins).
        if spec.clusters:
            prev_by_lane: Dict[int, int] = {}
            for tc in spec.clusters:
                ci = cindex_get(tc.name)
                if ci is not None:
                    prev_by_lane[ci] = tc.replicas
                elif r in on_device:
                    r = ROUTE_VANISHED_PREV
            prev_entries[b] = list(prev_by_lane.items())
            if r in on_device and (
                nrep > KERNEL_REPLICA_CAP
                or any(v > KERNEL_REPLICA_CAP for v in prev_by_lane.values())
            ):
                r = ROUTE_HUGE_REPLICAS
        elif nrep > KERNEL_REPLICA_CAP and r in on_device:
            r = ROUTE_HUGE_REPLICAS
        if compact and r in on_device:
            # compact-lane exactness bounds (see COMPACT_* above); the
            # division cap does not apply to Duplicated, whose replica
            # count is a wide broadcast rather than a Webster target
            divides = (placement.replica_scheduling_type()
                       != REPLICA_SCHEDULING_DUPLICATED)
            nprev = len(prev_entries.get(b, ()))
            over1 = ((divides and nrep > COMPACT_DIVISION_CAP)
                     or nprev > COMPACT_PREV_CAP)
            over2 = ((divides and nrep > COMPACT_DIVISION_CAP_BIG)
                     or nprev > COMPACT_PREV_CAP_BIG)
            if r in (ROUTE_DEVICE_SPREAD, ROUTE_DEVICE_SPREAD_BIG):
                # the spread pipeline's assignment picks its tier like the
                # main path: tier-1 caps -> big tier, big caps -> host
                if over2:
                    r = ROUTE_COMPACT_CAP
                elif over1:
                    r = ROUTE_DEVICE_SPREAD_BIG
            elif over2:
                r = ROUTE_COMPACT_CAP
            elif over1 or r == ROUTE_DEVICE_BIG:
                r = ROUTE_DEVICE_BIG
        if spec.graceful_eviction_tasks:
            for task in spec.graceful_eviction_tasks:
                ci = cindex_get(task.from_cluster)
                if ci is not None:
                    evict_entries.setdefault(b, []).append(ci)
        route[b] = r

    fast = None
    if nB:
        from karmada_tpu import native as _native

        fast = _native.load_encode_fast()
    if fast is not None:
        # the C loop fills arrays for common-shape bindings and calls
        # encode_one inline on misses (which registers vocabulary, so one
        # miss per distinct placement/class/GVK, not per binding)
        use_fast[0] = True
        items_list = items if isinstance(items, list) else list(items)
        fast.encode_fast(
            items_list, pid_route_by_id, gvks, classes,
            placement_id, gvk_id, class_id, replicas, uid_desc, fresh,
            non_workload, nw_shortcut, route, rep_cap, encode_one,
        )
    else:
        for b in range(nB):
            encode_one(b, set_uid=False)
        if nB:
            uid_desc[:nB] = fnv32a_batch_odd(uids)

    # rows the host path owns must not schedule NOR consume wave capacity on
    # device (their device results are discarded; charging them would price
    # later waves against phantom usage)
    b_valid[:nB] = route == ROUTE_DEVICE

    Kp = _next_pow2(
        max((len(e) for e in prev_entries.values()), default=0) or 1, 4)
    Ke = _next_pow2(
        max((len(e) for e in evict_entries.values()), default=0) or 1, 4)
    prev_idx = np.full((B, Kp), -1, np.int32)
    prev_val = np.zeros((B, Kp), np.int32)
    evict_idx = np.full((B, Ke), -1, np.int32)
    for b, entries in prev_entries.items():
        for j, (ci, r) in enumerate(entries):
            prev_idx[b, j] = ci
            prev_val[b, j] = min(r, MAX_INT32)
    for b, entries in evict_entries.items():
        for j, ci in enumerate(entries):
            evict_idx[b, j] = ci

    # the cluster/placement-side tensors below are fully determined by the
    # vocabulary discovered above plus the (cache-contract-stable) cluster
    # snapshot; chunks of one cycle with the same vocabulary reuse the
    # previous chunk's assembled set VERBATIM and skip this whole section
    assembled_sig = (
        C, tuple(pkeys), tuple(classes), tuple(gvks),
        tuple(res_names), tuple(region_names),
    )
    if (
        cache is not None
        and cache.assembled is not None
        and cache.assembled_sig == assembled_sig
    ):
        shared_hit = cache.assembled
        P_hit = shared_hit["pl_strategy"].shape[0]
        fail_plane = (_fail_plane(placements, clusters, C, P_hit, cache,
                                  assembled_sig)
                      if explain else np.zeros((P_hit, C), np.int32))
        batch = _build_solver_batch(
            shared_hit, B, C, nB, nC, b_valid, placement_id, gvk_id,
            class_id, replicas, uid_desc, fresh, non_workload, nw_shortcut,
            prev_idx, prev_val, evict_idx, route, cindex, region_names,
            list(res_names), list(classes), label_axes, explain, fail_plane,
        )
        batch.placements = list(placements)
        batch.gvk_keys = list(gvks)
        batch.class_reqs = list(class_reqs)
        return batch

    # ---- capacity tensors -------------------------------------------------
    # Every axis the jit signature depends on is pow2-bucketed: B, C, and
    # the four vocabulary axes Q/P/G/R below.  Unbucketed vocabulary sizes
    # recompile schedule_batch whenever a cycle sees a new number of
    # distinct placements/request classes/GVKs/resources — a real control
    # plane would thrash the compile cache.  Padding lanes are inert: zero
    # requests never constrain (req>0 guard), -1 overrides are ignored,
    # and padded placement/GVK rows are never indexed by a real binding.
    R = _next_pow2(max(len(res_names), 1), 4)
    Q = _next_pow2(max(len(class_reqs), 1), 4)
    avail_milli = np.zeros((C, R), np.int64)
    has_alloc = np.zeros((C, R), bool)
    req_is_cpu = np.zeros(R, bool)
    for n, r in res_names.items():
        req_is_cpu[r] = n == RESOURCE_CPU
    for i, c in enumerate(clusters):
        s = c.status.resource_summary
        if s is None:
            continue
        for n, r in res_names.items():
            alloc = s.allocatable.get(n)
            if alloc is None:
                continue
            has_alloc[i, r] = True
            m = alloc.milli
            used = s.allocated.get(n)
            if used is not None:
                m -= used.milli
            ing = s.allocating.get(n)
            if ing is not None:
                m -= ing.milli
            avail_milli[i, r] = m

    req_milli = np.zeros((Q, R), np.int64)
    req_pods = np.ones(Q, np.int64)
    for q, cr in enumerate(class_reqs):
        if isinstance(cr, _SetClass):
            for n, v in cr.per_set.items():
                req_milli[q, res_names[n]] = v
            req_pods[q] = max(cr.pods_per_set, 1)
        else:
            for n, qty in cr.resource_request.items():
                r = res_names[n]
                req_milli[q, r] = qty.milli_value() if n == RESOURCE_CPU else qty.value()

    # histogram-modeled clusters: host-side exact override (general.go:336)
    est_override = np.full((Q, C), -1, np.int64)
    modeled = [
        i for i, c in enumerate(clusters)
        if (
            estimator.enable_resource_modeling
            and c.status.resource_summary is not None
            and c.status.resource_summary.allocatable_modelings
        )
    ]
    if modeled:
        for q, (ck, rr) in enumerate(zip(classes, class_reqs)):
            if isinstance(rr, _SetClass):
                # sets math has no model-histogram refinement (the reference
                # getMaximumSetsBasedOnResourceModels is a no-op placeholder)
                continue
            row = None if cache is None else cache.override_rows.get(ck)
            if row is None:
                row = np.full(C, -1, np.int64)
                for i in modeled:
                    row[i] = estimator._max_for_cluster(clusters[i], rr)
                if cache is not None:
                    cache.override_rows[ck] = row
            est_override[q] = row

    # ---- placement axis ---------------------------------------------------
    P = _next_pow2(max(len(placements), 1), 8)
    pl_mask = np.zeros((P, C), bool)
    pl_tol_bypass = np.zeros((P, C), bool)
    pl_strategy = np.zeros(P, np.int32)
    pl_static_w = np.zeros((P, C), np.int64)
    pl_has_cluster_sc = np.zeros(P, bool)
    pl_sc_min = np.zeros(P, np.int32)
    pl_sc_max = np.zeros(P, np.int32)
    pl_ignore_avail = np.zeros(P, bool)
    pl_has_region_sc = np.zeros(P, bool)
    pl_extra_score = np.zeros((P, C), np.int64)
    pl_region_min = np.zeros(P, np.int32)
    pl_region_max = np.zeros(P, np.int32)
    pl_fail_bits = np.zeros((P, C), np.int32)

    dummy_status = ResourceBindingStatus()
    # one registry snapshot per encode: single lock acquisition, and every
    # placement row of this batch sees the same plugin set
    from karmada_tpu.scheduler.plugins import eval_filters, eval_scores

    plug_filters = _PLUGINS.enabled_filters()
    plug_scores = _PLUGINS.enabled_scores()
    for p, placement in enumerate(placements):
        strategy = serial.strategy_type(_spec_with(placement))
        pl_strategy[p] = {
            serial.DUPLICATED: STRAT_DUPLICATED,
            serial.STATIC_WEIGHT: STRAT_STATIC,
            serial.DYNAMIC_WEIGHT: STRAT_DYNAMIC,
            serial.AGGREGATED: STRAT_AGGREGATED,
        }.get(strategy, STRAT_DUPLICATED)
        pl_ignore_avail[p] = serial.should_ignore_available_resource(placement)
        if not serial.should_ignore_spread_constraint(placement):
            label_sc = None
            for sc in placement.spread_constraints:
                if sc.spread_by_field == SPREAD_BY_FIELD_CLUSTER:
                    pl_has_cluster_sc[p] = True
                    pl_sc_min[p] = sc.min_groups
                    pl_sc_max[p] = sc.max_groups
                elif sc.spread_by_field == SPREAD_BY_FIELD_REGION:
                    pl_has_region_sc[p] = True
                    pl_region_min[p] = sc.min_groups
                    pl_region_max[p] = sc.max_groups
                elif sc.spread_by_label and label_sc is None:
                    label_sc = sc
            if label_sc is not None and not pl_has_region_sc[p]:
                # label group axis (region wins when both are present —
                # spread_axis_of): the group min/max rows are shared
                pl_region_min[p] = label_sc.min_groups
                pl_region_max[p] = label_sc.max_groups

        pkey = _placement_key(placement)
        rows = None if cache is None else cache.placement_rows.get(pkey)
        fb = (cache.fail_rows.get(pkey) if explain and cache is not None
              else None)
        if rows is None:
            mask_row = np.zeros(C, bool)
            tol_row = np.zeros(C, bool)
            extra_row = np.zeros(C, np.int64)
            probe = _spec_with(placement)
            # explain decomposition rides the SAME pass: each stage is
            # evaluated once (without the folded mask's short-circuit)
            # and the mask derives from the bits — never a second O(C)
            # filter sweep for the armed encode
            build_fb = explain and fb is None
            fb_new = np.zeros(C, np.int32) if build_fb else None
            for i, c in enumerate(clusters):
                if build_fb:
                    bits = 0
                    if serial.filter_cluster_affinity(
                            probe, dummy_status, c) is not None:
                        bits |= VERDICT_AFFINITY
                    if serial.filter_spread_constraint(
                            probe, dummy_status, c) is not None:
                        bits |= VERDICT_SPREAD_PROP
                    if plug_filters and eval_filters(
                            plug_filters, placement, c) is not None:
                        bits |= VERDICT_PLUGIN
                    fb_new[i] = bits
                    mask_row[i] = bits == 0
                else:
                    # affinity + spread-property predicates (no prev
                    # bypass); out-of-tree registry filters fold into the
                    # same mask
                    mask_row[i] = (
                        serial.filter_cluster_affinity(probe, dummy_status, c) is None
                        and serial.filter_spread_constraint(probe, dummy_status, c) is None
                        and (not plug_filters
                             or eval_filters(plug_filters, placement, c) is None)
                    )
                # taint toleration WITHOUT the target_contains bypass
                tol_row[i] = _tolerated(placement, c)
                if plug_scores:
                    extra_row[i] = eval_scores(plug_scores, placement, c)
            if build_fb:
                fb = fb_new
                if cache is not None:
                    cache.fail_rows[pkey] = fb
            # static weights (division_algorithm.go:38-72) per cluster
            static_row = np.zeros(C, np.int64)
            s = placement.replica_scheduling
            wl = (
                s.weight_preference.static_weight_list
                if s is not None and s.weight_preference is not None
                else []
            )
            if pl_strategy[p] == STRAT_STATIC:
                if not wl:
                    static_row[:nC] = 1
                else:
                    for i, c in enumerate(clusters):
                        weight = 0
                        for rule in wl:
                            if rule.target_cluster.matches(c):
                                weight = max(weight, rule.weight)
                        static_row[i] = weight
            rows = (mask_row, tol_row, static_row, extra_row)
            if cache is not None:
                cache.placement_rows[pkey] = rows
        pl_mask[p], pl_tol_bypass[p], pl_static_w[p], pl_extra_score[p] = rows
        if explain:
            # mask rows cached from a disarmed encode: decompose the
            # stages standalone (a cluster failing affinity AND the
            # spread property carries both bits; the serial-parity
            # contract compares the lowest set bit only)
            if fb is None:
                fb = _fail_row(placement, clusters, C, plug_filters,
                               dummy_status)
                if cache is not None:
                    cache.fail_rows[pkey] = fb
            pl_fail_bits[p] = fb

    # ---- api enablement ---------------------------------------------------
    G = _next_pow2(max(len(gvks), 1), 4)
    api_ok = np.zeros((G, C), bool)
    for gk, g in gvks.items():
        row = None if cache is None else cache.gvk_rows.get(gk)
        if row is None:
            api_version, kind = gk
            row = np.array(
                [c.api_enablement(api_version, kind) == serial.API_ENABLED
                 for c in clusters]
                + [False] * (C - nC),
                dtype=bool,
            )
            if cache is not None:
                cache.gvk_rows[gk] = row
        api_ok[g] = row

    # assemble the cluster/placement tensor set; with a cache it is frozen
    # (read-only: an in-place mutation must fail loudly, not silently serve
    # a stale device copy) and stored for verbatim reuse by later chunks
    shared = {
        "cluster_valid": cluster_valid, "deleting": deleting,
        "name_rank": name_rank, "pods_allowed": pods_allowed,
        "has_summary": has_summary, "avail_milli": avail_milli,
        "has_alloc": has_alloc, "api_ok": api_ok,
        "req_milli": req_milli, "req_is_cpu": req_is_cpu,
        "req_pods": req_pods, "est_override": est_override,
        "pl_mask": pl_mask, "pl_tol_bypass": pl_tol_bypass,
        "pl_strategy": pl_strategy, "pl_static_w": pl_static_w,
        "pl_has_cluster_sc": pl_has_cluster_sc, "pl_sc_min": pl_sc_min,
        "pl_sc_max": pl_sc_max, "pl_ignore_avail": pl_ignore_avail,
        "pl_extra_score": pl_extra_score,
        "region_id": region_id,
        "pl_has_region_sc": pl_has_region_sc, "pl_region_min": pl_region_min,
        "pl_region_max": pl_region_max,
    }
    if cache is not None:
        for arr in shared.values():
            if arr.flags.owndata:
                arr.flags.writeable = False
        cache.assembled_sig = assembled_sig
        cache.assembled = shared
    if explain and cache is not None:
        # the explain plane caches beside — never inside — the assembled
        # slot (see EncoderCache.fail_plane)
        if pl_fail_bits.flags.owndata:
            pl_fail_bits.flags.writeable = False
        cache.fail_plane = (assembled_sig, pl_fail_bits)

    batch = _build_solver_batch(
        shared, B, C, nB, nC, b_valid, placement_id, gvk_id, class_id,
        replicas, uid_desc, fresh, non_workload, nw_shortcut,
        prev_idx, prev_val, evict_idx, route, cindex, region_names,
        list(res_names), list(classes), label_axes, explain, pl_fail_bits,
    )
    batch.placements = list(placements)
    batch.gvk_keys = list(gvks)
    batch.class_reqs = list(class_reqs)
    return batch


def _fail_row(placement, clusters, C, plug_filters, dummy_status
              ) -> np.ndarray:
    """One placement's static filter-failure bits per cluster lane
    (obs/decisions layout: affinity | spread-property | plugin)."""
    from karmada_tpu.scheduler.plugins import eval_filters

    fb = np.zeros(C, np.int32)
    probe = _spec_with(placement)
    for i, c in enumerate(clusters):
        if serial.filter_cluster_affinity(probe, dummy_status, c) is not None:
            fb[i] |= VERDICT_AFFINITY
        if serial.filter_spread_constraint(probe, dummy_status, c) is not None:
            fb[i] |= VERDICT_SPREAD_PROP
        if plug_filters and eval_filters(plug_filters, placement,
                                         c) is not None:
            fb[i] |= VERDICT_PLUGIN
    return fb


def _fail_plane(placements, clusters, C, P, cache, sig) -> np.ndarray:
    """The assembled [P, C] fail-bit plane for one vocabulary —
    single-slot cached on the assembled signature so armed chunks reuse
    it verbatim and armed/disarmed alternation (explain sampling) never
    disturbs the assembled/device-transfer caches."""
    if (cache is not None and cache.fail_plane is not None
            and cache.fail_plane[0] == sig):
        return cache.fail_plane[1]
    from karmada_tpu.scheduler.plugins import REGISTRY as _PLUGINS

    plug_filters = _PLUGINS.enabled_filters()
    dummy_status = ResourceBindingStatus()
    plane = np.zeros((P, C), np.int32)
    for p, placement in enumerate(placements):
        pkey = _placement_key(placement)
        fb = cache.fail_rows.get(pkey) if cache is not None else None
        if fb is None:
            fb = _fail_row(placement, clusters, C, plug_filters,
                           dummy_status)
            if cache is not None:
                cache.fail_rows[pkey] = fb
        plane[p] = fb
    if cache is not None:
        if plane.flags.owndata:
            plane.flags.writeable = False
        cache.fail_plane = (sig, plane)
    return plane


def _build_solver_batch(
    shared, B, C, nB, nC, b_valid, placement_id, gvk_id, class_id,
    replicas, uid_desc, fresh, non_workload, nw_shortcut,
    prev_idx, prev_val, evict_idx, route, cindex, region_names,
    res_names=None, class_keys=None, label_axes=None, explain=False,
    pl_fail_bits=None,
) -> SolverBatch:
    return SolverBatch(
        B=B, C=C, n_bindings=nB, n_clusters=nC,
        cluster_valid=shared["cluster_valid"], deleting=shared["deleting"],
        name_rank=shared["name_rank"], pods_allowed=shared["pods_allowed"],
        has_summary=shared["has_summary"],
        avail_milli=shared["avail_milli"], has_alloc=shared["has_alloc"],
        api_ok=shared["api_ok"],
        req_milli=shared["req_milli"], req_is_cpu=shared["req_is_cpu"],
        req_pods=shared["req_pods"], est_override=shared["est_override"],
        pl_mask=shared["pl_mask"], pl_tol_bypass=shared["pl_tol_bypass"],
        pl_strategy=shared["pl_strategy"], pl_static_w=shared["pl_static_w"],
        pl_has_cluster_sc=shared["pl_has_cluster_sc"],
        pl_sc_min=shared["pl_sc_min"], pl_sc_max=shared["pl_sc_max"],
        pl_ignore_avail=shared["pl_ignore_avail"],
        pl_extra_score=shared["pl_extra_score"],
        b_valid=b_valid, placement_id=placement_id, gvk_id=gvk_id,
        class_id=class_id, replicas=replicas, uid_desc=uid_desc, fresh=fresh,
        non_workload=non_workload, nw_shortcut=nw_shortcut,
        prev_idx=prev_idx, prev_val=prev_val, evict_idx=evict_idx,
        route=route, cluster_index=cindex,
        region_id=shared["region_id"], region_names=region_names,
        label_axes=label_axes or {},
        pl_has_region_sc=shared["pl_has_region_sc"],
        pl_region_min=shared["pl_region_min"],
        pl_region_max=shared["pl_region_max"],
        pl_fail_bits=(pl_fail_bits if pl_fail_bits is not None
                      else np.zeros_like(shared["pl_mask"], np.int32)),
        res_names=res_names or [], class_keys=class_keys or [],
        explain=explain,
    )


def remap_used(used, from_batch: SolverBatch, to_batch: SolverBatch):
    """Transport consumed-capacity accumulators (solver carry-out) between
    TWO batches of the same cycle whose resource/class vocabularies may
    differ: columns map by resource NAME, class rows by canonical key.
    Resources/classes absent from the target batch are dropped (nothing in
    it consults them); absent-from-source entries start at zero.

    For a CHAIN of batches use CarryState instead — pairwise remapping
    through an intermediate batch whose vocabulary lacks a resource would
    silently drop that resource's accumulated consumption."""
    um, up, us = used
    um2 = np.zeros_like(to_batch.avail_milli)
    r1 = {n: i for i, n in enumerate(from_batch.res_names)}
    for r2, name in enumerate(to_batch.res_names):
        if name in r1:
            um2[:, r2] = um[:, r1[name]]
    us2 = np.zeros_like(to_batch.est_override)
    q1 = {k: i for i, k in enumerate(from_batch.class_keys)}
    for q2, key in enumerate(to_batch.class_keys):
        if key in q1:
            us2[q2] = us[q1[key]]
    return um2, np.asarray(up), us2


class CarryState:
    """Vocabulary-stable transport for chained consumed-capacity carry.

    Accumulators live keyed by resource NAME / class KEY (never by a
    batch's padded axis), so a resource absent from an intermediate
    batch's vocabulary survives to the next batch that requests it.
    Per batch: `used0_for(batch)` renders the carry into the batch's
    vocabulary; after the solve, `absorb(batch, used_out, used0)` adds the
    batch's OWN consumption (carry-out minus carry-in) back into the
    stable store.

    Shortlisted sub-vocabulary batches (ops/shortlist: `sub_lanes` maps
    sub lane -> full-vocabulary lane) render and absorb through the lane
    map: the store's arrays stay in the FULL cluster vocabulary
    (`sub_full_c` lanes), used0_for gathers the sub-batch's rows out of
    them, and absorb scatter-adds the sub-batch's own consumption back —
    so consumption crosses per-chunk cluster vocabularies losslessly,
    exactly like the resource/class keying already crosses per-chunk
    resource vocabularies."""

    def __init__(self) -> None:
        self.milli: Dict[str, np.ndarray] = {}  # name -> int64[C]
        self.pods: Optional[np.ndarray] = None  # int64[C]
        self.sets: Dict = {}  # class key -> int64[C]

    @staticmethod
    def _lanes_of(batch):
        """(full_C, lanes, ok_mask) for a sub-vocabulary batch, else
        (batch.C, None, None) — the identity rendering."""
        lanes = getattr(batch, "sub_lanes", None)
        if lanes is None:
            return batch.C, None, None
        ok = lanes >= 0
        return int(batch.sub_full_c), np.where(ok, lanes, 0), ok

    def empty(self) -> bool:
        """True when no consumption has been absorbed yet (used0_for would
        render all-zero accumulators)."""
        return not self.milli and not self.sets and self.pods is None

    def copy(self) -> "CarryState":
        """Deep copy (independent arrays) — the incremental plane seeds
        each cycle's pipeline chain from its carried ledger, and the chain
        mutates its seed in place (merge/absorb are additive)."""
        out = CarryState()
        out.milli = {k: v.copy() for k, v in self.milli.items()}
        out.pods = self.pods.copy() if self.pods is not None else None
        out.sets = {k: v.copy() for k, v in self.sets.items()}
        return out

    def retire_lanes(self, lanes: np.ndarray) -> None:
        """Zero the accumulators at these full-vocabulary cluster lanes.

        The incremental plane's carried-consumption invariant: a lane's
        carried consumption stands in for allocations the cluster's
        status has not reported yet, so a status write for that cluster
        (resident last_cap_lanes) RETIRES the lane — the fresh
        allocatable/allocated numbers now embed whatever the carried
        placements actually landed.  Lanes beyond an accumulator's length
        (vocabulary padding drift) are ignored."""
        lanes = np.asarray(lanes, np.int64)
        if lanes.size == 0:
            return
        for arr in self.milli.values():
            arr[lanes[lanes < arr.shape[0]]] = 0
        if self.pods is not None:
            self.pods[lanes[lanes < self.pods.shape[0]]] = 0
        for arr in self.sets.values():
            arr[lanes[lanes < arr.shape[0]]] = 0

    def merge(self, other: "CarryState") -> None:
        """Fold another keyed store into this one (additive; the pipelined
        executor retires pending spread contributions this way)."""
        for name, arr in other.milli.items():
            self.milli[name] = (self.milli[name] + arr if name in self.milli
                                else arr.copy())
        if other.pods is not None:
            self.pods = (other.pods.copy() if self.pods is None
                         else self.pods + other.pods)
        for key, arr in other.sets.items():
            self.sets[key] = (self.sets[key] + arr if key in self.sets
                              else arr.copy())

    def used0_for(self, batch: SolverBatch):
        _full_c, lanes, ok = self._lanes_of(batch)

        def render(full_row):
            if lanes is None:
                return full_row.copy()
            return np.where(ok, full_row[lanes], 0)

        um = np.zeros_like(batch.avail_milli)
        for r, name in enumerate(batch.res_names):
            if name in self.milli:
                um[:, r] = render(self.milli[name])
        up = (render(self.pods) if self.pods is not None
              else np.zeros_like(batch.pods_allowed))
        us = np.zeros_like(batch.est_override)
        for q, key in enumerate(batch.class_keys):
            if key in self.sets:
                us[q] = render(self.sets[key])
        return um, up, us

    def absorb(self, batch: SolverBatch, used_out, used0) -> None:
        full_c, lanes, ok = self._lanes_of(batch)

        def widen(own):
            """A sub-batch's own consumption scattered back to the full
            vocabulary (additive; padding lanes carry zero by the
            solver's cluster_valid masking)."""
            if lanes is None:
                return own
            full = np.zeros(full_c, own.dtype)
            np.add.at(full, lanes[ok], own[ok])
            return full

        um_out, up_out, us_out = used_out
        for r, name in enumerate(batch.res_names):
            own = widen(np.asarray(um_out)[:, r] - used0[0][:, r])
            if name in self.milli:
                self.milli[name] = self.milli[name] + own
            else:
                self.milli[name] = own.copy()
        own_p = widen(np.asarray(up_out) - used0[1])
        self.pods = own_p.copy() if self.pods is None else self.pods + own_p
        for q, key in enumerate(batch.class_keys):
            own_s = widen(np.asarray(us_out)[q] - used0[2][q])
            if key in self.sets:
                self.sets[key] = self.sets[key] + own_s
            else:
                self.sets[key] = own_s.copy()


# -- fleet capacity memo (the shortlist plane's coarse per-cluster
# aggregate, reused by the rebalance detect; jax-free on purpose — the
# rebalance plane runs on host backends too) ---------------------------------
import threading as _threading  # noqa: E402 — local to this memo

# guarded-by: _FLEET_CAP_LOCK; mutators: fleet_capacity
_FLEET_CAP_MEMO: Dict[str, Tuple[int, int]] = {}  # name -> (rv, pods)
_FLEET_CAP_LOCK = _threading.Lock()


def fleet_capacity(clusters) -> np.ndarray:
    """Per-cluster allocatable-pod capacity int64[C], memoized by
    (name, resourceVersion): the store's list() hands back deep COPIES
    every call, so an identity memo could never hit — the rv key
    survives copies, and only clusters whose status actually moved
    re-parse their Quantity dicts.  Names absent from this call are
    pruned (the memo never outgrows the live fleet)."""
    out = np.zeros(len(clusters), np.int64)
    with _FLEET_CAP_LOCK:
        live: Dict[str, Tuple[int, int]] = {}
        for i, c in enumerate(clusters):
            name = c.metadata.name
            rv = int(c.metadata.resource_version or 0)
            ent = _FLEET_CAP_MEMO.get(name)
            if ent is not None and ent[0] == rv:
                out[i] = ent[1]
            else:
                cap = 0
                s = c.status.resource_summary
                if s is not None:
                    pods = s.allocatable.get("pods")
                    if pods is not None:
                        cap = int(pods.value())
                out[i] = cap
                ent = (rv, cap)
            live[name] = ent
        _FLEET_CAP_MEMO.clear()
        _FLEET_CAP_MEMO.update(live)
    return out


def _spec_with(placement: Placement) -> ResourceBindingSpec:
    return ResourceBindingSpec(placement=placement)


def _allowed_pods(summary) -> int:
    from karmada_tpu.estimator.general import allowed_pod_number

    return allowed_pod_number(summary)


def _tolerated(placement: Placement, cluster: Cluster) -> bool:
    """TaintToleration predicate (without the per-binding prev bypass)."""
    from karmada_tpu.models.cluster import EFFECT_NO_EXECUTE, EFFECT_NO_SCHEDULE

    tolerations = placement.cluster_tolerations
    for taint in cluster.spec.taints:
        if taint.effect not in (EFFECT_NO_SCHEDULE, EFFECT_NO_EXECUTE):
            continue
        if not any(t.tolerates(taint) for t in tolerations):
            return False
    return True


def decode_result(
    batch: SolverBatch,
    rep: np.ndarray,
    selected: np.ndarray,
    status: np.ndarray,
    *,
    enable_empty_workload_propagation: bool = False,
    items: Optional[Sequence[Tuple[ResourceBindingSpec, ResourceBindingStatus]]] = None,
) -> List:
    """Dense solver output -> per-binding List[TargetCluster] or an error.

    Returns a list of length n_bindings whose entries are either
    List[TargetCluster] (name-ascending) or an Exception mirroring the
    serial path (FitError / UnschedulableError).

    Pass the original `items` to get full per-cluster FitError diagnosis
    ("0/5 clusters are available: {m1: untolerated taint...}") — the
    operator's main debugging signal (generic_scheduler.go:119 semantics).
    Diagnosis is rebuilt host-side by re-running the serial filters, but
    only for the (rare) bindings the kernel marked FIT_ERROR, so the device
    path keeps its throughput.
    """
    names = batch.cluster_index.names
    out: List = []
    rep = np.asarray(rep)
    selected = np.asarray(selected)
    status = np.asarray(status)
    for b in range(batch.n_bindings):
        err = _status_error(batch, b, int(status[b]), items)
        if err is not None:
            out.append(err)
            continue
        row = rep[b]
        targets = [
            TargetCluster(name=names[i], replicas=int(row[i]))
            for i in np.nonzero(row[: batch.n_clusters] > 0)[0]
        ]
        if batch.non_workload[b]:
            targets = [
                TargetCluster(name=names[i], replicas=0)
                for i in np.nonzero(selected[b, : batch.n_clusters])[0]
            ]
        elif enable_empty_workload_propagation:
            have = {t.name for t in targets}
            targets += [
                TargetCluster(name=names[i], replicas=0)
                for i in np.nonzero(selected[b, : batch.n_clusters])[0]
                if names[i] not in have
            ]
        targets.sort(key=lambda t: t.name)
        out.append(targets)
    return out


def _status_error(batch, b: int, st: int, items) -> Optional[Exception]:
    """Map a solver status code to the serial path's exception (or None)."""
    if st == STATUS_FIT_ERROR:
        # host-routed rows are re-scheduled serially anyway; don't pay
        # the O(C) filter pass for a result the caller discards
        if items is not None and batch.route[b] == ROUTE_DEVICE:
            spec_b, status_b = items[b]
            _, diagnosis = serial.find_clusters_that_fit(
                spec_b, status_b, batch.cluster_index.clusters
            )
            return serial.FitError(diagnosis)
        return serial.FitError({})
    if st == STATUS_UNSCHEDULABLE:
        return serial.UnschedulableError("insufficient capacity (batched)")
    if st == STATUS_NO_CLUSTER:
        return serial.NoClusterAvailableError("no clusters available to schedule")
    return None


def decode_compact(
    batch: SolverBatch,
    idx: np.ndarray,
    val: np.ndarray,
    status: np.ndarray,
    *,
    enable_empty_workload_propagation: bool = False,
    items: Optional[Sequence[Tuple[ResourceBindingSpec, ResourceBindingStatus]]] = None,
    outcome: Optional[np.ndarray] = None,
) -> List:
    """decode_result over the sparse COO form from solver.solve_compact.

    idx/val carry every (selected OR replicas>0) lane: replicas>0 entries
    are assignments; val==0 entries are selected-only lanes, meaningful for
    non-workload propagation and empty-workload propagation.

    CONTRACT: idx must be ascending among its >=0 entries (row-major
    binding order) — solver._compact_of's jnp.nonzero guarantees this; any
    other producer must sort first (asserted below).

    The hot loop is native (native/decode_fast.c) when the extension
    builds: the raw int32 COO triple is row-split, rank-sorted and turned
    into TargetCluster lists in C, fed zero-copy from the d2h views
    finalize_compact hands over.  THIS Python implementation remains the
    behavior-defining parity control and the fallback when the extension
    is absent.  `outcome` (the explain plane's outcome vector, when the
    cycle ran the explain jit variant) attaches the dominant rejection
    reason to the error objects (`exc.reason`, obs/decisions layout).
    """
    names = batch.cluster_index.names
    C = batch.C
    nb = batch.n_bindings
    coo_status = np.ascontiguousarray(np.asarray(status), np.int32)
    # fused resident-gather batches carry non_workload as a DEVICE array
    # plus a host companion: prefer the companion — reading the device
    # plane here can block behind the next chunk's in-flight solve on
    # the runtime's transfer path, and the Python fallback loop must
    # not pay a sync per element either way
    non_workload = np.asarray(
        batch.non_workload_host if batch.non_workload_host is not None
        else batch.non_workload)
    out: List = [None] * nb

    # error slots are Python's (diagnosis construction); unknown nonzero
    # statuses with no mapped error fall through to target construction
    def _prefill_errors() -> None:
        for b in np.nonzero(coo_status[:nb] != 0)[0]:
            err = _status_error(batch, int(b), int(coo_status[b]), items)
            if err is not None:
                out[int(b)] = err

    _prefill_errors()

    from karmada_tpu import native as _native

    outcome_plane = None
    reason_names = None
    if outcome is not None:
        from karmada_tpu.obs.decisions import VERDICT_BIT_NAMES

        outcome_plane = np.ascontiguousarray(np.asarray(outcome), np.int32)
        reason_names = VERDICT_BIT_NAMES

    # native full-COO path: row split + rank sort + TargetCluster
    # construction in one C pass (wide Duplicated rows included)
    dec = _native.load_decode_fast()
    if dec is not None:
        idx_np = np.asarray(idx)
        val_np = np.asarray(val)
        if (idx_np.dtype == np.int32 and val_np.dtype == np.int32
                and tc_new_is_plain()):
            coo_idx = np.ascontiguousarray(idx_np, np.int32)
            coo_val = np.ascontiguousarray(val_np, np.int32)
            decode_name_rank = np.ascontiguousarray(batch.name_rank, np.int64)
            handled = dec.decode_coo(
                coo_idx, coo_val, coo_status, int(C), int(batch.n_clusters),
                decode_name_rank, names,
                np.ascontiguousarray(non_workload[:nb], np.uint8),
                bool(enable_empty_workload_propagation), TargetCluster, out,
                *((outcome_plane, reason_names)
                  if outcome_plane is not None else ()),
            )
            if handled >= 0:
                DECODE_NATIVE.inc(int(handled))
                return out
            # ascending contract violated: the C pass may have filled
            # slots before detecting it — rebuild and let the Python
            # path's assert own the diagnostic
            out = [None] * nb
            _prefill_errors()

    # vectorized COO split: solver._compact_of emits row-major (b ascending)
    # order, so per-binding runs are contiguous and searchsorted finds them
    idx = np.asarray(idx)
    val = np.asarray(val)
    keep = idx >= 0
    iv = idx[keep]
    vv = val[keep]
    b_arr = iv // C
    c_arr = iv - b_arr * C
    in_range = (b_arr < nb) & (c_arr < batch.n_clusters)
    b_arr = b_arr[in_range]
    c_arr = c_arr[in_range]
    vv = vv[in_range]
    assert b_arr.size == 0 or np.all(np.diff(b_arr) >= 0), (
        "decode_compact requires row-major (ascending) COO input"
    )
    bounds = np.searchsorted(b_arr, np.arange(nb + 1))
    status_arr = coo_status

    fast = _native.load_encode_fast()
    if fast is not None:
        fast.decode_fast(
            np.ascontiguousarray(bounds, np.int64),
            np.ascontiguousarray(c_arr, np.int64),
            np.ascontiguousarray(vv, np.int64),
            np.ascontiguousarray(batch.name_rank, np.int64),
            names, np.ascontiguousarray(non_workload[:nb], np.uint8),
            status_arr, TargetCluster,
            bool(enable_empty_workload_propagation), out,
        )

    # Python builder: every slot the C path did not fill (fallback mode,
    # or nonzero-status bindings whose error mapped to None)
    for b in range(nb):
        if out[b] is not None:
            continue
        lo, hi = bounds[b], bounds[b + 1]
        cs = c_arr[lo:hi].tolist()
        vs = vv[lo:hi].tolist()
        if non_workload[b]:
            targets = [TargetCluster(name=names[c], replicas=0) for c in cs]
        else:
            targets = [
                TargetCluster(name=names[c], replicas=v)
                for c, v in zip(cs, vs) if v > 0
            ]
            if enable_empty_workload_propagation:
                targets += [
                    TargetCluster(name=names[c], replicas=0)
                    for c, v in zip(cs, vs)
                    if v == 0
                ]
        targets.sort(key=lambda t: t.name)
        out[b] = targets
    if outcome_plane is not None:
        # fallback parity with the native pass: dominant rejection reason
        # onto the error objects (bits 8+ of the outcome code hold 1 +
        # the dominant stage's bit index — obs/decisions.split_outcome)
        for b in range(nb):
            dom = int(outcome_plane[b]) >> 8
            if 0 < dom <= len(reason_names) and isinstance(out[b], Exception):
                out[b].reason = reason_names[dom - 1]
    return out
