"""Solver kernels.

  webster.py — exact Sainte-Laguë/Webster seat allocation (greedy golden path)
  serial.py  — faithful serial re-implementation of the reference scheduling
               pipeline (the control baseline; reference pkg/scheduler/core)
  solver.py  — the TPU-native batched JAX program (the north star)
  tensors.py — host-side interning/packing of objects into dense tensors
"""

from karmada_tpu.ops.webster import (  # noqa: F401
    Party,
    allocate_webster_seats,
    dispense_by_weight,
    fnv32a,
    tiebreak_descending_by_uid,
)
