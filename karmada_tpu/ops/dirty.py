"""Device-side dirty-row detection for the incremental steady-state solve.

The reference control plane is watch-driven: between cycles almost
nothing changes, and karmada's reconcile loop only touches what the
watch stream dirtied.  The batched solver's equivalent is this kernel:
one jitted pass over the binding-row SLOT STORE (the resident plane's
[cap]-leading masters / device mirrors, karmada_tpu/resident/state.py)
classifies every row as clean or dirty for the cycle, and the
incremental solver (karmada_tpu/scheduler/incremental.py) re-solves ONLY
the dirty sub-batch.  Nothing here materializes an [n, C] plane — the
pass is O(cap * (Kp + Ke + F)) with F the cycle's handful of
feasibility-flip lanes.

Derivation rules (docs/PERF_NOTES.md "Incremental solve" is the prose
version; the solver math referenced is ops/solver._assign_lanes /
wave_step):

  rv-churn     the binding itself was written this window (resident
               deltas.bindings_touched + the incremental solver's own
               write-backs) — its encoded row is stale, re-solve.
  route        rows the compact device tier does not own (spread / big /
               host routes) re-solve every cycle: their sub-solves price
               against the cycle's carry and are cheap at steady-state
               counts.
  sensitive    capacity-sensitive rows — Dynamic/Aggregated rows that
               are fresh or whose previous assignment no longer covers
               the replica target under CURRENT feasibility
               (assigned != replicas), and spread-constrained rows.
               Their placement depends on the capacity environment, so
               any cycle's capacity churn (or carried consumption) can
               move them: always dirty.  Steady rows
               (assigned == replicas, not fresh) reproduce their
               previous assignment exactly and consume nothing — the
               solver's stickiness contract — so they are clean no
               matter how capacity moved.
  flip         a lane's feasibility actually changed this window
               (resident last_flip_lanes: `deleting` flips and api_ok
               column changes — the only feasibility inputs a
               non-structural delta can move).  Every row whose
               placement mask covers a flipped lane is dirty: its
               eligible set changed.  Structural changes (membership,
               spec, labels) rebuild the whole plane and force a full
               solve upstream — they never reach this kernel.

The kernel also grades each dirty row for the solver's visibility-exact
grouping (scheduler/incremental.py):

  sensitive    bit — the row's RESULT depends on consumed capacity seen
               at solve time (ordering matters for it).
  consumer     bit — the row's re-solve may CONSUME capacity (its new
               result can exceed its previous assignment), so later
               sensitive rows must either see its consumption (chained
               groups) or provably not care (disjoint placement masks).

Trace-safety: pure gathers/compares + one scatter-max for the rv mask —
no Python control flow on traced values, no host syncs; dtypes ride in
on the slot-store operands (ops/tensors.FIELD_DTYPES).
"""

from __future__ import annotations

from typing import Optional

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from karmada_tpu.ops import tensors as T  # noqa: E402
from karmada_tpu.utils.metrics import REGISTRY  # noqa: E402

#: code bits in the kernel's uint8 output (per slot)
DIRTY = 1        # re-solve this row this cycle
SENSITIVE = 2    # result depends on the consumed-capacity environment
CONSUMER = 4     # re-solve may consume capacity beyond the previous rep

DIRTY_DISPATCHES = REGISTRY.counter(
    "karmada_incremental_dirty_kernel_dispatches_total",
    "Dirty-set kernel dispatches (one per incremental cycle)",
)
DIRTY_ROWS = REGISTRY.counter(
    "karmada_incremental_dirty_rows_total",
    "Binding rows classified dirty by the incremental dirty-set kernel "
    "(re-solved as the cycle's compact sub-batch instead of the full "
    "roster)",
)
DIRTY_FRACTION = REGISTRY.gauge(
    "karmada_incremental_dirty_fraction",
    "Dirty rows / live roster rows in the most recent incremental cycle "
    "(the steady-state win is 1 minus this, roughly)",
)


def _dirty_core(placement_id, replicas, fresh, non_workload, route,
                prev_idx, prev_val, evict_idx,
                cluster_valid, deleting, pl_mask, pl_strategy,
                pl_has_cluster_sc, pl_has_region_sc,
                flip_lanes, rv_slots):
    """uint8[cap] dirty codes over the slot store — see module docstring.

    flip_lanes int64[F] / rv_slots int64[S]: -1 padded (static pow2
    buckets so the jit signature stays stable across cycles)."""
    cap = placement_id.shape[0]
    lanes_ok = cluster_valid & ~deleting  # [C]

    # previous-assignment feasibility under CURRENT planes — exactly the
    # solver's prev-lane formula (lanes_ok & pl_mask & ~evict; tolerance
    # and api gates auto-pass on prev-present lanes)
    okp = prev_idx >= 0                                    # [cap, Kp]
    pl = jnp.where(okp, prev_idx, 0)
    in_mask = pl_mask[placement_id[:, None], pl]           # [cap, Kp]
    ev = jnp.where(evict_idx >= 0, evict_idx, -2)          # [cap, Ke]
    evicted = jnp.any(pl[:, :, None] == ev[:, None, :], axis=2)
    feas = okp & lanes_ok[pl] & in_mask & ~evicted
    assigned = jnp.sum(
        jnp.where(feas, prev_val, 0), axis=1).astype(replicas.dtype)

    strat = pl_strategy[placement_id]
    dyn = ((strat == T.STRAT_DYNAMIC) | (strat == T.STRAT_AGGREGATED))
    has_sc = (pl_has_cluster_sc[placement_id]
              | pl_has_region_sc[placement_id])
    sensitive = (~non_workload) & (
        (dyn & (fresh | (assigned != replicas))) | has_sc)

    # a feasibility flip reaches every row whose placement mask covers
    # the flipped lane (lanes outside the mask are infeasible regardless)
    fl_ok = flip_lanes >= 0                                # [F]
    fl = jnp.where(fl_ok, flip_lanes, 0)
    flip_hit = jnp.any(
        pl_mask[placement_id[:, None], fl[None, :]] & fl_ok[None, :],
        axis=1)

    rv_ok = rv_slots >= 0
    rv_hit = (jnp.zeros(cap, bool)
              .at[jnp.where(rv_ok, rv_slots, 0)].max(rv_ok))

    route_hit = route != T.ROUTE_DEVICE
    # rv-churned rows grade conservatively sensitive+consumer: the kernel
    # reads the PRE-re-encode slot row, so their steadiness is unknown
    sens_out = sensitive | rv_hit | route_hit
    dirty = sens_out | flip_hit
    # Static/Duplicated rows are capacity-INsensitive but their re-solve
    # can still move replicas onto new lanes (consume); steady dynamic
    # rows hit only by an off-prev-lane flip reproduce prev exactly
    consumer = sens_out | (dirty & ~dyn & ~non_workload)
    return (dirty.astype(jnp.uint8)
            | (sens_out.astype(jnp.uint8) << 1)
            | (consumer.astype(jnp.uint8) << 2))


dirty_kernel = jax.jit(_dirty_core)


def _pad_lanes(arr: np.ndarray, lo: int = 8) -> np.ndarray:
    """-1-pad to the next pow2 bucket (stable jit signatures)."""
    arr = np.asarray(arr, np.int64).reshape(-1)
    n = T._next_pow2(max(arr.size, 1), lo)  # noqa: SLF001 — same package
    out = np.full(n, -1, np.int64)
    out[:arr.size] = arr
    return out


def dirty_codes(state, rv_slots: np.ndarray,
                mirrors: Optional[dict] = None) -> np.ndarray:
    """Run the dirty kernel against a ResidentState's slot store: returns
    the uint8[cap] code plane as numpy (DIRTY/SENSITIVE/CONSUMER bits).

    `rv_slots`: slot indices of rows the watch window (or the solver's
    own write-backs) touched.  `mirrors`: pass the fused device slot
    mirrors to run against live device arrays (zero binding-axis h2d);
    None gathers from the frozen host masters (XLA transfers them — free
    on CPU, the fused path is the headline elsewhere)."""
    p = state.plane
    src = mirrors if mirrors else p

    def f(name):
        return (src[name] if isinstance(src, dict) else getattr(src, name))

    codes = dirty_kernel(
        f("placement_id"), f("replicas"), f("fresh"), f("non_workload"),
        f("route"), f("prev_idx"), f("prev_val"), f("evict_idx"),
        p.cluster_valid, p.deleting, p.pl_mask, p.pl_strategy,
        p.pl_has_cluster_sc, p.pl_has_region_sc,
        _pad_lanes(state.last_flip_lanes), _pad_lanes(rv_slots))
    DIRTY_DISPATCHES.inc()
    return np.asarray(codes)
