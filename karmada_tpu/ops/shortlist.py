"""Tier-1 candidate shortlist: device-side top-k cluster lanes per binding.

The dense solve is O(B*C): every binding prices every cluster.  At the
north star's scale (1M+ bindings, 10k+ clusters) that is 10^10 cells —
out of reach on any hardware in one tier.  The reference control plane
itself solves hierarchically (PAPER.md §L4: SpreadConstraint group
selection runs BEFORE per-cluster replica division); this module is that
hierarchy for the batched path:

  tier 1 (this kernel)   one cheap jitted pass scores every (profile,
                         cluster) cell with a packed integer key —
                         feasibility bit, capacity estimate, a COARSE
                         per-group aggregate rank (built once per cycle
                         from the resident cluster planes), name order —
                         and emits the top-k candidate lanes (k ~ 32-64,
                         -1 padded).  Profiles are the encoder's own
                         dedup axes: bindings sharing (placement, gvk,
                         request class) have identical static rows, so
                         the kernel runs over the chunk's few DISTINCT
                         profiles — O(P'*C) per chunk, not O(B*C) — and
                         per-binding deltas (prev assignments) rejoin
                         the candidate union host-side.
  tier 2 (ops/solver)    the EXISTING dense solver runs over the chunk's
                         union-of-candidates sub-vocabulary — a [B, C']
                         problem with C' ~ O(k) instead of C — via the
                         per-chunk vocabulary remap below.  The solver's
                         lane math is lane-count agnostic (ops/solver
                         _assign_lanes), so the sub-solve is bit-exact.

Exactness contract (the parity fuzz in tests/test_shortlist.py): a
binding is COVERED when its whole eligible lane set — feasible lanes
plus every previous-assignment lane, which the solver's scale-down and
selection math read even when infeasible — fits in k.  A covered
binding's sub-solve result is bit-identical to the full dense solve:
absent lanes are exactly the lanes that contribute nothing (infeasible,
non-prev), and every packed sort key in the solver compares name_rank /
rank_eff only by ORDER, which the sub-vocabulary preserves.  A chunk
with any uncovered binding (or any row the device tier does not own)
widens k and retries, then falls back to the full dense dispatch —
loudly (karmada_shortlist_fallbacks_total{reason} + a ledger event),
never with a wrong placement.

Sharding chain: the kernel's outputs pin to the shard_specs entries for
SHORTLIST_OUT_FIELDS (ops/meshing — the SAME table the solver's dispatch
places its in-shardings with, the ops/resident_gather pattern), so under
a mesh the candidate plane flows toward the tier-2 dispatch without a
repartition step.  The coarse per-group aggregates are built once per
cycle from the cluster planes the resident plane keeps between cycles
(memoized on the frozen arrays' identities — the same identity
discipline as the solver's device-transfer cache).

Trace-safety: pure elementwise + top_k — no Python control flow on
traced values, no host syncs, dtypes ride in on the operands (built
against ops/tensors.FIELD_DTYPES).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass
from functools import partial
from typing import Dict, List, Optional, Tuple

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax import lax  # noqa: E402

from karmada_tpu.obs import events as ev  # noqa: E402
from karmada_tpu.ops import tensors as T  # noqa: E402
from karmada_tpu.utils.locks import VetLock  # noqa: E402
from karmada_tpu.utils.metrics import REGISTRY  # noqa: E402

# packed score-key geometry: prev-assignment bonus bit above a 34-bit
# capacity field above a 5-bit coarse group-rank field above the 21-bit
# lane field (1+34+5+21 = 61 bits — fits int64 with sign headroom)
_AVAIL_BITS = 34
_AVAIL_CAP = (1 << _AVAIL_BITS) - 1
_LANE_BITS = 21
_LANE_MASK = (1 << _LANE_BITS) - 1
_GROUP_BITS = 5
_GROUP_MASK = (1 << _GROUP_BITS) - 1

#: kernel outputs, in the order the jit returns them — the spec-coverage
#: vet pass checks every entry against meshing.shard_specs exactly like
#: the fused gather's OUT_FIELDS (one table, so the shortlist's
#: out-shardings cannot drift from the solver's in-shardings)
SHORTLIST_OUT_FIELDS = ("shortlist_idx", "shortlist_fcount")

SHORTLIST_DISPATCHES = REGISTRY.counter(
    "karmada_shortlist_dispatches_total",
    "Tier-1 shortlist kernel dispatches (one per shortlisted chunk, "
    "plus one per widen retry)",
)
SHORTLIST_ROWS = REGISTRY.counter(
    "karmada_shortlist_rows_total",
    "Binding rows whose tier-2 solve ran over the shortlisted "
    "sub-vocabulary instead of the full cluster axis",
)
SHORTLIST_FALLBACKS = REGISTRY.counter(
    "karmada_shortlist_fallbacks_total",
    "Chunks that fell back to the full dense dispatch, by reason "
    "(uncovered: a binding's eligible set outgrew k even after "
    "widening, with truncation off or unavailable; mixed_routes: the "
    "chunk holds rows the device tier does not own; union_wide: the "
    "candidate union approached the dense width; fused: a fused "
    "resident-gather batch arrived without its fused_src handle, so "
    "the shortlist cannot read binding fields host-side)",
    ("reason",),
)
SHORTLIST_FALLBACK_ROWS = REGISTRY.counter(
    "karmada_shortlist_fallback_rows_total",
    "Binding rows priced at full dense width, by kind: `needed` rows "
    "individually required it (eligible set beyond k_max, or a "
    "non-device route), `chunk_drag` rows were dragged along by a "
    "per-chunk fallback their own eligible set did not ask for — the "
    "per-binding routing win is this kind going to zero",
    ("kind",),
)
SHORTLIST_WIDENINGS = REGISTRY.counter(
    "karmada_shortlist_widenings_total",
    "Widen-and-retry rounds (k doubled because a binding's eligible "
    "lane set did not fit)",
)
SHORTLIST_CELLS = REGISTRY.counter(
    "karmada_shortlist_cells_total",
    "Tier-2 solver cell work, by tier: solve = B*C' actually "
    "dispatched over the sub-vocabulary, dense_equiv = B*C the full "
    "dense dispatch would have priced (their ratio is the measured "
    "cell-work reduction)",
    ("tier",),
)
SHORTLIST_UNION_LANES = REGISTRY.gauge(
    "karmada_shortlist_union_lanes",
    "Cluster lanes in the most recent shortlisted chunk's candidate "
    "union (the tier-2 sub-vocabulary width before pow2 padding)",
)


@dataclass(frozen=True)
class ShortlistConfig:
    """Tier selection knobs (Scheduler(shortlist_k=) / serve --shortlist).

    k: candidate lanes per binding (tier-1 top-k width).
    min_cells: a chunk shortlists only when its dense B*C cell count is
      at least this (the two-tier overhead only pays above a scale);
      <= 0 arms every chunk (tests, megafleet).
    k_max: widen-and-retry ceiling — k doubles toward this while any
      binding's eligible set does not fit, then the offending rows are
      truncated out (below) or the chunk falls back.
    union_frac: dense fallback when the candidate union exceeds this
      fraction of the real cluster count (a sub-solve near dense width
      costs more than dense: extra gather + remap for no cell savings).
    truncate: truncation-with-recall — a binding whose eligible set
      exceeds k_max is routed OUT of the shortlisted sub-solve as a
      per-binding dense residual (the pipeline solves it at full width
      against the chunk's own starting capacity) instead of dragging
      the whole chunk dense.  Exact at waves=1 (rows of one chunk never
      see each other's consumption there — docs/PERF_NOTES.md); the
      pipeline disables it at waves>1 or under keep_sel.
    """

    k: int = 64
    min_cells: int = 1 << 21
    k_max: int = 256
    union_frac: float = 0.5
    truncate: bool = True


def _shortlist_core(
    cluster_valid, deleting, name_rank, pods_allowed, has_summary,
    avail_milli, has_alloc, api_ok,
    req_milli, req_is_cpu, req_pods, est_override,
    pl_mask, pl_tol_bypass, group_pref,
    b_valid, placement_id, gvk_id, class_id, replicas,
    prev_idx, prev_val, evict_idx,
    *, k: int, shard_mesh=None,
):
    """One chunk's candidate plane: (shortlist_idx int32[B, k] — full-
    vocabulary cluster lanes, -1 padded, best first — and
    shortlist_fcount int32[B], the eligible-lane count whose comparison
    against k decides coverage).  Feasibility is the solver's own
    formula (ops/solver._schedule_core wave_step) so no feasible lane is
    ever dropped while fewer than k survive; previous-assignment lanes
    are eligible even when infeasible (the solver's scale-down and
    selection math read them)."""
    from karmada_tpu.ops.solver import MAX_INT32, _capacity_estimates

    B = b_valid.shape[0]
    C = cluster_valid.shape[0]
    Q = req_milli.shape[0]
    bidx = jnp.arange(B, dtype=jnp.int32)[:, None]
    pmask = prev_idx >= 0
    pic = jnp.where(pmask, prev_idx, 0)
    prev_present = (
        jnp.zeros((B, C), jnp.int32).at[bidx, pic]
        .add(pmask.astype(jnp.int32)) > 0
    )
    emask = evict_idx >= 0
    eic = jnp.where(emask, evict_idx, 0)
    evict = (
        jnp.zeros((B, C), jnp.int32).at[bidx, eic]
        .add(emask.astype(jnp.int32)) > 0
    )
    lanes_ok = cluster_valid[None, :] & ~deleting[None, :]
    feasible = (
        lanes_ok
        & pl_mask[placement_id]
        & (pl_tol_bypass[placement_id] | prev_present)
        & (api_ok[gvk_id] | prev_present)
        & ~evict
    )
    est_q = _capacity_estimates(
        req_milli, req_is_cpu, req_pods, avail_milli, has_alloc,
        pods_allowed, has_summary,
    )
    ovr = jnp.maximum(est_override, 0)
    est_q = est_q.at[:Q].set(jnp.where(est_override >= 0, ovr, est_q[:Q]))
    cid = jnp.where(class_id >= 0, class_id, Q)
    est_b = est_q[cid]  # [B, C]
    avail = jnp.clip(
        jnp.where(est_b == MAX_INT32, replicas[:, None], est_b),
        0, _AVAIL_CAP)
    eligible = (feasible | prev_present) & b_valid[:, None]
    key = (
        (prev_present.astype(jnp.int64)
         << (_AVAIL_BITS + _GROUP_BITS + _LANE_BITS))
        | (avail << (_GROUP_BITS + _LANE_BITS))
        | (jnp.asarray(group_pref, jnp.int64)[None, :] << _LANE_BITS)
        | (_LANE_MASK - jnp.asarray(name_rank, jnp.int64))[None, :]
    )
    key = jnp.where(eligible, key, jnp.int64(-1))
    vals, idx = lax.top_k(key, k)
    cand = jnp.where(vals >= 0, idx, -1).astype(jnp.int32)
    fcount = jnp.sum(eligible, axis=1).astype(jnp.int32)
    out = (cand, fcount)
    if shard_mesh is not None:
        # pin the candidate plane's out-shardings FROM the solver's spec
        # table (meshing.shard_specs) — the resident_gather pattern: one
        # table serves both tiers, so the chain cannot drift apart
        from karmada_tpu.ops import meshing

        out = tuple(
            lax.with_sharding_constraint(
                a, meshing.sharding_for(shard_mesh, f, a.shape))
            for f, a in zip(SHORTLIST_OUT_FIELDS, out))
    return out


shortlist_topk = partial(
    jax.jit, static_argnames=("k", "shard_mesh"))(_shortlist_core)


@partial(jax.jit, static_argnames=("n_groups",))
def _group_sums(group_id, cap_proxy, n_groups: int):
    """Coarse per-group aggregate: sum of the capacity proxy per group
    (groupless clusters land in the trailing bucket)."""
    gid = jnp.where(group_id >= 0, group_id, n_groups)
    return jax.ops.segment_sum(cap_proxy, gid, num_segments=n_groups + 1)


# one-slot per-cycle memo for the coarse aggregates: the encoder hands
# back the SAME frozen numpy cluster planes across every chunk of a cycle
# (EncoderCache.assembled / the resident plane's masters), so identity
# keying re-aggregates exactly once per cycle.  The memo PINS the source
# arrays it keyed on — a GC'd id must never alias a fresh cycle's plane
# (the solver's device-transfer cache discipline).
# guarded-by: _AGG_LOCK; mutators: cycle_aggregates,reset_for_tests
_AGG_MEMO: List[Optional[dict]] = [None]
_AGG_LOCK = VetLock("shortlist.agg")

# per-profile tier-1 memo (see _dispatch_profiles): one master-set slot,
# {(placement, gvk, class, k) -> (cand_row, fcount)} under it.  The rows
# dict is a BOUNDED LRU (recently-used profile keys survive, cold ones
# age out at _T1_ROWS_CAP) — a long steady run over a churning profile
# population must not grow host memory without limit; the master-identity
# check below already resets the whole slot when the cluster planes
# change.  Same pinning discipline as _AGG_MEMO.
# guarded-by: _T1_LOCK; mutators: _dispatch_profiles,reset_for_tests
_T1_MEMO: List[Optional[dict]] = [None]
_T1_LOCK = VetLock("shortlist.t1")
_T1_ROWS_CAP = 4096  # LRU bound on cached profile rows per master epoch

#: the per-cluster capacity aggregate the rebalance detect reuses —
#: implemented in ops/tensors (jax-free: host-backend planes import it
#: without paying a jax init) and re-exported here as part of the
#: shortlist plane's coarse-aggregate surface
fleet_capacity = T.fleet_capacity


def reset_for_tests() -> None:
    with _AGG_LOCK:
        _AGG_MEMO[0] = None
    with _T1_LOCK:
        _T1_MEMO[0] = None


def cycle_aggregates(batch) -> dict:
    """The cycle's coarse per-group aggregate tensors, built once from
    the (resident) cluster planes: group_cap int64[G+1] (free-pod proxy
    summed per region; trailing bucket = groupless), group_pref
    int64[C] (the 5-bit capacity-rank preference the score key packs —
    richer groups rank higher), cap_proxy int64[C], and the cluster
    names the arrays are aligned to (the rebalance plane's reuse key)."""
    src = (batch.avail_milli, batch.pods_allowed, batch.region_id)
    with _AGG_LOCK:
        memo = _AGG_MEMO[0]
        if (memo is not None and memo["c"] == batch.C
                and all(a is b for a, b in zip(memo["src"], src))):
            return memo
    region_id = (batch.region_id if batch.region_id is not None
                 else np.full(batch.C, -1, np.int32))
    n_groups = len(batch.region_names or [])
    valid = np.asarray(batch.cluster_valid) & ~np.asarray(batch.deleting)
    cap_proxy = np.where(valid, np.asarray(batch.pods_allowed), 0)
    group_cap = np.asarray(_group_sums(
        np.ascontiguousarray(region_id, np.int32),
        np.ascontiguousarray(cap_proxy, np.int64),
        n_groups=n_groups))
    # rank groups by aggregate capacity (desc); the key packs 5 bits
    order = np.argsort(-group_cap, kind="stable")
    rank = np.zeros(n_groups + 1, np.int64)
    rank[order] = np.arange(n_groups + 1)
    pref = _GROUP_MASK - np.minimum(rank, _GROUP_MASK)
    gid = np.where(region_id >= 0, region_id, n_groups)
    group_pref = pref[gid]
    memo = {
        # pinned sources: the identity check above is only sound while
        # these keep the keyed arrays alive
        "src": src,
        "c": batch.C,
        "group_cap": group_cap,
        "group_pref": np.ascontiguousarray(group_pref, np.int64),
        "cap_proxy": np.ascontiguousarray(cap_proxy, np.int64),
        "names": tuple(batch.cluster_index.names)
        if batch.cluster_index is not None else (),
        "n_groups": n_groups,
    }
    with _AGG_LOCK:
        _AGG_MEMO[0] = memo
    return memo


# /debug/state shortlist block: last-chunk snapshot + lifetime counters
# guarded-by: _AGG_LOCK; mutators: _note,reset_for_tests
_LAST: Dict[str, object] = {}


def _note(**kw) -> None:
    with _AGG_LOCK:
        _LAST.update(kw)


def state_payload() -> dict:
    """The `shortlist` section of /debug/state."""
    with _AGG_LOCK:
        last = dict(_LAST)
    return {
        "dispatches": int(SHORTLIST_DISPATCHES.value()),
        "rows": int(SHORTLIST_ROWS.value()),
        "widenings": int(SHORTLIST_WIDENINGS.value()),
        "fallbacks": int(SHORTLIST_FALLBACKS.total()),
        "last": last,
    }


def _fallback(batch, reason: str, detail: str) -> Tuple[None, dict]:
    """The loud dense-fallback path: metric + lifecycle-ledger event —
    a shortlisted chunk must never silently change width."""
    SHORTLIST_FALLBACKS.inc(reason=reason)
    ev.emit(ev.ObjectRef(kind="Scheduler", namespace="", name="shortlist"),
            ev.TYPE_WARNING, ev.REASON_SHORTLIST_FALLBACK,
            f"chunk fell back to the dense solve ({reason}): {detail}",
            origin="shortlist")
    _note(fallback_reason=reason)
    return None, {"fallback": reason, "detail": detail}


def _profiles(batch):
    """Profile dedup: bindings sharing (placement, gvk, request class)
    have IDENTICAL static feasibility and capacity rows — the encoder's
    own P/Q dedup axes — so the tier-1 kernel scores one row per
    DISTINCT profile (a handful per chunk) instead of one per binding:
    tier-1 cost is O(P'*C) per chunk, not O(B*C).  Per-binding deltas
    (prev assignments, evictions) rejoin host-side: prev lanes append to
    the candidate union, evict lanes only ever REMOVE feasibility (a
    superset union never changes the sub-solve's result).

    Returns (prof_keys int32[nprof, 3], prof_of int64[B], replicas_max
    int64[nprof])."""
    keys = np.stack([
        np.asarray(batch.placement_id, np.int32),
        np.asarray(batch.gvk_id, np.int32),
        np.asarray(batch.class_id, np.int32),
    ], axis=1)
    prof_keys, prof_of = np.unique(keys, axis=0, return_inverse=True)
    prof_of = prof_of.reshape(-1)
    rep_max = np.zeros(prof_keys.shape[0], np.int64)
    np.maximum.at(rep_max, prof_of, np.asarray(batch.replicas, np.int64))
    return prof_keys, prof_of, rep_max


def _t1_rows(batch, prof_keys, rep_max, k: int, agg, mesh):
    """Run the tier-1 kernel over the given profile rows (uncached):
    returns (cand int32[nprof, k], fcount int32[nprof]) as numpy."""
    nprof = prof_keys.shape[0]
    Bp = T._next_pow2(max(nprof, 1), 8)  # noqa: SLF001 — same package

    def pad1(a, fill, dtype):
        out = np.full(Bp, fill, dtype)
        out[:nprof] = a
        return out

    b_valid = np.zeros(Bp, bool)
    b_valid[:nprof] = True
    none_idx = np.full((Bp, 1), -1, np.int32)
    none_val = np.zeros((Bp, 1), np.int32)
    cand, fcount = shortlist_topk(
        batch.cluster_valid, batch.deleting, batch.name_rank,
        batch.pods_allowed, batch.has_summary, batch.avail_milli,
        batch.has_alloc, batch.api_ok, batch.req_milli, batch.req_is_cpu,
        batch.req_pods, batch.est_override, batch.pl_mask,
        batch.pl_tol_bypass, agg["group_pref"],
        b_valid,
        pad1(prof_keys[:, 0], 0, np.int32),
        pad1(prof_keys[:, 1], 0, np.int32),
        pad1(prof_keys[:, 2], -1, np.int32),
        pad1(rep_max, 0, np.int64),
        none_idx, none_val, none_idx,
        k=k, shard_mesh=mesh)
    SHORTLIST_DISPATCHES.inc()
    return np.asarray(cand)[:nprof], np.asarray(fcount)[:nprof]


def _dispatch_profiles(batch, prof_keys, rep_max, k: int, plan=None):
    """Tier-1 candidates for the chunk's profile rows: returns
    (cand int32[nprof, k], fcount int32[nprof]) as numpy.

    Cached PER PROFILE across calls: the kernel reads only the frozen
    lane/class masters (never the carried capacity ledger — tier 2 owns
    pricing), so for an unchanged master set the output is a pure
    function of (profile key, k).  rep_max is deliberately NOT part of
    the key: profile rows carry no prev/evict lanes, so the kernel's
    `eligible` mask (and fcount) is replica-independent — replicas only
    rank the packed score, and for every covered profile the widen loop
    guarantees k >= fcount, making cand the FULL eligible set whatever
    the order; an uncovered profile's truncated cand only adds superset
    lanes to the union, which never changes the sub-solve's result.
    Identity-keyed on the masters like _AGG_MEMO, pinning the keyed
    arrays (copy-on-write plane updates swap in fresh arrays, so a
    content change always changes identity).  The steady-state
    dirty-set cycle re-dispatches the same profiles every cycle — warm
    cycles skip tier-1 entirely."""
    agg = cycle_aggregates(batch)
    mesh = plan.mesh if plan is not None else None
    masters = (batch.cluster_valid, batch.deleting, batch.name_rank,
               batch.pods_allowed, batch.has_summary, batch.avail_milli,
               batch.has_alloc, batch.api_ok, batch.req_milli,
               batch.req_is_cpu, batch.req_pods, batch.est_override,
               batch.pl_mask, batch.pl_tol_bypass, agg["group_pref"])
    nprof = prof_keys.shape[0]
    pkeys = [(int(prof_keys[i, 0]), int(prof_keys[i, 1]),
              int(prof_keys[i, 2]), k)
             for i in range(nprof)]
    with _T1_LOCK:
        memo = _T1_MEMO[0]
        if (memo is None or memo["mesh"] is not mesh
                or len(memo["src"]) != len(masters)
                or not all(a is b for a, b in zip(memo["src"], masters))):
            memo = {"src": masters, "mesh": mesh, "rows": OrderedDict()}
            _T1_MEMO[0] = memo
        have = {key: memo["rows"].get(key) for key in pkeys}
        for key in pkeys:  # LRU touch: this cycle's profiles stay warm
            if have[key] is not None:
                memo["rows"].move_to_end(key)
    miss = [i for i, key in enumerate(pkeys) if have[key] is None]
    if miss:
        cand_m, fcount_m = _t1_rows(
            batch, prof_keys[miss], rep_max[np.asarray(miss)], k, agg, mesh)
        fresh = {pkeys[i]: (cand_m[j], fcount_m[j])
                 for j, i in enumerate(miss)}
        have.update(fresh)
        with _T1_LOCK:
            memo["rows"].update(fresh)
            while len(memo["rows"]) > _T1_ROWS_CAP:
                memo["rows"].popitem(last=False)  # evict coldest profile
    cand = np.stack([have[key][0] for key in pkeys]) if nprof else \
        np.zeros((0, k), np.int32)
    fcount = np.asarray([have[key][1] for key in pkeys], np.int32)
    return cand, fcount


def binding_candidates(batch, k: int, plan=None):
    """Per-binding candidate lane sets (profile candidates plus the
    binding's own prev lanes) — the recall measurement's view of tier 1
    (bench --megafleet, tests).  Host-side; small slices only."""
    prof_keys, prof_of, rep_max = _profiles(batch)
    cand, _fcount = _dispatch_profiles(batch, prof_keys, rep_max,
                                       min(k, batch.C), plan=plan)
    prev = np.asarray(batch.prev_idx)
    out = []
    for b in range(batch.n_bindings):
        s = set(int(c) for c in cand[prof_of[b]] if c >= 0)
        s.update(int(c) for c in prev[b] if c >= 0)
        out.append(s)
    return out


def _host_rows(batch):
    """Host view of the binding-axis fields the shrink logic reads
    (tier-1 profiles and coverage are host math).  Plain batches ARE the
    host view; fused resident-gather batches carry those fields as live
    device arrays, so the view is gathered lazily off the frozen
    slot-store masters in the batch's fused_src handle — cheap O(n)
    fancy-indexing of copy-on-write arrays, bit-identical to the device
    mirrors by the resident plane's sync contract."""
    if not getattr(batch, "fused", False):
        return batch
    from types import SimpleNamespace

    src = batch.fused_src
    p, sl = src["plane"], src["slots"]
    n = int(sl.shape[0])
    B = batch.B

    def pad(a, fill):
        out = np.full((B,) + a.shape[1:], fill, a.dtype)
        out[:n] = a[sl]
        return out

    b_valid = np.zeros(B, bool)
    b_valid[:n] = np.asarray(batch.route) == T.ROUTE_DEVICE
    return SimpleNamespace(
        b_valid=b_valid,
        placement_id=pad(p.placement_id, 0), gvk_id=pad(p.gvk_id, 0),
        class_id=pad(p.class_id, -1), replicas=pad(p.replicas, 0),
        non_workload=pad(p.non_workload, False),
        prev_idx=pad(p.prev_idx, -1), prev_val=pad(p.prev_val, 0),
        evict_idx=pad(p.evict_idx, -1))


def _row_names(part, rows, limit: int = 5) -> str:
    """Name offending binding rows for fallback/truncation messages —
    operators chase bindings by key, not by chunk-local row index."""
    from karmada_tpu.obs import decisions as obs_decisions

    rows = list(rows)
    if part is None:
        return f"{len(rows)} row(s)"
    names = [
        (obs_decisions.default_key(part[i][0])
         if i < len(part) else f"row {i}")
        for i in rows[:limit]
    ]
    extra = f" (+{len(rows) - limit} more)" if len(rows) > limit else ""
    return ", ".join(names) + extra


def shrink_chunk(batch, cfg: ShortlistConfig, plan=None, part=None,
                 allow_truncate: bool = True):
    """Tier selection for one encoded chunk: returns (sub_batch, info).

    sub_batch is a SolverBatch over the chunk's candidate-union
    sub-vocabulary (C' lanes instead of C) whose tier-2 solve is
    bit-exact against the full dense dispatch, or None when the chunk
    must stay dense (info["fallback"] says why — every fallback is
    counted and ledgered; `below_threshold` chunks are silent: staying
    dense below the arming scale is the configuration, not a failure).

    Fused resident-gather batches shortlist too: profile/coverage math
    reads the host slot-store masters (batch.fused_src) and the
    sub-batch's binding rows are gathered straight into the union
    vocabulary on device (ops/resident_gather.dispatch_sub_gather) —
    zero binding-axis field uploads, same as the dense fused path.

    Per-binding routing (cfg.truncate + allow_truncate): rows whose
    eligible set exceeds k_max leave the chunk as info["residual"]
    (chunk-local row indices) for the pipeline's per-binding dense
    mini-solve instead of dragging all B rows dense; `part` (the
    chunk's items) names the offenders in events.
    """
    if cfg.min_cells > 0 and batch.B * batch.C < cfg.min_cells:
        return None, {"fallback": "below_threshold"}
    if batch.C <= cfg.k:
        return None, {"fallback": "below_threshold"}
    if getattr(batch, "fused", False) and batch.fused_src is None:
        return _fallback(batch, "fused",
                         "fused batch without a fused_src handle "
                         "(explain/legacy assemble) keeps the dense path")
    hv = _host_rows(batch)
    valid = np.asarray(hv.b_valid)
    route = np.asarray(batch.route)
    if route.size and not bool(np.all(route == T.ROUTE_DEVICE)):
        n_other = int(np.sum(route != T.ROUTE_DEVICE))
        # non-device rows individually need the dense/spread machinery;
        # the chunk's device rows are dragged along — count both kinds
        # so the per-binding routing win is measurable
        SHORTLIST_FALLBACK_ROWS.inc(n_other, kind="needed")
        SHORTLIST_FALLBACK_ROWS.inc(int(valid.sum()), kind="chunk_drag")
        return _fallback(batch, "mixed_routes",
                         f"{n_other} row(s) owned by spread/big/host tiers")
    prof_keys, prof_of, rep_max = _profiles(hv)
    # per-binding prev-lane counts (host: the sparse plane is tiny);
    # coverage is judged conservatively as profile-feasible + prev —
    # prev lanes can add bypass feasibility beyond the profile row
    prev_count = np.sum(np.asarray(hv.prev_idx) >= 0, axis=1)
    k = min(cfg.k, batch.C)
    k_cap = min(cfg.k_max, batch.C)
    widened = 0
    drop = np.zeros(batch.B, bool)
    residual: List[int] = []
    while True:
        cand, fcount = _dispatch_profiles(batch, prof_keys, rep_max, k,
                                          plan=plan)
        need = fcount[prof_of] + prev_count
        active = valid & ~drop
        worst = int(need[active].max()) if bool(active.any()) else 0
        if worst > k_cap:
            # the eligible count is k-independent: rows beyond k_max can
            # never be covered, however far k widens
            offenders = np.flatnonzero(active & (need > k_cap))
            if cfg.truncate and allow_truncate:
                # truncation-with-recall: route ONLY the offenders to a
                # per-binding dense residual solve; everything else
                # keeps the shortlist.  Their recall is the full lane
                # axis (the residual prices every cluster), so nothing
                # is silently narrowed.
                drop[offenders] = True
                residual = [int(i) for i in offenders]
                SHORTLIST_FALLBACK_ROWS.inc(len(residual), kind="needed")
                ev.emit(ev.ObjectRef(kind="Scheduler", namespace="",
                                     name="shortlist"),
                        ev.TYPE_NORMAL, ev.REASON_SHORTLIST_TRUNCATE,
                        f"{len(residual)} binding(s) exceed "
                        f"k_max={cfg.k_max} (worst {worst} lane(s)): "
                        "routed to the per-binding dense residual: "
                        + _row_names(part, residual),
                        origin="shortlist")
                _note(residual=len(residual))
                active = valid & ~drop
                worst = int(need[active].max()) if bool(active.any()) else 0
            else:
                SHORTLIST_FALLBACK_ROWS.inc(len(offenders), kind="needed")
                SHORTLIST_FALLBACK_ROWS.inc(
                    int(active.sum()) - len(offenders), kind="chunk_drag")
                return _fallback(
                    batch, "uncovered",
                    f"eligible set of {worst} lane(s) exceeds "
                    f"k_max={cfg.k_max} for "
                    + _row_names(part, offenders))
        if worst <= k:
            break
        k = min(max(k * 2, worst), k_cap)
        widened += 1
        SHORTLIST_WIDENINGS.inc()
    prev_np = np.asarray(hv.prev_idx)
    # recall guarantee: EVERY kept row's prev lanes join the union
    # (residual rows' lanes are priced at full width — excluded here)
    prev_keep = prev_np[valid & ~drop]
    lanes = np.unique(np.concatenate([
        cand[cand >= 0].astype(np.int64).reshape(-1),
        prev_keep[prev_keep >= 0].astype(np.int64).reshape(-1),
    ]))
    max_union = max(cfg.k, int(cfg.union_frac * max(batch.n_clusters, 1)))
    if lanes.size > max_union:
        SHORTLIST_FALLBACK_ROWS.inc(int(valid.sum()), kind="chunk_drag")
        return _fallback(
            batch, "union_wide",
            f"candidate union of {lanes.size} lane(s) exceeds "
            f"{max_union} ({cfg.union_frac:.0%} of {batch.n_clusters})")
    sub = _sub_batch(batch, lanes, hv=hv,
                     drop=drop if residual else None)
    if sub is None:
        # a covered binding's prev lane missing from the union would be a
        # kernel bug; refuse the shortlist rather than mis-solve
        SHORTLIST_FALLBACK_ROWS.inc(int(valid.sum()), kind="chunk_drag")
        return _fallback(batch, "uncovered",
                         "prev-assignment lane absent from the union")
    SHORTLIST_ROWS.inc(int(batch.n_bindings) - len(residual))
    SHORTLIST_CELLS.inc(float(batch.B) * float(sub.C), tier="solve")
    SHORTLIST_CELLS.inc(float(batch.B) * float(batch.C), tier="dense_equiv")
    SHORTLIST_UNION_LANES.set(float(lanes.size))
    info = {"k": k, "widened": widened, "union": int(lanes.size),
            "sub_c": sub.C, "profiles": int(prof_keys.shape[0]),
            "residual": residual,
            "cells_solve": batch.B * sub.C,
            "cells_dense": batch.B * batch.C}
    _note(k=k, widened=widened, union=int(lanes.size), sub_c=sub.C,
          b=batch.B, c=batch.C, profiles=int(prof_keys.shape[0]),
          fallback_reason=None)
    return sub, info


def _sub_batch(batch, lanes: np.ndarray, hv=None, drop=None):
    """The per-chunk vocabulary remap: the full batch's planes gathered
    to the candidate union (cluster axis only — placements, request
    classes and the binding axis keep their vocabularies), name_rank
    re-densified order-preserving, sparse prev/evict lane indices
    remapped.  The result is an ordinary SolverBatch the existing
    dispatch/decode/carry machinery runs unchanged; `sub_lanes` /
    `sub_full_c` / `sub_sig` tag it for the keyed carry transport
    (tensors.CarryState renders accumulators across the lane remap).

    `drop` bool[B] marks rows routed OUT of the sub-solve (the
    truncation residual): their b_valid clears here.  On a fused batch
    (`hv` = its host view) the binding axis never touches the host —
    ops/resident_gather.dispatch_sub_gather emits the rows directly in
    the union vocabulary from the device slot store."""
    if hv is None:
        hv = _host_rows(batch)
    n2 = int(lanes.size)
    C2 = T._next_pow2(max(n2, 1), 8)  # noqa: SLF001 — same package
    inv = np.full(batch.C, -1, np.int32)
    inv[lanes] = np.arange(n2, dtype=np.int32)

    def g1(a, fill):
        out = np.full(C2, fill, a.dtype)
        out[:n2] = a[lanes]
        return out

    def g_rows(a, fill):  # [C, R] -> [C2, R]
        out = np.full((C2,) + a.shape[1:], fill, a.dtype)
        out[:n2] = a[lanes]
        return out

    def g_cols(a, fill):  # [.., C] -> [.., C2]
        out = np.full(a.shape[:-1] + (C2,), fill, a.dtype)
        out[..., :n2] = a[..., lanes]
        return out

    sub_clusters = [batch.cluster_index.clusters[int(i)] for i in lanes]
    cindex2 = T.ClusterIndex.build(sub_clusters)
    name_rank = np.zeros(C2, np.int64)
    name_rank[:n2] = cindex2.name_rank
    name_rank[n2:] = np.arange(n2, C2)

    def remap_sparse(idx, val=None):
        m = idx >= 0
        out_idx = np.where(m, inv[np.where(m, idx, 0)], -1).astype(np.int32)
        dropped = m & (out_idx < 0)
        if val is None:
            return out_idx, dropped
        out_val = np.where(out_idx >= 0, val, 0).astype(np.int32)
        return out_idx, out_val, dropped

    kept = np.asarray(hv.b_valid)
    if drop is not None:
        kept = kept & ~drop
    prev_idx, prev_val, prev_dropped = remap_sparse(
        np.asarray(hv.prev_idx), np.asarray(hv.prev_val))
    if bool(prev_dropped[kept].any()):
        return None  # prev lane outside the union: coverage bug, refuse
    evict_idx, _ = remap_sparse(np.asarray(hv.evict_idx))
    fused = bool(getattr(batch, "fused", False))
    if fused:
        # binding axis stays on device: gather the rows straight into
        # the union vocabulary from the slot mirrors (the -1 lane map
        # kills out-of-union prev/evict lanes in-kernel; `drop` clears
        # residual rows' b_valid without a host round-trip)
        from karmada_tpu.ops import resident_gather as rg

        src = batch.fused_src
        drop_b = (np.ascontiguousarray(drop) if drop is not None
                  else np.zeros(batch.B, bool))
        (b_valid_a, placement_a, gvk_a, class_a, replicas_a, uid_a,
         fresh_a, nw_a, nws_a, prev_idx_a, prev_val_a, evict_idx_a) = (
            rg.dispatch_sub_gather(src["slots_b"], src["mirrors"], inv,
                                   drop_b, src["plan"]))
        # donation-safety bound over the SUB width (solver._nnz_bound
        # semantics, recomputed like resident/state._assemble_fused)
        strat = np.asarray(batch.pl_strategy)[np.asarray(hv.placement_id)]
        wide = kept & ((strat == T.STRAT_DUPLICATED)
                       | np.asarray(hv.non_workload))
        Kp = np.asarray(hv.prev_idx).shape[1]
        per_row = np.minimum(np.asarray(hv.replicas, np.int64), C2) + Kp
        nnz_bound = (int(np.sum(wide)) * C2
                     + int(np.sum(per_row[kept & ~wide])))
    else:
        b_valid_a = kept if drop is not None else batch.b_valid
        placement_a, gvk_a, class_a = (batch.placement_id, batch.gvk_id,
                                       batch.class_id)
        replicas_a, uid_a, fresh_a = (batch.replicas, batch.uid_desc,
                                      batch.fresh)
        nw_a, nws_a = batch.non_workload, batch.nw_shortcut
        prev_idx_a, prev_val_a, evict_idx_a = prev_idx, prev_val, evict_idx
        nnz_bound = None
    label_axes = {
        key: (g1(gid, -1), values)
        for key, (gid, values) in (batch.label_axes or {}).items()
    }
    sub = T.SolverBatch(
        B=batch.B, C=C2, n_bindings=batch.n_bindings, n_clusters=n2,
        cluster_valid=g1(batch.cluster_valid, False),
        deleting=g1(batch.deleting, False),
        name_rank=name_rank,
        pods_allowed=g1(batch.pods_allowed, 0),
        has_summary=g1(batch.has_summary, False),
        avail_milli=g_rows(batch.avail_milli, 0),
        has_alloc=g_rows(batch.has_alloc, False),
        api_ok=g_cols(batch.api_ok, False),
        req_milli=batch.req_milli, req_is_cpu=batch.req_is_cpu,
        req_pods=batch.req_pods,
        est_override=g_cols(batch.est_override, -1),
        pl_mask=g_cols(batch.pl_mask, False),
        pl_tol_bypass=g_cols(batch.pl_tol_bypass, False),
        pl_strategy=batch.pl_strategy,
        pl_static_w=g_cols(batch.pl_static_w, 0),
        pl_has_cluster_sc=batch.pl_has_cluster_sc,
        pl_sc_min=batch.pl_sc_min, pl_sc_max=batch.pl_sc_max,
        pl_ignore_avail=batch.pl_ignore_avail,
        b_valid=b_valid_a, placement_id=placement_a,
        gvk_id=gvk_a, class_id=class_a,
        replicas=replicas_a, uid_desc=uid_a,
        fresh=fresh_a, non_workload=nw_a,
        nw_shortcut=nws_a,
        prev_idx=prev_idx_a, prev_val=prev_val_a, evict_idx=evict_idx_a,
        route=batch.route, cluster_index=cindex2,
        region_id=g1(batch.region_id, -1)
        if batch.region_id is not None else None,
        region_names=batch.region_names,
        label_axes=label_axes,
        pl_has_region_sc=batch.pl_has_region_sc,
        pl_region_min=batch.pl_region_min,
        pl_region_max=batch.pl_region_max,
        pl_extra_score=g_cols(batch.pl_extra_score, 0),
        res_names=batch.res_names, class_keys=batch.class_keys,
        pl_fail_bits=g_cols(batch.pl_fail_bits, 0),
        explain=batch.explain,
        placements=batch.placements, gvk_keys=batch.gvk_keys,
        class_reqs=batch.class_reqs,
        non_workload_host=batch.non_workload_host,
        sub_lanes=np.concatenate(
            [lanes, np.full(C2 - n2, -1, np.int64)]),
        sub_full_c=batch.C,
        sub_sig=hash((batch.C, C2, lanes.tobytes())),
        fused=fused,
        nnz_bound_hint=nnz_bound,
    )
    return sub


def aot_warm(batch, k: int, plan=None, profiles: int = 8) -> dict:
    """AOT-compile the shortlist kernel executable for this batch's
    cluster/placement geometry from abstract ShapeDtypeStructs (nothing
    executes) — with the persistent compile cache armed
    (ops/aotcache.enable) the first shortlisted chunk of the shape,
    mid-soak or in a later process, pays cache deserialization instead
    of an XLA compile.  The row axis is the PROFILE axis (pow2 floor 8
    — _dispatch_profiles' geometry), not the binding axis.  Returns the
    lower/compile timing split like solver.aot_warm_compile."""
    import time as _time

    fields = (
        "cluster_valid", "deleting", "name_rank", "pods_allowed",
        "has_summary", "avail_milli", "has_alloc", "api_ok",
        "req_milli", "req_is_cpu", "req_pods", "est_override",
        "pl_mask", "pl_tol_bypass",
    )

    def aval(arr):
        arr = np.asarray(arr)
        return jax.ShapeDtypeStruct(arr.shape, arr.dtype)

    Bp = T._next_pow2(max(profiles, 1), 8)  # noqa: SLF001 — same package
    args = tuple(aval(getattr(batch, f)) for f in fields)
    args = args + (jax.ShapeDtypeStruct((batch.C,), np.int64),)  # group_pref
    args = args + (
        jax.ShapeDtypeStruct((Bp,), np.bool_),    # b_valid
        jax.ShapeDtypeStruct((Bp,), np.int32),    # placement_id
        jax.ShapeDtypeStruct((Bp,), np.int32),    # gvk_id
        jax.ShapeDtypeStruct((Bp,), np.int32),    # class_id
        jax.ShapeDtypeStruct((Bp,), np.int64),    # replicas
        jax.ShapeDtypeStruct((Bp, 1), np.int32),  # prev_idx
        jax.ShapeDtypeStruct((Bp, 1), np.int32),  # prev_val
        jax.ShapeDtypeStruct((Bp, 1), np.int32),  # evict_idx
    )
    t0 = _time.perf_counter()
    lowered = shortlist_topk.lower(
        *args, k=int(k),
        shard_mesh=plan.mesh if plan is not None else None)
    t1 = _time.perf_counter()
    compiled = lowered.compile()
    t2 = _time.perf_counter()
    from karmada_tpu.obs import devprof

    return {"lower_s": round(t1 - t0, 3), "compile_s": round(t2 - t1, 3),
            "k": int(k), "cost": devprof.harvest_cost(compiled)}
