"""Serial golden scheduling pipeline — the control baseline.

A faithful Python re-implementation of the reference scheduler's algorithmic
core (pkg/scheduler/core/generic_scheduler.go:71-116):

    findClustersThatFit -> prioritizeClusters -> SelectClusters -> AssignReplicas

with the in-tree plugin set (pkg/scheduler/framework/plugins/registry.go:30-39),
spread-constraint group selection (pkg/scheduler/core/spreadconstraint/) and
the replica-division strategies (pkg/scheduler/core/assignment.go,
division_algorithm.go).

Every TPU kernel in ops/solver.py is golden-tested against this module.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from karmada_tpu.models.cluster import (
    API_ENABLED,
    EFFECT_NO_EXECUTE,
    EFFECT_NO_SCHEDULE,
    Cluster,
)
from karmada_tpu.models.policy import (
    REPLICA_DIVISION_AGGREGATED,
    REPLICA_DIVISION_WEIGHTED,
    REPLICA_SCHEDULING_DIVIDED,
    REPLICA_SCHEDULING_DUPLICATED,
    SPREAD_BY_FIELD_CLUSTER,
    SPREAD_BY_FIELD_PROVIDER,
    SPREAD_BY_FIELD_REGION,
    SPREAD_BY_FIELD_ZONE,
    ClusterAffinity,
    Placement,
    SpreadConstraint,
)
from karmada_tpu.models.work import (
    ResourceBindingSpec,
    ResourceBindingStatus,
    TargetCluster,
    get_sum_of_replicas,
    merge_target_clusters,
)
from karmada_tpu.ops.webster import dispense_by_weight

MIN_CLUSTER_SCORE = 0
MAX_CLUSTER_SCORE = 100
INVALID_REPLICAS = -1
MAX_INT32 = (1 << 31) - 1

# group-score weight unit (spreadconstraint/group_clusters.go:139)
WEIGHT_UNIT = 1000


class UnschedulableError(Exception):
    """framework.UnschedulableError — no capacity, retry later."""


class FitError(Exception):
    """No feasible cluster; carries per-cluster diagnosis."""

    def __init__(self, diagnosis: Dict[str, str]):
        super().__init__(f"0/{len(diagnosis)} clusters are available: {diagnosis}")
        self.diagnosis = diagnosis


class NoClusterAvailableError(Exception):
    """AssignReplicas with empty candidate set (core/common.go:44-46)."""


# ---------------------------------------------------------------------------
# Filter plugins (pkg/scheduler/framework/plugins/*)
# ---------------------------------------------------------------------------


def filter_api_enablement(
    spec: ResourceBindingSpec, status: ResourceBindingStatus, cluster: Cluster
) -> Optional[str]:
    if spec.target_contains(cluster.name):
        return None
    if cluster.api_enablement(spec.resource.api_version, spec.resource.kind) == API_ENABLED:
        return None
    return "cluster(s) did not have the API resource"


def filter_taint_toleration(
    spec: ResourceBindingSpec, status: ResourceBindingStatus, cluster: Cluster
) -> Optional[str]:
    if spec.target_contains(cluster.name):
        return None
    tolerations = spec.placement.cluster_tolerations if spec.placement else []
    for taint in cluster.spec.taints:
        if taint.effect not in (EFFECT_NO_SCHEDULE, EFFECT_NO_EXECUTE):
            continue
        if not any(t.tolerates(taint) for t in tolerations):
            return f"cluster(s) had untolerated taint {{{taint.key}={taint.value}:{taint.effect}}}"
    return None


def filter_cluster_affinity(
    spec: ResourceBindingSpec, status: ResourceBindingStatus, cluster: Cluster
) -> Optional[str]:
    affinity: Optional[ClusterAffinity] = None
    placement = spec.placement or Placement()
    if placement.cluster_affinity is not None:
        affinity = placement.cluster_affinity
    else:
        for term in placement.cluster_affinities:
            if term.affinity_name == status.scheduler_observed_affinity_name:
                affinity = term.affinity
                break
    if affinity is not None and not affinity.matches(cluster):
        return "cluster(s) did not match the placement cluster affinity constraint"
    return None


def filter_spread_constraint(
    spec: ResourceBindingSpec, status: ResourceBindingStatus, cluster: Cluster
) -> Optional[str]:
    placement = spec.placement or Placement()
    for sc in placement.spread_constraints:
        if sc.spread_by_field == SPREAD_BY_FIELD_PROVIDER and not cluster.spec.provider:
            return "cluster(s) did not have provider property"
        if sc.spread_by_field == SPREAD_BY_FIELD_REGION and not cluster.spec.region:
            return "cluster(s) did not have region property"
        if sc.spread_by_field == SPREAD_BY_FIELD_ZONE and not cluster.zones_effective():
            return "cluster(s) did not have zones property"
        if sc.spread_by_label and not cluster.metadata.labels.get(
            sc.spread_by_label
        ):
            return "cluster(s) did not have spread label " + sc.spread_by_label
    return None


def filter_cluster_eviction(
    spec: ResourceBindingSpec, status: ResourceBindingStatus, cluster: Cluster
) -> Optional[str]:
    if any(t.from_cluster == cluster.name for t in spec.graceful_eviction_tasks):
        return "cluster(s) is in the process of eviction"
    return None


FILTER_PLUGINS: List[Tuple[str, Callable]] = [
    ("APIEnablement", filter_api_enablement),
    ("TaintToleration", filter_taint_toleration),
    ("ClusterAffinity", filter_cluster_affinity),
    ("SpreadConstraint", filter_spread_constraint),
    ("ClusterEviction", filter_cluster_eviction),
]


def effective_placement(
    spec: ResourceBindingSpec, status: ResourceBindingStatus
) -> Placement:
    """Resolve ClusterAffinities terms to the scheduler-observed one; the
    single placement object out-of-tree plugins see on EVERY backend."""
    placement = spec.placement or Placement()
    if placement.cluster_affinity is not None or not placement.cluster_affinities:
        return placement
    affinity = None
    for term in placement.cluster_affinities:
        if term.affinity_name == status.scheduler_observed_affinity_name:
            affinity = term.affinity
            break
    return Placement(
        cluster_affinity=affinity,
        cluster_tolerations=placement.cluster_tolerations,
        spread_constraints=placement.spread_constraints,
        replica_scheduling=placement.replica_scheduling,
    )


def find_clusters_that_fit(
    spec: ResourceBindingSpec,
    status: ResourceBindingStatus,
    clusters: List[Cluster],
) -> Tuple[List[Cluster], Dict[str, str]]:
    """generic_scheduler.go:119-152 (deleting clusters skipped; unhealthy
    clusters are NOT filtered — users opt in via tolerations).  In-tree
    filters run first, then enabled out-of-tree registry filters
    (framework/runtime/registry.go), first rejection wins."""
    from karmada_tpu.scheduler.plugins import REGISTRY, eval_filters

    feasible: List[Cluster] = []
    diagnosis: Dict[str, str] = {}
    extra = REGISTRY.enabled_filters()
    eff = effective_placement(spec, status) if extra else None
    for cluster in clusters:
        if cluster.metadata.deleting:
            continue
        reason = None
        for _, plugin in FILTER_PLUGINS:
            reason = plugin(spec, status, cluster)
            if reason is not None:
                break
        if reason is None and extra:
            reason = eval_filters(extra, eff, cluster)
        if reason is None:
            feasible.append(cluster)
        else:
            diagnosis[cluster.name] = reason
    return feasible, diagnosis


# ---------------------------------------------------------------------------
# Score plugins
# ---------------------------------------------------------------------------


def score_cluster_locality(spec: ResourceBindingSpec, cluster: Cluster) -> int:
    if not spec.clusters:
        return MIN_CLUSTER_SCORE
    if spec.target_contains(cluster.name):
        return MAX_CLUSTER_SCORE
    return MIN_CLUSTER_SCORE


def prioritize_clusters(
    spec: ResourceBindingSpec, clusters: List[Cluster],
    status: Optional[ResourceBindingStatus] = None,
) -> List[Tuple[Cluster, int]]:
    """Sum of score plugins per cluster (generic_scheduler.go:155-183).
    In-tree scorers: ClusterAffinity (always 0) + ClusterLocality; enabled
    out-of-tree registry scores add on top (clamped sum, see
    scheduler/plugins.py)."""
    from karmada_tpu.scheduler.plugins import REGISTRY, eval_scores

    scorers = REGISTRY.enabled_scores()
    if not scorers:
        return [(c, MIN_CLUSTER_SCORE + score_cluster_locality(spec, c))
                for c in clusters]
    eff = effective_placement(spec, status or ResourceBindingStatus())
    return [
        (c, MIN_CLUSTER_SCORE + score_cluster_locality(spec, c)
         + eval_scores(scorers, eff, c))
        for c in clusters
    ]


# ---------------------------------------------------------------------------
# Spread-constraint grouping + selection (pkg/scheduler/core/spreadconstraint)
# ---------------------------------------------------------------------------


@dataclass
class ClusterDetailInfo:
    name: str
    score: int
    available_replicas: int  # includes already-assigned replicas
    allocatable_replicas: int  # estimator output alone
    cluster: Cluster


@dataclass
class GroupInfo:
    name: str
    score: int = 0
    available_replicas: int = 0
    clusters: List[ClusterDetailInfo] = field(default_factory=list)
    zones: set = field(default_factory=set)
    regions: set = field(default_factory=set)


@dataclass
class GroupClustersInfo:
    clusters: List[ClusterDetailInfo] = field(default_factory=list)
    providers: Dict[str, GroupInfo] = field(default_factory=dict)
    regions: Dict[str, GroupInfo] = field(default_factory=dict)
    zones: Dict[str, GroupInfo] = field(default_factory=dict)
    # spread-by-label groups (label VALUE -> group) for the placement's
    # first label constraint's key — this framework's extension beyond the
    # reference, whose scheduler never implemented SpreadByLabel
    # (select_clusters.go:55 fails it); group math mirrors regions
    labels: Dict[str, GroupInfo] = field(default_factory=dict)


def _sort_clusters(infos: List[ClusterDetailInfo]) -> None:
    """spreadconstraint/util.go sortClusters: score desc, available desc, name asc."""
    infos.sort(key=lambda c: (-c.score, -c.available_replicas, c.name))


def _label_constraint(placement: Placement) -> Optional[SpreadConstraint]:
    """First spread-by-label constraint — its key is the group axis
    (further label constraints filter only; ops/tensors.spread_axis_of)."""
    for sc in placement.spread_constraints:
        if sc.spread_by_label:
            return sc
    return None


def _spread_constraint(placement: Placement, by_field: str) -> Optional[SpreadConstraint]:
    for sc in placement.spread_constraints:
        if sc.spread_by_field == by_field:
            return sc
    return None


def should_ignore_spread_constraint(placement: Placement) -> bool:
    """select_clusters.go:57-69: static-weighted division ignores spread."""
    s = placement.replica_scheduling
    if (
        s is not None
        and s.replica_scheduling_type == REPLICA_SCHEDULING_DIVIDED
        and s.replica_division_preference == REPLICA_DIVISION_WEIGHTED
        and (
            s.weight_preference is None
            or (s.weight_preference.static_weight_list and not s.weight_preference.dynamic_weight)
        )
    ):
        return True
    return False


def should_ignore_available_resource(placement: Placement) -> bool:
    """select_clusters.go:71-80: Duplicated ignores capacity."""
    s = placement.replica_scheduling
    return s is None or s.replica_scheduling_type == REPLICA_SCHEDULING_DUPLICATED


def _is_topology_ignored(placement: Placement) -> bool:
    scs = placement.spread_constraints
    if not scs or (len(scs) == 1 and scs[0].spread_by_field == SPREAD_BY_FIELD_CLUSTER):
        return True
    return should_ignore_spread_constraint(placement)


def _calc_group_score_duplicate(
    clusters: List[ClusterDetailInfo], spec: ResourceBindingSpec
) -> int:
    """group_clusters.go:141-218."""
    target = spec.replicas
    valid = [c for c in clusters if c.available_replicas >= target]
    if not valid:
        return 0  # no valid cluster: validClusters==0 would divide by zero; score 0
    sum_valid_score = sum(c.score for c in valid)
    return len(valid) * WEIGHT_UNIT + sum_valid_score // len(valid)


def _calc_group_score(
    clusters: List[ClusterDetailInfo], spec: ResourceBindingSpec, min_groups: int
) -> int:
    """group_clusters.go:220-333."""
    placement = spec.placement
    if placement is None or placement.replica_scheduling_type() == REPLICA_SCHEDULING_DUPLICATED:
        return _calc_group_score_duplicate(clusters, spec)

    target = math.ceil(spec.replicas / float(min_groups)) if min_groups else spec.replicas
    cluster_min_groups = 0
    sc = _spread_constraint(placement, SPREAD_BY_FIELD_CLUSTER)
    if sc is not None:
        cluster_min_groups = sc.min_groups
    cluster_min_groups = max(cluster_min_groups, min_groups)

    sum_available = 0
    sum_score = 0
    valid = 0
    for c in clusters:  # clusters pre-sorted score desc
        sum_available += c.available_replicas
        sum_score += c.score
        valid += 1
        if valid >= cluster_min_groups and sum_available >= target:
            break
    if sum_available < target:
        return sum_available * WEIGHT_UNIT + sum_score // len(clusters)
    return target * WEIGHT_UNIT + sum_score // valid


def group_clusters_with_score(
    scored: List[Tuple[Cluster, int]],
    placement: Placement,
    spec: ResourceBindingSpec,
    cal_available: Callable[[List[Cluster], ResourceBindingSpec], List[TargetCluster]],
) -> GroupClustersInfo:
    """group_clusters.go:91-122 + generateClustersInfo/Zone/Region/Provider."""
    info = GroupClustersInfo()
    clusters = [c for c, _ in scored]
    replicas = cal_available(clusters, spec)
    for (cluster, score), tc in zip(scored, replicas):
        avail = tc.replicas + spec.assigned_replicas_for_cluster(tc.name)
        info.clusters.append(
            ClusterDetailInfo(
                name=cluster.name,
                score=score,
                available_replicas=avail,
                allocatable_replicas=tc.replicas,
                cluster=cluster,
            )
        )
    _sort_clusters(info.clusters)

    if _is_topology_ignored(placement):
        return info

    # zones
    if _spread_constraint(placement, SPREAD_BY_FIELD_ZONE) is not None:
        for ci in info.clusters:
            for zone in ci.cluster.zones_effective():
                g = info.zones.setdefault(zone, GroupInfo(name=zone))
                g.clusters.append(ci)
                g.available_replicas += ci.available_replicas
        mg = _spread_constraint(placement, SPREAD_BY_FIELD_ZONE).min_groups
        for g in info.zones.values():
            g.score = _calc_group_score(g.clusters, spec, mg)

    # regions
    if _spread_constraint(placement, SPREAD_BY_FIELD_REGION) is not None:
        for ci in info.clusters:
            region = ci.cluster.spec.region
            if not region:
                continue
            g = info.regions.setdefault(region, GroupInfo(name=region))
            if ci.cluster.spec.zone:
                g.zones.add(ci.cluster.spec.zone)
            g.clusters.append(ci)
            g.available_replicas += ci.available_replicas
        mg = _spread_constraint(placement, SPREAD_BY_FIELD_REGION).min_groups
        for g in info.regions.values():
            g.score = _calc_group_score(g.clusters, spec, mg)

    # label values (framework extension; group math mirrors regions)
    label_sc = _label_constraint(placement)
    if label_sc is not None:
        for ci in info.clusters:
            value = ci.cluster.metadata.labels.get(label_sc.spread_by_label)
            if not value:
                continue
            g = info.labels.setdefault(value, GroupInfo(name=value))
            g.clusters.append(ci)
            g.available_replicas += ci.available_replicas
        for g in info.labels.values():
            g.score = _calc_group_score(g.clusters, spec, label_sc.min_groups)

    # providers
    if _spread_constraint(placement, SPREAD_BY_FIELD_PROVIDER) is not None:
        for ci in info.clusters:
            provider = ci.cluster.spec.provider
            if not provider:
                continue
            g = info.providers.setdefault(provider, GroupInfo(name=provider))
            if ci.cluster.spec.zone:
                g.zones.add(ci.cluster.spec.zone)
            if ci.cluster.spec.region:
                g.regions.add(ci.cluster.spec.region)
            g.clusters.append(ci)
            g.available_replicas += ci.available_replicas
        mg = _spread_constraint(placement, SPREAD_BY_FIELD_PROVIDER).min_groups
        for g in info.providers.values():
            g.score = _calc_group_score(g.clusters, spec, mg)

    return info


# --- findFeasiblePaths DFS (select_groups.go:102-224) ----------------------


@dataclass
class _DfsGroup:
    name: str
    value: int  # e.g. number of clusters in the region
    weight: int  # group score


def select_groups(
    groups: List[_DfsGroup], min_constraint: int, max_constraint: int, target: int
) -> List[_DfsGroup]:
    """Port of selectGroups/findFeasiblePaths/prioritizePaths."""
    if not groups:
        return []
    groups = sorted(groups, key=lambda g: (g.value, -g.weight, g.name))

    paths: List[dict] = []  # {"id", "groups", "weight", "value"}
    current: List[_DfsGroup] = []
    counter = {"id": 0}

    def record() -> None:
        counter["id"] += 1
        gs = sorted(current, key=lambda g: (-g.weight, g.name))
        paths.append(
            {
                "id": counter["id"],
                "groups": gs,
                "weight": sum(g.weight for g in gs),
                "value": sum(g.value for g in gs),
            }
        )

    def dfs(total: int, begin: int) -> None:
        if total >= target and min_constraint <= len(current) <= max_constraint:
            record()
            return
        if len(current) >= max_constraint:
            return
        for i in range(begin, len(groups)):
            current.append(groups[i])
            dfs(total + groups[i].value, i + 1)
            if len(groups) == min_constraint:
                break
            current.pop()

    dfs(0, 0)
    if not paths:
        return []
    if len(paths) == 1:
        return paths[0]["groups"]

    paths.sort(key=lambda p: (-p["weight"], -p["value"], p["id"]))
    final = paths[0]

    def match_sub_path(path: dict, sub: dict) -> bool:
        if len(sub["groups"]) >= len(path["groups"]):
            return False
        return all(
            path["groups"][i].name == g.name for i, g in enumerate(sub["groups"])
        )

    for p in paths[1:]:
        if match_sub_path(final, p):
            final = p
    return final["groups"]


# --- SelectBestClusters (select_clusters*.go) -------------------------------


def select_best_clusters(
    placement: Placement, info: GroupClustersInfo, need_replicas: int
) -> List[ClusterDetailInfo]:
    if not placement.spread_constraints or should_ignore_spread_constraint(placement):
        return info.clusters
    if should_ignore_available_resource(placement):
        need_replicas = INVALID_REPLICAS
    sc_map = {sc.spread_by_field: sc for sc in placement.spread_constraints}
    if SPREAD_BY_FIELD_REGION in sc_map:
        return _select_by_region(sc_map, info)
    label_sc = _label_constraint(placement)
    if label_sc is not None:
        # framework extension: label-value groups select exactly like
        # regions (the reference fails SpreadByLabel outright)
        return _select_by_groups(
            label_sc,
            sc_map.get(SPREAD_BY_FIELD_CLUSTER, SpreadConstraint()),
            info.labels,
        )
    if SPREAD_BY_FIELD_CLUSTER in sc_map:
        return _select_by_cluster(sc_map[SPREAD_BY_FIELD_CLUSTER], info, need_replicas)
    raise UnschedulableError("just support cluster and region spread constraint")


def _select_by_cluster(
    sc: SpreadConstraint, info: GroupClustersInfo, need_replicas: int
) -> List[ClusterDetailInfo]:
    """select_clusters_by_cluster.go:25-105."""
    total = len(info.clusters)
    if total < sc.min_groups:
        raise UnschedulableError(
            "the number of feasible clusters is less than spreadConstraint.MinGroups"
        )
    # mirror select_clusters_by_cluster.go:32-35 exactly (MaxGroups is
    # validated >= MinGroups >= 1 upstream; 0 selects nothing, as in Go)
    need_cnt = sc.max_groups if total >= sc.max_groups else total
    if need_replicas == INVALID_REPLICAS:
        return info.clusters[:need_cnt]
    selected = _select_by_available_resource(list(info.clusters), need_cnt, need_replicas)
    if not selected:
        raise UnschedulableError(f"no enough resource when selecting {need_cnt} clusters")
    return selected


def _select_by_available_resource(
    candidates: List[ClusterDetailInfo], need_cnt: int, need_replicas: int
) -> List[ClusterDetailInfo]:
    ret = candidates[:need_cnt]
    rest = candidates[need_cnt:]

    def total_avail(cs: List[ClusterDetailInfo]) -> int:
        return sum(c.available_replicas for c in cs)

    update_id = len(ret) - 1
    while total_avail(ret) < need_replicas and update_id >= 0:
        # replace lowest-score retained cluster with the best remaining one
        best_id, best_avail = -1, ret[update_id].available_replicas
        for i, c in enumerate(rest):
            if c.available_replicas > best_avail:
                best_id, best_avail = i, c.available_replicas
        if best_id == -1:
            update_id -= 1
            continue
        ret[update_id], rest[best_id] = rest[best_id], ret[update_id]
        update_id -= 1
    if total_avail(ret) < need_replicas:
        return []
    return ret


def _select_by_region(
    sc_map: Dict[str, SpreadConstraint], info: GroupClustersInfo
) -> List[ClusterDetailInfo]:
    """select_clusters_by_region.go:27-118."""
    return _select_by_groups(
        sc_map[SPREAD_BY_FIELD_REGION],
        sc_map.get(SPREAD_BY_FIELD_CLUSTER, SpreadConstraint()),
        info.regions,
    )


def _select_by_groups(
    group_sc: SpreadConstraint,
    cluster_sc: SpreadConstraint,
    groups_map: Dict[str, GroupInfo],
) -> List[ClusterDetailInfo]:
    """select_clusters_by_region.go:27-118, generalized over any group map
    (regions, or label-value groups — the framework's SpreadByLabel
    extension reuses the identical selection)."""
    if len(groups_map) < group_sc.min_groups:
        raise UnschedulableError(
            "the number of feasible region is less than spreadConstraint.MinGroups"
        )
    groups = [
        _DfsGroup(name=g.name, value=len(g.clusters), weight=g.score)
        for g in groups_map.values()
    ]
    chosen = select_groups(
        groups, group_sc.min_groups, group_sc.max_groups, cluster_sc.min_groups
    )
    if not chosen:
        raise UnschedulableError(
            "the number of clusters is less than the cluster spreadConstraint.MinGroups"
        )
    picked = [groups_map[g.name] for g in chosen]
    selected: List[ClusterDetailInfo] = []
    candidates: List[ClusterDetailInfo] = []
    for r in picked:
        selected.append(r.clusters[0])
        candidates.extend(r.clusters[1:])
    need_cnt = len(candidates) + len(selected)
    # absent cluster constraint zero-values MaxGroups, capping extras to none
    # (select_clusters_by_region.go:49-52)
    if need_cnt > cluster_sc.max_groups:
        need_cnt = cluster_sc.max_groups
    rest_cnt = need_cnt - len(selected)
    if rest_cnt > 0:
        _sort_clusters(candidates)
        selected.extend(candidates[:rest_cnt])
    return selected


# ---------------------------------------------------------------------------
# Replica assignment (assignment.go + division_algorithm.go)
# ---------------------------------------------------------------------------

DUPLICATED = "Duplicated"
AGGREGATED = "Aggregated"
STATIC_WEIGHT = "StaticWeight"
DYNAMIC_WEIGHT = "DynamicWeight"

STEADY = "Steady"
FRESH = "Fresh"


def strategy_type(spec: ResourceBindingSpec) -> str:
    placement = spec.placement or Placement()
    if placement.replica_scheduling_type() == REPLICA_SCHEDULING_DUPLICATED:
        return DUPLICATED
    s = placement.replica_scheduling
    if s.replica_division_preference == REPLICA_DIVISION_AGGREGATED:
        return AGGREGATED
    if s.replica_division_preference == REPLICA_DIVISION_WEIGHTED:
        if s.weight_preference is not None and s.weight_preference.dynamic_weight:
            return DYNAMIC_WEIGHT
        return STATIC_WEIGHT
    return ""


def reschedule_required(spec: ResourceBindingSpec, status: ResourceBindingStatus) -> bool:
    """util.RescheduleRequired: a newer rescheduleTriggeredAt than the last
    schedule forces Fresh mode."""
    if spec.reschedule_triggered_at is None:
        return False
    if status.last_scheduled_time is None:
        return True
    return spec.reschedule_triggered_at > status.last_scheduled_time


@dataclass
class _AssignState:
    candidates: List[ClusterDetailInfo]
    spec: ResourceBindingSpec
    strategy: str
    mode: str
    scheduled: List[TargetCluster] = field(default_factory=list)
    assigned: int = 0
    available: List[TargetCluster] = field(default_factory=list)
    available_sum: int = 0
    target: int = 0

    def build_scheduled(self) -> None:
        names = {c.name for c in self.candidates}
        self.scheduled = [tc for tc in self.spec.clusters if tc.name in names]
        self.assigned = get_sum_of_replicas(self.scheduled)

    def resort_available(self) -> List[TargetCluster]:
        """assignment.go:145-172: previously scheduled clusters first."""
        prior = {tc.name for tc in self.scheduled if tc.replicas > 0}
        if not prior:
            return self.available
        prev = [tc for tc in self.available if tc.name in prior]
        left = [tc for tc in self.available if tc.name not in prior]
        self.available = prev + left
        return self.available


def _sort_by_replicas_desc(tcs: List[TargetCluster]) -> List[TargetCluster]:
    """TargetClustersList sort (division_algorithm.go:31-36). Stable on name
    for determinism where Go's unstable sort leaves ties unspecified."""
    return sorted(tcs, key=lambda tc: (-tc.replicas, tc.name))


def _static_weight_list(
    candidates: List[ClusterDetailInfo],
    weight_list,
) -> Dict[str, int]:
    """getStaticWeightInfoList (division_algorithm.go:38-72)."""
    weights: Dict[str, int] = {}
    for c in candidates:
        weight = 0
        for rule in weight_list:
            if rule.target_cluster.matches(c.cluster):
                weight = max(weight, rule.weight)
        if weight > 0:
            weights[c.name] = weight
    if sum(weights.values()) == 0:
        return {c.name: 1 for c in candidates}
    return weights


def _dynamic_divide(state: _AssignState) -> List[TargetCluster]:
    """dynamicDivideReplicas (division_algorithm.go:75-101)."""
    if state.available_sum < state.target:
        raise UnschedulableError(
            f"Clusters available replicas {state.available_sum} are not enough to schedule."
        )
    if state.strategy == AGGREGATED:
        state.available = state.resort_available()
        total = 0
        for i, tc in enumerate(state.available):
            total += tc.replicas
            if total >= state.target:
                state.available = state.available[: i + 1]
                break
    weights = {tc.name: tc.replicas for tc in state.available}
    result = dispense_by_weight(state.target, weights, None, state.spec.resource.uid)
    new = [TargetCluster(name=n, replicas=r) for n, r in sorted(result.items())]
    return merge_target_clusters(state.scheduled, new)


def assign_replicas(
    candidates: List[ClusterDetailInfo],
    spec: ResourceBindingSpec,
    status: ResourceBindingStatus,
) -> List[TargetCluster]:
    """AssignReplicas (core/common.go:40-78 + assignment.go strategies)."""
    if not candidates:
        raise NoClusterAvailableError("no clusters available to schedule")

    if not ((spec.replicas > 0 or spec.replica_requirements is not None) and len(spec.components) <= 1):
        # non-workloads & multi-component: propagate to all candidates
        return [TargetCluster(name=c.name, replicas=0) for c in candidates]

    strategy = strategy_type(spec)
    mode = FRESH if reschedule_required(spec, status) else STEADY
    state = _AssignState(candidates=candidates, spec=spec, strategy=strategy, mode=mode)

    if strategy == DUPLICATED:
        result = [TargetCluster(name=c.name, replicas=spec.replicas) for c in candidates]
    elif strategy == STATIC_WEIGHT:
        placement = spec.placement
        wp = placement.replica_scheduling.weight_preference
        weight_list = wp.static_weight_list if wp is not None else []
        if not weight_list:
            # defaulting: weight all candidates equally (assignment.go:196-198)
            weights = {c.name: 1 for c in candidates}
        else:
            weights = _static_weight_list(candidates, weight_list)
        result_map = dispense_by_weight(spec.replicas, weights, None, spec.resource.uid)
        result = [TargetCluster(name=n, replicas=r) for n, r in sorted(result_map.items())]
    elif strategy in (AGGREGATED, DYNAMIC_WEIGHT):
        result = _assign_dynamic(state)
    else:
        raise UnschedulableError(f"unsupported replica scheduling strategy: {strategy}")

    return [tc for tc in result if tc.replicas > 0]


def _assign_dynamic(state: _AssignState) -> List[TargetCluster]:
    """assignByDynamicStrategy (assignment.go:207-238)."""
    state.build_scheduled()
    spec = state.spec
    if state.mode == FRESH:
        return _dynamic_fresh_scale(state)
    if state.assigned > spec.replicas:
        return _dynamic_scale_down(state)
    if state.assigned < spec.replicas:
        return _dynamic_scale_up(state)
    return state.scheduled


def _dynamic_scale_down(state: _AssignState) -> List[TargetCluster]:
    """division_algorithm.go:103-119: previous result becomes the weights."""
    state.target = state.spec.replicas
    state.scheduled = []
    state.available = _sort_by_replicas_desc(list(state.spec.clusters))
    state.available_sum = get_sum_of_replicas(state.available)
    return _dynamic_divide(state)


def _dynamic_scale_up(state: _AssignState) -> List[TargetCluster]:
    """division_algorithm.go:121-136: weights = allocatable, merge with prior."""
    state.target = state.spec.replicas - state.assigned
    avail = [
        TargetCluster(name=c.name, replicas=c.allocatable_replicas)
        for c in state.candidates
    ]
    state.available = _sort_by_replicas_desc(avail)
    state.available_sum = get_sum_of_replicas(state.available)
    return _dynamic_divide(state)


def _dynamic_fresh_scale(state: _AssignState) -> List[TargetCluster]:
    """division_algorithm.go:139-166: allocatable + currently-assigned."""
    state.target = state.spec.replicas
    scheduled_by_name = {tc.name: tc.replicas for tc in state.scheduled}
    avail = [
        TargetCluster(
            name=c.name,
            replicas=c.allocatable_replicas + scheduled_by_name.get(c.name, 0),
        )
        for c in state.candidates
    ]
    state.available = _sort_by_replicas_desc(avail)
    state.available_sum = get_sum_of_replicas(state.available)
    state.scheduled = []
    return _dynamic_divide(state)


# ---------------------------------------------------------------------------
# Full pipeline
# ---------------------------------------------------------------------------


def is_multi_template_applicable(spec: ResourceBindingSpec) -> bool:
    """isMultiTemplateSchedulingApplicable (core/estimation.go:42-64): two or
    more components AND a Cluster spread constraint targeting exactly one
    cluster (MinGroups == MaxGroups == 1)."""
    if len(spec.components) < 2 or spec.placement is None:
        return False
    from karmada_tpu.models.policy import SPREAD_BY_FIELD_CLUSTER

    for sc in spec.placement.spread_constraints:
        if (
            sc.spread_by_field == SPREAD_BY_FIELD_CLUSTER
            and sc.min_groups == 1
            and sc.max_groups == 1
        ):
            return True
    return False


def make_cal_available(estimators) -> Callable:
    """calAvailableReplicas (core/util.go:56-110): min across estimators,
    skipping UnauthenticReplica; non-workloads shortcut to MaxInt32.  Multi-
    template workloads (feature MultiplePodTemplatesScheduling) estimate
    whole component SETS instead (calculateMultiTemplateAvailableSets,
    estimation.go:66-103)."""

    def cal(clusters: List[Cluster], spec: ResourceBindingSpec) -> List[TargetCluster]:
        out = [TargetCluster(name=c.name, replicas=MAX_INT32) for c in clusters]
        if spec.replicas == 0 and not spec.components:
            return out
        multi_template = is_multi_template_applicable(spec)
        ests = list(estimators)
        if multi_template and not any(
            hasattr(e, "max_available_component_sets") for e in ests
        ):
            # never silently skip capacity checking: the reference registry
            # always contains the GeneralEstimator (which implements
            # MaxAvailableComponentSets); mirror that as a fallback when the
            # caller supplied only replica-style estimators
            from karmada_tpu.estimator.general import GeneralEstimator

            ests.append(GeneralEstimator())
        for est in ests:
            if multi_template:
                if not hasattr(est, "max_available_component_sets"):
                    continue
                res = est.max_available_component_sets(clusters, spec.components)
            else:
                res = est.max_available_replicas(clusters, spec.replica_requirements)
            for i, tc in enumerate(res):
                if tc.replicas == -1:
                    continue
                if out[i].name == tc.name and out[i].replicas > tc.replicas:
                    out[i].replicas = tc.replicas
        # leftover MaxInt32 (no estimator authenticated a value) clamps to
        # spec.replicas to avoid overflow (core/util.go:104-109)
        for tc in out:
            if tc.replicas == MAX_INT32:
                tc.replicas = spec.replicas
        return out

    return cal


def schedule(
    spec: ResourceBindingSpec,
    status: ResourceBindingStatus,
    clusters: List[Cluster],
    cal_available: Callable[[List[Cluster], ResourceBindingSpec], List[TargetCluster]],
    *,
    enable_empty_workload_propagation: bool = False,
) -> List[TargetCluster]:
    """genericScheduler.Schedule (generic_scheduler.go:71-116)."""
    placement = spec.placement or Placement()
    feasible, diagnosis = find_clusters_that_fit(spec, status, clusters)
    if not feasible:
        raise FitError(diagnosis)
    scored = prioritize_clusters(spec, feasible, status)
    info = group_clusters_with_score(scored, placement, spec, cal_available)
    selected = select_best_clusters(placement, info, spec.replicas)
    result = assign_replicas(selected, spec, status)
    if enable_empty_workload_propagation:
        names = {tc.name for tc in result}
        result = result + [
            TargetCluster(name=c.name, replicas=0)
            for c in selected
            if c.name not in names
        ]
    return result
