"""Webster (Sainte-Laguë) proportional seat allocation — exact golden path.

Port of reference pkg/util/helper/webstermethod.go:112 (AllocateWebsterSeats)
and pkg/util/helper/binding.go:70-183 (Dispenser + UID tiebreaker):

  * one seat at a time to the party with the highest priority
    votes/(2*seats+1);
  * ties: fewer seats wins, then lexicographically smaller (or larger, when
    fnv32a(uid) is odd) name wins;
  * parties only present in the initial assignment keep their seats with
    zero votes.

Priority arithmetic: the Go reference compares float64 quotients
(webstermethod.go:131).  This framework instead defines the priority as the
QUANTIZED INTEGER  (votes << PRIORITY_QBITS) // (2*seats + 1)  — exact,
platform-independent integer math with 2^-28 relative resolution.  The TPU
kernel (ops/solver.py) computes the identical quantity in int64, so serial
and device paths agree bit-for-bit with no float in either.  Behavior
diverges from the Go float64 path only when two priorities collide within
one quantum (then the seats/name tiebreak decides instead of the 53-bit
mantissa) — strictly tighter determinism than the reference's.
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

# Quantization of the Webster priority votes/(2*seats+1): both the serial
# heap below and the TPU kernel (ops/solver.webster_divide) compare
# (votes << PRIORITY_QBITS) // (2*seats + 1) as integers.  28 bits keeps
# votes << 28 within int64 for votes < 2^34 (capacity values are clamped to
# MaxInt32 upstream).
PRIORITY_QBITS = 28


def priority_quantized(votes: int, seats: int) -> int:
    """The framework's Webster priority: integer-quantized votes/(2s+1)."""
    return (max(int(votes), 0) << PRIORITY_QBITS) // (2 * int(seats) + 1)


def fnv32a(data: str) -> int:
    """FNV-1a 32-bit (hash/fnv New32a), used for the UID tiebreak direction."""
    h = 0x811C9DC5
    for b in data.encode("utf-8"):
        h ^= b
        h = (h * 0x01000193) & 0xFFFFFFFF
    return h


def tiebreak_descending_by_uid(uid: str) -> bool:
    """binding.go:117-144 — odd fnv32a(uid) flips name order to descending."""
    if not uid:
        return False
    return bool(fnv32a(uid) & 1)


@dataclass
class Party:
    name: str
    votes: int
    seats: int


class _NameKey:
    """Orders names ascending or descending under heapq's min-ordering."""

    __slots__ = ("name", "desc")

    def __init__(self, name: str, desc: bool) -> None:
        self.name = name
        self.desc = desc

    def __lt__(self, other: "_NameKey") -> bool:
        return self.name > other.name if self.desc else self.name < other.name

    def __eq__(self, other: object) -> bool:
        return isinstance(other, _NameKey) and other.name == self.name


def allocate_webster_seats(
    new_seats: int,
    party_votes: Dict[str, int],
    initial_assignments: Optional[Dict[str, int]] = None,
    name_descending: bool = False,
) -> List[Party]:
    """Allocate `new_seats` additional seats; returns parties sorted by name.

    Matches AllocateWebsterSeats (webstermethod.go:112-161) with the
    Dispenser's UID tiebreaker (seats asc, then name asc/desc). The default
    tiebreaker in the reference reduces to name-ascending, so
    `name_descending=False` also covers the nil-tiebreaker case.
    """
    parties: Dict[str, Party] = {}
    for n, s in (initial_assignments or {}).items():
        parties[n] = Party(name=n, votes=0, seats=int(s))
    for n, v in party_votes.items():
        if n in parties:
            parties[n].votes = int(v)
        else:
            parties[n] = Party(name=n, votes=int(v), seats=0)
    if not parties:
        return []

    # heap entries: (-quantized_priority, seats, name_key, name)
    def entry(p: Party):
        prio = priority_quantized(p.votes, p.seats)
        return (-prio, p.seats, _NameKey(p.name, name_descending), p.name)

    heap = [entry(p) for p in parties.values()]
    heapq.heapify(heap)
    for _ in range(int(new_seats)):
        _, _, _, name = heapq.heappop(heap)
        p = parties[name]
        p.seats += 1
        heapq.heappush(heap, entry(p))

    return sorted(parties.values(), key=lambda p: p.name)


def dispense_by_weight(
    num_replicas: int,
    weights: Dict[str, int],
    init: Optional[Dict[str, int]] = None,
    uid: str = "",
) -> Dict[str, int]:
    """Dispenser.AllocateByWeight (binding.go:94-115): returns name→seats
    including initial seats. A zero weight sum leaves the initial result."""
    init = dict(init or {})
    if num_replicas == 0 and init:
        return init
    if sum(weights.values()) == 0:
        return init
    parties = allocate_webster_seats(
        num_replicas, weights, init, tiebreak_descending_by_uid(uid)
    )
    return {p.name: p.seats for p in parties}


def fnv32a_batch_odd(uids):
    """Vectorized tiebreak_descending_by_uid over a batch: bool[n] of
    fnv32a(uid) & 1, with empty uids False (webster.py:52-57 semantics).
    One numpy pass per character column instead of a Python loop per byte."""
    n = len(uids)
    bs = [u.encode("utf-8") for u in uids]
    lens = np.fromiter((len(x) for x in bs), np.int64, n)
    L = int(lens.max()) if n else 0
    if L == 0:
        return np.zeros(n, bool)
    flat = np.frombuffer(b"".join(bs), np.uint8)
    starts = np.zeros(n + 1, np.int64)
    np.cumsum(lens, out=starts[1:])
    h = np.full(n, 0x811C9DC5, np.uint64)
    idx0 = starts[:-1]
    for j in range(L):
        valid = lens > j
        c = np.zeros(n, np.uint64)
        c[valid] = flat[idx0[valid] + j]
        hv = (h ^ c) * np.uint64(0x01000193) & np.uint64(0xFFFFFFFF)
        h = np.where(valid, hv, h)
    return ((h & np.uint64(1)).astype(bool)) & (lens > 0)
