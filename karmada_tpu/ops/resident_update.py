"""Jitted scatter-update kernels for the resident-state plane.

The resident plane (karmada_tpu/resident/state.py) keeps the cluster-side
solver tensors device-resident BETWEEN scheduling cycles; watch-event
deltas touch a handful of cluster lanes per cycle, so advancing the
device mirrors is a scatter of the churned rows/columns, not a re-upload
of the whole ~5MB tensor set.  These are the only entrypoints that
mutate resident device state:

  scatter_rows(dst, lanes, rows)   dst[lanes, ...] = rows   (axis-0 lead:
                                   the [C]- and [C, R]-shaped capacity
                                   tensors — avail_milli, has_alloc,
                                   pods_allowed, has_summary, deleting)
  scatter_cols(dst, lanes, cols)   dst[:, lanes] = cols     (axis-1 lead:
                                   the [Q, C] / [G, C] planes —
                                   est_override, api_ok)

Both donate `dst`, so on backends that support donation the update is
in place (the old buffer is consumed); on CPU jax falls back to a
device-side copy, which still beats the host->device re-upload.  Callers
pad `lanes` to a pow2 bucket (karmada_tpu/resident/state.py) so the jit
signature set stays bounded — duplicate lanes in the pad carry the same
row and are therefore order-safe for `.at[].set`.

Trace-safety: pure gather/scatter — no Python control flow on traced
values, no host syncs, no dtype-defaulted constructors (the kernels
construct nothing; dtypes ride in on the operands, which the resident
plane builds against ops/tensors.FIELD_DTYPES).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, donate_argnums=(0,))
def scatter_rows(dst, lanes, rows):
    """dst[lanes, ...] = rows, donated (in place where supported)."""
    return dst.at[lanes].set(rows)


@partial(jax.jit, donate_argnums=(0,))
def scatter_cols(dst, lanes, cols):
    """dst[:, lanes] = cols, donated (in place where supported)."""
    return dst.at[:, lanes].set(cols)


@jax.jit
def scatter_rows_cow(dst, lanes, rows):
    """dst[lanes, ...] = rows WITHOUT donating dst (device-side
    copy-on-write).  The fused gather path (ops/resident_gather) uses
    this for the binding-row slot store: the previous chunk's async
    gather may still hold the mirror as an in-flight input, and donating
    a buffer with pending consumers stalls the dispatching host thread
    until they drain — measured as ~60ms/chunk of encode-stage stall on
    XLA:CPU.  The copy costs one allocation; the old buffer is dropped
    by the caller's mirror-table swap as soon as its readers finish."""
    return dst.at[lanes].set(rows)


def _pad(lanes, data, lane_axis: int):
    """Pow2-bucket a (lanes, data) scatter so the jit signature set stays
    bounded (same bucketing as tensors._next_pow2, floor 8): the pad
    repeats the LAST lane/value pair, which is a no-op rewrite of the
    same values.  Host-side helper (numpy in, numpy out)."""
    import numpy as np

    from karmada_tpu.ops.tensors import _next_pow2

    k = len(lanes)
    cap = _next_pow2(k, 8)
    data = np.asarray(data)
    if cap == k:
        return np.asarray(lanes), data
    lanes2 = np.empty(cap, np.int64)
    lanes2[:k] = lanes
    lanes2[k:] = lanes[-1]
    shape = list(data.shape)
    shape[lane_axis] = cap
    data2 = np.empty(tuple(shape), data.dtype)
    src = [slice(None)] * data.ndim
    src[lane_axis] = slice(0, k)
    pad = [slice(None)] * data.ndim
    pad[lane_axis] = slice(k, None)
    last = [slice(None)] * data.ndim
    last[lane_axis] = slice(k - 1, k)
    data2[tuple(src)] = data
    data2[tuple(pad)] = data[tuple(last)]
    return lanes2, data2


def pad_lanes(lanes, rows):
    """Pad a row scatter (rows carry the lane axis FIRST)."""
    return _pad(lanes, rows, 0)


def pad_lanes_cols(lanes, cols):
    """Pad a column scatter (cols carry the lane axis LAST)."""
    return _pad(lanes, cols, -1)
