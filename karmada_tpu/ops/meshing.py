"""Mesh lifecycle + shard placement for the (bindings, clusters) solver mesh.

The batched scheduling program scales over two axes: bindings are
embarrassingly data parallel, clusters are the model axis (capacity
tensors [C, R] and per-placement masks [P, C] shard over it; cross-
cluster reductions become XLA collectives).  This module is the single
authority for that mapping — the PartitionSpec per SolverBatch field,
mesh construction, and the process-wide "active mesh" the production
dispatch path (ops/solver.py) consults.  __graft_entry__.dryrun_multichip
is a thin wrapper over the same tables, so the dry-run's sharding IS the
production sharding.

Fallback contract: with one device, a 1x1 shape, or no activation the
module reports no active plan and the solver dispatches exactly as
before — no device_put with shardings, no new jit signatures, zero added
dispatch overhead (the single `active()` check is a list read).

Divisibility: jax.device_put(NamedSharding) requires every sharded
dimension to divide by its mesh-axis size.  Batch axes are pow2-padded
(floor 8, ops/tensors.encode_batch), so pow2 mesh axes up to 8 always
divide; any axis that does NOT divide (odd mesh shapes, tiny G/Q/R axes)
degrades to replication for that dimension only — always correct, the
solver is integer math and replication merely skips the split.

All jax imports are lazy: parse_shape()/mesh_info() must be callable
from CLI/serve code paths that may never initialise a backend.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

from karmada_tpu.utils.metrics import REGISTRY

AXIS_BINDINGS = "bindings"
AXIS_CLUSTERS = "clusters"

# -- observability ------------------------------------------------------------
MESH_DEVICES = REGISTRY.gauge(
    "karmada_mesh_devices",
    "Devices in the active solver mesh (0 = single-device fallback)",
    ("shape", "platform"),
)
MESH_ENABLED = REGISTRY.gauge(
    "karmada_mesh_enabled",
    "1 while a multi-device solver mesh is active, else 0",
)

#: canonical positional order of ops/solver._schedule_core's array args —
#: shared with solver._batch_args and __graft_entry__ (33 fields; the
#: optional used0_milli/used0_pods/used0_sets carry operands follow at
#: positions 33..35)
BATCH_FIELDS = (
    "cluster_valid", "deleting", "name_rank", "pods_allowed", "has_summary",
    "avail_milli", "has_alloc", "api_ok",
    "req_milli", "req_is_cpu", "req_pods", "est_override",
    "pl_mask", "pl_tol_bypass", "pl_strategy", "pl_static_w",
    "pl_has_cluster_sc", "pl_sc_min", "pl_sc_max", "pl_ignore_avail",
    "pl_extra_score",
    "b_valid", "placement_id", "gvk_id", "class_id", "replicas", "uid_desc",
    "fresh", "non_workload", "nw_shortcut", "prev_idx", "prev_val",
    "evict_idx",
)

#: SolverBatch ndarray fields that by design never cross the host->device
#: boundary (the spec-coverage vet pass exempts them from shard_specs):
#: `route` is the host-side routing verdict the encoder leaves behind;
#: `non_workload_host` is the fused resident-gather path's host decode
#: companion (the device plane of the same name is what dispatch ships);
#: `sub_lanes` is the shortlist plane's host-side sub-vocabulary lane
#: map (ops/shortlist) — the dispatch ships the GATHERED planes, the
#: map itself only drives the host-side carry/decode remap.
HOST_ONLY_FIELDS = frozenset({"route", "non_workload_host", "sub_lanes"})


def parse_shape(text) -> Optional[object]:
    """Parse a --mesh flag value.

    "BxC" -> (B, C); "off" / "" / None / "1x1" -> None (fallback);
    "auto" -> the string "auto" (resolved against the live device count
    at activation).  Raises ValueError on anything else.
    """
    if text is None:
        return None
    if isinstance(text, tuple):
        db, dc = text
        if not (isinstance(db, int) and isinstance(dc, int)
                and db >= 1 and dc >= 1):
            raise ValueError(f"mesh axes must be ints >= 1, got {text!r}")
        return None if (db, dc) == (1, 1) else (db, dc)
    s = str(text).strip().lower()
    if s in ("", "off", "none", "0", "1", "1x1"):
        return None
    if s == "auto":
        return "auto"
    try:
        # wrong token count or non-numeric axes both land here
        db, dc = (int(p) for p in s.split("x"))
    except ValueError:
        raise ValueError(
            f"mesh shape must be BxC or 'auto', got {text!r}") from None
    if db < 1 or dc < 1:
        raise ValueError(f"mesh axes must be >= 1, got {text!r}")
    if db * dc == 1:
        return None
    return (db, dc)


def default_shape(n_devices: int) -> Tuple[int, int]:
    """The dry-run's factoring: 2 x N/2 when even, else 1 x N — bindings
    stay the short axis (data parallelism is cheap to widen later)."""
    db = 2 if n_devices % 2 == 0 and n_devices > 1 else 1
    return (db, n_devices // db)


_SPECS_CACHE: List[Optional[Dict[str, object]]] = [None]


def shard_specs() -> Dict[str, object]:
    """PartitionSpec per SolverBatch field over a (bindings, clusters)
    mesh: cluster-axis capacity/mask tensors are model-parallel, binding-
    axis rows data-parallel, request classes replicated.  Sparse
    prev/evict shard on the binding axis only (the sparse column axis
    Kp/Ke is tiny); the kernel scatters them to dense lanes on device.
    Built once and cached (callers must treat it as read-only): the hot
    dispatch path looks fields up per chunk."""
    if _SPECS_CACHE[0] is not None:
        return _SPECS_CACHE[0]
    from jax.sharding import PartitionSpec as P

    _SPECS_CACHE[0] = {
        # cluster axis
        "cluster_valid": P(AXIS_CLUSTERS), "deleting": P(AXIS_CLUSTERS),
        "name_rank": P(AXIS_CLUSTERS), "pods_allowed": P(AXIS_CLUSTERS),
        "has_summary": P(AXIS_CLUSTERS),
        "avail_milli": P(AXIS_CLUSTERS, None),
        "has_alloc": P(AXIS_CLUSTERS, None),
        "api_ok": P(None, AXIS_CLUSTERS),
        # request classes (replicated)
        "req_milli": P(None, None), "req_is_cpu": P(None),
        "req_pods": P(None), "est_override": P(None, AXIS_CLUSTERS),
        # placements: shard the cluster axis
        "pl_mask": P(None, AXIS_CLUSTERS),
        "pl_tol_bypass": P(None, AXIS_CLUSTERS),
        "pl_strategy": P(None), "pl_static_w": P(None, AXIS_CLUSTERS),
        "pl_has_cluster_sc": P(None), "pl_sc_min": P(None),
        "pl_sc_max": P(None), "pl_ignore_avail": P(None),
        "pl_extra_score": P(None, AXIS_CLUSTERS),
        # spread-path rows (vet spec-coverage: these rode in with the r4
        # spread work without spec entries — the device spread sub-solves
        # run single-device today, but the table must stay total so a
        # future sharded spread dispatch places them deliberately)
        "region_id": P(AXIS_CLUSTERS),
        "pl_has_region_sc": P(None), "pl_region_min": P(None),
        "pl_region_max": P(None),
        # explain plane (obs/decisions bit layout): placement-static
        # failure bits shard with the other [P, C] placement rows
        "pl_fail_bits": P(None, AXIS_CLUSTERS),
        # shortlist plane (ops/shortlist): the tier-1 kernel's outputs
        # pin to these — candidate lanes ride the binding axis (the
        # per-binding top-k column axis is tiny, like prev_idx's Kp)
        "shortlist_idx": P(AXIS_BINDINGS, None),
        "shortlist_fcount": P(AXIS_BINDINGS),
        # binding axis: data parallel
        "b_valid": P(AXIS_BINDINGS), "placement_id": P(AXIS_BINDINGS),
        "gvk_id": P(AXIS_BINDINGS), "class_id": P(AXIS_BINDINGS),
        "replicas": P(AXIS_BINDINGS), "uid_desc": P(AXIS_BINDINGS),
        "fresh": P(AXIS_BINDINGS), "non_workload": P(AXIS_BINDINGS),
        "nw_shortcut": P(AXIS_BINDINGS),
        "prev_idx": P(AXIS_BINDINGS, None),
        "prev_val": P(AXIS_BINDINGS, None),
        "evict_idx": P(AXIS_BINDINGS, None),
    }
    return _SPECS_CACHE[0]


def used_specs() -> Tuple[object, object, object]:
    """PartitionSpecs for the consumed-capacity carry accumulators
    (used_milli [C, R], used_pods [C], used_sets [Q, C]): cluster-sharded
    consistently with the capacity tensors they subtract from, so the
    chunk-to-chunk carry chain stays device-resident with no resharding
    between chunks."""
    from jax.sharding import PartitionSpec as P

    return (P(AXIS_CLUSTERS, None), P(AXIS_CLUSTERS), P(None, AXIS_CLUSTERS))


def build_mesh(devices: Sequence, shape: Tuple[int, int]):
    """A (bindings, clusters) Mesh over the first db*dc devices (row-major,
    clusters contiguous — cross-cluster collectives ride the fastest
    links)."""
    from jax.sharding import Mesh

    db, dc = shape
    need = db * dc
    if len(devices) < need:
        raise RuntimeError(
            f"mesh shape {db}x{dc} needs {need} devices, have {len(devices)}")
    return Mesh(
        [[devices[i * dc + j] for j in range(dc)] for i in range(db)],
        (AXIS_BINDINGS, AXIS_CLUSTERS),
    )


def _divisible_spec(spec, shape: Tuple[int, ...], axis_sizes: Dict[str, int]):
    """Drop mesh axes a dimension cannot divide by (replicate that dim
    instead) — device_put(NamedSharding) hard-errors on uneven splits."""
    from jax.sharding import PartitionSpec as P

    names = list(spec) + [None] * (len(shape) - len(spec))
    out = []
    for dim, name in zip(shape, names):
        if name is not None and dim % axis_sizes[name] != 0:
            name = None
        out.append(name)
    return P(*out)


def sharding_for(mesh, field: str, shape: Tuple[int, ...]):
    """The NamedSharding for one batch field's concrete shape (uneven
    axes degraded to replication)."""
    from jax.sharding import NamedSharding

    axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    spec = _divisible_spec(shard_specs()[field], shape, axis_sizes)
    return NamedSharding(mesh, spec)


def sharded_batch_args(batch, mesh) -> tuple:
    """The full solver arg tuple (BATCH_FIELDS order) placed on the mesh."""
    import jax

    return tuple(
        jax.device_put(getattr(batch, f),
                       sharding_for(mesh, f, getattr(batch, f).shape))
        for f in BATCH_FIELDS
    )


def wave_output_shardings(mesh, Bw: int, C: int):
    """Shardings for one contention wave's (rep [Bw, C], sel [Bw, C],
    status [Bw]) — the solver pins the wave scan's stacked outputs to
    these (ops/solver._schedule_core, shard_mesh)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    bc = _divisible_spec(P(AXIS_BINDINGS, AXIS_CLUSTERS), (Bw, C),
                         axis_sizes)
    b = _divisible_spec(P(AXIS_BINDINGS), (Bw,), axis_sizes)
    return (NamedSharding(mesh, bc), NamedSharding(mesh, bc),
            NamedSharding(mesh, b))


def scan_result_shardings(mesh, B: int, Bw: int, C: int):
    """Shardings for the wave scan's RESHAPED results (rep [B, C],
    sel [B, C], status [B]).  The bindings axis participates only when
    the PER-WAVE row count Bw divides it: sharding B when Bw does not
    (e.g. one-binding waves) back-propagates through the reshape as a
    sharding of the scan's stacking dimension — the index dimension of
    its dynamic-update-slice, the exact partitioner path the shard_mesh
    pin exists to avoid (ops/solver._schedule_core docstring)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    db = axis_sizes[AXIS_BINDINGS]
    b_ok = Bw % db == 0 and B % db == 0
    bc = _divisible_spec(
        P(AXIS_BINDINGS if b_ok else None, AXIS_CLUSTERS), (B, C),
        axis_sizes)
    b = _divisible_spec(P(AXIS_BINDINGS if b_ok else None), (B,),
                        axis_sizes)
    return (NamedSharding(mesh, bc), NamedSharding(mesh, bc),
            NamedSharding(mesh, b))


def resident_slot_sharding(mesh):
    """NamedSharding for the resident binding-row slot store's device
    mirrors (ops/resident_gather): fully REPLICATED.  The store's row
    order is slot-allocation order, not batch order, so partitioning it
    would turn every fused gather into an all-to-all; replicating keeps
    the gather local per shard while the gather OUTPUTS pin to the
    solver's binding-axis specs (shard_specs) — the repartition-free
    chain into the dispatch."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P())


def used_shardings(mesh, used_shapes: Sequence[Tuple[int, ...]]):
    """NamedShardings for a (used_milli, used_pods, used_sets) triple."""
    from jax.sharding import NamedSharding

    axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    return tuple(
        NamedSharding(mesh, _divisible_spec(spec, shape, axis_sizes))
        for spec, shape in zip(used_specs(), used_shapes)
    )


# -- the process-wide active mesh --------------------------------------------


class MeshPlan:
    """An activated mesh: the Mesh object plus the identity the solver's
    device-transfer cache keys on (generation) and the topology the
    observability surfaces report."""

    _GEN = [0]

    def __init__(self, mesh, shape: Tuple[int, int], platform: str) -> None:
        MeshPlan._GEN[0] += 1
        self.generation = MeshPlan._GEN[0]
        self.mesh = mesh
        self.shape = shape
        self.platform = platform

    @property
    def n_devices(self) -> int:
        return self.shape[0] * self.shape[1]

    @property
    def shape_str(self) -> str:
        return f"{self.shape[0]}x{self.shape[1]}"


_LOCK = threading.Lock()
_PLAN: List[Optional[MeshPlan]] = [None]


def activate(shape, devices: Sequence = None) -> Optional[MeshPlan]:
    """Activate the process-wide solver mesh.

    `shape` is anything parse_shape accepts ("2x4", (2, 4), "auto", "off").
    Returns the active MeshPlan, or None when the single-device no-op
    fallback applies (shape off/1x1, or fewer than 2 devices available) —
    in which case any previously active mesh is deactivated."""
    shape = parse_shape(shape)
    if shape is None:
        deactivate()
        return None
    import jax

    devs = list(devices) if devices is not None else list(jax.devices())
    if shape == "auto":
        if len(devs) < 2:
            deactivate()
            return None
        shape = default_shape(len(devs))
    if len(devs) < shape[0] * shape[1]:
        raise RuntimeError(
            f"mesh shape {shape[0]}x{shape[1]} needs {shape[0] * shape[1]} "
            f"devices, have {len(devs)} — pass a smaller --mesh or 'off'")
    mesh = build_mesh(devs, shape)
    plan = MeshPlan(mesh, shape, devs[0].platform)
    with _LOCK:
        prev = _PLAN[0]
        _PLAN[0] = plan
    if prev is not None and (prev.shape_str != plan.shape_str
                             or prev.platform != plan.platform):
        # re-activation with a different topology: zero the old label or
        # /metrics would report two meshes as simultaneously active
        MESH_DEVICES.set(0.0, shape=prev.shape_str, platform=prev.platform)
    MESH_ENABLED.set(1.0)
    MESH_DEVICES.set(float(plan.n_devices), shape=plan.shape_str,
                     platform=plan.platform)
    return plan


def deactivate() -> None:
    with _LOCK:
        plan = _PLAN[0]
        _PLAN[0] = None
    MESH_ENABLED.set(0.0)
    if plan is not None:
        MESH_DEVICES.set(0.0, shape=plan.shape_str, platform=plan.platform)


def active() -> Optional[MeshPlan]:
    """The active mesh plan, or None (the single-device fallback)."""
    return _PLAN[0]


def mesh_info() -> dict:
    """Structured snapshot for /debug/state and bench payloads.  Never
    initialises a jax backend: with no active plan it reports the
    fallback without touching jax."""
    plan = _PLAN[0]
    if plan is None:
        return {"enabled": False, "shape": None, "devices": 1,
                "platform": None}
    return {"enabled": True, "shape": plan.shape_str,
            "devices": plan.n_devices, "platform": plan.platform,
            "axes": {AXIS_BINDINGS: plan.shape[0],
                     AXIS_CLUSTERS: plan.shape[1]}}
