"""Fused device-side gather over the resident binding-row slot store.

The resident plane (karmada_tpu/resident) already keeps the CLUSTER-side
solver tensors device-resident between cycles (ops/resident_update
scatter kernels + ops/solver.prime_cluster_slot).  This module closes
the other half of the steady-state loop: the BINDING-axis slot store
stays device-resident too, and a cycle's batch rows are pulled out of it
by one jitted gather instead of the host assembling numpy rows and
re-uploading them every dispatch.  The steady-state chain becomes

  scatter watch deltas into the device mirrors   (ops/resident_update)
  -> gather the pending batch's rows ON DEVICE   (this module)
  -> solve with operands already placed          (ops/solver.dispatch_compact)
  -> d2h only the compact COO triple             (solver.finalize_compact)

so the only per-cycle host->device traffic for a warm (all-hits) cycle
is the [B] slot-index vector — zero binding-axis field uploads
(karmada_solver_h2d_binding_fields_total stays flat; bench --delta
asserts exactly that).

Sharding chain: the gather's outputs are pinned to the SAME
(bindings, clusters) PartitionSpecs the solver's dispatch places its
binding-axis operands with (ops/meshing.shard_specs — derived here, not
re-declared, so the two tables cannot drift; the spec-coverage vet pass
checks the slot-store field set against the same table).  pjit inputs
already partitioned to match in_axis_resources skip the repartition
(SNIPPETS [1]/[2]), so under a mesh the gathered rows flow into the
solve with no resharding step.  The slot-store mirrors themselves are
REPLICATED over the mesh (ops/meshing.resident_slot_sharding): the
store's row order is slot-allocation order, not batch order, so a
sharded store would turn every gather into an all-to-all; replication
keeps the gather local and only the OUTPUTS partition.

Trace-safety: pure gathers + jnp.where masking — no Python control flow
on traced values, no host syncs, no dtype-defaulted constructors (fill
values are weak-typed scalars; dtypes ride in on the slot-store
operands, built against ops/tensors.FIELD_DTYPES).
"""

from __future__ import annotations

from functools import partial

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402

from karmada_tpu.ops.tensors import FIELD_DTYPES, ROUTE_DEVICE  # noqa: E402
from karmada_tpu.utils.metrics import REGISTRY  # noqa: E402

#: slot-store fields the kernel gathers, in the order the jit takes them
#: (resident/state.py DEVICE_SLOT_FIELDS mirrors exactly this set; the
#: spec-coverage vet pass checks both against meshing.shard_specs)
GATHER_FIELDS = (
    "placement_id", "gvk_id", "class_id", "replicas", "uid_desc",
    "fresh", "non_workload", "nw_shortcut", "route",
    "prev_idx", "prev_val", "evict_idx",
)

#: kernel outputs, in ops/solver._BINDING_FIELDS order — the dispatch
#: operand contract.  b_valid is computed on device (route == DEVICE on
#: real rows); route itself stays host-only (meshing.HOST_ONLY_FIELDS)
#: and is not emitted.
OUT_FIELDS = (
    "b_valid", "placement_id", "gvk_id", "class_id", "replicas",
    "uid_desc", "fresh", "non_workload", "nw_shortcut",
    "prev_idx", "prev_val", "evict_idx",
)

#: pad fill per output field — MUST match the host control's zeros
#: (resident/state.ResidentState._assemble) so a fused batch is
#: bit-identical to the host-assembled one on every row, padding
#: included (the parity fuzz in tests/test_resident_fused.py compares
#: all B rows, not just the real ones)
_FILL = {
    "placement_id": 0, "gvk_id": 0, "class_id": -1, "replicas": 0,
    "uid_desc": False, "fresh": False, "non_workload": False,
    "nw_shortcut": False, "prev_idx": -1, "prev_val": 0, "evict_idx": -1,
}

GATHER_DISPATCHES = REGISTRY.counter(
    "karmada_resident_gather_dispatches_total",
    "Fused device-side binding-row gathers dispatched (one per chunk on "
    "the fused resident path)",
)
GATHER_ROWS = REGISTRY.counter(
    "karmada_resident_gather_rows_total",
    "Binding rows pulled out of the device slot store by the fused gather",
)
GATHER_SCATTERS = REGISTRY.counter(
    "karmada_resident_gather_row_scatters_total",
    "Churned binding rows scattered into the device slot store (miss "
    "re-encodes advancing the mirrors in place)",
)


def _gather_core(slots, placement_id, gvk_id, class_id, replicas, uid_desc,
                 fresh, non_workload, nw_shortcut, route,
                 prev_idx, prev_val, evict_idx, *, shard_mesh=None):
    """slots int64[B] (-1 = padding) against the [cap]-leading slot store:
    returns the solver's binding-axis operand tuple (OUT_FIELDS order),
    padded rows rewritten to the host control's fill values."""
    ok = slots >= 0
    sl = jnp.where(ok, slots, 0)

    def g1(a, fill):
        return jnp.where(ok, a[sl], fill)

    def g2(a, fill):
        return jnp.where(ok[:, None], a[sl], fill)

    route_g = route[sl]
    b_valid = ok & (route_g == ROUTE_DEVICE)
    F = _FILL
    out = (
        b_valid,
        g1(placement_id, F["placement_id"]), g1(gvk_id, F["gvk_id"]),
        g1(class_id, F["class_id"]), g1(replicas, F["replicas"]),
        g1(uid_desc, F["uid_desc"]), g1(fresh, F["fresh"]),
        g1(non_workload, F["non_workload"]),
        g1(nw_shortcut, F["nw_shortcut"]),
        g2(prev_idx, F["prev_idx"]), g2(prev_val, F["prev_val"]),
        g2(evict_idx, F["evict_idx"]),
    )
    if shard_mesh is not None:
        # chain the gather's out-shardings into the solver's in-shardings:
        # ONE spec table (meshing.shard_specs) serves both, so a dispatch
        # of these outputs repartitions nothing
        from karmada_tpu.ops import meshing

        out = tuple(
            lax.with_sharding_constraint(
                a, meshing.sharding_for(shard_mesh, f, a.shape))
            for f, a in zip(OUT_FIELDS, out))
    return out


gather_batch = partial(
    jax.jit, static_argnames=("shard_mesh",))(_gather_core)


def _sub_gather_core(slots, lane_inv, drop, placement_id, gvk_id, class_id,
                     replicas, uid_desc, fresh, non_workload, nw_shortcut,
                     route, prev_idx, prev_val, evict_idx, *,
                     shard_mesh=None):
    """The fused gather, emitting rows directly in a shortlist
    SUB-vocabulary: `lane_inv` int32[C] maps full-vocabulary cluster
    lanes to union lanes (-1 = outside the union), `drop` bool[B] marks
    rows the shortlist routed out of the compact solve (residual /
    non-device rows) — their b_valid is cleared on device instead of a
    host round-trip.  prev/evict lane indices are remapped in-kernel
    (out-of-union prev lanes -> -1 with value zeroed; the shortlist
    union always contains every row's prev lanes, so a -1 here only
    appears on rows already dropped)."""
    ok = slots >= 0
    sl = jnp.where(ok, slots, 0)

    def g1(a, fill):
        return jnp.where(ok, a[sl], fill)

    def g2(a, fill):
        return jnp.where(ok[:, None], a[sl], fill)

    def remap(lanes):
        m = jnp.where(lanes >= 0, lane_inv[jnp.where(lanes >= 0, lanes, 0)],
                      -1)
        return m.astype(lanes.dtype)

    route_g = route[sl]
    b_valid = ok & (route_g == ROUTE_DEVICE) & ~drop
    F = _FILL
    pidx = remap(g2(prev_idx, F["prev_idx"]))
    pval = jnp.where(pidx >= 0, g2(prev_val, F["prev_val"]), 0)
    eidx = remap(g2(evict_idx, F["evict_idx"]))
    out = (
        b_valid,
        g1(placement_id, F["placement_id"]), g1(gvk_id, F["gvk_id"]),
        g1(class_id, F["class_id"]), g1(replicas, F["replicas"]),
        g1(uid_desc, F["uid_desc"]), g1(fresh, F["fresh"]),
        g1(non_workload, F["non_workload"]),
        g1(nw_shortcut, F["nw_shortcut"]),
        pidx, pval, eidx,
    )
    if shard_mesh is not None:
        from karmada_tpu.ops import meshing

        out = tuple(
            lax.with_sharding_constraint(
                a, meshing.sharding_for(shard_mesh, f, a.shape))
            for f, a in zip(OUT_FIELDS, out))
    return out


sub_gather_batch = partial(
    jax.jit, static_argnames=("shard_mesh",))(_sub_gather_core)


def dispatch_sub_gather(slots, mirrors, lane_inv, drop, plan=None):
    """Run the sub-vocabulary gather (see _sub_gather_core): the per-call
    h2d is the [B] slot vector plus the [C] lane map and [B] drop mask —
    still zero binding-axis FIELD uploads.  Returns the solver
    binding-axis operand tuple (OUT_FIELDS order) with prev/evict lanes
    already living in the union vocabulary."""
    args = tuple(mirrors[f] for f in GATHER_FIELDS)
    out = sub_gather_batch(
        slots, lane_inv, drop, *args,
        shard_mesh=plan.mesh if plan is not None else None)
    GATHER_DISPATCHES.inc()
    return out


def place_slot(arr, plan=None):
    """Place one slot-store master on device: replicated over the active
    mesh (the gather is local per shard; only its outputs partition),
    plain default placement single-device."""
    if plan is None:
        return jax.device_put(arr)
    from karmada_tpu.ops import meshing

    return jax.device_put(arr, meshing.resident_slot_sharding(plan.mesh))


def dispatch_gather(slots, mirrors, plan=None):
    """Run the fused gather over the device slot store: `slots` is the
    int64[B] (-1 padded) slot vector — the ONLY per-cycle h2d on this
    path — and `mirrors` maps GATHER_FIELDS to their device arrays.
    Returns the solver binding-axis operand tuple (OUT_FIELDS order) as
    live device values (async; nothing is forced here)."""
    args = tuple(mirrors[f] for f in GATHER_FIELDS)
    out = gather_batch(slots, *args,
                       shard_mesh=plan.mesh if plan is not None else None)
    GATHER_DISPATCHES.inc()
    return out


def aot_warm(B: int, cap: int, Kp: int = 4, Ke: int = 4, plan=None) -> dict:
    """AOT-compile the fused gather executable for one (B, cap, Kp, Ke)
    geometry from abstract ShapeDtypeStructs — nothing executes, no
    device slot store need exist yet.  With the persistent compile cache
    armed (ops/aotcache.enable) the executable lands on disk, so the
    first fused cycle of the shape — mid-soak, or in a later process —
    pays cache deserialization instead of an XLA compile (the same gap
    aotcache closes for the solver variants).  Returns the lower/compile
    timing split like solver.aot_warm_compile."""
    import numpy as _onp

    def aval(shape, dtype_name):
        dt = _onp.bool_ if dtype_name == "bool" else _onp.dtype(dtype_name)
        if plan is None:
            return jax.ShapeDtypeStruct(shape, dt)
        from karmada_tpu.ops import meshing

        return jax.ShapeDtypeStruct(
            shape, dt, sharding=meshing.resident_slot_sharding(plan.mesh))

    def field_aval(f):
        if f in ("prev_idx", "prev_val"):
            shape = (cap, Kp)
        elif f == "evict_idx":
            shape = (cap, Ke)
        else:
            shape = (cap,)
        return aval(shape, FIELD_DTYPES[f])

    slots = jax.ShapeDtypeStruct((B,), _onp.int64)
    args = (slots,) + tuple(field_aval(f) for f in GATHER_FIELDS)
    import time as _time

    t0 = _time.perf_counter()
    lowered = gather_batch.lower(
        *args, shard_mesh=plan.mesh if plan is not None else None)
    t1 = _time.perf_counter()
    compiled = lowered.compile()
    t2 = _time.perf_counter()
    from karmada_tpu.obs import devprof

    return {"lower_s": round(t1 - t0, 3), "compile_s": round(t2 - t1, 3),
            "slot_cap": int(cap),
            "cost": devprof.harvest_cost(compiled)}
