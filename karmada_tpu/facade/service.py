"""FacadeService: server-side batch coalescing over the detached solver.

Concurrent `AssignReplicas` callers (one small binding each) enqueue
into a deadline-vs-size batch former — the scheduler's own admission
shape: cut when the window fills OR the oldest caller has waited the
deadline, never cut empty — and ONE detached solve through the
unchanged pipelined solver answers the whole batch.  Per-call demux
stamps each caller's ledger event and the shared trace id.  Many small
RPCs become one device dispatch: the coalesce ratio
(karmada_facade_calls_total / karmada_facade_batches_total) is the
plane's headline number, and ``bench.py --facade`` measures it against
a serial per-call control.

`SelectClusters` (a host-side feasibility filter) and `WhatIf`
(whatif.py's hypothetical solves) answer inline — no coalescing; they
share the solve lock so facade work never races itself.  NOTHING in
this module mutates the store or the resident plane: the facade is a
solver service, not a second writer.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from karmada_tpu import obs
from karmada_tpu.utils.locks import VetLock
from karmada_tpu.estimator import wire
from karmada_tpu.facade import metrics as facade_metrics
from karmada_tpu.facade import whatif as whatif_mod
from karmada_tpu.facade.messages import (
    WhatIfRequest,
    WhatIfResponse,
)
from karmada_tpu.models.cluster import Cluster
from karmada_tpu.models.work import ResourceBindingStatus
from karmada_tpu.obs import events as obs_events
from karmada_tpu.obs import incidents as obs_incidents
from karmada_tpu.ops import serial

OUTCOME_SCHEDULED = "scheduled"
OUTCOME_UNSCHEDULABLE = "unschedulable"
OUTCOME_ERROR = "error"


@dataclass
class _Pending:
    request: wire.AssignReplicasRequest
    t_enqueue: float
    done: threading.Event = field(default_factory=threading.Event)
    response: Optional[wire.AssignReplicasResponse] = None


class PendingAssign:
    """An in-flight AssignReplicas call (FacadeService.assign_async):
    ``result()`` blocks until the coalesced dispatch demuxes this
    caller's slice.  One event-driven server thread can hold many of
    these open at once — the wire handler shape — without a Python
    thread per caller."""

    __slots__ = ("_svc", "_p")

    def __init__(self, svc: "FacadeService", p: _Pending) -> None:
        self._svc = svc
        self._p = p

    def result(self,
               timeout: Optional[float] = None
               ) -> wire.AssignReplicasResponse:
        if not self._p.done.wait(timeout):
            raise TimeoutError("facade assign still in flight")
        return self._svc._finish(self._p)  # noqa: SLF001 — owning service


class FacadeService:
    """One facade plane over one live Scheduler + store.

    The owning serve plane starts it (`serve --facade[=ADDR]`), tests
    construct it directly.  ``batch_window`` defaults to the
    scheduler's own; ``batch_deadline_s`` is deliberately SHORT (an RPC
    caller is blocked for it) — coalescing comes from concurrency, the
    deadline only bounds a straggler's wait."""

    def __init__(self, scheduler, store, *,
                 batch_window: Optional[int] = None,
                 batch_deadline_s: float = 0.02,
                 clock=time.monotonic) -> None:
        self.scheduler = scheduler
        self.store = store
        self.batch_window = int(batch_window or scheduler.batch_window)
        self.batch_deadline_s = float(batch_deadline_s)
        self._clock = clock
        self._lock = VetLock("facade.state")
        self._cond = threading.Condition(self._lock)
        # _cond wraps _lock, so waiters and counter updates share one
        # mutual exclusion; _pending mutations happen in `with _cond:`
        self._pending: List[_Pending] = []  # guarded-by: _cond
        self._closed = False  # guarded-by: _cond
        self._calls = 0  # guarded-by: _cond
        self._batch_id = 0  # guarded-by: _cond
        # post-solve bookkeeping lands under the bare _lock (same mutex
        # as _cond — Condition(self._lock) — different lexical name)
        self._batches = 0  # guarded-by: _lock
        self._coalesced_calls = 0  # guarded-by: _lock
        self._errors = 0  # guarded-by: _lock
        self._whatif_counts: Dict[str, int] = {}  # guarded-by: _lock
        self._last_batch_size = 0  # guarded-by: _lock
        # serializes every detached solve this service issues (assign
        # batches and what-if probes) — detached solves are safe against
        # the live cycle worker but not against each other
        self._solve_lock = VetLock("facade.solve")
        self._server: Optional[wire.EstimatorTcpServer] = None
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name="facade-coalescer")
        self._worker.start()

    # -- serving --------------------------------------------------------------
    def serve(self, host: str = "127.0.0.1", port: int = 0,
              ssl_context=None) -> tuple:
        """Expose the facade over the wire tier; returns the bound
        (host, port)."""
        self._server = wire.serve_tcp(self.dispatch, host, port,
                                      ssl_context=ssl_context)
        return self._server.server_address[:2]

    @property
    def address(self) -> Optional[tuple]:
        if self._server is None:
            return None
        return self._server.server_address[:2]

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        self._worker.join(timeout=2.0)

    def dispatch(self, method: str, body: dict) -> dict:
        """The wire handler (serve_tcp): method + JSON body in, JSON
        body out.  Unknown methods raise — the transport serializes
        that as an error frame, which the client surfaces typed."""
        if method == "AssignReplicas":
            return self.assign(
                wire.AssignReplicasRequest.from_json(body)).to_json()
        if method == "SelectClusters":
            return self.select_clusters(
                wire.SelectClustersRequest.from_json(body)).to_json()
        if method == "WhatIf":
            return self.whatif(WhatIfRequest.from_json(body)).to_json()
        raise ValueError(f"unknown facade method {method!r}")

    # -- AssignReplicas (the coalesced verb) ----------------------------------
    def assign(self,
               req: wire.AssignReplicasRequest
               ) -> wire.AssignReplicasResponse:
        """Blocking per caller: enqueue, ride the next coalesced
        dispatch, return this caller's demuxed slice."""
        return self.assign_async(req).result()

    def assign_async(self,
                     req: wire.AssignReplicasRequest) -> PendingAssign:
        """Non-blocking admission: enqueue the call and return a handle
        whose ``result()`` blocks for the demuxed response.  Lets one
        event-driven server thread keep a whole window of callers in
        flight — the coalescer sees identical pressure to thread-per-
        call admission without the thread-per-call cost.

        Caller-runs cut: the admission that FILLS the window dispatches
        the batch inline on its own thread instead of waking the former.
        Under burst load the whole coalescing path then runs single-
        threaded — no second runnable thread fighting for the GIL per
        batch (on a one-core deployment that contention roughly doubles
        per-call cost).  The background former only fires DEADLINE cuts,
        i.e. when traffic stalls with a partial window."""
        p = _Pending(request=req, t_enqueue=self._clock())
        batch: Optional[List[_Pending]] = None
        with self._cond:
            if self._closed:
                raise RuntimeError("facade service is closed")
            self._pending.append(p)
            self._calls += 1
            n_pending = len(self._pending)
            if n_pending >= self.batch_window:
                batch = self._pending[:self.batch_window]
                del self._pending[:len(batch)]
                self._batch_id += 1
                bid = self._batch_id
            elif n_pending == 1:
                # first pending call starts the former's deadline clock;
                # notifying every enqueue would GIL-ping-pong it awake
                self._cond.notify_all()
        if batch is not None:
            self._dispatch(batch, bid)
        return PendingAssign(self, p)

    def _finish(self, p: _Pending) -> wire.AssignReplicasResponse:
        """Per-call epilogue once the dispatch demuxed: latency + result
        metrics, closed-race fallback (PendingAssign.result)."""
        facade_metrics.FACADE_CALL_LATENCY.observe(
            self._clock() - p.t_enqueue, method="AssignReplicas")
        resp = p.response
        if resp is None:  # close() raced the wait
            resp = wire.AssignReplicasResponse(
                outcome=OUTCOME_ERROR, message="facade service closed")
        facade_metrics.FACADE_CALLS.inc(method="AssignReplicas",
                                        result=resp.outcome)
        return resp

    def _run(self) -> None:
        """The batch former: cut when the window fills or the oldest
        caller has waited the deadline; never cut empty."""
        while True:
            with self._cond:
                while not self._closed:
                    if self._pending:
                        age = self._clock() - self._pending[0].t_enqueue
                        if (len(self._pending) >= self.batch_window
                                or age >= self.batch_deadline_s):
                            break
                        self._cond.wait(
                            timeout=max(self.batch_deadline_s - age, 0.001))
                    else:
                        self._cond.wait(timeout=0.5)
                if self._closed and not self._pending:
                    return
                batch = self._pending[:self.batch_window]
                del self._pending[:len(batch)]
                self._batch_id += 1
                bid = self._batch_id
            self._dispatch(batch, bid)

    def _dispatch(self, batch: List[_Pending], bid: int) -> None:
        """Run one cut batch to completion — shared by the deadline
        former and the caller-runs window cut; every caller in the
        batch is unblocked no matter what the solve does."""
        try:
            self._solve_assign(batch, bid)
        # vet: ignore[exception-hygiene] demuxed to every caller as an error response
        except Exception as e:  # noqa: BLE001 — callers must unblock
            with self._lock:
                self._errors += 1
            for p in batch:
                p.response = wire.AssignReplicasResponse(
                    outcome=OUTCOME_ERROR, message=str(e),
                    batch_id=bid, batch_size=len(batch))
                p.done.set()

    def _solve_assign(self, batch: List[_Pending], bid: int) -> None:
        """One coalesced dispatch: synthesize bindings, fork the live
        cluster view, ONE detached solve, demux per caller."""
        bindings = [whatif_mod.synthesize_binding(p.request) for p in batch]
        # a caller-supplied (namespace, name) may collide across the
        # batch; the solve is positional so only ledger keys care
        clusters = self.store.list(Cluster.KIND)
        tracer = obs.TRACER
        trace_id = ""
        # caller-side trace ids off the wire frames: a bundle's facade
        # flight record stitches these to the server-side timeline of
        # the one coalesced dispatch they shared
        caller_traces = sorted({p.request.trace_id for p in batch
                                if p.request.trace_id})
        with tracer.span(obs.SPAN_FACADE_CYCLE, callers=len(batch),
                         batch_id=bid):
            sp = tracer.current()
            if sp is not None:
                trace_id = sp.trace.trace_id
                if caller_traces:
                    sp.set_attr(caller_trace_ids=caller_traces)
            with self._solve_lock:
                results, _ = self.scheduler.solve_batch(
                    bindings, clusters, detached=True)
        if obs_incidents.flight_armed():
            obs_incidents.record(
                "facade", t=round(time.time(), 6), batch_id=bid,
                trace_id=trace_id or None, batch=len(batch),
                caller_trace_ids=caller_traces)
        with self._lock:
            self._batches += 1
            self._coalesced_calls += len(batch)
            self._last_batch_size = len(batch)
        facade_metrics.FACADE_BATCHES.inc()
        facade_metrics.FACADE_BATCH_SIZE.observe(len(batch))
        # the armed() guard hoisted out of emit_key: building the ledger
        # message strings per caller is the demux loop's dominant cost,
        # and a disarmed ledger must not pay it (the guards._ARMED
        # pattern — coalescing economics live on this loop)
        ledger_armed = obs_events.armed()
        for i, p in enumerate(batch):
            res = results.get(i)
            key = (p.request.namespace, p.request.name)
            if isinstance(res, Exception) or res is None:
                msg = str(res) if res is not None else "no result"
                p.response = wire.AssignReplicasResponse(
                    outcome=OUTCOME_UNSCHEDULABLE, message=msg,
                    trace_id=trace_id, batch_id=bid,
                    batch_size=len(batch))
                if ledger_armed:
                    obs_events.emit_key(
                        key, obs_events.TYPE_WARNING,
                        obs_events.REASON_FACADE_REJECTED,
                        f"facade batch {bid} ({len(batch)} callers): {msg}",
                        origin="facade", trace_id=trace_id or None)
            else:
                p.response = wire.AssignReplicasResponse(
                    assignments=[{"cluster": t.name, "replicas": t.replicas}
                                 for t in res],
                    outcome=OUTCOME_SCHEDULED, trace_id=trace_id,
                    batch_id=bid, batch_size=len(batch))
                if ledger_armed:
                    where = ", ".join(f"{t.name}({t.replicas})"
                                      for t in res)
                    obs_events.emit_key(
                        key, obs_events.TYPE_NORMAL,
                        obs_events.REASON_FACADE_ASSIGNED,
                        f"facade batch {bid} ({len(batch)} callers) "
                        "assigned"
                        + (f" to {where}" if where else ""),
                        origin="facade", trace_id=trace_id or None)
            p.done.set()

    # -- SelectClusters (inline feasibility filter) ---------------------------
    def select_clusters(self,
                        req: wire.SelectClustersRequest
                        ) -> wire.SelectClustersResponse:
        rb = whatif_mod.synthesize_binding(wire.AssignReplicasRequest(
            namespace=req.namespace, name=req.name,
            resource_request=req.resource_request,
            cluster_names=req.cluster_names))
        clusters = self.store.list(Cluster.KIND)
        fit, diagnosis = serial.find_clusters_that_fit(
            rb.spec, ResourceBindingStatus(), clusters)
        facade_metrics.FACADE_CALLS.inc(method="SelectClusters",
                                        result=OUTCOME_SCHEDULED)
        return wire.SelectClustersResponse(
            clusters=sorted(c.name for c in fit), excluded=diagnosis)

    # -- WhatIf (the capacity-planning plane) ---------------------------------
    def whatif(self, req: WhatIfRequest) -> WhatIfResponse:
        t0 = self._clock()
        with obs.TRACER.span(obs.SPAN_FACADE_WHATIF, query=req.query):
            resp = whatif_mod.run_query(self.scheduler, self.store, req,
                                        solve_lock=self._solve_lock)
        with self._lock:
            self._whatif_counts[req.query] = (
                self._whatif_counts.get(req.query, 0) + 1)
        facade_metrics.FACADE_WHATIF.inc(query=req.query)
        facade_metrics.FACADE_CALL_LATENCY.observe(
            self._clock() - t0, method="WhatIf")
        facade_metrics.FACADE_CALLS.inc(method="WhatIf",
                                        result=OUTCOME_SCHEDULED)
        return resp

    # -- /debug/facade --------------------------------------------------------
    def state_payload(self) -> dict:
        with self._lock:
            calls, batches = self._calls, self._batches
            payload = {
                "enabled": True,
                "batch_window": self.batch_window,
                "batch_deadline_s": self.batch_deadline_s,
                "calls": calls,
                "batches": batches,
                "coalesced_calls": self._coalesced_calls,
                "coalesce_ratio": (round(self._coalesced_calls / batches, 4)
                                   if batches else 0.0),
                "last_batch_size": self._last_batch_size,
                "inflight": len(self._pending),
                "errors": self._errors,
                "whatif": dict(self._whatif_counts),
            }
        addr = self.address
        payload["address"] = (f"{addr[0]}:{addr[1]}" if addr else None)
        return payload
