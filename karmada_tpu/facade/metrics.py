"""Facade-plane metrics (karmada_facade_*).

The coalescing story is the whole point of the plane, so the metric set
is built to prove it: calls vs batches gives the coalesce ratio, the
batch-size histogram shows how full the shared dispatches run, and the
per-call latency includes the admission wait (the price a caller pays
for riding a shared device dispatch).
"""

from __future__ import annotations

from karmada_tpu.utils.metrics import REGISTRY, exponential_buckets

FACADE_CALLS = REGISTRY.counter(
    "karmada_facade_calls_total",
    "Facade RPCs served, by method (AssignReplicas / SelectClusters / "
    "WhatIf) and result (scheduled / unschedulable / error)",
    ("method", "result"),
)

FACADE_BATCHES = REGISTRY.counter(
    "karmada_facade_batches_total",
    "Coalesced facade solve cycles dispatched (calls_total / "
    "batches_total is the coalesce ratio)",
)

FACADE_BATCH_SIZE = REGISTRY.histogram(
    "karmada_facade_batch_size",
    "Concurrent AssignReplicas callers coalesced into one detached solve "
    "dispatch",
    buckets=exponential_buckets(1, 2, 12),
)

FACADE_CALL_LATENCY = REGISTRY.histogram(
    "karmada_facade_call_duration_seconds",
    "Per-caller facade latency (admission wait + shared solve + demux), "
    "by method",
    ("method",),
    buckets=exponential_buckets(0.0005, 2, 16),
)

FACADE_WHATIF = REGISTRY.counter(
    "karmada_facade_whatif_total",
    "What-if capacity-planning queries answered, by query kind "
    "(placement / cluster-loss / headroom)",
    ("query",),
)
