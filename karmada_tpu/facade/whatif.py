"""What-if capacity planning: hypothetical solves, zero live mutation.

Every query runs the scheduler's DETACHED solve (Scheduler.solve_batch
with ``detached=True`` — the unchanged pipelined solver minus every
live-state hook) against a copy-on-write fork of the member-cluster
view: the resident plane's cluster snapshot when that plane is armed
(``ResidentState.fork_clusters`` — the masters themselves are frozen
device arrays, shared by reference), the store's deep-copied list
otherwise.  Nothing here calls ``store.mutate``/``_apply_result``, so a
what-if query mid-soak leaves live placements bit-identical — the
loadgen ``whatif`` scenario proves exactly that.

Query payload shapes (WhatIfResponse.result):

  placement     {"replicas", "assignments": [{"cluster", "replicas"}],
                 "outcome", "message"}
  cluster-loss  {"ranking": [{"cluster", "bindings", "replicas",
                 "stranded_bindings", "stranded_replicas", "truncated"}],
                 "worst": <cluster name or "">}
  headroom      {"max_replicas", "probes", "assignments"}
"""

from __future__ import annotations

from functools import lru_cache
from typing import Dict, List, Optional, Tuple

from karmada_tpu.estimator.wire import AssignReplicasRequest
from karmada_tpu.facade.messages import (
    QUERIES,
    QUERY_CLUSTER_LOSS,
    QUERY_HEADROOM,
    QUERY_PLACEMENT,
    WhatIfRequest,
    WhatIfResponse,
)
from karmada_tpu.models.cluster import Cluster
from karmada_tpu.models.policy import (
    ClusterAffinity,
    Placement,
    REPLICA_DIVISION_AGGREGATED,
    REPLICA_SCHEDULING_DIVIDED,
    REPLICA_SCHEDULING_DUPLICATED,
    ReplicaSchedulingStrategy,
)
from karmada_tpu.models.work import (
    ObjectReference,
    ReplicaRequirements,
    ResourceBinding,
    ResourceBindingSpec,
)
from karmada_tpu.utils.quantity import Quantity

WHATIF_NS = "whatif"


@lru_cache(maxsize=4096)
def _parse_qty(s: str) -> Quantity:
    """Quantity is frozen, so identical request strings (the common
    facade shape: thousands of callers asking for "500m") share one
    parsed instance instead of re-running the regex per call."""
    return Quantity.parse(s)

#: headroom search: doubling probes + bisection steps are each bounded,
#: so one query costs at most ~2 * HEADROOM_MAX_PROBES detached solves
HEADROOM_MAX_PROBES = 24


def synthesize_binding(req: AssignReplicasRequest) -> ResourceBinding:
    """A hypothetical ResourceBinding from a facade request — never
    created in any store, so names need only be unique per batch."""
    rb = ResourceBinding()
    rb.metadata.namespace = req.namespace or WHATIF_NS
    rb.metadata.name = req.name or "whatif"
    rr = None
    if req.resource_request:
        rr = ReplicaRequirements(resource_request={
            k: _parse_qty(str(v)) for k, v in req.resource_request.items()})
    if req.divided:
        strategy = ReplicaSchedulingStrategy(
            replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
            replica_division_preference=REPLICA_DIVISION_AGGREGATED)
    else:
        strategy = ReplicaSchedulingStrategy(
            replica_scheduling_type=REPLICA_SCHEDULING_DUPLICATED)
    rb.spec = ResourceBindingSpec(
        resource=ObjectReference(
            api_version="apps/v1", kind="Deployment",
            namespace=rb.metadata.namespace, name=rb.metadata.name,
            uid=f"uid-{rb.metadata.namespace}-{rb.metadata.name}"),
        replicas=max(int(req.replicas), 0),
        replica_requirements=rr,
        placement=Placement(
            cluster_affinity=(
                ClusterAffinity(cluster_names=list(req.cluster_names))
                if req.cluster_names else None),
            replica_scheduling=strategy),
    )
    return rb


def fork_clusters(scheduler, store) -> Tuple[List[Cluster], str]:
    """The copy-on-write fork every hypothetical solve runs against:
    the resident plane's cluster view when armed (and populated), the
    store's deep-copied snapshot otherwise.  Either way the returned
    objects share nothing mutable with live state.

    Concurrency contract (the fork bookkeeping has NO lock of its own):
    every fork is CALL-LOCAL — this module keeps zero shared mutable
    state across queries, so concurrent run_query callers each hold a
    private fork and never observe each other.  The only shared
    resource is the detached solver itself, serialized by the caller's
    ``solve_lock`` (FacadeService._solve_lock, a VetLock the armed
    runtime detector tracks); ``state.fork_clusters()`` is itself safe
    against the live cycle worker (frozen masters, copy-on-write)."""
    state = getattr(scheduler, "_resident", None)
    if state is not None:
        forked = state.fork_clusters()
        if forked:
            return forked, "resident"
    return store.list(Cluster.KIND), "store"


def _solve_one(scheduler, rb: ResourceBinding,
               clusters: List[Cluster]) -> object:
    results, _ = scheduler.solve_batch([rb], clusters, detached=True)
    return results.get(0)


def _placement_result(res: object) -> Dict:
    if isinstance(res, Exception):
        return {"assignments": [], "outcome": "unschedulable",
                "message": str(res)}
    targets = res or []
    return {"assignments": [{"cluster": t.name, "replicas": t.replicas}
                            for t in targets],
            "outcome": "scheduled", "message": ""}


def run_query(scheduler, store, req: WhatIfRequest,
              solve_lock=None) -> WhatIfResponse:
    """Answer one what-if query.  ``solve_lock`` (the FacadeService's)
    serializes detached solves among facade callers; a bare None runs
    unserialized (single-threaded tests)."""
    if req.query not in QUERIES:
        raise ValueError(
            f"unknown what-if query {req.query!r}; available: "
            f"{', '.join(QUERIES)}")
    clusters, source = fork_clusters(scheduler, store)
    lock = solve_lock if solve_lock is not None else _NULL_LOCK
    with lock:
        if req.query == QUERY_PLACEMENT:
            result = _query_placement(scheduler, clusters, req)
        elif req.query == QUERY_CLUSTER_LOSS:
            result = _query_cluster_loss(scheduler, store, clusters, req)
        else:
            result = _query_headroom(scheduler, clusters, req)
    return WhatIfResponse(query=req.query, source=source, result=result)


class _NullLock:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_LOCK = _NullLock()


def _query_placement(scheduler, clusters: List[Cluster],
                     req: WhatIfRequest) -> Dict:
    rb = synthesize_binding(AssignReplicasRequest(
        namespace=WHATIF_NS, name="placement",
        replicas=req.replicas, resource_request=req.resource_request,
        divided=req.divided))
    out = _placement_result(_solve_one(scheduler, rb, clusters))
    out["replicas"] = req.replicas
    return out


def _query_cluster_loss(scheduler, store, clusters: List[Cluster],
                        req: WhatIfRequest) -> Dict:
    """For each candidate cluster: re-solve the bindings it currently
    hosts against the forked fleet WITHOUT it; whatever no longer
    schedules is stranded by that loss.  The re-solve strips the old
    placement (spec.clusters / observed affinity state) so the solver
    prices the survivors fresh."""
    import copy

    live = store.list(ResourceBinding.KIND)
    by_cluster: Dict[str, List[ResourceBinding]] = {}
    for rb in live:
        for t in rb.spec.clusters:
            by_cluster.setdefault(t.name, []).append(rb)
    names = ([req.cluster] if req.cluster
             else sorted(by_cluster, key=lambda n: -len(by_cluster[n])))
    ranking = []
    for name in names:
        hosted = by_cluster.get(name, [])
        if not hosted and not req.cluster:
            continue
        victims = hosted[:max(req.limit, 0)]
        survivors = [c for c in clusters if c.name != name]
        stranded_b = 0
        stranded_r = 0
        if victims:
            probes = []
            for rb in victims:
                probe = copy.deepcopy(rb)
                probe.spec.clusters = []
                probe.status.scheduler_observed_affinity_name = ""
                probes.append(probe)
            results, _ = scheduler.solve_batch(probes, survivors,
                                               detached=True)
            for i, rb in enumerate(victims):
                res = results.get(i)
                if isinstance(res, Exception) or res is None:
                    stranded_b += 1
                    stranded_r += sum(t.replicas for t in rb.spec.clusters
                                      if t.name == name)
        ranking.append({
            "cluster": name,
            "bindings": len(hosted),
            "replicas": sum(t.replicas for rb in hosted
                            for t in rb.spec.clusters if t.name == name),
            "stranded_bindings": stranded_b,
            "stranded_replicas": stranded_r,
            "truncated": len(hosted) - len(victims),
        })
    ranking.sort(key=lambda r: (-r["stranded_replicas"],
                                -r["stranded_bindings"], r["cluster"]))
    return {"ranking": ranking,
            "worst": ranking[0]["cluster"] if ranking else ""}


def _query_headroom(scheduler, clusters: List[Cluster],
                    req: WhatIfRequest) -> Dict:
    """Largest replica count of the request class that still FULLY
    schedules (every replica placed): doubling to find an infeasible
    upper bound, then bisection.  Each probe is one detached solve."""
    probes = 0

    def fits(n: int) -> Optional[List]:
        nonlocal probes
        probes += 1
        rb = synthesize_binding(AssignReplicasRequest(
            namespace=WHATIF_NS, name=f"headroom-{n}",
            replicas=n, resource_request=req.resource_request,
            divided=True))
        res = _solve_one(scheduler, rb, clusters)
        if isinstance(res, Exception) or res is None:
            return None
        placed = sum(t.replicas for t in res)
        return list(res) if placed >= n else None

    lo = max(int(req.replicas), 1)
    best = fits(lo)
    if best is None:
        return {"max_replicas": 0, "probes": probes, "assignments": []}
    hi = lo * 2
    while probes < HEADROOM_MAX_PROBES:
        targets = fits(hi)
        if targets is None:
            break
        best, lo = targets, hi
        hi *= 2
    # invariant: lo fits (best is its assignment), hi does not
    while hi - lo > 1 and probes < 2 * HEADROOM_MAX_PROBES:
        mid = (lo + hi) // 2
        targets = fits(mid)
        if targets is None:
            hi = mid
        else:
            best, lo = targets, mid
    return {"max_replicas": lo, "probes": probes,
            "assignments": [{"cluster": t.name, "replicas": t.replicas}
                            for t in best]}
