"""Wire-compatible ReplicaEstimator facade: scheduler-as-a-service.

BASELINE.json's north star is the batched TPU solver exposed as a
`ReplicaEstimator`-style service a Go scheduler would call with
`--replica-scheduling-backend=tpu`.  This package is that seam served
over the repo's wire tier (estimator/wire.py's length-prefixed frames —
the gRPC analogue, grpcio being absent by design):

  * **Protocol** — `SelectClusters`/`AssignReplicas` request/response
    messages (estimator/wire.py) plus the facade-only `WhatIf` query
    (messages.py): many independent callers each submit ONE small
    binding and get back a placement.
  * **Coalescing service** — `FacadeService` (service.py) admits
    concurrent in-flight calls through a deadline-vs-size batch former
    (the scheduler's own admission shape), runs ONE detached solve
    through the unchanged pipelined solver, and demuxes per-call
    responses with trace ids + ledger events stamped per caller.  Many
    small RPCs become one device dispatch — the economic argument for
    the TPU sidecar.
  * **What-if plane** — capacity-planning queries (whatif.py) answered
    by hypothetical solves against a copy-on-write fork of the resident
    masters' cluster view, never mutating live state; surfaced at
    `/whatif`, `/debug/facade`, `serve --facade[=ADDR]`, and the
    `karmadactl whatif` / `karmadactl estimate` verbs.

Process-wide registry below follows the loadgen/chaos idiom: `serve
--facade` arms one service, /debug endpoints read it lazily, and a
disarmed plane reports ``{"enabled": False}``.
"""

from __future__ import annotations

import threading
from typing import Optional

from karmada_tpu.facade.client import FacadeClient
from karmada_tpu.facade.messages import (
    FACADE_METHODS,
    WhatIfRequest,
    WhatIfResponse,
)
from karmada_tpu.facade.service import FacadeService

__all__ = [
    "FACADE_METHODS",
    "FacadeClient",
    "FacadeService",
    "WhatIfRequest",
    "WhatIfResponse",
    "active",
    "set_active",
    "state_payload",
    "whatif_payload",
]

_LOCK = threading.Lock()
_ACTIVE: list = [None]


def set_active(service: Optional[FacadeService]) -> None:
    with _LOCK:
        _ACTIVE[0] = service


def active() -> Optional[FacadeService]:
    with _LOCK:
        return _ACTIVE[0]


def state_payload() -> dict:
    """/debug/facade: the armed service's coalescing/what-if counters,
    or the disarmed sentinel."""
    svc = active()
    if svc is None:
        return {"enabled": False}
    return svc.state_payload()


def whatif_payload(params: dict) -> dict:
    """/whatif: run one capacity-planning query against the armed
    service (query params -> WhatIfRequest -> hypothetical solve)."""
    svc = active()
    if svc is None:
        return {"enabled": False,
                "error": "facade plane not armed (serve --facade)"}
    try:
        req = WhatIfRequest.from_params(params)
        return svc.whatif(req).to_json()
    except ValueError as e:  # unknown query / unparseable number -> 400
        return {"enabled": True, "error": str(e)}
