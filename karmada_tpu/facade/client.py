"""Caller-side facade stub: the hardened wire path for facade verbs.

Reuses the estimator tier's whole failure machinery — the typed error
taxonomy (classify_exception), the circuit breaker, and the
`estimator.rpc` chaos seam — so a facade endpoint fault flows through
EXACTLY the paths the per-cluster estimator faults already exercise:
error/timeout/slow/garbage fired at this transport surface as
EstimatorUnreachable / EstimatorTimeout / EstimatorMalformed, the
breaker opens after consecutive failures and half-open-recovers after
its window.  The chaos soak's SafetyAuditor therefore audits facade
outages with zero new machinery.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from karmada_tpu import chaos
from karmada_tpu.estimator import wire
from karmada_tpu.estimator.client import (
    ESTIMATOR_ERRORS,
    CircuitBreaker,
    EstimatorCircuitOpen,
    EstimatorError,
    EstimatorUnreachable,
    classify_exception,
)
from karmada_tpu.facade.messages import WhatIfRequest, WhatIfResponse

#: the breaker "cluster" key for a facade endpoint (one endpoint = one
#: circuit, the per-cluster analogue)
FACADE_ENDPOINT = "facade"


class FacadeClient:
    """One facade endpoint: typed errors, retry, one breaker circuit.

    ``transport`` is any wire.Transport (TcpTransport against a served
    facade, LocalTransport(service.dispatch) in-process) or a bare
    ``(host, port)`` pair, dialed as a TcpTransport — the address
    `FacadeService.serve` returned is directly constructible.  ``sleep``
    is injectable so compressed-time soaks never wall-sleep."""

    def __init__(self, transport, *,
                 endpoint: str = FACADE_ENDPOINT,
                 breaker: Optional[CircuitBreaker] = None,
                 retry_attempts: int = 2,
                 retry_base_s: float = 0.02,
                 sleep: Callable[[float], None] = time.sleep) -> None:
        if isinstance(transport, (tuple, list)):
            transport = wire.TcpTransport(*transport)
        self.transport = transport
        self.endpoint = endpoint
        self.breaker = breaker if breaker is not None else CircuitBreaker()
        self.retry_attempts = max(1, retry_attempts)
        self.retry_base_s = retry_base_s
        self._sleep = sleep

    def close(self) -> None:
        self.transport.close()

    # -- verbs ----------------------------------------------------------------
    def assign_replicas(
            self,
            req: wire.AssignReplicasRequest) -> wire.AssignReplicasResponse:
        if not req.trace_id:
            # stamp the caller's ambient trace id onto the frame so the
            # server-side flight record of the coalesced batch can
            # stitch this caller's timeline (obs/incidents)
            from karmada_tpu import obs

            sp = obs.TRACER.current()
            if sp is not None:
                req.trace_id = sp.trace.trace_id
        return wire.AssignReplicasResponse.from_json(
            self._call("AssignReplicas", req.to_json()))

    def select_clusters(
            self,
            req: wire.SelectClustersRequest) -> wire.SelectClustersResponse:
        return wire.SelectClustersResponse.from_json(
            self._call("SelectClusters", req.to_json()))

    def whatif(self, req: WhatIfRequest) -> WhatIfResponse:
        return WhatIfResponse.from_json(self._call("WhatIf", req.to_json()))

    # -- the hardened wire path ----------------------------------------------
    def _transport_call(self, method: str, payload: dict) -> dict:
        """One raw attempt with the chaos seam in front of the wire —
        the same `estimator.rpc` site the accurate tier fires, keyed by
        this endpoint, so one fault grammar covers both planes."""
        if chaos.armed():
            f = chaos.fire(chaos.SITE_ESTIMATOR_RPC, cluster=self.endpoint,
                           method=method)
            if f is not None:
                if f.mode == "error":
                    raise ConnectionError("chaos: facade connection refused")
                if f.mode == "timeout":
                    raise TimeoutError("chaos: facade call timed out")
                if f.mode == "slow":
                    self._sleep(f.delay)
                elif f.mode == "garbage":
                    # structurally unusable on every verb's parse path
                    return {"assignments": 0, "clusters": 0, "excluded": 0,
                            "result": 0}
        return self.transport.call(method, payload)

    def _call(self, method: str, payload: dict) -> dict:
        """Breaker gate, bounded retry, typed classification — the
        estimator client's _request shape for a single endpoint."""
        if not self.breaker.allow(self.endpoint):
            ESTIMATOR_ERRORS.inc(kind=EstimatorCircuitOpen.kind)
            raise EstimatorCircuitOpen(
                f"facade circuit open for endpoint {self.endpoint!r}")
        err: EstimatorError = EstimatorUnreachable("no attempt made")
        for attempt in range(self.retry_attempts):
            if attempt:
                self._sleep(self.retry_base_s * (2 ** (attempt - 1)))
            try:
                reply = self._transport_call(method, payload)
                # force the parse NOW so a garbage reply classifies as
                # malformed inside the retry loop, not at the caller
                self._parse_check(method, reply)
            except Exception as exc:  # noqa: BLE001 — classified + counted
                err = classify_exception(exc)
                ESTIMATOR_ERRORS.inc(kind=err.kind)
                continue
            self.breaker.record_success(self.endpoint)
            return reply
        self.breaker.record_failure(self.endpoint)
        raise err

    @staticmethod
    def _parse_check(method: str, reply: dict) -> None:
        if method == "AssignReplicas":
            wire.AssignReplicasResponse.from_json(reply)
        elif method == "SelectClusters":
            wire.SelectClustersResponse.from_json(reply)
        elif method == "WhatIf":
            WhatIfResponse.from_json(reply)
