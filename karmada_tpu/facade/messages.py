"""Facade message schemas + the method registry.

`SelectClusters`/`AssignReplicas` live in estimator/wire.py (they are
wire-tier contract messages, alongside the pb equivalents); the
facade-only `WhatIf` query pair lives here.  Every message is a
dataclass with explicit camelCase to/from_json — the wire-drift test
(tests/test_facade.py) round-trips seeded instances of each entry in
``FACADE_METHODS``/``FACADE_RESPONSES`` so a field rename cannot
silently fork the wire format.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict

from karmada_tpu.estimator.wire import (
    AssignReplicasRequest,
    AssignReplicasResponse,
    SelectClustersRequest,
    SelectClustersResponse,
)

QUERY_PLACEMENT = "placement"
QUERY_CLUSTER_LOSS = "cluster-loss"
QUERY_HEADROOM = "headroom"

QUERIES = (QUERY_PLACEMENT, QUERY_CLUSTER_LOSS, QUERY_HEADROOM)


@dataclass
class WhatIfRequest:
    """One capacity-planning question.  kinds:

    placement     where would `replicas` new replicas land right now
    cluster-loss  which single cluster loss strands the most replicas
                  (`cluster` restricts to one named candidate)
    headroom      the largest replica count that still fully schedules
                  (bisected; `replicas` seeds the search)
    """

    query: str = QUERY_PLACEMENT
    replicas: int = 1
    resource_request: Dict[str, str] = field(default_factory=dict)
    divided: bool = True
    cluster: str = ""
    # cluster-loss: per-cluster re-solve cap (truncation is reported)
    limit: int = 512

    def to_json(self) -> dict:
        return {"query": self.query, "replicas": self.replicas,
                "resourceRequest": self.resource_request,
                "divided": self.divided, "cluster": self.cluster,
                "limit": self.limit}

    @staticmethod
    def from_json(d: dict) -> "WhatIfRequest":
        return WhatIfRequest(
            query=d.get("query", QUERY_PLACEMENT),
            replicas=int(d.get("replicas", 1)),
            resource_request=dict(d.get("resourceRequest", {})),
            divided=bool(d.get("divided", True)),
            cluster=d.get("cluster", ""),
            limit=int(d.get("limit", 512)),
        )

    @staticmethod
    def from_params(params: dict) -> "WhatIfRequest":
        """HTTP query params (/whatif?query=...&replicas=...&cpu=...&
        memory=...) — every value arrives as a string."""
        req: Dict[str, str] = {}
        if params.get("cpu"):
            req["cpu"] = str(params["cpu"])
        if params.get("memory"):
            req["memory"] = str(params["memory"])
        return WhatIfRequest(
            query=str(params.get("query", QUERY_PLACEMENT)),
            replicas=int(params.get("replicas", 1)),
            resource_request=req,
            divided=str(params.get("divided", "true")).lower() != "false",
            cluster=str(params.get("cluster", "")),
            limit=int(params.get("limit", 512)),
        )


@dataclass
class WhatIfResponse:
    """`source` names the forked snapshot tier ("resident" when the
    resident masters' cluster view was forked, "store" otherwise);
    `result` is the per-query payload (whatif.py documents each)."""

    query: str = QUERY_PLACEMENT
    source: str = "store"
    result: Dict = field(default_factory=dict)

    def to_json(self) -> dict:
        return {"query": self.query, "source": self.source,
                "result": self.result}

    @staticmethod
    def from_json(d: dict) -> "WhatIfResponse":
        return WhatIfResponse(
            query=d.get("query", QUERY_PLACEMENT),
            source=d.get("source", "store"),
            result=dict(d.get("result", {})),
        )


#: facade wire methods -> request class (the _METHODS analogue)
FACADE_METHODS = {
    "SelectClusters": SelectClustersRequest,
    "AssignReplicas": AssignReplicasRequest,
    "WhatIf": WhatIfRequest,
}

#: facade wire methods -> response class (wire-drift fixture coverage)
FACADE_RESPONSES = {
    "SelectClusters": SelectClustersResponse,
    "AssignReplicas": AssignReplicasResponse,
    "WhatIf": WhatIfResponse,
}
