"""Kind-aware table printers (reference pkg/printers — the server-side
table renderers for aggregated APIs; here one shared implementation serves
karmadactl and the search/proxy surfaces)."""

from __future__ import annotations

from typing import Callable, Dict, List, Tuple

Row = List[str]


def _meta_cols(o) -> Tuple[str, str]:
    return (o.metadata.namespace or "-", o.metadata.name)


def _cluster_row(o) -> Row:
    ns, name = _meta_cols(o)
    return [
        name,
        str(getattr(o, "ready", "-")),
        o.spec.sync_mode,
        o.spec.region or "-",
        o.spec.provider or "-",
        str(len(o.spec.taints)),
    ]


def _binding_row(o) -> Row:
    ns, name = _meta_cols(o)
    clusters = ",".join(
        f"{tc.name}:{tc.replicas}" for tc in o.spec.clusters) or "-"
    return [ns, name, str(o.spec.replicas), clusters]


def _work_row(o) -> Row:
    ns, name = _meta_cols(o)
    applied = "-"
    for c in o.status.conditions:
        if c.type == "Applied":
            applied = c.status
    return [ns, name, str(len(o.spec.workload)), applied]


def _unstructured_row(o) -> Row:
    ns, name = _meta_cols(o)
    spec = o.manifest.get("spec", {}) if hasattr(o, "manifest") else {}
    status = o.manifest.get("status", {}) if hasattr(o, "manifest") else {}
    replicas = spec.get("replicas", "-")
    ready = status.get("readyReplicas", status.get("ready", "-"))
    return [ns, name, o.KIND, str(replicas), str(ready)]


def _default_row(o) -> Row:
    ns, name = _meta_cols(o)
    return [ns, name, type(o).__name__]


_PRINTERS: Dict[str, Tuple[List[str], Callable]] = {
    "Cluster": (
        ["NAME", "READY", "MODE", "REGION", "PROVIDER", "TAINTS"],
        _cluster_row,
    ),
    "ResourceBinding": (
        ["NAMESPACE", "NAME", "REPLICAS", "CLUSTERS"],
        _binding_row,
    ),
    "ClusterResourceBinding": (
        ["NAMESPACE", "NAME", "REPLICAS", "CLUSTERS"],
        _binding_row,
    ),
    "Work": (
        ["NAMESPACE", "NAME", "MANIFESTS", "APPLIED"],
        _work_row,
    ),
}

_DEFAULT = (["NAMESPACE", "NAME", "TYPE"], _default_row)
_UNSTRUCTURED = (["NAMESPACE", "NAME", "KIND", "REPLICAS", "READY"],
                 _unstructured_row)


def table_for(kind: str, objs) -> Tuple[List[str], List[Row]]:
    """(headers, rows) for a homogeneous object list."""
    headers, fn = _PRINTERS.get(kind, _DEFAULT)
    if kind not in _PRINTERS and objs and hasattr(objs[0], "manifest"):
        headers, fn = _UNSTRUCTURED
    rows = []
    for o in objs:
        try:
            rows.append(fn(o))
        # vet: ignore[exception-hygiene] a malformed object still renders a table row
        except Exception:  # noqa: BLE001 — a malformed object still prints
            rows.append(_default_row(o))
    return headers, rows


def render(headers: List[str], rows: List[Row]) -> str:
    cells = [headers] + rows
    widths = [max(len(str(r[i])) for r in cells) for i in range(len(headers))]
    return "\n".join(
        "  ".join(str(v).ljust(w) for v, w in zip(r, widths)) for r in cells
    )
