"""Declarative interpreter tier: sandboxed, data-driven customizations.

Reference: pkg/resourceinterpreter/customized/declarative/ — user-supplied
Lua scripts from ResourceInterpreterCustomization objects run in a
sandboxed gopher-lua VM (luavm/lua.go:1-422) per operation, ranked above
the third-party bundle and the native defaults.

This framework's script dialect is a restricted EXPRESSION language with
Python syntax, evaluated over a whitelisted AST — no imports, no attribute
access, no statements, no dunder anything; only literals, arithmetic,
comparisons, conditionals, comprehensions, subscripts, and calls to the
helper functions below.  A customization is pure data: it can be created,
updated and deleted at runtime through the store, and changes take effect
without touching framework code (the point of the feature).

Bound names per operation (mirroring the reference's Lua conventions,
luavm/lua.go GetReplicas(obj)/ReviseReplica(obj, replicas)/...):

  InterpretReplica    obj                       -> int | {"replicas": int,
                                                   "requirements": {res: qty}}
  InterpretComponent  obj                       -> [{"name","replicas",
                                                     "requirements"}]
  ReviseReplica       obj, replicas             -> manifest
  Retain              desired, observed         -> manifest
  AggregateStatus     obj, items ([{cluster,status}]) -> manifest
  InterpretStatus     obj                       -> dict (reflected status)
  InterpretHealth     obj                       -> bool
  InterpretDependency obj                       -> [{apiVersion,kind,
                                                    namespace,name}]

Helpers: get(d, "a.b", default), set(d, "a.b", v) (copy-on-write),
merge(a, b), quantity("500m") -> milli, plus len/int/float/str/bool/min/
max/sum/round/sorted/any/all/abs.
"""

from __future__ import annotations

import ast
import copy
from typing import Any, Callable, Dict, List, Optional, Tuple

from karmada_tpu.models.config import ResourceInterpreterCustomization
from karmada_tpu.models.meta import deep_get, deep_set
from karmada_tpu.utils.quantity import Quantity


class ScriptError(Exception):
    """Compile- or eval-time failure of a customization script."""


_ALLOWED_NODES = (
    ast.Expression, ast.BoolOp, ast.BinOp, ast.UnaryOp, ast.IfExp,
    ast.Dict, ast.List, ast.Tuple, ast.Set, ast.Compare, ast.Call,
    # Store appears only as comprehension-target context in eval mode
    # (assignment statements cannot parse); real stores are unreachable
    ast.Constant, ast.Name, ast.Load, ast.Store, ast.Subscript, ast.Slice,
    ast.ListComp, ast.DictComp, ast.SetComp, ast.GeneratorExp,
    ast.comprehension, ast.keyword, ast.Starred,
    # operators
    ast.And, ast.Or, ast.Not, ast.Add, ast.Sub, ast.Mult, ast.Div,
    ast.FloorDiv, ast.Mod, ast.Pow, ast.USub, ast.UAdd,
    ast.Eq, ast.NotEq, ast.Lt, ast.LtE, ast.Gt, ast.GtE,
    ast.In, ast.NotIn, ast.Is, ast.IsNot,
)


def _safe_get(d: Any, path: str, default: Any = None) -> Any:
    return deep_get(d, path, default)


def _safe_set(d: Dict[str, Any], path: str, value: Any) -> Dict[str, Any]:
    out = copy.deepcopy(d)
    deep_set(out, path, value)
    return out


def _safe_merge(a: Dict[str, Any], b: Dict[str, Any]) -> Dict[str, Any]:
    out = copy.deepcopy(a)
    for k, v in (b or {}).items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _safe_merge(out[k], v)
        else:
            out[k] = copy.deepcopy(v)
    return out


def _safe_quantity(raw: Any) -> int:
    return Quantity.parse(raw).milli


_SAFE_FUNCS: Dict[str, Callable] = {
    "get": _safe_get,
    "set": _safe_set,
    "merge": _safe_merge,
    "quantity": _safe_quantity,
    # attribute access is forbidden, so dict methods become helpers
    "items": lambda d: list((d or {}).items()),
    "keys": lambda d: list((d or {}).keys()),
    "values": lambda d: list((d or {}).values()),
    "len": len, "int": int, "float": float, "str": str, "bool": bool,
    "min": min, "max": max, "sum": sum, "round": round, "sorted": sorted,
    "any": any, "all": all, "abs": abs, "enumerate": enumerate,
    "range": range, "zip": zip, "list": list,
}


def compile_script(script: str) -> Callable[[Dict[str, Any]], Any]:
    """Compile one sandboxed expression; returns eval(env_names) -> value."""
    try:
        tree = ast.parse(script, mode="eval")
    except SyntaxError as e:
        raise ScriptError(f"syntax error: {e}") from e
    for node in ast.walk(tree):
        if not isinstance(node, _ALLOWED_NODES):
            raise ScriptError(
                f"forbidden construct {type(node).__name__} in script"
            )
        if isinstance(node, ast.Name) and node.id.startswith("__"):
            raise ScriptError("dunder names are forbidden")
    code = compile(tree, "<customization>", "eval")

    def run(env: Dict[str, Any]) -> Any:
        full = dict(_SAFE_FUNCS)
        full.update(env)
        try:
            return eval(code, {"__builtins__": {}}, full)  # noqa: S307 — sandboxed AST
        except Exception as e:  # noqa: BLE001
            raise ScriptError(f"script failed: {e!r}") from e

    return run


# -- operation adapters: script values -> facade types -----------------------


def _to_requirements(req: Optional[Dict[str, Any]], namespace: str):
    from karmada_tpu.models.work import ReplicaRequirements

    if not req:
        return None
    return ReplicaRequirements(
        resource_request={k: Quantity.parse(v) for k, v in req.items()},
        namespace=namespace,
    )


def make_hooks(scripts: Dict[str, str]) -> Dict[str, Callable]:
    """Compile a customization's op->script table into facade hooks."""
    from karmada_tpu.interpreter.interpreter import (
        HEALTHY,
        OP_AGGREGATE_STATUS,
        OP_INTERPRET_COMPONENT,
        OP_INTERPRET_DEPENDENCY,
        OP_INTERPRET_HEALTH,
        OP_INTERPRET_REPLICA,
        OP_INTERPRET_STATUS,
        OP_RETAIN,
        OP_REVISE_REPLICA,
        UNHEALTHY,
        DependentObjectReference,
    )
    from karmada_tpu.models.work import Component

    hooks: Dict[str, Callable] = {}
    compiled = {op: compile_script(s) for op, s in scripts.items()}

    if OP_INTERPRET_REPLICA in compiled:
        fn = compiled[OP_INTERPRET_REPLICA]

        def get_replicas(manifest, fn=fn):
            ns = deep_get(manifest, "metadata.namespace", "")
            v = fn({"obj": manifest})
            if isinstance(v, dict):
                return int(v.get("replicas", 0)), _to_requirements(
                    v.get("requirements"), ns
                )
            return int(v or 0), None
        hooks[OP_INTERPRET_REPLICA] = get_replicas

    if OP_INTERPRET_COMPONENT in compiled:
        fn = compiled[OP_INTERPRET_COMPONENT]

        def get_components(manifest, fn=fn):
            ns = deep_get(manifest, "metadata.namespace", "")
            out = []
            for c in fn({"obj": manifest}) or []:
                out.append(Component(
                    name=c.get("name", ""),
                    replicas=int(c.get("replicas", 0)),
                    replica_requirements=_to_requirements(
                        c.get("requirements"), ns
                    ),
                ))
            return out
        hooks[OP_INTERPRET_COMPONENT] = get_components

    if OP_REVISE_REPLICA in compiled:
        fn = compiled[OP_REVISE_REPLICA]
        hooks[OP_REVISE_REPLICA] = lambda manifest, replicas, fn=fn: fn(
            {"obj": manifest, "replicas": int(replicas)}
        )

    if OP_RETAIN in compiled:
        fn = compiled[OP_RETAIN]
        hooks[OP_RETAIN] = lambda desired, observed, fn=fn: fn(
            {"desired": desired, "observed": observed}
        )

    if OP_AGGREGATE_STATUS in compiled:
        fn = compiled[OP_AGGREGATE_STATUS]

        def aggregate(manifest, items, fn=fn):
            plain = [
                {"cluster": i.cluster_name, "status": (i.status or {})}
                for i in items
            ]
            return fn({"obj": manifest, "items": plain})
        hooks[OP_AGGREGATE_STATUS] = aggregate

    if OP_INTERPRET_STATUS in compiled:
        fn = compiled[OP_INTERPRET_STATUS]
        hooks[OP_INTERPRET_STATUS] = lambda manifest, fn=fn: fn({"obj": manifest})

    if OP_INTERPRET_HEALTH in compiled:
        fn = compiled[OP_INTERPRET_HEALTH]
        hooks[OP_INTERPRET_HEALTH] = lambda manifest, fn=fn: (
            HEALTHY if fn({"obj": manifest}) else UNHEALTHY
        )

    if OP_INTERPRET_DEPENDENCY in compiled:
        fn = compiled[OP_INTERPRET_DEPENDENCY]

        def dependencies(manifest, fn=fn):
            out = []
            for d in fn({"obj": manifest}) or []:
                out.append(DependentObjectReference(
                    api_version=d.get("apiVersion", ""),
                    kind=d.get("kind", ""),
                    namespace=d.get("namespace",
                                    deep_get(manifest, "metadata.namespace", "")),
                    name=d.get("name", ""),
                ))
            return out
        hooks[OP_INTERPRET_DEPENDENCY] = dependencies

    return hooks


class DeclarativeManager:
    """Store-driven customization tier: watches
    ResourceInterpreterCustomization objects and keeps a compiled hook
    table per (apiVersion, kind).  Multiple customizations targeting the
    same kind merge in name order (alphabetically first wins per op),
    matching the reference's deterministic config ordering."""

    def __init__(self) -> None:
        self._store = None
        self._compiled: Dict[Tuple[str, str], Dict[str, Callable]] = {}

    def attach_store(self, store) -> None:
        self._store = store
        store.bus.subscribe(
            self._on_event, kind=ResourceInterpreterCustomization.KIND
        )
        self._rebuild()

    def _on_event(self, event) -> None:
        self._rebuild()

    def _rebuild(self) -> None:
        if self._store is None:
            return
        table: Dict[Tuple[str, str], Dict[str, Callable]] = {}
        customizations = sorted(
            self._store.list(ResourceInterpreterCustomization.KIND),
            key=lambda c: c.metadata.name,
        )
        for cust in customizations:
            if cust.metadata.deleting:
                continue
            key = (cust.spec.target.api_version, cust.spec.target.kind)
            try:
                hooks = make_hooks(cust.spec.customizations)
            except ScriptError:
                continue  # invalid scripts never shadow working tiers
            slot = table.setdefault(key, {})
            for op, hook in hooks.items():
                slot.setdefault(op, hook)  # first (alphabetical) wins
        self._compiled = table

    def hook(self, api_version: str, kind: str, op: str) -> Optional[Callable]:
        return self._compiled.get((api_version, kind), {}).get(op)
