"""Interpreter webhook tier — out-of-process customizations over HTTP.

Reference: pkg/resourceinterpreter/customized/webhook/ (the engine: match a
manifest against ResourceInterpreterWebhook configs, POST an
InterpreterContext, apply the response) and pkg/webhook/interpreter/ (the
host serving the protocol inside the user's interpreter process).

Wire protocol (the InterpreterContext analog,
pkg/apis/config/v1alpha1/interpretercontext_types.go):

    request  = {"operation": OP_*, "object": {...},
                "desiredReplicas": int?, "observedObject": {...}?,
                "aggregatedStatusItems": [{"cluster": str, "status": {}}]?}
    response = {"successful": bool, "message": str?,
                "replicas": int?, "requirements": {res: "qty"}?,
                "components": [...]?, "revised": {...}?, "retained": {...}?,
                "status": {...}?, "healthy": bool?, "dependencies": [...]?}

Transports: ``http://host:port/path`` via http.client (loopback services),
or ``local:<name>`` resolving to an in-process handler registered with
:func:`register_local_endpoint` — tests and embedded interpreters use the
latter, mirroring estimator/wire.LocalTransport.
"""

from __future__ import annotations

import json
import threading
from typing import Any, Callable, Dict, Optional, Tuple

from karmada_tpu.models.config import ResourceInterpreterWebhook

# in-process endpoints: name -> handler(request_dict) -> response_dict
_LOCAL_ENDPOINTS: Dict[str, Callable[[dict], dict]] = {}
_LOCAL_LOCK = threading.Lock()


class WebhookCallError(Exception):
    """Transport failure or unsuccessful response from an interpreter
    webhook — surfaced to the caller instead of silently falling through
    to a lower tier (interpreter.go treats webhook errors as errors, not
    as absence)."""


def register_local_endpoint(name: str, handler: Callable[[dict], dict]) -> None:
    with _LOCAL_LOCK:
        _LOCAL_ENDPOINTS[f"local:{name}"] = handler


def unregister_local_endpoint(name: str) -> None:
    with _LOCAL_LOCK:
        _LOCAL_ENDPOINTS.pop(f"local:{name}", None)


def _call_endpoint(endpoint: str, request: dict, timeout_s: float) -> dict:
    if endpoint.startswith("local:"):
        with _LOCAL_LOCK:
            handler = _LOCAL_ENDPOINTS.get(endpoint)
        if handler is None:
            raise WebhookCallError(f"no local endpoint {endpoint!r}")
        # JSON round-trip for transport parity with http://: a handler must
        # never receive references into live control-plane manifests
        try:
            return json.loads(json.dumps(handler(json.loads(json.dumps(request)))))
        except WebhookCallError:
            raise
        except Exception as e:  # noqa: BLE001 — handler/serialization fault
            raise WebhookCallError(f"{endpoint}: {e!r}") from e
    if endpoint.startswith("http://"):
        import http.client
        from urllib.parse import urlparse

        u = urlparse(endpoint)
        conn = http.client.HTTPConnection(u.hostname, u.port, timeout=timeout_s)
        try:
            body = json.dumps(request)
            conn.request("POST", u.path or "/", body,
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            data = resp.read()
            if resp.status != 200:
                raise WebhookCallError(
                    f"{endpoint}: HTTP {resp.status} {data[:200]!r}")
            return json.loads(data)
        except WebhookCallError:
            raise
        except Exception as e:  # noqa: BLE001 — network layer
            raise WebhookCallError(f"{endpoint}: {e!r}") from e
        finally:
            conn.close()
    raise WebhookCallError(f"unsupported endpoint scheme {endpoint!r}")


def _rule_matches(rule, api_version: str, kind: str, op: str) -> bool:
    """Wildcards must be EXPLICIT ("*") on every axis: an empty pattern
    list matches nothing, so a partially-filled InterpreterRule can never
    hijack kinds or operations the user did not spell out."""
    def hit(patterns, value) -> bool:
        return any(p == "*" or p == value for p in patterns)

    return (hit(rule.api_versions, api_version)
            and hit(rule.kinds, kind)
            and hit(rule.operations, op))


class WebhookManager:
    """Store-fed registry of ResourceInterpreterWebhook configs; produces
    facade hooks (same calling conventions as declarative.make_hooks) that
    forward over the wire."""

    def __init__(self) -> None:
        self._configs: Dict[str, ResourceInterpreterWebhook] = {}
        self._lock = threading.Lock()
        # resolved-hook cache, invalidated wholesale on any config change —
        # hook() sits on every controller's interpretation hot path
        self._gen = 0
        self._hook_cache: Dict[Tuple[str, str, str],
                               Tuple[int, Optional[Callable]]] = {}

    def attach_store(self, store) -> None:
        # subscribe FIRST, then rebuild: a config created in the gap is
        # delivered as an event instead of being lost forever
        store.bus.subscribe(self._on_event, kind=ResourceInterpreterWebhook.KIND)
        with self._lock:
            for obj in store.list(ResourceInterpreterWebhook.KIND):
                self._configs[obj.metadata.name] = obj

    def _on_event(self, event) -> None:
        obj = event.obj
        with self._lock:
            if event.type == "DELETED" or obj.metadata.deleting:
                self._configs.pop(obj.metadata.name, None)
            else:
                self._configs[obj.metadata.name] = obj
            self._gen += 1
            self._hook_cache.clear()

    def _find(self, api_version: str, kind: str, op: str):
        with self._lock:
            configs = sorted(self._configs.values(), key=lambda c: c.metadata.name)
        for cfg in configs:
            for rule in cfg.spec.rules:
                if _rule_matches(rule, api_version, kind, op):
                    return cfg
        return None

    def hook(self, api_version: str, kind: str, op: str) -> Optional[Callable]:
        key = (api_version, kind, op)
        with self._lock:
            gen = self._gen
            cached = self._hook_cache.get(key)
            if cached is not None and cached[0] == gen:
                return cached[1]
        resolved = self._resolve(api_version, kind, op)
        with self._lock:
            if self._gen == gen:  # a config change mid-resolve invalidates
                self._hook_cache[key] = (gen, resolved)
        return resolved

    def _resolve(self, api_version: str, kind: str, op: str) -> Optional[Callable]:
        cfg = self._find(api_version, kind, op)
        if cfg is None:
            return None
        endpoint = cfg.spec.endpoint
        timeout_s = cfg.spec.timeout_s

        def call(request: dict) -> dict:
            request["operation"] = op
            resp = _call_endpoint(endpoint, request, timeout_s)
            if not isinstance(resp, dict):
                raise WebhookCallError(
                    f"{endpoint}: response is {type(resp).__name__}, "
                    "expected an object")
            if not resp.get("successful", False):
                raise WebhookCallError(
                    f"{endpoint}: {resp.get('message', 'unsuccessful')}")
            return resp

        return _bind_hook(op, call)


def _to_requirements(req: Optional[Dict[str, Any]], namespace: str):
    from karmada_tpu.interpreter.declarative import _to_requirements as conv

    return conv(req, namespace)


def _bind_hook(op: str, call: Callable[[dict], dict]) -> Callable:
    """Adapt the wire response to the facade hook convention for `op`
    (mirrors declarative.make_hooks signatures)."""
    from karmada_tpu.interpreter.interpreter import (
        HEALTHY,
        OP_AGGREGATE_STATUS,
        OP_INTERPRET_COMPONENT,
        OP_INTERPRET_DEPENDENCY,
        OP_INTERPRET_HEALTH,
        OP_INTERPRET_REPLICA,
        OP_INTERPRET_STATUS,
        OP_RETAIN,
        OP_REVISE_REPLICA,
        UNHEALTHY,
        DependentObjectReference,
    )

    if op == OP_INTERPRET_REPLICA:
        def get_replicas(manifest):
            ns = (manifest.get("metadata") or {}).get("namespace", "")
            r = call({"object": manifest})
            return int(r.get("replicas", 0)), _to_requirements(
                r.get("requirements"), ns)
        return get_replicas

    if op == OP_INTERPRET_COMPONENT:
        def get_components(manifest):
            from karmada_tpu.models.work import Component

            ns = (manifest.get("metadata") or {}).get("namespace", "")
            r = call({"object": manifest})
            return [
                Component(
                    name=c.get("name", ""),
                    replicas=int(c.get("replicas", 0)),
                    replica_requirements=_to_requirements(
                        c.get("requirements"), ns),
                )
                for c in r.get("components", [])
            ]
        return get_components

    if op == OP_REVISE_REPLICA:
        return lambda manifest, replicas: call(
            {"object": manifest, "desiredReplicas": int(replicas)}
        ).get("revised", manifest)

    if op == OP_RETAIN:
        return lambda desired, observed: call(
            {"object": desired, "observedObject": observed}
        ).get("retained", desired)

    if op == OP_AGGREGATE_STATUS:
        def aggregate(manifest, items):
            plain = [{"cluster": i.cluster_name, "status": (i.status or {})}
                     for i in items]
            r = call({"object": manifest, "aggregatedStatusItems": plain})
            # the hook contract returns a FULL manifest (like every other
            # tier); accept either a whole object ("aggregated") or a bare
            # status dict folded onto the input
            if "aggregated" in r:
                return r["aggregated"]
            if "status" in r:
                return {**manifest, "status": r["status"]}
            return manifest
        return aggregate

    if op == OP_INTERPRET_STATUS:
        return lambda manifest: call({"object": manifest}).get("status")

    if op == OP_INTERPRET_HEALTH:
        return lambda manifest: (
            HEALTHY if call({"object": manifest}).get("healthy") else UNHEALTHY
        )

    if op == OP_INTERPRET_DEPENDENCY:
        def dependencies(manifest):
            ns = (manifest.get("metadata") or {}).get("namespace", "")
            r = call({"object": manifest})
            return [
                DependentObjectReference(
                    api_version=d.get("apiVersion", ""),
                    kind=d.get("kind", ""),
                    namespace=d.get("namespace", ns),
                    name=d.get("name", ""),
                )
                for d in r.get("dependencies", [])
            ]
        return dependencies

    return None


# ---------------------------------------------------------------------------
# Host side: serve the protocol for user-implemented interpreters
# (pkg/webhook/interpreter — the karmada-webhook binary's interpreter host)
# ---------------------------------------------------------------------------


class InterpreterWebhookServer:
    """Minimal HTTP host: register per-operation python callables, serve
    them under the wire protocol.  `start()` binds 127.0.0.1 on an
    ephemeral port and returns the endpoint URL."""

    def __init__(self) -> None:
        self._ops: Dict[Tuple[str, str, str], Callable[[dict], dict]] = {}
        self._httpd = None
        self._thread: Optional[threading.Thread] = None

    def handle(self, api_version: str, kind: str, op: str,
               fn: Callable[[dict], dict]) -> None:
        """fn receives the request dict, returns the response dict body
        (successful defaults True)."""
        self._ops[(api_version, kind, op)] = fn

    def _dispatch(self, request: dict) -> dict:
        obj = request.get("object") or {}
        key = (obj.get("apiVersion", ""), obj.get("kind", ""),
               request.get("operation", ""))
        fn = self._ops.get(key)
        if fn is None:
            return {"successful": False,
                    "message": f"no handler for {key}"}
        try:
            resp = fn(request)
            if not isinstance(resp, dict):
                raise TypeError(
                    f"handler for {key} returned {type(resp).__name__}, "
                    "expected a response dict")
            resp.setdefault("successful", True)
            return resp
        # vet: ignore[exception-hygiene] returned as an unsuccessful admission response
        except Exception as e:  # noqa: BLE001 — user handler fault
            return {"successful": False, "message": repr(e)}

    def as_local_endpoint(self, name: str) -> str:
        """Register in-process (no socket) under ``local:<name>``."""
        register_local_endpoint(name, self._dispatch)
        return f"local:{name}"

    def start(self) -> str:
        import http.server

        dispatch = self._dispatch

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802 — http.server convention
                length = int(self.headers.get("Content-Length", 0))
                try:
                    request = json.loads(self.rfile.read(length))
                    body = json.dumps(dispatch(request)).encode()
                    self.send_response(200)
                # vet: ignore[exception-hygiene] serialized as the HTTP 500 response body
                except Exception as e:  # noqa: BLE001
                    body = json.dumps(
                        {"successful": False, "message": repr(e)}).encode()
                    self.send_response(500)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # silence per-request stderr noise
                pass

        self._httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        host, port = self._httpd.server_address
        return f"http://{host}:{port}/interpret"

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
