"""Resource interpreter (L2): how the framework understands workload kinds.

Mirrors the reference ResourceInterpreter facade
(pkg/resourceinterpreter/interpreter.go:43-150) and its priority chain:
customized hooks (the reference's webhook / declarative-Lua tiers; here
registered Python callables) take precedence over the built-in native
defaults (pkg/resourceinterpreter/default/native/*.go).

Operations (interpreter.go:43-81): GetReplicas, ReviseReplica, Retain,
AggregateStatus, GetDependencies, ReflectStatus, InterpretHealth.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from karmada_tpu.models.meta import deep_get
from karmada_tpu.models.work import (
    AggregatedStatusItem,
    ReplicaRequirements,
)
from karmada_tpu.utils.quantity import Quantity

# operation names (config/v1alpha1 InterpreterOperation)
OP_INTERPRET_REPLICA = "InterpretReplica"
OP_INTERPRET_COMPONENT = "InterpretComponent"
OP_REVISE_REPLICA = "ReviseReplica"
OP_RETAIN = "Retain"
OP_AGGREGATE_STATUS = "AggregateStatus"
OP_INTERPRET_DEPENDENCY = "InterpretDependency"
OP_INTERPRET_STATUS = "InterpretStatus"
OP_INTERPRET_HEALTH = "InterpretHealth"

HEALTHY = "Healthy"
UNHEALTHY = "Unhealthy"
UNKNOWN = "Unknown"


@dataclass
class DependentObjectReference:
    """A dependency the workload needs propagated alongside it
    (pkg/apis/config/v1alpha1 DependentObjectReference)."""

    api_version: str = ""
    kind: str = ""
    namespace: str = ""
    name: str = ""
    label_selector: Optional[Dict[str, Any]] = None


@dataclass
class Customization:
    """Per-(apiVersion, kind) hook table -- the framework's counterpart of a
    ResourceInterpreterCustomization Lua script or interpreter webhook."""

    api_version: str = ""
    kind: str = ""
    hooks: Dict[str, Callable] = field(default_factory=dict)


def _pod_template_requirements(pod_spec: Dict[str, Any], namespace: str) -> Optional[ReplicaRequirements]:
    """Aggregate container resource requests into ReplicaRequirements
    (mirrors helper GetReplicaRequirements semantics: sum container requests)."""
    if not pod_spec:
        return None
    totals: Dict[str, int] = {}
    for container in pod_spec.get("containers", []) or []:
        requests = deep_get(container, "resources.requests", {}) or {}
        for name, raw in requests.items():
            totals[name] = totals.get(name, 0) + Quantity.parse(raw).milli
    node_selector = pod_spec.get("nodeSelector") or {}
    priority_class = pod_spec.get("priorityClassName", "")
    if not totals and not node_selector and not priority_class:
        return None
    return ReplicaRequirements(
        resource_request={k: Quantity.from_milli(v) for k, v in totals.items()},
        namespace=namespace,
        priority_class_name=priority_class,
    )


_PRUNED_METADATA = (
    "resourceVersion", "uid", "generation", "creationTimestamp",
    "deletionTimestamp", "selfLink", "managedFields", "ownerReferences",
)


def prune_for_propagation(manifest: Dict[str, Any]) -> Dict[str, Any]:
    """Strip server-populated fields before packing into a Work
    (pkg/resourceinterpreter/default/native/prune): status and system
    metadata never propagate to member clusters."""
    out = copy.deepcopy(manifest)
    out.pop("status", None)
    md = out.get("metadata")
    if isinstance(md, dict):
        for f in _PRUNED_METADATA:
            md.pop(f, None)
    return out


class ResourceInterpreter:
    """Facade dispatching per-kind with the reference's tier priority
    (interpreter.go:104-150): customized webhook (out-of-process, over
    HTTP — interpreter/webhook.py) > in-process registered hooks >
    declarative store customizations > third-party bundle > native
    defaults."""

    def __init__(self) -> None:
        from karmada_tpu.interpreter.declarative import DeclarativeManager
        from karmada_tpu.interpreter.webhook import WebhookManager

        self._customizations: Dict[Tuple[str, str], Customization] = {}
        self.declarative = DeclarativeManager()
        self.webhooks = WebhookManager()

    def attach_store(self, store) -> None:
        """Enable the store-fed customization tiers:
        ResourceInterpreterCustomization objects become declarative
        customizations, ResourceInterpreterWebhook objects become live
        out-of-process interpreters."""
        self.declarative.attach_store(store)
        self.webhooks.attach_store(store)

    # -- in-process customization registry (outranked by the webhook tier) --
    def register(self, customization: Customization) -> None:
        key = (customization.api_version, customization.kind)
        self._customizations[key] = customization

    def unregister(self, api_version: str, kind: str) -> None:
        self._customizations.pop((api_version, kind), None)

    def _hook(self, manifest: Dict[str, Any], op: str) -> Optional[Callable]:
        """Tier priority (interpreter.go:104-150): customized webhook >
        in-process registered hooks > declarative store customizations >
        third-party bundle; callers fall through to native defaults."""
        from karmada_tpu.interpreter.thirdparty import thirdparty_hook

        api_version = manifest.get("apiVersion", "")
        kind = manifest.get("kind", "")
        hook = self.webhooks.hook(api_version, kind, op)
        if hook is not None:
            return hook
        c = self._customizations.get((api_version, kind))
        if c is not None and op in c.hooks:
            return c.hooks[op]
        hook = self.declarative.hook(api_version, kind, op)
        if hook is not None:
            return hook
        return thirdparty_hook(api_version, kind, op)

    # -- operations ---------------------------------------------------------
    def get_replicas(self, manifest: Dict[str, Any]) -> Tuple[int, Optional[ReplicaRequirements]]:
        """(replica count, per-replica requirements) for a workload
        (native/replica.go)."""
        hook = self._hook(manifest, OP_INTERPRET_REPLICA)
        if hook is not None:
            return hook(manifest)
        kind = manifest.get("kind", "")
        ns = deep_get(manifest, "metadata.namespace", "")
        if kind in ("Deployment", "StatefulSet", "ReplicaSet"):
            replicas = int(deep_get(manifest, "spec.replicas", 1) or 0)
            pod_spec = deep_get(manifest, "spec.template.spec", {})
            return replicas, _pod_template_requirements(pod_spec, ns)
        if kind == "Job":
            parallelism = int(deep_get(manifest, "spec.parallelism", 1) or 1)
            pod_spec = deep_get(manifest, "spec.template.spec", {})
            return parallelism, _pod_template_requirements(pod_spec, ns)
        if kind == "Pod":
            return 1, _pod_template_requirements(deep_get(manifest, "spec", {}), ns)
        return 0, None

    def get_components(self, manifest: Dict[str, Any]):
        """Components of a multi-template workload (binding_types.go:98), or
        None when no customization implements InterpretComponent — the
        native default declines, exactly like the reference
        (native/default.go:115 'no plan to implement this method yet');
        callers then fall back to get_replicas (detector.go:1454-1482)."""
        hook = self._hook(manifest, OP_INTERPRET_COMPONENT)
        if hook is None:
            return None
        return hook(manifest)

    def revise_replica(self, manifest: Dict[str, Any], replicas: int) -> Dict[str, Any]:
        """Set the per-cluster replica count (native/revisereplica.go)."""
        hook = self._hook(manifest, OP_REVISE_REPLICA)
        if hook is not None:
            return hook(manifest, replicas)
        out = copy.deepcopy(manifest)
        kind = out.get("kind", "")
        if kind in ("Deployment", "StatefulSet", "ReplicaSet"):
            out.setdefault("spec", {})["replicas"] = int(replicas)
        elif kind == "Job":
            out.setdefault("spec", {})["parallelism"] = int(replicas)
        return out

    def revise_job_completions(self, manifest: Dict[str, Any], completions: int) -> Dict[str, Any]:
        """Jobs also divide .spec.completions (binding/common.go:95-108)."""
        out = copy.deepcopy(manifest)
        if out.get("kind") == "Job" and deep_get(out, "spec.completions") is not None:
            out["spec"]["completions"] = int(completions)
        return out

    def retain(self, desired: Dict[str, Any], observed: Dict[str, Any]) -> Dict[str, Any]:
        """Keep member-cluster-owned fields on update
        (native/retain.go; objectwatcher.go:127 retainClusterFields)."""
        hook = self._hook(desired, OP_RETAIN)
        if hook is not None:
            return hook(desired, observed)
        out = copy.deepcopy(desired)
        kind = out.get("kind", "")
        # retain-replicas label: member-side HPAs own the replica count
        # (native/retain.go:145 retainWorkloadReplicas)
        from karmada_tpu.utils.constants import (
            RETAIN_REPLICAS_LABEL,
            RETAIN_REPLICAS_VALUE,
        )

        labels = deep_get(out, "metadata.labels", {}) or {}
        if labels.get(RETAIN_REPLICAS_LABEL) == RETAIN_REPLICAS_VALUE:
            observed_replicas = deep_get(observed, "spec.replicas")
            if observed_replicas is not None:
                out.setdefault("spec", {})["replicas"] = observed_replicas
        if kind == "Service":
            ip = deep_get(observed, "spec.clusterIP")
            if ip is not None:
                out.setdefault("spec", {})["clusterIP"] = ip
        if kind == "ServiceAccount":
            secrets = observed.get("secrets")
            if secrets is not None:
                out["secrets"] = secrets
        if kind == "PersistentVolumeClaim":
            vn = deep_get(observed, "spec.volumeName")
            if vn is not None:
                out.setdefault("spec", {})["volumeName"] = vn
        # always retain member-side resourceVersion bookkeeping fields
        return out

    def aggregate_status(
        self, manifest: Dict[str, Any], items: List[AggregatedStatusItem]
    ) -> Dict[str, Any]:
        """Merge per-cluster statuses back onto the template
        (native/aggregatestatus.go)."""
        hook = self._hook(manifest, OP_AGGREGATE_STATUS)
        if hook is not None:
            return hook(manifest, items)
        out = copy.deepcopy(manifest)
        kind = out.get("kind", "")
        if kind == "Deployment":
            agg = {"replicas": 0, "readyReplicas": 0, "updatedReplicas": 0,
                   "availableReplicas": 0, "unavailableReplicas": 0}
            for item in items:
                st = item.status or {}
                for k in agg:
                    agg[k] += int(st.get(k, 0) or 0)
            out["status"] = agg
        elif kind == "Job":
            agg = {"active": 0, "succeeded": 0, "failed": 0}
            for item in items:
                st = item.status or {}
                for k in agg:
                    agg[k] += int(st.get(k, 0) or 0)
            out["status"] = agg
        else:
            out["status"] = {
                "clusters": {i.cluster_name: (i.status or {}) for i in items}
            }
        return out

    def get_dependencies(self, manifest: Dict[str, Any]) -> List[DependentObjectReference]:
        """ConfigMaps/Secrets/PVCs/ServiceAccounts the pod template references
        (native/dependencies.go)."""
        hook = self._hook(manifest, OP_INTERPRET_DEPENDENCY)
        if hook is not None:
            return hook(manifest)
        kind = manifest.get("kind", "")
        ns = deep_get(manifest, "metadata.namespace", "")
        pod_spec: Dict[str, Any] = {}
        if kind in ("Deployment", "StatefulSet", "ReplicaSet", "Job", "DaemonSet"):
            pod_spec = deep_get(manifest, "spec.template.spec", {}) or {}
        elif kind == "Pod":
            pod_spec = manifest.get("spec", {}) or {}
        if not pod_spec:
            return []
        deps: List[DependentObjectReference] = []

        def add(kind_: str, name: str) -> None:
            if name and not any(d.kind == kind_ and d.name == name for d in deps):
                api = "v1"
                deps.append(DependentObjectReference(
                    api_version=api, kind=kind_, namespace=ns, name=name))

        for vol in pod_spec.get("volumes", []) or []:
            cm = deep_get(vol, "configMap.name")
            if cm:
                add("ConfigMap", cm)
            sec = deep_get(vol, "secret.secretName")
            if sec:
                add("Secret", sec)
            pvc = deep_get(vol, "persistentVolumeClaim.claimName")
            if pvc:
                add("PersistentVolumeClaim", pvc)
        for container in pod_spec.get("containers", []) or []:
            for envfrom in container.get("envFrom", []) or []:
                add("ConfigMap", deep_get(envfrom, "configMapRef.name", ""))
                add("Secret", deep_get(envfrom, "secretRef.name", ""))
            for env in container.get("env", []) or []:
                add("ConfigMap", deep_get(env, "valueFrom.configMapKeyRef.name", ""))
                add("Secret", deep_get(env, "valueFrom.secretKeyRef.name", ""))
        sa = pod_spec.get("serviceAccountName")
        if sa and sa != "default":
            add("ServiceAccount", sa)
        return deps

    def reflect_status(self, observed: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """Pick the status to reflect into work.status.manifestStatuses
        (native/reflectstatus.go: whole .status by default)."""
        hook = self._hook(observed, OP_INTERPRET_STATUS)
        if hook is not None:
            return hook(observed)
        status = observed.get("status")
        return copy.deepcopy(status) if status is not None else None

    def interpret_health(self, observed: Dict[str, Any]) -> str:
        """Healthy / Unhealthy / Unknown (native/healthy.go)."""
        hook = self._hook(observed, OP_INTERPRET_HEALTH)
        if hook is not None:
            return hook(observed)
        kind = observed.get("kind", "")
        st = observed.get("status") or {}
        if kind == "Deployment":
            gen = deep_get(observed, "metadata.generation", 0)
            ogen = st.get("observedGeneration", 0)
            want = int(deep_get(observed, "spec.replicas", 1) or 0)
            if ogen >= gen and int(st.get("availableReplicas", 0) or 0) >= want:
                return HEALTHY
            return UNHEALTHY
        if kind == "Job":
            for cond in st.get("conditions", []) or []:
                if cond.get("type") == "Failed" and cond.get("status") == "True":
                    return UNHEALTHY
            return HEALTHY
        if kind in ("Pod",):
            phase = st.get("phase")
            if phase in ("Running", "Succeeded"):
                return HEALTHY
            if phase in ("Failed",):
                return UNHEALTHY
            return UNKNOWN
        return UNKNOWN
