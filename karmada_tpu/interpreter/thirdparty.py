"""Third-party customization bundle — pure data, like the reference's
embedded Lua tree (pkg/resourceinterpreter/default/thirdparty/
resourcecustomizations/<group>/<Kind>/customizations.yaml: Kruise, Argo,
Flink, ...).  Each entry is the same script dialect users write in
ResourceInterpreterCustomization objects; the facade ranks this tier below
user customizations and above the native defaults.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

from karmada_tpu.interpreter.declarative import make_hooks

# (apiVersion, kind) -> op -> script
THIRDPARTY_BUNDLE: Dict[Tuple[str, str], Dict[str, str]] = {
    # Argo Rollouts (argoproj.io/v1alpha1 Rollout/customizations.yaml)
    ("argoproj.io/v1alpha1", "Rollout"): {
        "InterpretReplica": (
            "{'replicas': get(obj, 'spec.replicas', 0) or 0,"
            " 'requirements': {"
            "   name: req for c in get(obj, 'spec.template.spec.containers', [])"
            "   for name, req in items(get(c, 'resources.requests', {}))"
            " }}"
        ),
        "ReviseReplica": "set(obj, 'spec.replicas', replicas)",
        "InterpretHealth": (
            "get(obj, 'status.observedGeneration', 0) =="
            " get(obj, 'metadata.generation', 0)"
            " and (get(obj, 'status.availableReplicas', 0) or 0) >="
            " (get(obj, 'spec.replicas', 0) or 0)"
            " and get(obj, 'status.phase', '') != 'Degraded'"
        ),
        "InterpretStatus": (
            "{'replicas': get(obj, 'status.replicas', 0),"
            " 'readyReplicas': get(obj, 'status.readyReplicas', 0),"
            " 'availableReplicas': get(obj, 'status.availableReplicas', 0),"
            " 'updatedReplicas': get(obj, 'status.updatedReplicas', 0),"
            " 'phase': get(obj, 'status.phase', '')}"
        ),
        "AggregateStatus": (
            "set(obj, 'status', {"
            " 'replicas': sum([get(i, 'status.replicas', 0) or 0 for i in items]),"
            " 'readyReplicas': sum([get(i, 'status.readyReplicas', 0) or 0 for i in items]),"
            " 'availableReplicas': sum([get(i, 'status.availableReplicas', 0) or 0 for i in items]),"
            " 'updatedReplicas': sum([get(i, 'status.updatedReplicas', 0) or 0 for i in items])})"
        ),
    },
    # OpenKruise CloneSet (apps.kruise.io/v1alpha1 CloneSet/customizations.yaml)
    ("apps.kruise.io/v1alpha1", "CloneSet"): {
        "InterpretReplica": (
            "{'replicas': get(obj, 'spec.replicas', 0) or 0,"
            " 'requirements': {"
            "   name: req for c in get(obj, 'spec.template.spec.containers', [])"
            "   for name, req in items(get(c, 'resources.requests', {}))"
            " }}"
        ),
        "ReviseReplica": "set(obj, 'spec.replicas', replicas)",
        "InterpretHealth": (
            "get(obj, 'status.observedGeneration', 0) =="
            " get(obj, 'metadata.generation', 0)"
            " and (get(obj, 'status.updatedReadyReplicas', 0) or 0) >="
            " (get(obj, 'spec.replicas', 0) or 0)"
        ),
        "InterpretStatus": (
            "{'replicas': get(obj, 'status.replicas', 0),"
            " 'readyReplicas': get(obj, 'status.readyReplicas', 0),"
            " 'updatedReplicas': get(obj, 'status.updatedReplicas', 0),"
            " 'updatedReadyReplicas': get(obj, 'status.updatedReadyReplicas', 0),"
            " 'expectedUpdatedReplicas': get(obj, 'status.expectedUpdatedReplicas', 0)}"
        ),
        "AggregateStatus": (
            "set(obj, 'status', {"
            " 'replicas': sum([get(i, 'status.replicas', 0) or 0 for i in items]),"
            " 'readyReplicas': sum([get(i, 'status.readyReplicas', 0) or 0 for i in items]),"
            " 'updatedReplicas': sum([get(i, 'status.updatedReplicas', 0) or 0 for i in items]),"
            " 'updatedReadyReplicas': sum([get(i, 'status.updatedReadyReplicas', 0) or 0 for i in items])})"
        ),
    },
    # OpenKruise Advanced StatefulSet (apps.kruise.io/v1beta1
    # StatefulSet/customizations.yaml)
    ("apps.kruise.io/v1beta1", "StatefulSet"): {
        "InterpretReplica": (
            "{'replicas': get(obj, 'spec.replicas', 0) or 0,"
            " 'requirements': {"
            "   name: req for c in get(obj, 'spec.template.spec.containers', [])"
            "   for name, req in items(get(c, 'resources.requests', {}))"
            " }}"
        ),
        "ReviseReplica": "set(obj, 'spec.replicas', replicas)",
        "InterpretHealth": (
            "get(obj, 'status.observedGeneration', 0) =="
            " get(obj, 'metadata.generation', 0)"
            " and (get(obj, 'status.readyReplicas', 0) or 0) >="
            " (get(obj, 'spec.replicas', 0) or 0)"
        ),
        "InterpretStatus": (
            "{'replicas': get(obj, 'status.replicas', 0),"
            " 'readyReplicas': get(obj, 'status.readyReplicas', 0),"
            " 'updatedReplicas': get(obj, 'status.updatedReplicas', 0),"
            " 'availableReplicas': get(obj, 'status.availableReplicas', 0)}"
        ),
        "AggregateStatus": (
            "set(obj, 'status', {"
            " 'replicas': sum([get(i, 'status.replicas', 0) or 0 for i in items]),"
            " 'readyReplicas': sum([get(i, 'status.readyReplicas', 0) or 0 for i in items]),"
            " 'updatedReplicas': sum([get(i, 'status.updatedReplicas', 0) or 0 for i in items]),"
            " 'availableReplicas': sum([get(i, 'status.availableReplicas', 0) or 0 for i in items])})"
        ),
    },
    # Flink operator (flink.apache.org/v1beta1
    # FlinkDeployment/customizations.yaml): replica weight is the
    # taskmanager count; health tracks the operator's lifecycle state
    ("flink.apache.org/v1beta1", "FlinkDeployment"): {
        "InterpretReplica": (
            # `or 0` (not `or 1`): an EXPLICIT replicas: 0 (suspended
            # deployment) must round-trip with ReviseReplica(0)
            "{'replicas': int(get(obj, 'spec.taskManager.replicas', 1) or 0),"
            " 'requirements': {"
            "   'cpu': get(obj, 'spec.taskManager.resource.cpu', 1),"
            "   'memory': get(obj, 'spec.taskManager.resource.memory', '1Gi')}}"
        ),
        "ReviseReplica": "set(obj, 'spec.taskManager.replicas', replicas)",
        "InterpretHealth": (
            "get(obj, 'status.lifecycleState', '') == 'STABLE'"
        ),
        "InterpretStatus": (
            "{'lifecycleState': get(obj, 'status.lifecycleState', ''),"
            " 'jobState': get(obj, 'status.jobStatus.state', '')}"
        ),
    },
    # Volcano batch Job (batch.volcano.sh/v1alpha1 Job/customizations.yaml):
    # replicas is the sum over task groups; health follows the job phase
    ("batch.volcano.sh/v1alpha1", "Job"): {
        "InterpretReplica": (
            "{'replicas': sum([get(t, 'replicas', 1) or 1"
            "                  for t in get(obj, 'spec.tasks', [])])}"
        ),
        # divide by sequential fill over the task list: task i keeps
        # min(own, total - sum(earlier)); minAvailable clamps to the revised
        # total so the gang-scheduling bar stays satisfiable
        "ReviseReplica": (
            "set(set(obj, 'spec.tasks', ["
            "  set(t, 'replicas', max(0, min(get(t, 'replicas', 1) or 1,"
            "    replicas - sum([get(u, 'replicas', 1) or 1"
            "      for u in get(obj, 'spec.tasks', [])[:i]]))))"
            "  for i, t in enumerate(get(obj, 'spec.tasks', []))"
            "]), 'spec.minAvailable',"
            " min(get(obj, 'spec.minAvailable', replicas) or replicas, replicas))"
        ),
        "InterpretHealth": (
            "get(obj, 'status.state.phase', '') in"
            " ('Running', 'Completed', 'Completing')"
        ),
        "InterpretStatus": (
            "{'state': get(obj, 'status.state', {}),"
            " 'succeeded': get(obj, 'status.succeeded', 0),"
            " 'failed': get(obj, 'status.failed', 0),"
            " 'running': get(obj, 'status.running', 0)}"
        ),
        "AggregateStatus": (
            "set(obj, 'status', {"
            " 'running': sum([get(i, 'status.running', 0) or 0 for i in items]),"
            " 'succeeded': sum([get(i, 'status.succeeded', 0) or 0 for i in items]),"
            " 'failed': sum([get(i, 'status.failed', 0) or 0 for i in items]),"
            " 'state': {'phase':"
            "   'Running' if sum([get(i, 'status.running', 0) or 0 for i in items]) > 0"
            "   else ('Failed' if sum([get(i, 'status.failed', 0) or 0 for i in items]) > 0"
            "   else ('Completed' if sum([get(i, 'status.succeeded', 0) or 0 for i in items]) > 0"
            "   else ''))}})"
        ),
    },
    # Kubeflow TFJob (kubeflow.org/v1 TFJob/customizations.yaml): replicas
    # is the sum over the role replica specs; health from the Succeeded/
    # Running conditions
    ("kubeflow.org/v1", "TFJob"): {
        "InterpretReplica": (
            "{'replicas': sum(["
            "   get(s, 'replicas', 1) or 1"
            "   for role, s in items(get(obj, 'spec.tfReplicaSpecs', {}))])}"
        ),
        # division scales the Worker role; fixed roles (PS/Chief/...) keep
        # their counts and the Worker absorbs the difference
        "ReviseReplica": (
            "set(obj, 'spec.tfReplicaSpecs.Worker.replicas',"
            " max(0, replicas - sum(["
            "   get(s, 'replicas', 1) or 1"
            "   for role, s in items(get(obj, 'spec.tfReplicaSpecs', {}))"
            "   if role != 'Worker'])))"
        ),
        "InterpretHealth": (
            "any([get(c, 'type', '') in ('Running', 'Succeeded')"
            "     and get(c, 'status', '') == 'True'"
            "     for c in get(obj, 'status.conditions', [])])"
        ),
        "InterpretStatus": (
            "{'conditions': get(obj, 'status.conditions', []),"
            " 'replicaStatuses': get(obj, 'status.replicaStatuses', {})}"
        ),
    },
    # Flux HelmRelease (helm.toolkit.fluxcd.io/v2beta1
    # HelmRelease/customizations.yaml): non-workload; health is the Ready
    # condition
    ("helm.toolkit.fluxcd.io/v2beta1", "HelmRelease"): {
        "InterpretReplica": "{'replicas': 0}",
        "InterpretHealth": (
            "any([get(c, 'type', '') == 'Ready'"
            "     and get(c, 'status', '') == 'True'"
            "     for c in get(obj, 'status.conditions', [])])"
        ),
        "InterpretStatus": (
            "{'conditions': get(obj, 'status.conditions', []),"
            " 'lastAppliedRevision': get(obj, 'status.lastAppliedRevision', '')}"
        ),
    },
    # OpenKruise DaemonSet (apps.kruise.io/v1alpha1
    # DaemonSet/customizations.yaml): no divisible replicas; health is
    # generation-observed + updated>=desired + available>=updated
    ("apps.kruise.io/v1alpha1", "DaemonSet"): {
        "InterpretReplica": "{'replicas': 0}",
        "InterpretHealth": (
            "get(obj, 'status.observedGeneration', 0) =="
            " get(obj, 'metadata.generation', 0)"
            " and (get(obj, 'status.updatedNumberScheduled', 0) or 0) >="
            " (get(obj, 'status.desiredNumberScheduled', 0) or 0)"
            " and (get(obj, 'status.numberAvailable', 0) or 0) >="
            " (get(obj, 'status.updatedNumberScheduled', 0) or 0)"
        ),
        "InterpretStatus": (
            "{'currentNumberScheduled': get(obj, 'status.currentNumberScheduled', 0),"
            " 'desiredNumberScheduled': get(obj, 'status.desiredNumberScheduled', 0),"
            " 'numberReady': get(obj, 'status.numberReady', 0),"
            " 'numberAvailable': get(obj, 'status.numberAvailable', 0),"
            " 'updatedNumberScheduled': get(obj, 'status.updatedNumberScheduled', 0)}"
        ),
        "AggregateStatus": (
            "set(obj, 'status', {"
            " 'currentNumberScheduled': sum([get(i, 'status.currentNumberScheduled', 0) or 0 for i in items]),"
            " 'desiredNumberScheduled': sum([get(i, 'status.desiredNumberScheduled', 0) or 0 for i in items]),"
            " 'numberReady': sum([get(i, 'status.numberReady', 0) or 0 for i in items]),"
            " 'numberAvailable': sum([get(i, 'status.numberAvailable', 0) or 0 for i in items]),"
            " 'updatedNumberScheduled': sum([get(i, 'status.updatedNumberScheduled', 0) or 0 for i in items])})"
        ),
    },
    # OpenKruise SidecarSet (apps.kruise.io/v1alpha1
    # SidecarSet/customizations.yaml): injects into pods, manages none
    # itself; healthy when nothing is matched or every matched pod updated
    ("apps.kruise.io/v1alpha1", "SidecarSet"): {
        "InterpretReplica": "{'replicas': 0}",
        "InterpretHealth": (
            "(get(obj, 'status.matchedPods', 0) or 0) == 0"
            " or (get(obj, 'status.updatedPods', 0) or 0) >="
            " (get(obj, 'status.matchedPods', 0) or 0)"
        ),
        "InterpretStatus": (
            "{'matchedPods': get(obj, 'status.matchedPods', 0),"
            " 'updatedPods': get(obj, 'status.updatedPods', 0),"
            " 'readyPods': get(obj, 'status.readyPods', 0)}"
        ),
        "AggregateStatus": (
            "set(obj, 'status', {"
            " 'matchedPods': sum([get(i, 'status.matchedPods', 0) or 0 for i in items]),"
            " 'updatedPods': sum([get(i, 'status.updatedPods', 0) or 0 for i in items]),"
            " 'readyPods': sum([get(i, 'status.readyPods', 0) or 0 for i in items])})"
        ),
    },
    # OpenKruise UnitedDeployment (apps.kruise.io/v1alpha1
    # UnitedDeployment/customizations.yaml)
    ("apps.kruise.io/v1alpha1", "UnitedDeployment"): {
        # the pod template nests under the per-flavor sub-template
        # (spec.template.{statefulSetTemplate|deploymentTemplate|
        # cloneSetTemplate|advancedStatefulSetTemplate}.spec.template)
        "InterpretReplica": (
            "{'replicas': get(obj, 'spec.replicas', 0) or 0,"
            " 'requirements': {"
            "   name: req for c in ("
            "     get(obj, 'spec.template.statefulSetTemplate.spec.template.spec.containers', [])"
            "     or get(obj, 'spec.template.advancedStatefulSetTemplate.spec.template.spec.containers', [])"
            "     or get(obj, 'spec.template.deploymentTemplate.spec.template.spec.containers', [])"
            "     or get(obj, 'spec.template.cloneSetTemplate.spec.template.spec.containers', [])"
            "     or [])"
            "   for name, req in items(get(c, 'resources.requests', {}))"
            " }}"
        ),
        "ReviseReplica": "set(obj, 'spec.replicas', replicas)",
        "InterpretHealth": (
            "get(obj, 'status.observedGeneration', 0) =="
            " get(obj, 'metadata.generation', 0)"
            " and (get(obj, 'status.updatedReplicas', 0) or 0) >="
            " (get(obj, 'spec.replicas', 0) or 0)"
        ),
        "InterpretStatus": (
            "{'replicas': get(obj, 'status.replicas', 0),"
            " 'readyReplicas': get(obj, 'status.readyReplicas', 0),"
            " 'updatedReplicas': get(obj, 'status.updatedReplicas', 0)}"
        ),
        "AggregateStatus": (
            "set(obj, 'status', {"
            " 'replicas': sum([get(i, 'status.replicas', 0) or 0 for i in items]),"
            " 'readyReplicas': sum([get(i, 'status.readyReplicas', 0) or 0 for i in items]),"
            " 'updatedReplicas': sum([get(i, 'status.updatedReplicas', 0) or 0 for i in items])})"
        ),
    },
    # OpenKruise BroadcastJob (apps.kruise.io/v1alpha1
    # BroadcastJob/customizations.yaml): parallelism-shaped like a Job
    ("apps.kruise.io/v1alpha1", "BroadcastJob"): {
        "InterpretReplica": (
            "{'replicas': int(get(obj, 'spec.parallelism', 1) or 1)}"
        ),
        "ReviseReplica": "set(obj, 'spec.parallelism', replicas)",
        "InterpretHealth": (
            "(get(obj, 'status.desired', 0) or 0) > 0"
            " and (get(obj, 'status.failed', 0) or 0) == 0"
            " and ((get(obj, 'status.succeeded', 0) or 0) > 0"
            "      or (get(obj, 'status.active', 0) or 0) > 0)"
        ),
        "InterpretStatus": (
            "{'active': get(obj, 'status.active', 0),"
            " 'succeeded': get(obj, 'status.succeeded', 0),"
            " 'failed': get(obj, 'status.failed', 0),"
            " 'desired': get(obj, 'status.desired', 0)}"
        ),
        "AggregateStatus": (
            "set(obj, 'status', {"
            " 'active': sum([get(i, 'status.active', 0) or 0 for i in items]),"
            " 'succeeded': sum([get(i, 'status.succeeded', 0) or 0 for i in items]),"
            " 'failed': sum([get(i, 'status.failed', 0) or 0 for i in items]),"
            " 'desired': sum([get(i, 'status.desired', 0) or 0 for i in items])})"
        ),
    },
    # OpenKruise AdvancedCronJob (apps.kruise.io/v1alpha1
    # AdvancedCronJob/customizations.yaml): cron trigger, nothing divisible
    ("apps.kruise.io/v1alpha1", "AdvancedCronJob"): {
        "InterpretReplica": "{'replicas': 0}",
        "InterpretStatus": (
            "{'active': get(obj, 'status.active', []),"
            " 'lastScheduleTime': get(obj, 'status.lastScheduleTime', ''),"
            " 'type': get(obj, 'status.type', '')}"
        ),
        "AggregateStatus": (
            "set(obj, 'status', {"
            " 'active': [a for i in items"
            "            for a in (get(i, 'status.active', []) or [])],"
            " 'lastScheduleTime': max("
            "   [get(i, 'status.lastScheduleTime', '') or '' for i in items]"
            "   + ['']),"
            " 'type': get(items[0] if items else {}, 'status.type', '')})"
        ),
    },
    # Argo Workflow (argoproj.io/v1alpha1 Workflow/customizations.yaml):
    # parallelism is the replica axis; Failed/Error phases are unhealthy
    ("argoproj.io/v1alpha1", "Workflow"): {
        "InterpretReplica": (
            "{'replicas': int(get(obj, 'spec.parallelism', 1) or 1)}"
        ),
        "ReviseReplica": "set(obj, 'spec.parallelism', replicas)",
        "InterpretHealth": (
            "get(obj, 'status.phase', '') not in ('', 'Failed', 'Error')"
        ),
        "InterpretStatus": (
            "{'phase': get(obj, 'status.phase', ''),"
            " 'startedAt': get(obj, 'status.startedAt', ''),"
            " 'finishedAt': get(obj, 'status.finishedAt', ''),"
            " 'progress': get(obj, 'status.progress', '')}"
        ),
    },
    # Kubeflow Notebook (kubeflow.org/v1 Notebook/customizations.yaml):
    # single-pod workload; healthy when running or still creating
    ("kubeflow.org/v1", "Notebook"): {
        "InterpretReplica": (
            "{'replicas': 1,"
            " 'requirements': {"
            "   name: req for c in get(obj, 'spec.template.spec.containers', [])"
            "   for name, req in items(get(c, 'resources.requests', {}))"
            " }}"
        ),
        "InterpretHealth": (
            "get(obj, 'status.containerState.running', None) is not None"
            " or get(obj, 'status.containerState.waiting.reason', '')"
            " == 'ContainerCreating'"
        ),
        "InterpretStatus": (
            "{'containerState': get(obj, 'status.containerState', {}),"
            " 'readyReplicas': get(obj, 'status.readyReplicas', 0),"
            " 'conditions': get(obj, 'status.conditions', [])}"
        ),
    },
    # Kubeflow MPIJob (kubeflow.org/v2beta1 MPIJob/customizations.yaml):
    # role replica specs are the component sets; Failed=True condition
    # is terminal-unhealthy
    ("kubeflow.org/v2beta1", "MPIJob"): {
        "InterpretReplica": (
            "{'replicas': sum(["
            "   get(s, 'replicas', 1) or 1"
            "   for role, s in items(get(obj, 'spec.mpiReplicaSpecs', {}))])}"
        ),
        "InterpretComponent": (
            "[{'name': role, 'replicas': get(s, 'replicas', 1) or 1}"
            " for role, s in items(get(obj, 'spec.mpiReplicaSpecs', {}))]"
        ),
        "ReviseReplica": (
            "set(obj, 'spec.mpiReplicaSpecs.Worker.replicas',"
            " max(0, replicas - sum(["
            "   get(s, 'replicas', 1) or 1"
            "   for role, s in items(get(obj, 'spec.mpiReplicaSpecs', {}))"
            "   if role != 'Worker'])))"
        ),
        "InterpretHealth": (
            "len(get(obj, 'status.conditions', []) or []) > 0"
            " and not any([get(c, 'type', '') == 'Failed'"
            "              and get(c, 'status', '') == 'True'"
            "              for c in get(obj, 'status.conditions', [])])"
        ),
        "InterpretStatus": (
            "{'conditions': get(obj, 'status.conditions', []),"
            " 'replicaStatuses': get(obj, 'status.replicaStatuses', {})}"
        ),
    },
    # Flux Kustomization (kustomize.toolkit.fluxcd.io/v1
    # Kustomization/customizations.yaml): Ready/ReconciliationSucceeded
    ("kustomize.toolkit.fluxcd.io/v1", "Kustomization"): {
        "InterpretReplica": "{'replicas': 0}",
        "InterpretHealth": (
            "any([get(c, 'type', '') == 'Ready'"
            "     and get(c, 'status', '') == 'True'"
            "     and get(c, 'reason', '') == 'ReconciliationSucceeded'"
            "     for c in get(obj, 'status.conditions', [])])"
        ),
        "InterpretStatus": (
            "{'conditions': get(obj, 'status.conditions', []),"
            " 'lastAppliedRevision': get(obj, 'status.lastAppliedRevision', '')}"
        ),
    },
    # Kyverno policies (kyverno.io/v1 {Cluster,}Policy/customizations.yaml):
    # status.ready wins; otherwise the Ready/Succeeded condition
    ("kyverno.io/v1", "ClusterPolicy"): {
        "InterpretReplica": "{'replicas': 0}",
        "InterpretHealth": (
            "get(obj, 'status.ready', None)"
            " if get(obj, 'status.ready', None) is not None"
            " else any([get(c, 'type', '') == 'Ready'"
            "           and get(c, 'status', '') == 'True'"
            "           and get(c, 'reason', '') == 'Succeeded'"
            "           for c in get(obj, 'status.conditions', [])])"
        ),
        "InterpretStatus": (
            "{'ready': get(obj, 'status.ready', False),"
            " 'conditions': get(obj, 'status.conditions', [])}"
        ),
    },
    # Spark operator (sparkoperator.k8s.io/v1beta2
    # SparkApplication/customizations.yaml)
    ("sparkoperator.k8s.io/v1beta2", "SparkApplication"): {
        "InterpretReplica": (
            # `or 0` keeps the driver+executors total invertible with
            # ReviseReplica: an explicit instances: 0 reads back as 1 total
            "{'replicas': 1 + int(get(obj, 'spec.executor.instances', 1) or 0)}"
        ),
        "ReviseReplica": (
            "set(obj, 'spec.executor.instances',"
            "    replicas - 1 if replicas > 0 else 0)"
        ),
        "InterpretHealth": (
            "get(obj, 'status.applicationState.state', '') in"
            " ('RUNNING', 'COMPLETED', 'SUBMITTED')"
        ),
        "InterpretStatus": (
            "{'applicationState': get(obj, 'status.applicationState', {}),"
            " 'executorState': get(obj, 'status.executorState', {}),"
            " 'lastSubmissionAttemptTime':"
            "   get(obj, 'status.lastSubmissionAttemptTime', '')}"
        ),
    },
}

# Namespaced Kyverno Policy shares ClusterPolicy's semantics verbatim
THIRDPARTY_BUNDLE[("kyverno.io/v1", "Policy")] = \
    THIRDPARTY_BUNDLE[("kyverno.io/v1", "ClusterPolicy")]


def _flux_source(ready_reasons: Tuple[str, ...]) -> Dict[str, str]:
    """Flux source-controller kinds (source.toolkit.fluxcd.io
    {GitRepository,Bucket,HelmChart,HelmRepository,OCIRepository}/
    customizations.yaml): non-workload, healthy on a True Ready condition
    with a fetch-succeeded reason; status reflects conditions + artifact."""
    reasons = ", ".join(f"'{r}'" for r in ready_reasons)
    return {
        "InterpretReplica": "{'replicas': 0}",
        "InterpretHealth": (
            "any([get(c, 'type', '') == 'Ready'"
            "     and get(c, 'status', '') == 'True'"
            f"     and get(c, 'reason', '') in ({reasons},)"
            "     for c in get(obj, 'status.conditions', [])])"
        ),
        "InterpretStatus": (
            "{'conditions': get(obj, 'status.conditions', []),"
            " 'artifact': get(obj, 'status.artifact', {}),"
            " 'observedGeneration': get(obj, 'status.observedGeneration', 0)}"
        ),
    }


THIRDPARTY_BUNDLE[("source.toolkit.fluxcd.io/v1", "GitRepository")] = \
    _flux_source(("Succeeded",))
THIRDPARTY_BUNDLE[("source.toolkit.fluxcd.io/v1beta2", "Bucket")] = \
    _flux_source(("Succeeded",))
THIRDPARTY_BUNDLE[("source.toolkit.fluxcd.io/v1beta2", "HelmChart")] = \
    _flux_source(("Succeeded", "ChartPullSucceeded"))
THIRDPARTY_BUNDLE[("source.toolkit.fluxcd.io/v1beta2", "HelmRepository")] = \
    _flux_source(("Succeeded", "IndexationSucceeded"))
THIRDPARTY_BUNDLE[("source.toolkit.fluxcd.io/v1beta2", "OCIRepository")] = \
    _flux_source(("Succeeded",))

_compiled: Dict[Tuple[str, str], Dict[str, Callable]] = {}


def thirdparty_hook(api_version: str, kind: str, op: str) -> Optional[Callable]:
    key = (api_version, kind)
    if key not in THIRDPARTY_BUNDLE:
        return None
    if key not in _compiled:
        _compiled[key] = make_hooks(THIRDPARTY_BUNDLE[key])
    return _compiled[key].get(op)
