"""Third-party customization bundle — pure data, like the reference's
embedded Lua tree (pkg/resourceinterpreter/default/thirdparty/
resourcecustomizations/<group>/<Kind>/customizations.yaml: Kruise, Argo,
Flink, ...).  Each entry is the same script dialect users write in
ResourceInterpreterCustomization objects; the facade ranks this tier below
user customizations and above the native defaults.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

from karmada_tpu.interpreter.declarative import make_hooks

# (apiVersion, kind) -> op -> script
THIRDPARTY_BUNDLE: Dict[Tuple[str, str], Dict[str, str]] = {
    # Argo Rollouts (argoproj.io/v1alpha1 Rollout/customizations.yaml)
    ("argoproj.io/v1alpha1", "Rollout"): {
        "InterpretReplica": (
            "{'replicas': get(obj, 'spec.replicas', 0) or 0,"
            " 'requirements': {"
            "   name: req for c in get(obj, 'spec.template.spec.containers', [])"
            "   for name, req in items(get(c, 'resources.requests', {}))"
            " }}"
        ),
        "ReviseReplica": "set(obj, 'spec.replicas', replicas)",
        "InterpretHealth": (
            "get(obj, 'status.observedGeneration', 0) =="
            " get(obj, 'metadata.generation', 0)"
            " and (get(obj, 'status.availableReplicas', 0) or 0) >="
            " (get(obj, 'spec.replicas', 0) or 0)"
            " and get(obj, 'status.phase', '') != 'Degraded'"
        ),
        "InterpretStatus": (
            "{'replicas': get(obj, 'status.replicas', 0),"
            " 'readyReplicas': get(obj, 'status.readyReplicas', 0),"
            " 'availableReplicas': get(obj, 'status.availableReplicas', 0),"
            " 'updatedReplicas': get(obj, 'status.updatedReplicas', 0),"
            " 'phase': get(obj, 'status.phase', '')}"
        ),
        "AggregateStatus": (
            "set(obj, 'status', {"
            " 'replicas': sum([get(i, 'status.replicas', 0) or 0 for i in items]),"
            " 'readyReplicas': sum([get(i, 'status.readyReplicas', 0) or 0 for i in items]),"
            " 'availableReplicas': sum([get(i, 'status.availableReplicas', 0) or 0 for i in items]),"
            " 'updatedReplicas': sum([get(i, 'status.updatedReplicas', 0) or 0 for i in items])})"
        ),
    },
    # OpenKruise CloneSet (apps.kruise.io/v1alpha1 CloneSet/customizations.yaml)
    ("apps.kruise.io/v1alpha1", "CloneSet"): {
        "InterpretReplica": (
            "{'replicas': get(obj, 'spec.replicas', 0) or 0,"
            " 'requirements': {"
            "   name: req for c in get(obj, 'spec.template.spec.containers', [])"
            "   for name, req in items(get(c, 'resources.requests', {}))"
            " }}"
        ),
        "ReviseReplica": "set(obj, 'spec.replicas', replicas)",
        "InterpretHealth": (
            "get(obj, 'status.observedGeneration', 0) =="
            " get(obj, 'metadata.generation', 0)"
            " and (get(obj, 'status.updatedReadyReplicas', 0) or 0) >="
            " (get(obj, 'spec.replicas', 0) or 0)"
        ),
        "InterpretStatus": (
            "{'replicas': get(obj, 'status.replicas', 0),"
            " 'readyReplicas': get(obj, 'status.readyReplicas', 0),"
            " 'updatedReplicas': get(obj, 'status.updatedReplicas', 0),"
            " 'updatedReadyReplicas': get(obj, 'status.updatedReadyReplicas', 0),"
            " 'expectedUpdatedReplicas': get(obj, 'status.expectedUpdatedReplicas', 0)}"
        ),
        "AggregateStatus": (
            "set(obj, 'status', {"
            " 'replicas': sum([get(i, 'status.replicas', 0) or 0 for i in items]),"
            " 'readyReplicas': sum([get(i, 'status.readyReplicas', 0) or 0 for i in items]),"
            " 'updatedReplicas': sum([get(i, 'status.updatedReplicas', 0) or 0 for i in items]),"
            " 'updatedReadyReplicas': sum([get(i, 'status.updatedReadyReplicas', 0) or 0 for i in items])})"
        ),
    },
}

_compiled: Dict[Tuple[str, str], Dict[str, Callable]] = {}


def thirdparty_hook(api_version: str, kind: str, op: str) -> Optional[Callable]:
    key = (api_version, kind)
    if key not in THIRDPARTY_BUNDLE:
        return None
    if key not in _compiled:
        _compiled[key] = make_hooks(THIRDPARTY_BUNDLE[key])
    return _compiled[key].get(op)
