"""Third-party customization bundle — pure data, like the reference's
embedded Lua tree (pkg/resourceinterpreter/default/thirdparty/
resourcecustomizations/<group>/<Kind>/customizations.yaml: Kruise, Argo,
Flink, ...).  Each entry is the same script dialect users write in
ResourceInterpreterCustomization objects; the facade ranks this tier below
user customizations and above the native defaults.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

from karmada_tpu.interpreter.declarative import make_hooks

# (apiVersion, kind) -> op -> script
THIRDPARTY_BUNDLE: Dict[Tuple[str, str], Dict[str, str]] = {
    # Argo Rollouts (argoproj.io/v1alpha1 Rollout/customizations.yaml)
    ("argoproj.io/v1alpha1", "Rollout"): {
        "InterpretReplica": (
            "{'replicas': get(obj, 'spec.replicas', 0) or 0,"
            " 'requirements': {"
            "   name: req for c in get(obj, 'spec.template.spec.containers', [])"
            "   for name, req in items(get(c, 'resources.requests', {}))"
            " }}"
        ),
        "ReviseReplica": "set(obj, 'spec.replicas', replicas)",
        "InterpretHealth": (
            "get(obj, 'status.observedGeneration', 0) =="
            " get(obj, 'metadata.generation', 0)"
            " and (get(obj, 'status.availableReplicas', 0) or 0) >="
            " (get(obj, 'spec.replicas', 0) or 0)"
            " and get(obj, 'status.phase', '') != 'Degraded'"
        ),
        "InterpretStatus": (
            "{'replicas': get(obj, 'status.replicas', 0),"
            " 'readyReplicas': get(obj, 'status.readyReplicas', 0),"
            " 'availableReplicas': get(obj, 'status.availableReplicas', 0),"
            " 'updatedReplicas': get(obj, 'status.updatedReplicas', 0),"
            " 'phase': get(obj, 'status.phase', '')}"
        ),
        "AggregateStatus": (
            "set(obj, 'status', {"
            " 'replicas': sum([get(i, 'status.replicas', 0) or 0 for i in items]),"
            " 'readyReplicas': sum([get(i, 'status.readyReplicas', 0) or 0 for i in items]),"
            " 'availableReplicas': sum([get(i, 'status.availableReplicas', 0) or 0 for i in items]),"
            " 'updatedReplicas': sum([get(i, 'status.updatedReplicas', 0) or 0 for i in items])})"
        ),
    },
    # OpenKruise CloneSet (apps.kruise.io/v1alpha1 CloneSet/customizations.yaml)
    ("apps.kruise.io/v1alpha1", "CloneSet"): {
        "InterpretReplica": (
            "{'replicas': get(obj, 'spec.replicas', 0) or 0,"
            " 'requirements': {"
            "   name: req for c in get(obj, 'spec.template.spec.containers', [])"
            "   for name, req in items(get(c, 'resources.requests', {}))"
            " }}"
        ),
        "ReviseReplica": "set(obj, 'spec.replicas', replicas)",
        "InterpretHealth": (
            "get(obj, 'status.observedGeneration', 0) =="
            " get(obj, 'metadata.generation', 0)"
            " and (get(obj, 'status.updatedReadyReplicas', 0) or 0) >="
            " (get(obj, 'spec.replicas', 0) or 0)"
        ),
        "InterpretStatus": (
            "{'replicas': get(obj, 'status.replicas', 0),"
            " 'readyReplicas': get(obj, 'status.readyReplicas', 0),"
            " 'updatedReplicas': get(obj, 'status.updatedReplicas', 0),"
            " 'updatedReadyReplicas': get(obj, 'status.updatedReadyReplicas', 0),"
            " 'expectedUpdatedReplicas': get(obj, 'status.expectedUpdatedReplicas', 0)}"
        ),
        "AggregateStatus": (
            "set(obj, 'status', {"
            " 'replicas': sum([get(i, 'status.replicas', 0) or 0 for i in items]),"
            " 'readyReplicas': sum([get(i, 'status.readyReplicas', 0) or 0 for i in items]),"
            " 'updatedReplicas': sum([get(i, 'status.updatedReplicas', 0) or 0 for i in items]),"
            " 'updatedReadyReplicas': sum([get(i, 'status.updatedReadyReplicas', 0) or 0 for i in items])})"
        ),
    },
    # OpenKruise Advanced StatefulSet (apps.kruise.io/v1beta1
    # StatefulSet/customizations.yaml)
    ("apps.kruise.io/v1beta1", "StatefulSet"): {
        "InterpretReplica": (
            "{'replicas': get(obj, 'spec.replicas', 0) or 0,"
            " 'requirements': {"
            "   name: req for c in get(obj, 'spec.template.spec.containers', [])"
            "   for name, req in items(get(c, 'resources.requests', {}))"
            " }}"
        ),
        "ReviseReplica": "set(obj, 'spec.replicas', replicas)",
        "InterpretHealth": (
            "get(obj, 'status.observedGeneration', 0) =="
            " get(obj, 'metadata.generation', 0)"
            " and (get(obj, 'status.readyReplicas', 0) or 0) >="
            " (get(obj, 'spec.replicas', 0) or 0)"
        ),
        "InterpretStatus": (
            "{'replicas': get(obj, 'status.replicas', 0),"
            " 'readyReplicas': get(obj, 'status.readyReplicas', 0),"
            " 'updatedReplicas': get(obj, 'status.updatedReplicas', 0),"
            " 'availableReplicas': get(obj, 'status.availableReplicas', 0)}"
        ),
        "AggregateStatus": (
            "set(obj, 'status', {"
            " 'replicas': sum([get(i, 'status.replicas', 0) or 0 for i in items]),"
            " 'readyReplicas': sum([get(i, 'status.readyReplicas', 0) or 0 for i in items]),"
            " 'updatedReplicas': sum([get(i, 'status.updatedReplicas', 0) or 0 for i in items]),"
            " 'availableReplicas': sum([get(i, 'status.availableReplicas', 0) or 0 for i in items])})"
        ),
    },
    # Flink operator (flink.apache.org/v1beta1
    # FlinkDeployment/customizations.yaml): replica weight is the
    # taskmanager count; health tracks the operator's lifecycle state
    ("flink.apache.org/v1beta1", "FlinkDeployment"): {
        "InterpretReplica": (
            # `or 0` (not `or 1`): an EXPLICIT replicas: 0 (suspended
            # deployment) must round-trip with ReviseReplica(0)
            "{'replicas': int(get(obj, 'spec.taskManager.replicas', 1) or 0),"
            " 'requirements': {"
            "   'cpu': get(obj, 'spec.taskManager.resource.cpu', 1),"
            "   'memory': get(obj, 'spec.taskManager.resource.memory', '1Gi')}}"
        ),
        "ReviseReplica": "set(obj, 'spec.taskManager.replicas', replicas)",
        "InterpretHealth": (
            "get(obj, 'status.lifecycleState', '') == 'STABLE'"
        ),
        "InterpretStatus": (
            "{'lifecycleState': get(obj, 'status.lifecycleState', ''),"
            " 'jobState': get(obj, 'status.jobStatus.state', '')}"
        ),
    },
    # Volcano batch Job (batch.volcano.sh/v1alpha1 Job/customizations.yaml):
    # replicas is the sum over task groups; health follows the job phase
    ("batch.volcano.sh/v1alpha1", "Job"): {
        "InterpretReplica": (
            "{'replicas': sum([get(t, 'replicas', 1) or 1"
            "                  for t in get(obj, 'spec.tasks', [])])}"
        ),
        # divide by sequential fill over the task list: task i keeps
        # min(own, total - sum(earlier)); minAvailable clamps to the revised
        # total so the gang-scheduling bar stays satisfiable
        "ReviseReplica": (
            "set(set(obj, 'spec.tasks', ["
            "  set(t, 'replicas', max(0, min(get(t, 'replicas', 1) or 1,"
            "    replicas - sum([get(u, 'replicas', 1) or 1"
            "      for u in get(obj, 'spec.tasks', [])[:i]]))))"
            "  for i, t in enumerate(get(obj, 'spec.tasks', []))"
            "]), 'spec.minAvailable',"
            " min(get(obj, 'spec.minAvailable', replicas) or replicas, replicas))"
        ),
        "InterpretHealth": (
            "get(obj, 'status.state.phase', '') in"
            " ('Running', 'Completed', 'Completing')"
        ),
        "InterpretStatus": (
            "{'state': get(obj, 'status.state', {}),"
            " 'succeeded': get(obj, 'status.succeeded', 0),"
            " 'failed': get(obj, 'status.failed', 0),"
            " 'running': get(obj, 'status.running', 0)}"
        ),
        "AggregateStatus": (
            "set(obj, 'status', {"
            " 'running': sum([get(i, 'status.running', 0) or 0 for i in items]),"
            " 'succeeded': sum([get(i, 'status.succeeded', 0) or 0 for i in items]),"
            " 'failed': sum([get(i, 'status.failed', 0) or 0 for i in items]),"
            " 'state': {'phase':"
            "   'Running' if sum([get(i, 'status.running', 0) or 0 for i in items]) > 0"
            "   else ('Failed' if sum([get(i, 'status.failed', 0) or 0 for i in items]) > 0"
            "   else ('Completed' if sum([get(i, 'status.succeeded', 0) or 0 for i in items]) > 0"
            "   else ''))}})"
        ),
    },
    # Kubeflow TFJob (kubeflow.org/v1 TFJob/customizations.yaml): replicas
    # is the sum over the role replica specs; health from the Succeeded/
    # Running conditions
    ("kubeflow.org/v1", "TFJob"): {
        "InterpretReplica": (
            "{'replicas': sum(["
            "   get(s, 'replicas', 1) or 1"
            "   for role, s in items(get(obj, 'spec.tfReplicaSpecs', {}))])}"
        ),
        # division scales the Worker role; fixed roles (PS/Chief/...) keep
        # their counts and the Worker absorbs the difference
        "ReviseReplica": (
            "set(obj, 'spec.tfReplicaSpecs.Worker.replicas',"
            " max(0, replicas - sum(["
            "   get(s, 'replicas', 1) or 1"
            "   for role, s in items(get(obj, 'spec.tfReplicaSpecs', {}))"
            "   if role != 'Worker'])))"
        ),
        "InterpretHealth": (
            "any([get(c, 'type', '') in ('Running', 'Succeeded')"
            "     and get(c, 'status', '') == 'True'"
            "     for c in get(obj, 'status.conditions', [])])"
        ),
        "InterpretStatus": (
            "{'conditions': get(obj, 'status.conditions', []),"
            " 'replicaStatuses': get(obj, 'status.replicaStatuses', {})}"
        ),
    },
    # Flux HelmRelease (helm.toolkit.fluxcd.io/v2beta1
    # HelmRelease/customizations.yaml): non-workload; health is the Ready
    # condition
    ("helm.toolkit.fluxcd.io/v2beta1", "HelmRelease"): {
        "InterpretReplica": "{'replicas': 0}",
        "InterpretHealth": (
            "any([get(c, 'type', '') == 'Ready'"
            "     and get(c, 'status', '') == 'True'"
            "     for c in get(obj, 'status.conditions', [])])"
        ),
        "InterpretStatus": (
            "{'conditions': get(obj, 'status.conditions', []),"
            " 'lastAppliedRevision': get(obj, 'status.lastAppliedRevision', '')}"
        ),
    },
    # Spark operator (sparkoperator.k8s.io/v1beta2
    # SparkApplication/customizations.yaml)
    ("sparkoperator.k8s.io/v1beta2", "SparkApplication"): {
        "InterpretReplica": (
            # `or 0` keeps the driver+executors total invertible with
            # ReviseReplica: an explicit instances: 0 reads back as 1 total
            "{'replicas': 1 + int(get(obj, 'spec.executor.instances', 1) or 0)}"
        ),
        "ReviseReplica": (
            "set(obj, 'spec.executor.instances',"
            "    replicas - 1 if replicas > 0 else 0)"
        ),
        "InterpretHealth": (
            "get(obj, 'status.applicationState.state', '') in"
            " ('RUNNING', 'COMPLETED', 'SUBMITTED')"
        ),
        "InterpretStatus": (
            "{'applicationState': get(obj, 'status.applicationState', {}),"
            " 'executorState': get(obj, 'status.executorState', {}),"
            " 'lastSubmissionAttemptTime':"
            "   get(obj, 'status.lastSubmissionAttemptTime', '')}"
        ),
    },
}

_compiled: Dict[Tuple[str, str], Dict[str, Callable]] = {}


def thirdparty_hook(api_version: str, kind: str, op: str) -> Optional[Callable]:
    key = (api_version, kind)
    if key not in THIRDPARTY_BUNDLE:
        return None
    if key not in _compiled:
        _compiled[key] = make_hooks(THIRDPARTY_BUNDLE[key])
    return _compiled[key].get(op)
