from karmada_tpu.interpreter.interpreter import (  # noqa: F401
    Customization,
    ResourceInterpreter,
)
