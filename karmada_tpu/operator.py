"""karmada-operator: install/manage control planes from a Karmada CR.

Reference: operator/pkg/ — the `Karmada` CR
(operator/pkg/apis/operator/v1alpha1/type.go:33) describes a whole control
plane; the operator's workflow engine (operator/pkg/workflow/{job,task}.go)
runs the install task list (tasks/init: cert -> etcd -> apiserver ->
component -> wait -> upload) and deinit in reverse.

Here a control plane is an in-process ControlPlane with a persistence
directory, so "install" provisions exactly that: each workflow phase does
its real counterpart (issue the CA credential, create the store, start the
components, verify readiness) and records a status condition per phase —
the same observable surface the reference exposes to `kubectl get karmada`.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from karmada_tpu.models.meta import Condition, ObjectMeta, TypedObject, set_condition
from karmada_tpu.store.store import Event, ObjectStore
from karmada_tpu.store.worker import AsyncWorker, Runtime

PHASE_CERT = "CertificatesReady"
PHASE_STORE = "EtcdReady"  # the store IS the framework's etcd
PHASE_APISERVER = "ApiServerReady"
PHASE_COMPONENTS = "ComponentsReady"
COND_READY = "Ready"

INSTALL_PHASES = [PHASE_CERT, PHASE_STORE, PHASE_APISERVER, PHASE_COMPONENTS]


@dataclass
class KarmadaComponents:
    """Which optional components the plane runs (type.go spec.components)."""

    scheduler_backend: str = "serial"  # serial | device
    descheduler: bool = False
    search: bool = True
    metrics_adapter: bool = True


@dataclass
class KarmadaSpec:
    host_data_dir: str = ""  # persistence root; defaults under the operator dir
    components: KarmadaComponents = field(default_factory=KarmadaComponents)
    feature_gates: Dict[str, bool] = field(default_factory=dict)


@dataclass
class KarmadaStatus:
    phase: str = ""  # Installing | Upgrading | Running | Failed | Deinstalling
    conditions: List[Condition] = field(default_factory=list)
    api_ready: bool = False


@dataclass
class Karmada(TypedObject):
    KIND = "Karmada"
    API_VERSION = "operator.karmada.io/v1alpha1"

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: KarmadaSpec = field(default_factory=KarmadaSpec)
    status: KarmadaStatus = field(default_factory=KarmadaStatus)


class _Workflow:
    """The reference's workflow job: ordered tasks, stop on first failure
    (workflow/job.go RunTask semantics), each task reporting a condition."""

    def __init__(self) -> None:
        self.tasks: List[tuple] = []  # (condition_type, fn)

    def add(self, condition: str, fn: Callable[[], None]) -> None:
        self.tasks.append((condition, fn))

    def run(self, report: Callable[[str, bool, str], None]) -> bool:
        for condition, fn in self.tasks:
            try:
                fn()
            except Exception as e:  # noqa: BLE001 — reported, not raised
                report(condition, False, repr(e))
                return False
            report(condition, True, "")
        return True


class KarmadaOperator:
    """Reconciles Karmada CRs in a MANAGEMENT store into live planes."""

    def __init__(self, mgmt_store: ObjectStore, runtime: Runtime,
                 base_dir: str) -> None:
        self.store = mgmt_store
        self.base_dir = base_dir
        self.planes: Dict[str, object] = {}  # name -> ControlPlane
        self.observed: Dict[str, int] = {}   # name -> reconciled generation
        self.worker = runtime.register(AsyncWorker("karmada-operator", self._reconcile))
        mgmt_store.bus.subscribe(self._on_event, kind=Karmada.KIND)

    def _on_event(self, event: Event) -> None:
        self.worker.enqueue(event.obj.name)

    def plane(self, name: str):
        return self.planes.get(name)

    def _reconcile(self, name: str) -> None:
        cr = self.store.try_get(Karmada.KIND, "", name)
        if cr is None or cr.metadata.deleting:
            self._deinstall(name)
            return
        if name in self.planes:
            if cr.metadata.generation != self.observed.get(name):
                return self._upgrade(name)
            self._probe(name)
            return None

        def set_phase(obj: Karmada) -> None:
            obj.status.phase = "Installing"
        self.store.mutate(Karmada.KIND, "", name, set_phase)

        data_dir = cr.spec.host_data_dir or os.path.join(self.base_dir, name)
        plane_box: Dict[str, object] = {}

        def report(condition: str, ok: bool, msg: str) -> None:
            def upd(obj: Karmada) -> None:
                set_condition(obj.status.conditions, Condition(
                    type=condition, status="True" if ok else "False",
                    reason="Succeed" if ok else "Failed", message=msg,
                ))
                if not ok:
                    obj.status.phase = "Failed"
            self.store.mutate(Karmada.KIND, "", name, upd)

        wf = _Workflow()
        # cert task: the plane's CA credential material (tasks/init/cert.go)
        wf.add(PHASE_CERT, lambda: os.makedirs(data_dir, exist_ok=True))
        # etcd task: bring up the persistent store (tasks/init/etcd.go)

        def start_store() -> None:
            from karmada_tpu.store.persistence import load_store

            load_store(data_dir).persistence.close()
        wf.add(PHASE_STORE, start_store)

        # apiserver + components: the ControlPlane wires both
        def start_plane() -> None:
            from karmada_tpu.e2e import ControlPlane

            plane_box["plane"] = ControlPlane(
                backend=cr.spec.components.scheduler_backend,
                enable_descheduler=cr.spec.components.descheduler,
                feature_gates=cr.spec.feature_gates or None,
                persist_dir=data_dir,
            )
        wf.add(PHASE_APISERVER, start_plane)

        # wait task: verify the plane answers (tasks/init/wait.go) with a
        # canary write/read/delete through the real store path
        def verify() -> None:
            from karmada_tpu.models.unstructured import Unstructured

            plane = plane_box["plane"]
            plane.tick()
            canary = Unstructured.from_manifest({
                "apiVersion": "v1", "kind": "ConfigMap",
                "metadata": {"name": "operator-canary",
                             "namespace": "karmada-system"},
                "data": {"probe": name},
            })
            plane.store.create(canary)
            got = plane.store.get("ConfigMap", "karmada-system", "operator-canary")
            assert got.manifest["data"]["probe"] == name
            plane.store.delete("ConfigMap", "karmada-system", "operator-canary")
            plane.tick()
        wf.add(PHASE_COMPONENTS, verify)

        ok = wf.run(report)

        def finish(obj: Karmada) -> None:
            if ok:
                obj.status.phase = "Running"
                obj.status.api_ready = True
                set_condition(obj.status.conditions, Condition(
                    type=COND_READY, status="True", reason="Running",
                ))
            else:
                obj.status.api_ready = False
                set_condition(obj.status.conditions, Condition(
                    type=COND_READY, status="False", reason="InstallFailed",
                ))
        self.store.mutate(Karmada.KIND, "", name, finish)
        if ok:
            self.planes[name] = plane_box["plane"]
            self.observed[name] = cr.metadata.generation
            return None
        return False  # AsyncWorker requeues with its bounded retry budget

    def _upgrade(self, name: str):
        """Reconcile a SPEC CHANGE on a live plane (the reference operator's
        upgrade/reconfigure workflow, operator/pkg/controller/karmada):
        checkpoint + stop the old component set, then rebuild from the SAME
        data dir under the new spec — state survives through the WAL the way
        the reference's control planes survive through etcd.  A failed
        rebuild returns False so the worker retries with backoff budget."""
        def set_phase(obj: Karmada) -> None:
            obj.status.phase = "Upgrading"
            obj.status.api_ready = False
            set_condition(obj.status.conditions, Condition(
                type=COND_READY, status="False", reason="Upgrading",
            ))
        self.store.mutate(Karmada.KIND, "", name, set_phase)

        old = self.planes.pop(name, None)
        if old is not None:
            old.checkpoint()
            old.runtime.stop()
        self.observed.pop(name, None)
        return self._reconcile(name)  # install path against the persisted dir

    def _probe(self, name: str) -> None:
        plane = self.planes[name]
        healthy = True
        try:
            plane.tick()
        except Exception:  # noqa: BLE001
            healthy = False

        def upd(obj: Karmada) -> None:
            obj.status.api_ready = healthy
            set_condition(obj.status.conditions, Condition(
                type=COND_READY, status="True" if healthy else "False",
                reason="Running" if healthy else "Unhealthy",
            ))
            obj.status.phase = "Running" if healthy else "Failed"
        try:
            self.store.mutate(Karmada.KIND, "", name, upd)
        except KeyError:
            pass

    def _deinstall(self, name: str) -> None:
        """tasks/deinit: stop components; the data dir is left for the
        operator's owner to reclaim (the reference keeps etcd PVs too)."""
        plane = self.planes.pop(name, None)
        self.observed.pop(name, None)
        if plane is not None:
            plane.checkpoint()
            plane.runtime.stop()
