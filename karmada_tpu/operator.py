"""karmada-operator: install/manage control planes from a Karmada CR.

Reference: operator/pkg/ — the `Karmada` CR
(operator/pkg/apis/operator/v1alpha1/type.go:33) describes a whole control
plane; the operator's workflow engine (operator/pkg/workflow/{job,task}.go)
runs the install task list (tasks/init: cert -> etcd -> apiserver ->
component -> wait -> upload) and deinit in reverse.

Here a control plane is an in-process ControlPlane with a persistence
directory, so "install" provisions exactly that: each workflow phase does
its real counterpart (issue the CA credential, create the store, start the
components, verify readiness) and records a status condition per phase —
the same observable surface the reference exposes to `kubectl get karmada`.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from karmada_tpu.models.meta import Condition, ObjectMeta, TypedObject, set_condition
from karmada_tpu.store.store import Event, ObjectStore
from karmada_tpu.store.worker import AsyncWorker, Runtime

PHASE_CERT = "CertificatesReady"
PHASE_STORE = "EtcdReady"  # the store IS the framework's etcd
PHASE_APISERVER = "ApiServerReady"
PHASE_CRDS = "CrdsReady"
PHASE_COMPONENTS = "ComponentsReady"
COND_READY = "Ready"

INSTALL_PHASES = [PHASE_CERT, PHASE_STORE, PHASE_APISERVER, PHASE_CRDS,
                  PHASE_COMPONENTS]

# components whose credentials the cert task issues off the CA
# (operator/pkg/tasks/init/cert.go issues the karmada-apiserver /
# front-proxy / etcd leaf certs; same component list here)
CERT_COMPONENTS = ("apiserver", "front-proxy", "etcd", "scheduler",
                   "webhook", "agent")


@dataclass
class KarmadaComponents:
    """Which optional components the plane runs (type.go spec.components)."""

    scheduler_backend: str = "serial"  # serial | device
    descheduler: bool = False
    search: bool = True
    metrics_adapter: bool = True


@dataclass
class KarmadaSpec:
    host_data_dir: str = ""  # persistence root; defaults under the operator dir
    components: KarmadaComponents = field(default_factory=KarmadaComponents)
    feature_gates: Dict[str, bool] = field(default_factory=dict)


@dataclass
class KarmadaStatus:
    phase: str = ""  # Installing | Upgrading | Running | Failed | Deinstalling
    conditions: List[Condition] = field(default_factory=list)
    api_ready: bool = False


@dataclass
class Karmada(TypedObject):
    KIND = "Karmada"
    API_VERSION = "operator.karmada.io/v1alpha1"

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: KarmadaSpec = field(default_factory=KarmadaSpec)
    status: KarmadaStatus = field(default_factory=KarmadaStatus)


def copy_spec(spec: KarmadaSpec) -> KarmadaSpec:
    """The rollback target must not alias live CR fields (and must track
    future KarmadaSpec fields without hand-maintenance)."""
    import copy

    return copy.deepcopy(spec)


class _Workflow:
    """The reference's workflow job: ordered tasks, stop on first failure
    (workflow/job.go RunTask semantics), each task reporting a condition."""

    def __init__(self) -> None:
        self.tasks: List[tuple] = []  # (condition_type, fn)

    def add(self, condition: str, fn: Callable[[], None]) -> None:
        self.tasks.append((condition, fn))

    def run(self, report: Callable[[str, bool, str], None]) -> bool:
        for condition, fn in self.tasks:
            try:
                fn()
            # vet: ignore[exception-hygiene] reported into the install-condition callback
            except Exception as e:  # noqa: BLE001 — reported, not raised
                report(condition, False, repr(e))
                return False
            report(condition, True, "")
        return True


def issue_cert_material(data_dir: str) -> Dict[str, Dict]:
    """The cert task's material (tasks/init/cert.go): a CA secret plus one
    derived leaf credential per component, persisted under data_dir/pki/.
    Idempotent — an existing CA is REUSED (the reference keeps the CA
    stable across reinstall/upgrade so member kubeconfigs stay valid)."""
    import hashlib
    import json
    import secrets

    pki = os.path.join(data_dir, "pki")
    os.makedirs(pki, exist_ok=True)
    ca_path = os.path.join(pki, "ca.json")
    if os.path.exists(ca_path):
        with open(ca_path) as f:
            ca = json.load(f)
    else:
        ca = {"secret": secrets.token_hex(32), "created_at": time.time()}
        with open(ca_path, "w") as f:
            json.dump(ca, f)
    out = {"ca": {"fingerprint": hashlib.sha256(
        ca["secret"].encode()).hexdigest()[:16]}}
    for comp in CERT_COMPONENTS:
        fingerprint = hashlib.sha256(
            (ca["secret"] + ":" + comp).encode()).hexdigest()
        leaf = {"component": comp, "fingerprint": fingerprint[:32],
                "issued_at": time.time(),
                "expires_at": time.time() + 365 * 24 * 3600}
        with open(os.path.join(pki, f"{comp}.json"), "w") as f:
            json.dump(leaf, f)
        out[comp] = {"fingerprint": leaf["fingerprint"]}
    return out


class KarmadaOperator:
    """Reconciles Karmada CRs in a MANAGEMENT store into live planes."""

    def __init__(self, mgmt_store: ObjectStore, runtime: Runtime,
                 base_dir: str, fault_injector=None) -> None:
        self.store = mgmt_store
        self.base_dir = base_dir
        self.planes: Dict[str, object] = {}  # name -> ControlPlane
        self.observed: Dict[str, int] = {}   # name -> reconciled generation
        # spec the RUNNING plane was installed with (upgrade rollback target)
        self.installed_spec: Dict[str, KarmadaSpec] = {}
        # chaos hook: fault_injector(phase, name) raises to fail that task
        # (same idiom as the e2e chaos harness)
        self.fault_injector = fault_injector
        self.worker = runtime.register(AsyncWorker("karmada-operator", self._reconcile))
        mgmt_store.bus.subscribe(self._on_event, kind=Karmada.KIND)
        # periodic resync drives the health probe of installed planes
        runtime.register_periodic(self._resync, name="karmada-operator")

    def _on_event(self, event: Event) -> None:
        # generation predicate (the reference operator's spec-change
        # filter): the install workflow's own STATUS writes must not
        # re-enqueue the reconcile — a failing install would otherwise
        # re-arm its own retry forever
        if (event.old is None
                or event.obj.metadata.deleting
                or event.obj.metadata.generation
                != event.old.metadata.generation):
            self.worker.enqueue(event.obj.name)

    def _resync(self) -> None:
        # EVERY CR, not just installed planes: a CR whose install exhausted
        # its retry budget must revive when the fault clears, and the
        # generation filter means no event will do it
        for cr in self.store.list(Karmada.KIND):
            self.worker.enqueue(cr.metadata.name)

    def plane(self, name: str):
        return self.planes.get(name)

    def _reconcile(self, name: str) -> None:
        cr = self.store.try_get(Karmada.KIND, "", name)
        if cr is None or cr.metadata.deleting:
            self._deinstall(name)
            return
        if name in self.planes:
            if cr.metadata.generation != self.observed.get(name):
                return self._upgrade(name)
            self._probe(name)
            return None

        ok = self._install(name, cr, cr.spec)
        if ok:
            self.observed[name] = cr.metadata.generation
            self.installed_spec[name] = copy_spec(cr.spec)
            return None
        return False  # AsyncWorker requeues with its bounded retry budget

    def _install(self, name: str, cr: Karmada, spec: KarmadaSpec) -> bool:
        """The staged install task graph (operator/pkg/tasks/init/):
        cert -> etcd -> apiserver -> crds -> components, each reporting a
        condition; a failed task stops the graph, marks phase Failed, and
        the next reconcile retries — completed phases are idempotent so
        the retry converges from where it failed."""
        def set_phase(obj: Karmada) -> None:
            obj.status.phase = "Installing"
        self.store.mutate(Karmada.KIND, "", name, set_phase)

        data_dir = spec.host_data_dir or os.path.join(self.base_dir, name)
        plane_box: Dict[str, object] = {}

        def report(condition: str, ok: bool, msg: str) -> None:
            def upd(obj: Karmada) -> None:
                set_condition(obj.status.conditions, Condition(
                    type=condition, status="True" if ok else "False",
                    reason="Succeed" if ok else "Failed", message=msg,
                ))
                if not ok:
                    obj.status.phase = "Failed"
            self.store.mutate(Karmada.KIND, "", name, upd)

        def faultable(phase: str, fn: Callable[[], None]) -> Callable[[], None]:
            def run() -> None:
                if self.fault_injector is not None:
                    self.fault_injector(phase, name)
                fn()
            return run

        wf = _Workflow()
        # cert task: CA + per-component leaf credentials on disk
        # (tasks/init/cert.go); the CA survives reinstalls
        def certs() -> None:
            os.makedirs(data_dir, exist_ok=True)
            plane_box["certs"] = issue_cert_material(data_dir)
        wf.add(PHASE_CERT, faultable(PHASE_CERT, certs))

        # etcd task: bring up the persistent store (tasks/init/etcd.go)
        def start_store() -> None:
            from karmada_tpu.store.persistence import load_store

            load_store(data_dir).persistence.close()
        wf.add(PHASE_STORE, faultable(PHASE_STORE, start_store))

        # apiserver task: the ControlPlane process set
        def start_plane() -> None:
            from karmada_tpu.e2e import ControlPlane

            plane_box["plane"] = ControlPlane(
                backend=spec.components.scheduler_backend,
                enable_descheduler=spec.components.descheduler,
                feature_gates=spec.feature_gates or None,
                persist_dir=data_dir,
            )
        wf.add(PHASE_APISERVER, faultable(PHASE_APISERVER, start_plane))

        # crds task (tasks/init/crd.go): the API surface registered in the
        # new plane, recorded as the api-resources ConfigMap
        def install_crds() -> None:
            from karmada_tpu.models.codec import model_registry

            plane = plane_box["plane"]
            plane.apply({
                "apiVersion": "v1", "kind": "ConfigMap",
                "metadata": {"name": "api-resources",
                             "namespace": "karmada-system"},
                "data": {"kinds": ",".join(sorted(model_registry()))},
            })
        wf.add(PHASE_CRDS, faultable(PHASE_CRDS, install_crds))

        # components task (tasks/init/component.go): render each
        # component's config into the plane, then verify the plane answers
        # (tasks/init/wait.go) with a canary write/read/delete
        def components() -> None:
            from karmada_tpu.models.unstructured import Unstructured

            plane = plane_box["plane"]
            certs_out = plane_box.get("certs", {})
            plane.apply({
                "apiVersion": "v1", "kind": "ConfigMap",
                "metadata": {"name": "scheduler", "namespace": "karmada-system"},
                "data": {"backend": spec.components.scheduler_backend,
                         "cert": certs_out.get("scheduler", {}).get(
                             "fingerprint", "")},
            })
            plane.apply({
                "apiVersion": "v1", "kind": "ConfigMap",
                "metadata": {"name": "controller-manager-config",
                             "namespace": "karmada-system"},
                "data": {
                    "featureGates": ",".join(
                        f"{k}={v}" for k, v in sorted(
                            (spec.feature_gates or {}).items())),
                    "descheduler": str(spec.components.descheduler),
                    "search": str(spec.components.search),
                    "metricsAdapter": str(spec.components.metrics_adapter),
                },
            })
            plane.tick()
            canary = Unstructured.from_manifest({
                "apiVersion": "v1", "kind": "ConfigMap",
                "metadata": {"name": "operator-canary",
                             "namespace": "karmada-system"},
                "data": {"probe": name},
            })
            plane.store.create(canary)
            got = plane.store.get("ConfigMap", "karmada-system", "operator-canary")
            assert got.manifest["data"]["probe"] == name
            plane.store.delete("ConfigMap", "karmada-system", "operator-canary")
            plane.tick()
        wf.add(PHASE_COMPONENTS, faultable(PHASE_COMPONENTS, components))

        ok = wf.run(report)

        def finish(obj: Karmada) -> None:
            if ok:
                obj.status.phase = "Running"
                obj.status.api_ready = True
                set_condition(obj.status.conditions, Condition(
                    type=COND_READY, status="True", reason="Running",
                ))
                # a clean install supersedes any stale upgrade-failure
                # signal from an earlier rollback
                if any(c.type == "UpgradeFailed"
                       for c in obj.status.conditions):
                    set_condition(obj.status.conditions, Condition(
                        type="UpgradeFailed", status="False",
                        reason="Recovered",
                    ))
            else:
                obj.status.api_ready = False
                set_condition(obj.status.conditions, Condition(
                    type=COND_READY, status="False", reason="InstallFailed",
                ))
        self.store.mutate(Karmada.KIND, "", name, finish)
        if ok:
            self.planes[name] = plane_box["plane"]
        elif "plane" in plane_box:
            # a partially-started plane must not leak its threads/WAL handle
            plane_box["plane"].runtime.stop()
        return ok

    def _upgrade(self, name: str):
        """Reconcile a SPEC CHANGE on a live plane (the reference operator's
        upgrade/reconfigure workflow, operator/pkg/controller/karmada):
        checkpoint + stop the old component set, then rebuild from the SAME
        data dir under the new spec — state survives through the WAL the way
        the reference's control planes survive through etcd.  A failed
        rebuild ROLLS BACK: the previous spec is reinstalled from the same
        data dir, so the plane keeps serving while the bad spec sits in
        phase Failed / condition UpgradeFailed for the operator's owner."""
        cr = self.store.try_get(Karmada.KIND, "", name)
        if cr is None:
            return None

        def set_phase(obj: Karmada) -> None:
            obj.status.phase = "Upgrading"
            obj.status.api_ready = False
            set_condition(obj.status.conditions, Condition(
                type=COND_READY, status="False", reason="Upgrading",
            ))
        self.store.mutate(Karmada.KIND, "", name, set_phase)

        old = self.planes.pop(name, None)
        if old is not None:
            old.checkpoint()
            old.runtime.stop()
        self.observed.pop(name, None)

        ok = self._install(name, cr, cr.spec)
        if ok:
            self.observed[name] = cr.metadata.generation
            self.installed_spec[name] = copy_spec(cr.spec)
            return None

        prev = self.installed_spec.get(name)
        if prev is None:
            return False  # nothing to roll back to: retry the new spec
        rolled = self._install(name, cr, prev)

        def record(obj: Karmada) -> None:
            set_condition(obj.status.conditions, Condition(
                type="UpgradeFailed", status="True", reason="RolledBack"
                if rolled else "RollbackFailed",
                message="upgrade install failed; previous spec "
                        + ("restored" if rolled else "could NOT be restored"),
            ))
            if rolled:
                # the plane is serving again — on the OLD spec
                obj.status.phase = "Running"
                obj.status.api_ready = True
        self.store.mutate(Karmada.KIND, "", name, record)
        if rolled:
            # observe the failed generation so the bad spec is not retried
            # in a loop; a NEW generation (fixed spec) upgrades again
            self.observed[name] = cr.metadata.generation
            return None
        return False

    def _probe(self, name: str) -> None:
        plane = self.planes[name]
        healthy = True
        try:
            plane.tick()
        # vet: ignore[exception-hygiene] surfaced as status.api_ready=False
        except Exception:  # noqa: BLE001
            healthy = False

        def upd(obj: Karmada) -> None:
            obj.status.api_ready = healthy
            set_condition(obj.status.conditions, Condition(
                type=COND_READY, status="True" if healthy else "False",
                reason="Running" if healthy else "Unhealthy",
            ))
            obj.status.phase = "Running" if healthy else "Failed"
        try:
            self.store.mutate(Karmada.KIND, "", name, upd)
        except KeyError:
            pass

    def _deinstall(self, name: str) -> None:
        """tasks/deinit: stop components; the data dir is left for the
        operator's owner to reclaim (the reference keeps etcd PVs too)."""
        plane = self.planes.pop(name, None)
        self.observed.pop(name, None)
        self.installed_spec.pop(name, None)
        if plane is not None:
            plane.checkpoint()
            plane.runtime.stop()
