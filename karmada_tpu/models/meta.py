"""Object metadata, conditions, and label-selector semantics.

Mirrors the slices of k8s apimachinery the reference relies on:
ObjectMeta (labels/annotations/uid/generation/deletionTimestamp/finalizers),
metav1.Condition, and LabelSelector matching (matchLabels + matchExpressions
with In/NotIn/Exists/DoesNotExist/Gt/Lt) used by ClusterAffinity
(reference pkg/util/cluster.go ClusterMatches).
"""

from __future__ import annotations

import time
import uuid as _uuid
from dataclasses import dataclass, field
from typing import Any, ClassVar, Dict, List, Optional


def new_uid() -> str:
    return str(_uuid.uuid4())


def now() -> float:
    return time.time()


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = ""
    uid: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    resource_version: int = 0
    generation: int = 0
    creation_timestamp: float = 0.0
    deletion_timestamp: Optional[float] = None
    finalizers: List[str] = field(default_factory=list)
    owner_references: List["OwnerReference"] = field(default_factory=list)

    def key(self) -> str:
        return f"{self.namespace}/{self.name}" if self.namespace else self.name

    @property
    def deleting(self) -> bool:
        return self.deletion_timestamp is not None


@dataclass
class OwnerReference:
    api_version: str = ""
    kind: str = ""
    name: str = ""
    uid: str = ""


@dataclass
class Condition:
    type: str = ""
    status: str = "Unknown"  # "True" | "False" | "Unknown"
    reason: str = ""
    message: str = ""
    last_transition_time: float = 0.0
    observed_generation: int = 0


def get_condition(conditions: List[Condition], cond_type: str) -> Optional[Condition]:
    for c in conditions:
        if c.type == cond_type:
            return c
    return None


def set_condition(conditions: List[Condition], new: Condition) -> bool:
    """Upsert keeping last_transition_time stable when status unchanged.

    Returns True when the condition list changed.
    """
    existing = get_condition(conditions, new.type)
    if existing is None:
        if not new.last_transition_time:
            new.last_transition_time = now()
        conditions.append(new)
        return True
    if (
        existing.status == new.status
        and existing.reason == new.reason
        and existing.message == new.message
    ):
        return False
    if existing.status != new.status:
        new.last_transition_time = now()
    else:
        new.last_transition_time = existing.last_transition_time
    conditions[conditions.index(existing)] = new
    return True


def is_condition_true(conditions: List[Condition], cond_type: str) -> bool:
    c = get_condition(conditions, cond_type)
    return c is not None and c.status == "True"


@dataclass
class LabelSelectorRequirement:
    key: str = ""
    operator: str = "In"  # In | NotIn | Exists | DoesNotExist | Gt | Lt
    values: List[str] = field(default_factory=list)


@dataclass
class LabelSelector:
    match_labels: Dict[str, str] = field(default_factory=dict)
    match_expressions: List[LabelSelectorRequirement] = field(default_factory=list)

    def matches(self, labels: Dict[str, str]) -> bool:
        for k, v in self.match_labels.items():
            if labels.get(k) != v:
                return False
        for req in self.match_expressions:
            have = req.key in labels
            val = labels.get(req.key)
            if req.operator == "In":
                if not have or val not in req.values:
                    return False
            elif req.operator == "NotIn":
                if have and val in req.values:
                    return False
            elif req.operator == "Exists":
                if not have:
                    return False
            elif req.operator == "DoesNotExist":
                if have:
                    return False
            elif req.operator == "Gt":
                if not have or not _int_ok(val) or int(val) <= int(req.values[0]):
                    return False
            elif req.operator == "Lt":
                if not have or not _int_ok(val) or int(val) >= int(req.values[0]):
                    return False
            else:
                raise ValueError(f"unknown selector operator {req.operator}")
        return True


def _int_ok(v: Optional[str]) -> bool:
    try:
        int(v)  # type: ignore[arg-type]
        return True
    except (TypeError, ValueError):
        return False


@dataclass
class TypedObject:
    """Base for every API object: kind + metadata."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)

    KIND: ClassVar[str] = ""
    API_VERSION: ClassVar[str] = ""

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    def key(self) -> str:
        return self.metadata.key()


def deep_get(obj: Any, path: str, default: Any = None) -> Any:
    """Fetch a dotted path from nested dicts (manifest helpers)."""
    cur = obj
    for part in path.split("."):
        if isinstance(cur, dict) and part in cur:
            cur = cur[part]
        else:
            return default
    return cur


def deep_set(obj: Dict[str, Any], path: str, value: Any) -> None:
    parts = path.split(".")
    cur = obj
    for part in parts[:-1]:
        cur = cur.setdefault(part, {})
    cur[parts[-1]] = value
