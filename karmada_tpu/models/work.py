"""Work API types: ResourceBinding (the scheduling unit) and Work.

Mirrors reference pkg/apis/work/v1alpha2/binding_types.go:59-409 and
work/v1alpha1/work_types.go:45-103.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from karmada_tpu.models.meta import Condition, ObjectMeta, TypedObject
from karmada_tpu.models.policy import Placement
from karmada_tpu.utils.quantity import Quantity

# Binding condition types
COND_SCHEDULED = "Scheduled"
COND_FULLY_APPLIED = "FullyApplied"

# Work condition types
COND_WORK_APPLIED = "Applied"
COND_WORK_AVAILABLE = "Available"
COND_WORK_DEGRADED = "Degraded"


@dataclass
class ObjectReference:
    """Reference to the propagated template (binding_types.go Resource)."""

    api_version: str = ""
    kind: str = ""
    namespace: str = ""
    name: str = ""
    uid: str = ""
    resource_version: int = 0


@dataclass
class NodeClaim:
    """Node-level scheduling claims carried to the accurate estimator
    (pkg/estimator/pb/generated.proto NodeClaim)."""

    node_selector: Dict[str, str] = field(default_factory=dict)
    tolerations: List[Any] = field(default_factory=list)
    hard_node_affinity: Optional[Any] = None


@dataclass
class ReplicaRequirements:
    """Per-replica resource demand (binding_types.go:211)."""

    resource_request: Dict[str, Quantity] = field(default_factory=dict)
    node_claim: Optional[NodeClaim] = None
    namespace: str = ""
    priority_class_name: str = ""


@dataclass
class Component:
    """One pod template of a multi-template workload
    (binding_types.go:98, feature MultiplePodTemplatesScheduling)."""

    name: str = ""
    replicas: int = 0
    replica_requirements: Optional[ReplicaRequirements] = None


@dataclass
class TargetCluster:
    """Schedule result entry (binding_types.go .spec.clusters)."""

    name: str = ""
    replicas: int = 0


@dataclass
class BindingSnapshot:
    """RequiredBy entry: another binding's schedule result that this (attached)
    binding must follow (dependencies distribution)."""

    namespace: str = ""
    name: str = ""
    clusters: List[TargetCluster] = field(default_factory=list)


@dataclass
class GracefulEvictionTask:
    """binding_types.go:330-353."""

    from_cluster: str = ""
    replicas: int = 0
    reason: str = ""
    message: str = ""
    producer: str = ""
    grace_period_seconds: Optional[int] = None
    suppress_deletion: Optional[bool] = None
    creation_timestamp: float = 0.0
    # how the legacy application on from_cluster is purged; recorded so the
    # binding controller can decide whether preserved state may be injected
    # (binding/common.go:171-207: only Immediately/Directly tasks inject)
    purge_mode: str = ""
    # StatefulFailoverInjection payload (binding_types.go:330-353)
    clusters_before_failover: List[str] = field(default_factory=list)
    preserved_label_state: Dict[str, str] = field(default_factory=dict)


@dataclass
class BindingSuspension:
    scheduling: bool = False
    dispatching: bool = False
    dispatching_on_clusters: List[str] = field(default_factory=list)


@dataclass
class ResourceBindingSpec:
    resource: ObjectReference = field(default_factory=ObjectReference)
    replicas: int = 0
    replica_requirements: Optional[ReplicaRequirements] = None
    components: List[Component] = field(default_factory=list)
    placement: Optional[Placement] = None
    clusters: List[TargetCluster] = field(default_factory=list)
    required_by: List[BindingSnapshot] = field(default_factory=list)
    graceful_eviction_tasks: List[GracefulEvictionTask] = field(default_factory=list)
    reschedule_triggered_at: Optional[float] = None
    suspension: Optional[BindingSuspension] = None
    schedule_priority: Optional[int] = None
    conflict_resolution: str = "Abort"
    propagate_deps: bool = False
    failover: Optional[Any] = None

    def target_contains(self, cluster_name: str) -> bool:
        return any(tc.name == cluster_name for tc in self.clusters)

    def assigned_replicas_for_cluster(self, cluster_name: str) -> int:
        """binding_types.go AssignedReplicasForCluster."""
        for tc in self.clusters:
            if tc.name == cluster_name:
                return tc.replicas
        return 0

    def cluster_names(self) -> List[str]:
        return [tc.name for tc in self.clusters]


@dataclass
class AggregatedStatusItem:
    cluster_name: str = ""
    status: Optional[Dict[str, Any]] = None
    applied: bool = False
    applied_message: str = ""
    health: str = "Unknown"  # Healthy | Unhealthy | Unknown


@dataclass
class ResourceBindingStatus:
    scheduler_observed_generation: int = 0
    scheduler_observed_affinity_name: str = ""
    last_scheduled_time: Optional[float] = None
    conditions: List[Condition] = field(default_factory=list)
    aggregated_status: List[AggregatedStatusItem] = field(default_factory=list)


@dataclass
class ResourceBinding(TypedObject):
    KIND = "ResourceBinding"
    API_VERSION = "work.karmada.io/v1alpha2"

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ResourceBindingSpec = field(default_factory=ResourceBindingSpec)
    status: ResourceBindingStatus = field(default_factory=ResourceBindingStatus)


@dataclass
class ClusterResourceBinding(ResourceBinding):
    KIND = "ClusterResourceBinding"


@dataclass
class ManifestStatus:
    identifier: Dict[str, Any] = field(default_factory=dict)
    status: Optional[Dict[str, Any]] = None
    health: str = "Unknown"


@dataclass
class WorkSpec:
    workload: List[Dict[str, Any]] = field(default_factory=list)  # raw manifests
    suspend_dispatching: bool = False


@dataclass
class WorkStatus:
    conditions: List[Condition] = field(default_factory=list)
    manifest_statuses: List[ManifestStatus] = field(default_factory=list)


@dataclass
class Work(TypedObject):
    KIND = "Work"
    API_VERSION = "work.karmada.io/v1alpha1"

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: WorkSpec = field(default_factory=WorkSpec)
    status: WorkStatus = field(default_factory=WorkStatus)


def get_sum_of_replicas(clusters: List[TargetCluster]) -> int:
    return sum(tc.replicas for tc in clusters)


def merge_target_clusters(
    old: List[TargetCluster], new: List[TargetCluster]
) -> List[TargetCluster]:
    """Port of util.MergeTargetClusters: sum replicas per cluster name,
    keeping clusters from both lists (old order first, then new-only)."""
    merged: Dict[str, int] = {}
    order: List[str] = []
    for tc in list(old) + list(new):
        if tc.name not in merged:
            merged[tc.name] = 0
            order.append(tc.name)
        merged[tc.name] += tc.replicas
    return [TargetCluster(name=n, replicas=merged[n]) for n in order]
