"""config.karmada.io API types (reference pkg/apis/config/v1alpha1).

ResourceInterpreterCustomization: DATA-DRIVEN per-kind interpreter scripts
(the reference ships Lua executed by gopher-lua,
resourceinterpretercustomization_types.go + customized/declarative/luavm/
lua.go).  This framework's script language is a sandboxed expression
dialect (interpreter/declarative.py); each operation carries one
expression string evaluated against the operation's bound names.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict

from karmada_tpu.models.meta import ObjectMeta, TypedObject


@dataclass
class CustomizationTarget:
    api_version: str = ""
    kind: str = ""


@dataclass
class ResourceInterpreterCustomizationSpec:
    target: CustomizationTarget = field(default_factory=CustomizationTarget)
    # operation name (interpreter.OP_*) -> sandboxed expression script
    customizations: Dict[str, str] = field(default_factory=dict)


@dataclass
class ResourceInterpreterCustomization(TypedObject):
    KIND = "ResourceInterpreterCustomization"
    API_VERSION = "config.karmada.io/v1alpha1"

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ResourceInterpreterCustomizationSpec = field(
        default_factory=ResourceInterpreterCustomizationSpec
    )


@dataclass
class InterpreterRule:
    """Which (apiVersion, kind, operations) a webhook serves
    (resourceinterpreterwebhook_types.go RuleWithOperations)."""

    # wildcards are EXPLICIT on every axis: an empty list matches nothing
    api_versions: list = field(default_factory=list)  # ["apps/v1"] or ["*"]
    kinds: list = field(default_factory=list)         # ["Deployment"] or ["*"]
    operations: list = field(default_factory=list)    # interpreter.OP_* or ["*"]


@dataclass
class ResourceInterpreterWebhookSpec:
    """Endpoint + rules (resourceinterpreterwebhook_types.go:34-77).  The
    reference dials HTTPS with CA bundles; this framework's transport is a
    pluggable URL (http:// for loopback services, or the in-process
    `local:` scheme used in tests) — the mTLS story lives one layer down
    in estimator/wire.py's transport seam."""

    endpoint: str = ""
    rules: list = field(default_factory=list)  # List[InterpreterRule]
    timeout_s: float = 5.0


@dataclass
class ResourceInterpreterWebhook(TypedObject):
    KIND = "ResourceInterpreterWebhook"
    API_VERSION = "config.karmada.io/v1alpha1"

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ResourceInterpreterWebhookSpec = field(
        default_factory=ResourceInterpreterWebhookSpec
    )
