"""config.karmada.io API types (reference pkg/apis/config/v1alpha1).

ResourceInterpreterCustomization: DATA-DRIVEN per-kind interpreter scripts
(the reference ships Lua executed by gopher-lua,
resourceinterpretercustomization_types.go + customized/declarative/luavm/
lua.go).  This framework's script language is a sandboxed expression
dialect (interpreter/declarative.py); each operation carries one
expression string evaluated against the operation's bound names.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict

from karmada_tpu.models.meta import ObjectMeta, TypedObject


@dataclass
class CustomizationTarget:
    api_version: str = ""
    kind: str = ""


@dataclass
class ResourceInterpreterCustomizationSpec:
    target: CustomizationTarget = field(default_factory=CustomizationTarget)
    # operation name (interpreter.OP_*) -> sandboxed expression script
    customizations: Dict[str, str] = field(default_factory=dict)


@dataclass
class ResourceInterpreterCustomization(TypedObject):
    KIND = "ResourceInterpreterCustomization"
    API_VERSION = "config.karmada.io/v1alpha1"

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ResourceInterpreterCustomizationSpec = field(
        default_factory=ResourceInterpreterCustomizationSpec
    )
