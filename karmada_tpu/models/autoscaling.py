"""Autoscaling APIs: FederatedHPA + CronFederatedHPA.

Mirrors reference pkg/apis/autoscaling/v1alpha1
(federatedhpa_types.go, cronfederatedhpa_types.go): the k8s
autoscaling/v2 HPA surface (resource-metric targets, scaling behavior
rules) federated across member clusters, plus cron-driven scaling.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from karmada_tpu.models.meta import Condition, ObjectMeta, TypedObject

# metric target types (autoscaling/v2)
TARGET_UTILIZATION = "Utilization"
TARGET_AVERAGE_VALUE = "AverageValue"
TARGET_VALUE = "Value"

# scaling policy types
POLICY_PODS = "Pods"
POLICY_PERCENT = "Percent"

SELECT_MAX = "Max"
SELECT_MIN = "Min"
SELECT_DISABLED = "Disabled"


@dataclass
class CrossVersionObjectReference:
    api_version: str = ""
    kind: str = ""
    name: str = ""


@dataclass
class MetricTarget:
    type: str = TARGET_UTILIZATION
    average_utilization: Optional[int] = None  # percent of request
    average_value: Optional[int] = None  # milli-units per pod
    value: Optional[int] = None  # absolute (Object/External Value targets)


@dataclass
class ResourceMetricSource:
    name: str = "cpu"  # resource name
    target: MetricTarget = field(default_factory=MetricTarget)


@dataclass
class PodsMetricSource:
    """custom.metrics.k8s.io per-pod series (autoscaling/v2 PodsMetricSource);
    served multi-cluster by the metrics adapter's custom provider."""

    metric: str = ""
    target: MetricTarget = field(default_factory=MetricTarget)  # AverageValue


@dataclass
class ObjectMetricSource:
    """A single object's custom metric (autoscaling/v2 ObjectMetricSource)."""

    described_object: CrossVersionObjectReference = field(
        default_factory=CrossVersionObjectReference)
    metric: str = ""
    target: MetricTarget = field(default_factory=MetricTarget)  # Value | AverageValue


@dataclass
class ExternalMetricSource:
    """external.metrics.k8s.io series (autoscaling/v2 ExternalMetricSource)."""

    metric: str = ""
    selector: Dict[str, str] = field(default_factory=dict)
    target: MetricTarget = field(default_factory=MetricTarget)  # Value | AverageValue


@dataclass
class MetricSpec:
    type: str = "Resource"  # Resource | Pods | Object | External
    resource: Optional[ResourceMetricSource] = None
    pods: Optional[PodsMetricSource] = None
    object: Optional[ObjectMetricSource] = None
    external: Optional[ExternalMetricSource] = None


@dataclass
class HPAScalingPolicy:
    type: str = POLICY_PODS  # Pods | Percent
    value: int = 0
    period_seconds: int = 60


@dataclass
class HPAScalingRules:
    stabilization_window_seconds: Optional[int] = None
    select_policy: str = SELECT_MAX
    policies: List[HPAScalingPolicy] = field(default_factory=list)


@dataclass
class HPABehavior:
    scale_up: Optional[HPAScalingRules] = None
    scale_down: Optional[HPAScalingRules] = None


@dataclass
class FederatedHPASpec:
    scale_target_ref: CrossVersionObjectReference = field(
        default_factory=CrossVersionObjectReference)
    min_replicas: int = 1
    max_replicas: int = 0
    metrics: List[MetricSpec] = field(default_factory=list)
    behavior: Optional[HPABehavior] = None


@dataclass
class MetricStatusValue:
    name: str = ""
    current_utilization: Optional[int] = None
    current_average_value: Optional[int] = None


@dataclass
class FederatedHPAStatus:
    current_replicas: int = 0
    desired_replicas: int = 0
    current_metrics: List[MetricStatusValue] = field(default_factory=list)
    last_scale_time: Optional[float] = None
    conditions: List[Condition] = field(default_factory=list)


@dataclass
class FederatedHPA(TypedObject):
    KIND = "FederatedHPA"
    API_VERSION = "autoscaling.karmada.io/v1alpha1"

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: FederatedHPASpec = field(default_factory=FederatedHPASpec)
    status: FederatedHPAStatus = field(default_factory=FederatedHPAStatus)


# -- CronFederatedHPA (cronfederatedhpa_types.go) ----------------------------


@dataclass
class CronFederatedHPARule:
    name: str = ""
    schedule: str = ""  # standard 5-field cron, evaluated each sync
    target_replicas: Optional[int] = None  # workload / FHPA replica target
    target_min_replicas: Optional[int] = None  # FHPA minReplicas
    target_max_replicas: Optional[int] = None  # FHPA maxReplicas
    suspend: bool = False


@dataclass
class CronFederatedHPASpec:
    scale_target_ref: CrossVersionObjectReference = field(
        default_factory=CrossVersionObjectReference)
    rules: List[CronFederatedHPARule] = field(default_factory=list)


@dataclass
class ExecutionHistory:
    rule_name: str = ""
    next_execution_time: Optional[float] = None
    last_execution_time: Optional[float] = None
    last_result: str = ""  # Succeed | Failed
    message: str = ""


@dataclass
class CronFederatedHPAStatus:
    execution_histories: List[ExecutionHistory] = field(default_factory=list)


@dataclass
class CronFederatedHPA(TypedObject):
    KIND = "CronFederatedHPA"
    API_VERSION = "autoscaling.karmada.io/v1alpha1"

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: CronFederatedHPASpec = field(default_factory=CronFederatedHPASpec)
    status: CronFederatedHPAStatus = field(default_factory=CronFederatedHPAStatus)
