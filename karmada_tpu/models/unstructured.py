"""Unstructured API objects: arbitrary workload manifests in the store.

The reference detector watches every ListWatch-able GVR via dynamic
informers (pkg/detector/detector.go:183 discoverResources) and handles
objects as unstructured.Unstructured.  This is the equivalent: a manifest
dict (apiVersion/kind/metadata/spec/status) wrapped as a TypedObject whose
KIND comes from the manifest, so templates of any kind live in the same
ObjectStore next to the framework's own CRD-style types.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from karmada_tpu.models.meta import ObjectMeta, TypedObject


@dataclass
class Unstructured(TypedObject):
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    manifest: Dict[str, Any] = field(default_factory=dict)

    # KIND/API_VERSION are instance-derived for unstructured objects
    @property  # type: ignore[override]
    def KIND(self) -> str:  # noqa: N802 - mirrors the TypedObject contract
        return self.manifest.get("kind", "")

    @property  # type: ignore[override]
    def API_VERSION(self) -> str:  # noqa: N802
        return self.manifest.get("apiVersion", "")

    @staticmethod
    def from_manifest(manifest: Dict[str, Any]) -> "Unstructured":
        manifest = copy.deepcopy(manifest)
        md = manifest.setdefault("metadata", {})
        meta = ObjectMeta(
            name=md.get("name", ""),
            namespace=md.get("namespace", ""),
            labels=dict(md.get("labels", {})),
            annotations=dict(md.get("annotations", {})),
        )
        return Unstructured(metadata=meta, manifest=manifest)

    def to_manifest(self) -> Dict[str, Any]:
        """Manifest with metadata synced back from ObjectMeta."""
        m = copy.deepcopy(self.manifest)
        md = m.setdefault("metadata", {})
        md["name"] = self.metadata.name
        if self.metadata.namespace:
            md["namespace"] = self.metadata.namespace
        if self.metadata.labels:
            md["labels"] = dict(self.metadata.labels)
        if self.metadata.annotations:
            md["annotations"] = dict(self.metadata.annotations)
        if self.metadata.uid:
            md["uid"] = self.metadata.uid
        if self.metadata.resource_version:
            md["resourceVersion"] = self.metadata.resource_version
        return m

    def spec(self) -> Dict[str, Any]:
        return self.manifest.setdefault("spec", {})

    def status(self) -> Optional[Dict[str, Any]]:
        return self.manifest.get("status")

    def spec_view(self) -> Dict[str, Any]:
        """Generation-relevant content: the manifest sans status (the store
        bumps metadata.generation only when this changes)."""
        return {k: v for k, v in self.manifest.items() if k != "status"}
