"""Certificate plumbing types (agent bootstrap + rotation).

Reference: pull-mode agents bootstrap kubeadm-style — they post a
CertificateSigningRequest which karmada auto-approves
(pkg/controllers/certificate/agent_csr_approving.go:59), and the rotation
controller renews credentials before expiry
(pkg/controllers/certificate/cert_rotation_controller.go:89).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from karmada_tpu.models.meta import ObjectMeta, TypedObject

AGENT_SIGNER = "karmada.io/agent"
AGENT_USER_PREFIX = "system:karmada:agent:"


@dataclass
class CertificateSigningRequestSpec:
    signer_name: str = AGENT_SIGNER
    username: str = ""  # system:karmada:agent:<cluster>
    cluster: str = ""
    ttl_seconds: int = 30 * 24 * 3600


@dataclass
class CertificateSigningRequestStatus:
    approved: bool = False
    denied_reason: str = ""
    # the "certificate": issue + expiry timestamps (the simulator's stand-in
    # for x509 NotBefore/NotAfter)
    issued_at: Optional[float] = None
    expires_at: Optional[float] = None


@dataclass
class CertificateSigningRequest(TypedObject):
    KIND = "CertificateSigningRequest"
    API_VERSION = "certificates.karmada.io/v1alpha1"

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: CertificateSigningRequestSpec = field(
        default_factory=CertificateSigningRequestSpec
    )
    status: CertificateSigningRequestStatus = field(
        default_factory=CertificateSigningRequestStatus
    )


@dataclass
class ClusterCredentialStatus:
    issued_at: Optional[float] = None
    expires_at: Optional[float] = None
    rotations: int = 0


@dataclass
class ClusterCredential(TypedObject):
    """The live credential a cluster connection uses (the reference keeps
    these in Secrets; typed here so expiry is first-class)."""

    KIND = "ClusterCredential"
    API_VERSION = "certificates.karmada.io/v1alpha1"

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    status: ClusterCredentialStatus = field(default_factory=ClusterCredentialStatus)
