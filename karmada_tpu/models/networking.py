"""networking.karmada.io + mcs.k8s.io API types.

Reference: pkg/apis/networking/v1alpha1 (MultiClusterService,
MultiClusterIngress) and the upstream MCS API kinds karmada consumes
(ServiceExport / ServiceImport, sigs.k8s.io/mcs-api).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List

from karmada_tpu.models.meta import Condition, ObjectMeta, TypedObject

# MultiClusterService exposure types (service_types.go)
EXPOSURE_CROSS_CLUSTER = "CrossCluster"
EXPOSURE_LOAD_BALANCER = "LoadBalancer"


@dataclass
class ExposureRange:
    cluster_names: List[str] = field(default_factory=list)


@dataclass
class MultiClusterServiceSpec:
    types: List[str] = field(default_factory=lambda: [EXPOSURE_CROSS_CLUSTER])
    ports: List[dict] = field(default_factory=list)
    provider_clusters: List[ExposureRange] = field(default_factory=list)
    consumer_clusters: List[ExposureRange] = field(default_factory=list)


@dataclass
class MultiClusterServiceStatus:
    conditions: List[Condition] = field(default_factory=list)


@dataclass
class MultiClusterService(TypedObject):
    KIND = "MultiClusterService"
    API_VERSION = "networking.karmada.io/v1alpha1"

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: MultiClusterServiceSpec = field(default_factory=MultiClusterServiceSpec)
    status: MultiClusterServiceStatus = field(
        default_factory=MultiClusterServiceStatus
    )

    def provider_names(self) -> List[str]:
        return [n for r in self.spec.provider_clusters for n in r.cluster_names]

    def consumer_names(self) -> List[str]:
        return [n for r in self.spec.consumer_clusters for n in r.cluster_names]


@dataclass
class MultiClusterIngressSpec:
    rules: List[dict] = field(default_factory=list)
    default_backend: dict = field(default_factory=dict)


@dataclass
class MultiClusterIngress(TypedObject):
    KIND = "MultiClusterIngress"
    API_VERSION = "networking.karmada.io/v1alpha1"

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: MultiClusterIngressSpec = field(default_factory=MultiClusterIngressSpec)


# -- mcs.k8s.io (ServiceExport / ServiceImport) ------------------------------


@dataclass
class ServiceExport(TypedObject):
    KIND = "ServiceExport"
    API_VERSION = "multicluster.x-k8s.io/v1alpha1"

    metadata: ObjectMeta = field(default_factory=ObjectMeta)


@dataclass
class ServiceImportSpec:
    type: str = "ClusterSetIP"
    ports: List[dict] = field(default_factory=list)


@dataclass
class ServiceImport(TypedObject):
    KIND = "ServiceImport"
    API_VERSION = "multicluster.x-k8s.io/v1alpha1"

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ServiceImportSpec = field(default_factory=ServiceImportSpec)
