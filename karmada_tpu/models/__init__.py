"""L0 API data model.

Dataclass equivalents of the reference CRD types (SURVEY.md §2.2):
  meta     — ObjectMeta / conditions / label selectors
  cluster  — cluster.karmada.io/v1alpha1 (reference pkg/apis/cluster/v1alpha1/types.go)
  policy   — policy.karmada.io/v1alpha1 (propagation/override/quota/taint policies)
  work     — work.karmada.io/v1alpha1+v1alpha2 (ResourceBinding, Work)
  workload — plain workload templates (Deployment-like) used by the interpreter
"""

from karmada_tpu.models.meta import (  # noqa: F401
    Condition,
    LabelSelector,
    ObjectMeta,
    TypedObject,
)
from karmada_tpu.models.cluster import (  # noqa: F401
    AllocatableModeling,
    Cluster,
    ClusterSpec,
    ClusterStatus,
    NodeSummary,
    ResourceModel,
    ResourceModelRange,
    ResourceSummary,
    Taint,
    EFFECT_NO_EXECUTE,
    EFFECT_NO_SCHEDULE,
)
from karmada_tpu.models.policy import (  # noqa: F401
    ClusterAffinity,
    ClusterAffinityTerm,
    OverridePolicy,
    Placement,
    PropagationPolicy,
    ReplicaSchedulingStrategy,
    ResourceSelector,
    SpreadConstraint,
    StaticClusterWeight,
    Toleration,
    SPREAD_BY_FIELD_CLUSTER,
    SPREAD_BY_FIELD_PROVIDER,
    SPREAD_BY_FIELD_REGION,
    SPREAD_BY_FIELD_ZONE,
)
from karmada_tpu.models.work import (  # noqa: F401
    AggregatedStatusItem,
    BindingSnapshot,
    GracefulEvictionTask,
    ObjectReference,
    ReplicaRequirements,
    ResourceBinding,
    ResourceBindingSpec,
    ResourceBindingStatus,
    TargetCluster,
    Work,
    WorkSpec,
    WorkStatus,
)
