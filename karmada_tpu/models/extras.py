"""Auxiliary CRD-style APIs: rebalancer, taint policy, remedy, quota.

Mirrors reference pkg/apis/{apps,policy,remedy}/v1alpha1:
WorkloadRebalancer (workloadrebalancer_types.go), ClusterTaintPolicy
(clustertaint_types.go), Remedy (remedy_types.go:29-39), and
FederatedResourceQuota (federatedresourcequota_types.go).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from karmada_tpu.models.meta import LabelSelector, ObjectMeta, TypedObject
from karmada_tpu.utils.quantity import Quantity


# -- WorkloadRebalancer (apps/v1alpha1) -------------------------------------


@dataclass
class ObjectReferenceSpec:
    api_version: str = ""
    kind: str = ""
    namespace: str = ""
    name: str = ""


@dataclass
class WorkloadRebalancerSpec:
    workloads: List[ObjectReferenceSpec] = field(default_factory=list)
    ttl_seconds_after_finished: Optional[int] = None


@dataclass
class ObservedWorkload:
    workload: ObjectReferenceSpec = field(default_factory=ObjectReferenceSpec)
    result: str = ""  # Successful | Failed | NotFound
    reason: str = ""


@dataclass
class WorkloadRebalancerStatus:
    observed_workloads: List[ObservedWorkload] = field(default_factory=list)
    finish_time: Optional[float] = None


@dataclass
class WorkloadRebalancer(TypedObject):
    KIND = "WorkloadRebalancer"
    API_VERSION = "apps.karmada.io/v1alpha1"

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: WorkloadRebalancerSpec = field(default_factory=WorkloadRebalancerSpec)
    status: WorkloadRebalancerStatus = field(default_factory=WorkloadRebalancerStatus)


# -- ClusterTaintPolicy (policy/v1alpha1) -----------------------------------


@dataclass
class MatchCondition:
    condition_type: str = ""
    operator: str = "In"  # In | NotIn
    status_values: List[str] = field(default_factory=list)


@dataclass
class TaintSpec:
    key: str = ""
    value: str = ""
    effect: str = "NoSchedule"


@dataclass
class ClusterTaintPolicySpec:
    target_clusters: Optional[object] = None  # ClusterAffinity or None (all)
    add_on_conditions: List[MatchCondition] = field(default_factory=list)
    remove_on_conditions: List[MatchCondition] = field(default_factory=list)
    taints: List[TaintSpec] = field(default_factory=list)


@dataclass
class ClusterTaintPolicy(TypedObject):
    KIND = "ClusterTaintPolicy"
    API_VERSION = "policy.karmada.io/v1alpha1"

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ClusterTaintPolicySpec = field(default_factory=ClusterTaintPolicySpec)


# -- Remedy (remedy/v1alpha1) -----------------------------------------------


@dataclass
class DecisionMatch:
    cluster_condition_type: str = ""
    cluster_condition_status: str = "True"


@dataclass
class RemedySpec:
    cluster_affinity: Optional[object] = None  # ClusterAffinity-ish (names)
    decision_matches: List[DecisionMatch] = field(default_factory=list)
    actions: List[str] = field(default_factory=list)  # e.g. TrafficControl


@dataclass
class Remedy(TypedObject):
    KIND = "Remedy"
    API_VERSION = "remedy.karmada.io/v1alpha1"

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: RemedySpec = field(default_factory=RemedySpec)


# -- FederatedResourceQuota (policy/v1alpha1) -------------------------------


@dataclass
class StaticClusterAssignment:
    cluster_name: str = ""
    hard: Dict[str, Quantity] = field(default_factory=dict)


@dataclass
class FederatedResourceQuotaSpec:
    overall: Dict[str, Quantity] = field(default_factory=dict)
    static_assignments: List[StaticClusterAssignment] = field(default_factory=list)


@dataclass
class ClusterQuotaStatus:
    cluster_name: str = ""
    hard: Dict[str, Quantity] = field(default_factory=dict)
    used: Dict[str, Quantity] = field(default_factory=dict)


@dataclass
class FederatedResourceQuotaStatus:
    overall: Dict[str, Quantity] = field(default_factory=dict)
    overall_used: Dict[str, Quantity] = field(default_factory=dict)
    aggregated_status: List[ClusterQuotaStatus] = field(default_factory=list)


@dataclass
class FederatedResourceQuota(TypedObject):
    KIND = "FederatedResourceQuota"
    API_VERSION = "policy.karmada.io/v1alpha1"

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: FederatedResourceQuotaSpec = field(default_factory=FederatedResourceQuotaSpec)
    status: FederatedResourceQuotaStatus = field(
        default_factory=FederatedResourceQuotaStatus
    )
