"""Cluster API types.

Mirrors reference pkg/apis/cluster/v1alpha1/types.go:43-420 — SyncMode
(:259-264), taints, provider/region/zone(s), ResourceModels (:207),
Status.ResourceSummary (:346, Allocatable/Allocating/Allocated +
AllocatableModelings) which is the capacity-tensor source for the TPU solver.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from karmada_tpu.models.meta import Condition, ObjectMeta, TypedObject, is_condition_true
from karmada_tpu.utils.quantity import Quantity

SYNC_MODE_PUSH = "Push"
SYNC_MODE_PULL = "Pull"

EFFECT_NO_SCHEDULE = "NoSchedule"
EFFECT_NO_EXECUTE = "NoExecute"
EFFECT_PREFER_NO_SCHEDULE = "PreferNoSchedule"

COND_CLUSTER_READY = "Ready"
COND_COMPLETE_API_ENABLEMENTS = "CompleteAPIEnablements"

API_ENABLED = "Enabled"
API_DISABLED = "Disabled"
API_UNKNOWN = "Unknown"


@dataclass
class Taint:
    key: str = ""
    value: str = ""
    effect: str = EFFECT_NO_SCHEDULE
    time_added: Optional[float] = None


@dataclass
class ResourceModelRange:
    """[min, max) range of one resource for a model grade (types.go:207+)."""

    name: str = ""
    min: Quantity = field(default_factory=lambda: Quantity(0))
    max: Quantity = field(default_factory=lambda: Quantity(0))


@dataclass
class ResourceModel:
    grade: int = 0
    ranges: List[ResourceModelRange] = field(default_factory=list)


@dataclass
class AllocatableModeling:
    grade: int = 0
    count: int = 0


@dataclass
class NodeSummary:
    total_num: int = 0
    ready_num: int = 0


@dataclass
class ResourceSummary:
    """Cluster-wide capacity: available = allocatable - allocated - allocating.

    Reference cluster/v1alpha1/types.go:346 + estimator math
    pkg/estimator/client/general.go:294-334.
    """

    allocatable: Dict[str, Quantity] = field(default_factory=dict)
    allocating: Dict[str, Quantity] = field(default_factory=dict)
    allocated: Dict[str, Quantity] = field(default_factory=dict)
    allocatable_modelings: List[AllocatableModeling] = field(default_factory=list)


@dataclass
class APIEnablement:
    group_version: str = ""
    resources: List[str] = field(default_factory=list)  # kinds


@dataclass
class ClusterSpec:
    sync_mode: str = SYNC_MODE_PUSH
    api_endpoint: str = ""
    provider: str = ""
    region: str = ""
    zone: str = ""  # deprecated singular (still read by region grouping)
    zones: List[str] = field(default_factory=list)
    taints: List[Taint] = field(default_factory=list)
    resource_models: List[ResourceModel] = field(default_factory=list)


@dataclass
class ClusterStatus:
    kubernetes_version: str = ""
    api_enablements: List[APIEnablement] = field(default_factory=list)
    conditions: List[Condition] = field(default_factory=list)
    node_summary: Optional[NodeSummary] = None
    resource_summary: Optional[ResourceSummary] = None
    remedy_actions: List[str] = field(default_factory=list)


@dataclass
class Cluster(TypedObject):
    KIND = "Cluster"
    API_VERSION = "cluster.karmada.io/v1alpha1"

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ClusterSpec = field(default_factory=ClusterSpec)
    status: ClusterStatus = field(default_factory=ClusterStatus)

    def api_enablement(self, api_version: str, kind: str) -> str:
        """Whether this cluster serves the given API
        (cluster_helper.go:46-67): Disabled is only certain when the
        CompleteAPIEnablements condition holds; otherwise Unknown."""
        for e in self.status.api_enablements:
            if e.group_version == api_version and kind in e.resources:
                return API_ENABLED
        if is_condition_true(self.status.conditions, COND_COMPLETE_API_ENABLEMENTS):
            return API_DISABLED
        return API_UNKNOWN

    @property
    def ready(self) -> bool:
        return is_condition_true(self.status.conditions, COND_CLUSTER_READY)

    def zones_effective(self) -> List[str]:
        """Zones for spread grouping; falls back to the singular field."""
        if self.spec.zones:
            return self.spec.zones
        return [self.spec.zone] if self.spec.zone else []
