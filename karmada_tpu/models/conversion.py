"""Multi-version API conversion registry (CRD conversion-webhook parity).

The reference serves several versions per API group and converts between
them through the webhook's `/convert` endpoint
(/root/reference/cmd/webhook/app/webhook.go:186-232 wires
ConversionReview handling; pkg/apis/work carries the v1alpha1/v1alpha2
pair).  Evolving a live control plane's schema without rewriting stored
objects is the capability; the machinery here is the k8s hub-and-spoke
model made explicit:

- every kind's dataclass in models/ IS the hub (storage) version — the
  store holds exactly one representation, like etcd's storage version;
- additional *served* versions register manifest-level up/down converters
  (conversions are renames/moves of unstructured fields, exactly what a
  CRD conversion webhook sees — it converts unstructured objects, not
  typed ones);
- ingress (codec.from_manifest_typed) converts served -> storage before
  decoding; egress (codec.to_manifest_typed(version=...)) converts
  storage -> served after encoding.  Reads and watches can therefore ask
  for any served version while the store round-trips one schema.

Served today: work.karmada.io/v1alpha1 `Work` is also served at
work.karmada.io/v1alpha2, where `spec.suspendDispatching` is renamed to
`spec.suspend` (the field-rename class of schema evolution).

DELIBERATE DIVERGENCE from the reference API surface: in the reference,
the work.karmada.io/v1alpha2 group contains only the binding kinds —
`Work` exists solely at v1alpha1 (with spec.suspendDispatching) and was
never re-served.  The synthetic Work v1alpha2 here is kept ON PURPOSE as
the living exercise of the field-RENAME conversion class (the binding
v1alpha1 pair below exercises the structural-MOVE class); /apis discovery
therefore advertises one served version the upstream surface does not
have.  Clients comparing discovery output against upstream should ignore
Work@v1alpha2; everything else matches.
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Dict, List, Optional, Tuple

Manifest = Dict[str, Any]
Converter = Callable[[Manifest], Manifest]


class ConversionRegistry:
    """(kind, served_version) -> up/down converters to the storage version."""

    def __init__(self) -> None:
        # (kind, version) -> (to_storage, from_storage)
        self._by_version: Dict[Tuple[str, str], Tuple[Converter, Converter]] = {}

    def register(self, kind: str, version: str,
                 to_storage: Converter, from_storage: Converter) -> None:
        self._by_version[(kind, version)] = (to_storage, from_storage)

    def served(self, kind: str, version: str) -> bool:
        if self._by_version.get((kind, version)) is not None:
            return True
        from karmada_tpu.models.codec import model_registry

        cls = model_registry().get(kind)
        return cls is not None and cls.API_VERSION == version

    def served_versions(self, kind: str) -> List[str]:
        from karmada_tpu.models.codec import model_registry

        out = []
        cls = model_registry().get(kind)
        if cls is not None:
            out.append(cls.API_VERSION)
        out.extend(v for (k, v) in self._by_version if k == kind)
        return out

    def storage_version(self, kind: str) -> Optional[str]:
        from karmada_tpu.models.codec import model_registry

        cls = model_registry().get(kind)
        return cls.API_VERSION if cls is not None else None

    def to_storage(self, manifest: Manifest) -> Manifest:
        """Convert a served-version manifest up to the storage version."""
        kind = manifest.get("kind", "")
        version = manifest.get("apiVersion", "")
        if version == self.storage_version(kind):
            return manifest
        pair = self._by_version.get((kind, version))
        if pair is None:
            raise KeyError(f"{kind} has no served version {version!r}")
        out = pair[0](copy.deepcopy(manifest))
        out["apiVersion"] = self.storage_version(kind)
        return out

    def convert(self, manifest: Manifest, target_version: str) -> Manifest:
        """The /convert verb: any served version -> any served version,
        always routed through the storage hub (spoke-to-spoke conversions
        compose the two halves — no N^2 converter matrix)."""
        kind = manifest.get("kind", "")
        if manifest.get("apiVersion") == target_version:
            return manifest
        hub = self.to_storage(manifest)
        if target_version == self.storage_version(kind):
            return hub
        pair = self._by_version.get((kind, target_version))
        if pair is None:
            raise KeyError(f"{kind} has no served version {target_version!r}")
        out = pair[1](copy.deepcopy(hub))
        out["apiVersion"] = target_version
        return out


REGISTRY = ConversionRegistry()


def _rename(spec: Manifest, old: str, new: str) -> None:
    if old in spec:
        spec[new] = spec.pop(old)


def _work_v1alpha2_to_storage(m: Manifest) -> Manifest:
    _rename(m.get("spec") or {}, "suspend", "suspendDispatching")
    return m


def _work_storage_to_v1alpha2(m: Manifest) -> Manifest:
    _rename(m.get("spec") or {}, "suspendDispatching", "suspend")
    return m


WORK_V1ALPHA2 = "work.karmada.io/v1alpha2"

# Synthetic served version — a deliberate divergence from the reference,
# where Work is v1alpha1-only; see the module docstring before matching
# /apis discovery against the upstream surface.
REGISTRY.register("Work", WORK_V1ALPHA2,
                  _work_v1alpha2_to_storage, _work_storage_to_v1alpha2)


# -- ResourceBinding / ClusterResourceBinding at work/v1alpha1 ---------------
# The reference's REAL legacy pair: bindings began life at v1alpha1 where
# per-replica demand and the replica count lived INSIDE spec.resource
# (ObjectReference.ReplicaResourceRequirements / .Replicas); the v1alpha2
# hub hoisted them to spec.replicaRequirements.resourceRequest and
# spec.replicas (/root/reference/pkg/apis/work/v1alpha1/
# binding_types_conversion.go:77-128).  These converters perform the same
# structural MOVES; the down-convert keeps only the fields v1alpha1
# carries (resource + clusters in spec, conditions + the four
# aggregatedStatus scalars in status), exactly like ConvertBindingSpec/
# StatusFromHub — an old served version is inherently lossy about newer
# spec machinery (placement, eviction tasks, components).

BINDING_V1ALPHA1 = "work.karmada.io/v1alpha1"


def _binding_v1alpha1_to_storage(m: Manifest) -> Manifest:
    spec = m.get("spec") or {}
    res = spec.get("resource") or {}
    if "replicaResourceRequirements" in res:
        spec.setdefault("replicaRequirements", {})["resourceRequest"] = (
            res.pop("replicaResourceRequirements"))
    if "replicas" in res:
        spec["replicas"] = res.pop("replicas")
    return m


def _binding_storage_to_v1alpha1(m: Manifest) -> Manifest:
    spec = m.get("spec") or {}
    # only the five ObjectReference fields v1alpha1 defines survive
    # (ConvertBindingSpecFromHub copies exactly these; hub-only fields
    # like uid have no v1alpha1 home and must not leak into the old
    # schema — CRD pruning there would reject them)
    res = {k: v for k, v in (spec.get("resource") or {}).items()
           if k in ("apiVersion", "kind", "namespace", "name",
                    "resourceVersion")}
    rr = spec.get("replicaRequirements") or {}
    if "resourceRequest" in rr:  # membership: {} must round-trip as {}
        res["replicaResourceRequirements"] = rr["resourceRequest"]
    if "replicas" in spec:
        res["replicas"] = spec["replicas"]
    out_spec: Manifest = {"resource": res}
    if "clusters" in spec:
        out_spec["clusters"] = spec["clusters"]
    m["spec"] = out_spec
    status = m.get("status") or {}
    out_status: Manifest = {}
    if "conditions" in status:
        out_status["conditions"] = status["conditions"]
    if "aggregatedStatus" in status:
        out_status["aggregatedStatus"] = [
            {k: v for k, v in item.items()
             if k in ("clusterName", "status", "applied", "appliedMessage")}
            for item in status["aggregatedStatus"]
        ]
    if out_status:
        m["status"] = out_status
    elif "status" in m:
        del m["status"]
    return m


for _kind in ("ResourceBinding", "ClusterResourceBinding"):
    REGISTRY.register(_kind, BINDING_V1ALPHA1,
                      _binding_v1alpha1_to_storage,
                      _binding_storage_to_v1alpha1)
