"""Policy API types: PropagationPolicy / OverridePolicy and Placement.

Mirrors reference pkg/apis/policy/v1alpha1/propagation_types.go:
Placement (:470) = ClusterAffinity (:567) / ClusterAffinities (:590) /
ClusterTolerations / SpreadConstraints (:538) / ReplicaScheduling (:624),
plus cluster-affinity matching semantics from pkg/util/selector.go:96-205.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from karmada_tpu.models.cluster import Cluster
from karmada_tpu.models.meta import LabelSelector, ObjectMeta, TypedObject

# Spread constraint fields (propagation_types.go:538)
SPREAD_BY_FIELD_CLUSTER = "cluster"
SPREAD_BY_FIELD_REGION = "region"
SPREAD_BY_FIELD_ZONE = "zone"
SPREAD_BY_FIELD_PROVIDER = "provider"

# Replica scheduling (propagation_types.go:624)
REPLICA_SCHEDULING_DUPLICATED = "Duplicated"
REPLICA_SCHEDULING_DIVIDED = "Divided"
REPLICA_DIVISION_AGGREGATED = "Aggregated"
REPLICA_DIVISION_WEIGHTED = "Weighted"
DYNAMIC_WEIGHT_AVAILABLE_REPLICAS = "AvailableReplicas"

# Conflict resolution for member-cluster apply
CONFLICT_OVERWRITE = "Overwrite"
CONFLICT_ABORT = "Abort"

# ActivationPreference
LAZY_ACTIVATION = "Lazy"

# Cluster field-selector keys (pkg/util/selector.go)
PROVIDER_FIELD = "provider"
REGION_FIELD = "region"
ZONE_FIELD = "zone"


@dataclass
class ResourceSelector:
    """Which template objects a policy claims (propagation_types.go:69+)."""

    api_version: str = ""
    kind: str = ""
    namespace: str = ""
    name: str = ""
    label_selector: Optional[LabelSelector] = None


@dataclass
class FieldSelectorRequirement:
    key: str = ""  # provider | region | zone
    operator: str = "In"  # In | NotIn | Exists | DoesNotExist
    values: List[str] = field(default_factory=list)


@dataclass
class FieldSelector:
    match_expressions: List[FieldSelectorRequirement] = field(default_factory=list)


@dataclass
class ClusterAffinity:
    label_selector: Optional[LabelSelector] = None
    field_selector: Optional[FieldSelector] = None
    cluster_names: List[str] = field(default_factory=list)
    exclude_clusters: List[str] = field(default_factory=list)

    def matches(self, cluster: Cluster) -> bool:
        """Port of pkg/util/selector.go:96 ClusterMatches."""
        if cluster.name in self.exclude_clusters:
            return False
        if self.label_selector is not None and not self.label_selector.matches(
            cluster.metadata.labels
        ):
            return False
        if self.field_selector is not None:
            fields = {}
            if cluster.spec.provider:
                fields[PROVIDER_FIELD] = cluster.spec.provider
            if cluster.spec.region:
                fields[REGION_FIELD] = cluster.spec.region
            for req in self.field_selector.match_expressions:
                if req.key == ZONE_FIELD:
                    if not _match_zones(req, cluster.spec.zones):
                        return False
                    continue
                if not _match_field(req, fields.get(req.key)):
                    return False
        if self.cluster_names and cluster.name not in self.cluster_names:
            return False
        return True


def _match_zones(req: FieldSelectorRequirement, zones: List[str]) -> bool:
    """Port of pkg/util/selector.go:214 matchZones (In requires subset)."""
    if req.operator == "In":
        return bool(zones) and all(z in req.values for z in zones)
    if req.operator == "NotIn":
        return all(z not in req.values for z in zones)
    if req.operator == "Exists":
        return bool(zones)
    if req.operator == "DoesNotExist":
        return not zones
    return False


def _match_field(req: FieldSelectorRequirement, value: Optional[str]) -> bool:
    if req.operator == "In":
        return value is not None and value in req.values
    if req.operator == "NotIn":
        return value is None or value not in req.values
    if req.operator == "Exists":
        return value is not None
    if req.operator == "DoesNotExist":
        return value is None
    return False


@dataclass
class ClusterAffinityTerm:
    affinity_name: str = ""
    affinity: ClusterAffinity = field(default_factory=ClusterAffinity)


@dataclass
class Toleration:
    """Cluster-taint toleration (mirrors corev1.Toleration semantics)."""

    key: str = ""
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""  # empty tolerates all effects
    toleration_seconds: Optional[int] = None

    def tolerates(self, taint) -> bool:
        if self.effect and self.effect != taint.effect:
            return False
        if self.operator == "Exists":
            return self.key == "" or self.key == taint.key
        # Equal: empty key with Equal means "match all keys AND values"? k8s:
        # empty key requires operator Exists; mirror k8s ToleratesTaint:
        return self.key == taint.key and self.value == taint.value


@dataclass
class SpreadConstraint:
    spread_by_field: str = ""  # cluster|region|zone|provider
    spread_by_label: str = ""
    min_groups: int = 0
    max_groups: int = 0


@dataclass
class StaticClusterWeight:
    target_cluster: ClusterAffinity = field(default_factory=ClusterAffinity)
    weight: int = 0


@dataclass
class ClusterPreferences:
    static_weight_list: List[StaticClusterWeight] = field(default_factory=list)
    dynamic_weight: str = ""  # "" or AvailableReplicas


@dataclass
class ReplicaSchedulingStrategy:
    replica_scheduling_type: str = REPLICA_SCHEDULING_DUPLICATED
    replica_division_preference: str = ""  # Aggregated | Weighted
    weight_preference: Optional[ClusterPreferences] = None


@dataclass
class Placement:
    cluster_affinity: Optional[ClusterAffinity] = None
    cluster_affinities: List[ClusterAffinityTerm] = field(default_factory=list)
    cluster_tolerations: List[Toleration] = field(default_factory=list)
    spread_constraints: List[SpreadConstraint] = field(default_factory=list)
    replica_scheduling: Optional[ReplicaSchedulingStrategy] = None

    def replica_scheduling_type(self) -> str:
        """Defaulting mirror of Placement.ReplicaSchedulingType()."""
        if self.replica_scheduling is None:
            return REPLICA_SCHEDULING_DUPLICATED
        return self.replica_scheduling.replica_scheduling_type or REPLICA_SCHEDULING_DUPLICATED


@dataclass
class StatePreservationRule:
    """One state-preservation extraction rule (propagation_types.go:385-420
    StatePreservation.Rules): pull `json_path` out of the failed cluster's
    collected status and re-inject it as label `alias_label_name` on the
    replacement cluster's rendered workload."""

    alias_label_name: str = ""
    json_path: str = ""


@dataclass
class FailoverBehavior:
    # application failover
    toleration_seconds: int = 300
    decision_conditions_toleration_seconds: Optional[int] = None
    purge_mode: str = "Graciously"  # Immediately | Graciously | Never
    grace_period_seconds: Optional[int] = None
    # StatefulFailoverInjection (alpha, gated): state data preserved across
    # failover events (propagation_types.go StatePreservation)
    state_preservation: List[StatePreservationRule] = field(default_factory=list)


@dataclass
class PropagationSpec:
    resource_selectors: List[ResourceSelector] = field(default_factory=list)
    placement: Placement = field(default_factory=Placement)
    propagate_deps: bool = False
    priority: int = 0
    preemption: str = "Never"  # Always | Never
    schedule_priority: Optional[int] = None
    activation_preference: str = ""  # "" | Lazy
    failover: Optional[FailoverBehavior] = None
    conflict_resolution: str = CONFLICT_ABORT
    suspension: Optional["Suspension"] = None


@dataclass
class Suspension:
    dispatching: bool = False
    scheduling: bool = False


@dataclass
class PropagationPolicy(TypedObject):
    KIND = "PropagationPolicy"
    API_VERSION = "policy.karmada.io/v1alpha1"

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PropagationSpec = field(default_factory=PropagationSpec)

    @property
    def cluster_scoped(self) -> bool:
        return not self.metadata.namespace


@dataclass
class ClusterPropagationPolicy(PropagationPolicy):
    KIND = "ClusterPropagationPolicy"

    @property
    def cluster_scoped(self) -> bool:
        return True


# ---------------------------------------------------------------------------
# Override policies (override_types.go) — JSON-patch style per-cluster edits
# ---------------------------------------------------------------------------


@dataclass
class PlaintextOverrider:
    path: str = ""  # dotted path into the manifest
    operator: str = "replace"  # add | remove | replace
    value: Any = None


@dataclass
class ImageOverrider:
    component: str = "Registry"  # Registry | Repository | Tag
    operator: str = "replace"  # add | remove | replace
    value: str = ""


@dataclass
class CommandArgsOverrider:
    container_name: str = ""
    operator: str = "add"  # add | remove
    value: List[str] = field(default_factory=list)


@dataclass
class LabelAnnotationOverrider:
    operator: str = "add"  # add | remove | replace
    value: Dict[str, str] = field(default_factory=dict)


@dataclass
class Overriders:
    plaintext: List[PlaintextOverrider] = field(default_factory=list)
    image_overrider: List[ImageOverrider] = field(default_factory=list)
    command_overrider: List[CommandArgsOverrider] = field(default_factory=list)
    args_overrider: List[CommandArgsOverrider] = field(default_factory=list)
    labels_overrider: List[LabelAnnotationOverrider] = field(default_factory=list)
    annotations_overrider: List[LabelAnnotationOverrider] = field(default_factory=list)


@dataclass
class RuleWithCluster:
    target_cluster: Optional[ClusterAffinity] = None
    overriders: Overriders = field(default_factory=Overriders)


@dataclass
class OverrideSpec:
    resource_selectors: List[ResourceSelector] = field(default_factory=list)
    override_rules: List[RuleWithCluster] = field(default_factory=list)


@dataclass
class OverridePolicy(TypedObject):
    KIND = "OverridePolicy"
    API_VERSION = "policy.karmada.io/v1alpha1"

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: OverrideSpec = field(default_factory=OverrideSpec)


@dataclass
class ClusterOverridePolicy(OverridePolicy):
    KIND = "ClusterOverridePolicy"
