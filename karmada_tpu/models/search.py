"""search.karmada.io API types (reference pkg/apis/search).

ResourceRegistry (searchregistry_types.go) selects which resources to cache
from which member clusters; the multi-cluster cache (search/cache.py) is
driven by these objects exactly like the reference's registry controller
(pkg/search/controller.go:79-248) builds per-cluster informers.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List

from karmada_tpu.models.meta import ObjectMeta, TypedObject
from karmada_tpu.models.policy import ClusterAffinity


@dataclass
class ResourceRegistrySelector:
    """One (apiVersion, kind) the registry caches."""

    api_version: str = ""
    kind: str = ""


@dataclass
class BackendStoreConfig:
    """Optional external sink (the reference supports OpenSearch); the
    in-tree default store is the in-memory cache itself."""

    kind: str = "Default"  # Default | OpenSearch (external; not bundled)
    addresses: List[str] = field(default_factory=list)


@dataclass
class ResourceRegistrySpec:
    target_cluster: ClusterAffinity = field(default_factory=ClusterAffinity)
    resource_selectors: List[ResourceRegistrySelector] = field(default_factory=list)
    backend_store: BackendStoreConfig = field(default_factory=BackendStoreConfig)


@dataclass
class ResourceRegistryStatus:
    conditions: List = field(default_factory=list)


@dataclass
class ResourceRegistry(TypedObject):
    KIND = "ResourceRegistry"
    API_VERSION = "search.karmada.io/v1alpha1"

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ResourceRegistrySpec = field(default_factory=ResourceRegistrySpec)
    status: ResourceRegistryStatus = field(default_factory=ResourceRegistryStatus)
