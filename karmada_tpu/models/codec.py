"""Manifest <-> typed-model codec.

The reference's client machinery decodes YAML/JSON manifests into typed Go
structs via generated deepcopy/scheme code; here one generic loader walks
the dataclass tree instead (no generated code): camelCase manifest keys map
to snake_case fields, nested dataclasses / lists / dicts / Optionals
recurse, and `Quantity` values parse from their k8s string forms.

Used by karmadactl apply/create/edit (a `PropagationPolicy` YAML becomes a
real models.policy.PropagationPolicy, so admission mutators/validators and
controllers see typed objects) and usable by any API ingress.
"""

from __future__ import annotations

import dataclasses
import re
import typing
from typing import Any, Dict, Optional

from karmada_tpu.utils.quantity import Quantity


def model_registry() -> Dict[str, type]:
    """kind -> dataclass for every registered API type."""
    from karmada_tpu.models import (autoscaling, certs, cluster, config,
                                    extras, networking, policy, search, work)

    out: Dict[str, type] = {}
    for mod in (cluster, policy, work, config, extras,
                autoscaling, networking, search, certs):
        for obj in vars(mod).values():
            kind = getattr(obj, "KIND", None)
            if dataclasses.is_dataclass(obj) and isinstance(kind, str) and kind:
                out[kind] = obj
    return out


_SNAKE_RE = re.compile(r"(?<!^)(?=[A-Z])")


def _snake(key: str) -> str:
    return _SNAKE_RE.sub("_", key).lower()


def _load_value(tp, value):
    """Coerce a manifest value into the annotated type."""
    if value is None:
        return None
    origin = typing.get_origin(tp)
    if origin is typing.Union:  # Optional[X] and friends
        for arg in typing.get_args(tp):
            if arg is type(None):
                continue
            return _load_value(arg, value)
        return value
    if origin in (list, typing.List):
        (item_tp,) = typing.get_args(tp) or (Any,)
        return [_load_value(item_tp, v) for v in value]
    if origin in (dict, typing.Dict):
        args = typing.get_args(tp)
        val_tp = args[1] if len(args) == 2 else Any
        return {k: _load_value(val_tp, v) for k, v in dict(value).items()}
    if tp is Quantity or (isinstance(tp, type) and issubclass(tp, Quantity)):
        if isinstance(value, Quantity):
            return value
        return Quantity.parse(str(value))
    if dataclasses.is_dataclass(tp):
        return _load_dataclass(tp, value)
    if tp is float and isinstance(value, (int, float)):
        return float(value)
    if tp is int and isinstance(value, str) and value.isdigit():
        return int(value)
    return value


def _load_dataclass(cls, data: Dict[str, Any]):
    if not isinstance(data, dict):
        return data
    hints = typing.get_type_hints(cls)
    fields = {f.name: f for f in dataclasses.fields(cls)}
    kwargs = {}
    for key, value in data.items():
        # no special-casing of the manifest envelope's apiVersion/kind:
        # root models carry them as ClassVars (not fields), so the
        # unknown-key skip below drops them — while NESTED dataclasses
        # (ObjectReference, ResourceSelector) legitimately have
        # api_version/kind as DATA fields and must receive them
        name = key if key in fields else _snake(key)
        if name not in fields:
            continue  # forward-compat: unknown manifest keys are ignored
        kwargs[name] = _load_value(hints.get(name, Any), value)
    return cls(**kwargs)


def from_manifest_typed(manifest: Dict[str, Any]):
    """Decode a manifest into its registered typed model, or None when the
    kind is not a registered API type (callers fall back to Unstructured).

    A manifest arriving at a registered SERVED (non-storage) version is
    converted up to the storage version first (models/conversion.py) — the
    decode half of the reference's CRD conversion webhook."""
    kind = manifest.get("kind")
    cls = model_registry().get(kind)
    if cls is None:
        return None
    api_version = manifest.get("apiVersion")
    if api_version and api_version != cls.API_VERSION:
        from karmada_tpu.models.conversion import REGISTRY as conv

        if not conv.served(kind, api_version):
            # rejecting beats silently decoding version-specific fields
            # into nothing (a v9 manifest's renamed field would vanish)
            raise ValueError(
                f"{kind} is not served at apiVersion {api_version!r}; "
                f"served: {conv.served_versions(kind)}")
        manifest = conv.to_storage(manifest)
    return _load_dataclass(cls, manifest)


def registered_kind(kind: Optional[str]) -> bool:
    return kind in model_registry() if kind else False


def _camel(name: str) -> str:
    head, *rest = name.split("_")
    return head + "".join(p[:1].upper() + p[1:] for p in rest)


def _dump_value(value):
    if isinstance(value, Quantity):  # a dataclass too: must win this check
        return str(value)
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        out = {}
        for f in dataclasses.fields(value):
            v = getattr(value, f.name)
            # lean manifests: omit fields still at their default (the
            # loader refills them), keep everything the user set
            if f.default is not dataclasses.MISSING and v == f.default:
                continue
            if (f.default_factory is not dataclasses.MISSING  # type: ignore[misc]
                    and v == f.default_factory()):  # type: ignore[misc]
                continue
            out[_camel(f.name)] = _dump_value(v)
        return out
    if isinstance(value, list):
        return [_dump_value(v) for v in value]
    if isinstance(value, dict):
        # mapping KEYS are data (resource names, label keys): never cameled
        return {k: _dump_value(v) for k, v in value.items()}
    return value


def to_manifest_typed(obj, version: Optional[str] = None) -> Dict[str, Any]:
    """Encode a typed model back into a camelCase manifest (inverse of
    from_manifest_typed; field defaults are omitted).  `version` re-encodes
    at a registered served version via models/conversion.py — the encode
    half of the reference's CRD conversion webhook."""
    manifest = {"apiVersion": type(obj).API_VERSION, "kind": type(obj).KIND}
    manifest.update(_dump_value(obj))
    if version and version != type(obj).API_VERSION:
        from karmada_tpu.models.conversion import REGISTRY as conv

        manifest = conv.convert(manifest, version)
    return manifest
