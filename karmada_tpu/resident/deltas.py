"""Watch-event ingestion for the resident-state plane.

The reference control plane is informer-driven: components receive
ADDED/MODIFIED/DELETED deltas, never snapshots (PAPER.md L3).  The
resident plane mirrors that on device — but it must know which KIND of
change each cluster event carries, because the update cost differs by
orders of magnitude:

  capacity    status-only churn (ResourceSummary, deletion timestamp):
              scatter-update the churned cluster's capacity lanes and
              estimator-override column in place — the steady-state path.
  api         status.api_enablements changed: recompute that cluster's
              api_ok column for every resident GVK (cheap, O(G)).
  structural  membership changed (ADDED/DELETED), or spec / labels
              changed: cluster lanes, name ranks, placement-predicate
              columns, routes and region vocabulary may all shift — the
              resident plane falls back losslessly to a full re-encode
              (karmada_tpu/resident/state.py::_reset).

Events are coalesced per cluster per cycle (the strongest class wins),
exactly like an informer's per-key delta compression: a cluster that
flapped five times between cycles is applied once.  Binding events are
tracked only for row-cache hygiene (DELETED prunes the cached row; the
row cache's own (key, resourceVersion) tokens handle invalidation).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from karmada_tpu.models.cluster import Cluster
from karmada_tpu.models.work import ResourceBinding
from karmada_tpu.store.store import DELETED, Event

# coalescing order: a stronger class absorbs a weaker one for the same
# cluster within one cycle's window
CAPACITY = "capacity"
API = "api"
STRUCTURAL = "structural"
_RANK = {CAPACITY: 0, API: 1, STRUCTURAL: 2}


@dataclass
class CycleDeltas:
    """One cycle's coalesced delta set (DeltaTracker.drain)."""

    structural: bool = False
    structural_reason: str = ""
    # cluster name -> strongest observed class (capacity | api); clusters
    # classified structural are folded into the `structural` flag instead
    # (the whole plane rebuilds, per-lane detail is moot)
    clusters: Dict[str, str] = field(default_factory=dict)
    binding_events: int = 0
    bindings_deleted: List[Tuple[str, str]] = field(default_factory=list)
    # ADDED/MODIFIED binding keys seen this window — the incremental
    # dirty-set plane (scheduler/incremental.py) seeds its rv-churn mask
    # from these instead of sweeping a million row tokens per cycle
    bindings_touched: List[Tuple[str, str]] = field(default_factory=list)

    def empty(self) -> bool:
        return (not self.structural and not self.clusters
                and not self.bindings_deleted and not self.bindings_touched)


def classify_change(old: Cluster, new: Cluster) -> Tuple[str, str]:
    """(class, reason) for one observed cluster old->new transition.
    Shared by the event path below and the resident plane's per-cycle
    resourceVersion sweep (state.py), so both classify identically."""
    if new.spec != old.spec:
        # taints, region, provider, zone: placement predicates, name-rank
        # neighbors and the region vocabulary can all move
        return STRUCTURAL, "cluster-spec"
    if new.metadata.labels != old.metadata.labels:
        # labels drive placement label selectors and spread-by-label axes
        return STRUCTURAL, "cluster-labels"
    if new.status.api_enablements != old.status.api_enablements:
        return API, "api-enablement"
    return CAPACITY, "status"


def classify_cluster_event(event: Event) -> Tuple[str, str]:
    """(class, reason) for one Cluster event — see module docstring."""
    if event.type == DELETED or event.old is None:
        return STRUCTURAL, "membership"
    return classify_change(event.old, event.obj)


class DeltaTracker:
    """Subscribes to the store's watch bus and coalesces events per
    scheduling cycle.  drain() hands the accumulated set to the resident
    plane and resets the window; thread-safe (publisher threads write,
    the scheduler's device-cycle thread drains)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # guarded-by: _lock
        self._clusters: Dict[str, str] = {}
        # guarded-by: _lock
        self._structural: Optional[str] = None
        # guarded-by: _lock
        self._binding_events = 0
        # guarded-by: _lock
        self._bindings_deleted: List[Tuple[str, str]] = []
        # guarded-by: _lock
        self._bindings_touched: List[Tuple[str, str]] = []

    def on_event(self, event: Event) -> None:
        kind = event.kind
        if kind == Cluster.KIND:
            cls, reason = classify_cluster_event(event)
            with self._lock:
                if cls == STRUCTURAL:
                    if self._structural is None:
                        self._structural = reason
                    return
                name = event.obj.metadata.name
                prev = self._clusters.get(name)
                if prev is None or _RANK[cls] > _RANK[prev]:
                    self._clusters[name] = cls
        elif kind == ResourceBinding.KIND:
            with self._lock:
                self._binding_events += 1
                m = event.obj.metadata
                if event.type == DELETED:
                    self._bindings_deleted.append((m.namespace, m.name))
                else:
                    self._bindings_touched.append((m.namespace, m.name))

    def drain(self) -> CycleDeltas:
        """The coalesced window since the previous drain (resets it)."""
        with self._lock:
            out = CycleDeltas(
                structural=self._structural is not None,
                structural_reason=self._structural or "",
                clusters=self._clusters,
                binding_events=self._binding_events,
                bindings_deleted=self._bindings_deleted,
                bindings_touched=self._bindings_touched,
            )
            self._clusters = {}
            self._structural = None
            self._binding_events = 0
            self._bindings_deleted = []
            self._bindings_touched = []
        return out
